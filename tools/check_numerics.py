#!/usr/bin/env python
"""Validate a ``--numerics-demo`` report (ISSUE 10 satellite).

Usage: ``python tools/check_numerics.py report.json [...]`` (or ``-``
for stdin).  No jax import — this is the ``make numerics-demo`` gate
and runs anywhere.

What a valid numerics report must prove (docs/OBSERVABILITY.md):

  * the trace is real — ``numerics.mode == "trace"`` with one record
    per superstep (pivot ids in range, finite criterion values on a
    nonsingular solve), and the MODELED field list names exactly the
    fields that come from an error model (``residual_est``) — nothing
    measured-labeled is modeled;
  * the ladder actually fired — at least one recovery rung ran and the
    last one passed (the demo's ill-conditioned fixture is chosen to
    walk refine → fp32 re-solve);
  * **causality** — every ``recovery_rung`` and every
    ``residual_gate_failure`` event in the embedded black-box slice is
    preceded (by ``seq``) by a ``numerics_spike`` event: the rung is
    explained by the numerics evidence recorded before it.  A rung
    with no preceding spike is the exit-2 class — an unexplained
    ladder, exactly the blind spot ISSUE 10 exists to close;
  * the report's own ledger agrees — ``rung_count`` matches the rung
    events, ``spike_count`` the spike events, ``silent_rung`` is
    false, and the ring slice is gap-free (``dropped == 0``).

Exit taxonomy (the check_fleet/check_slo convention): 0 = valid,
1 = unreadable/structurally invalid, 2 = an unexplained rung.
"""

from __future__ import annotations

import json
import math
import sys


def check(report: dict) -> tuple[list[str], list[str]]:
    """Returns ``(errs, unexplained)``: structural violations and the
    exit-2 causality violations, both empty for a valid report.

    Two report flavors (ISSUE 11): the historical invert demo carries a
    full per-superstep TRACE; a ``workload: "solve"`` demo is
    summary-mode (the [A | B] engine has no per-superstep
    instrumentation yet — ROADMAP remainder), judged on the κ-free
    ‖A·X − B‖ backward error, so the superstep checks are skipped and
    the mode contract flips to "summary".  The causal spike→rung chain
    is validated identically for both."""
    errs: list[str] = []
    if report.get("metric") != "numerics_demo":
        return ([f"not a numerics_demo report "
                 f"(metric={report.get('metric')!r})"], [])

    workload = report.get("workload", "invert")
    num = report.get("numerics")
    if not isinstance(num, dict):
        errs.append("no numerics record in the report")
        num = {}
    if workload != "invert":
        if num.get("mode") != "summary":
            errs.append(f"solve-workload numerics mode is "
                        f"{num.get('mode')!r}, not 'summary' (the solve "
                        f"engine has no instrumented trace twin)")
        if num.get("workload") != workload:
            errs.append(f"numerics record workload "
                        f"{num.get('workload')!r} != report workload "
                        f"{workload!r}")
        rel = num.get("rel_residual")
        if not isinstance(rel, (int, float)) or not math.isfinite(rel):
            errs.append(f"solve rel_residual {rel!r} is not a finite "
                        f"number (the ‖A·X − B‖ backward error)")
    else:
        if num.get("mode") != "trace":
            errs.append(f"numerics mode is {num.get('mode')!r}, "
                        f"not 'trace'")
        n = report.get("n", 0)
        bs = num.get("block_size") or report.get("block_size", 1)
        nr = -(-n // max(1, min(bs, n))) if n else 0
        pivots = num.get("pivot_block") or []
        if len(pivots) != nr:
            errs.append(f"{len(pivots)} superstep records for Nr={nr}")
        for t, p in enumerate(pivots):
            if not (t <= p < nr):
                errs.append(f"step {t}: pivot block {p} outside the "
                            f"live window [{t}, {nr})")
        for fname in ("pivot_inv_norm", "cand_norm_max", "growth",
                      "residual_est"):
            vals = num.get(fname) or []
            if len(vals) != nr:
                errs.append(f"{fname}: {len(vals)} values for Nr={nr}")
            if fname != "residual_est":
                bad = [v for v in vals
                       if not isinstance(v, (int, float))
                       or not math.isfinite(v)]
                if bad:
                    errs.append(f"{fname}: non-finite values {bad[:3]} "
                                f"on a nonsingular solve")
        modeled = set(num.get("modeled_fields") or [])
        if modeled != {"residual_est"}:
            errs.append(f"modeled_fields {sorted(modeled)} != "
                        f"['residual_est'] — a modeled number may be "
                        f"masquerading as measured (or vice versa)")

    recovery = report.get("recovery") or []
    if not recovery:
        errs.append("no recovery rungs — the demo's ladder never fired "
                    "(the run was vacuous)")
    elif not recovery[-1].get("passed"):
        errs.append("the ladder exhausted without passing — the demo "
                    "fixture should recover through the fp32 re-solve")

    # ---- the causal chain (the exit-2 class) ------------------------
    bb = report.get("blackbox") or {}
    events = bb.get("events") or []
    if bb.get("dropped", 1) != 0:
        errs.append(f"black-box slice dropped {bb.get('dropped')} "
                    f"events — the causal chain may have gaps")
    spike_seqs = [e["seq"] for e in events
                  if e.get("kind") == "numerics_spike"]
    rung_events = [e for e in events
                   if e.get("kind") in ("recovery_rung",
                                        "residual_gate_failure")]
    unexplained = [
        f"{e['kind']} at seq {e['seq']} has no preceding "
        f"numerics_spike — an unexplained ladder"
        for e in rung_events
        if not any(s < e["seq"] for s in spike_seqs)]
    if report.get("silent_rung", True) and not unexplained:
        errs.append("silent_rung flagged by the demo itself but every "
                    "rung event has a preceding spike — the report "
                    "disagrees with its own black box")
    if not spike_seqs:
        errs.append("no numerics_spike events — an ill-conditioned "
                    "traced solve that spiked nothing")
    nrungs = sum(1 for e in events if e.get("kind") == "recovery_rung")
    if nrungs != len(recovery):
        errs.append(f"{nrungs} recovery_rung events vs "
                    f"{len(recovery)} recovery records")
    if report.get("spike_count") != len(spike_seqs):
        errs.append(f"spike_count {report.get('spike_count')} != "
                    f"{len(spike_seqs)} spike events")
    return errs, unexplained


def main(argv) -> int:
    if not argv:
        print("usage: check_numerics.py report.json [...]",
              file=sys.stderr)
        return 1
    rc = 0
    for path in argv:
        try:
            if path == "-":
                report = json.load(sys.stdin)
            else:
                with open(path) as f:
                    report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable report ({e})", file=sys.stderr)
            rc = max(rc, 1)
            continue
        errs, unexplained = check(report)
        for e in unexplained:
            print(f"UNEXPLAINED-RUNG {path}: {e}", file=sys.stderr)
        for e in errs:
            print(f"FAIL {path}: {e}", file=sys.stderr)
        if unexplained:
            rc = 2
        elif errs:
            rc = max(rc, 1)
        else:
            num = report["numerics"]
            if report.get("workload", "invert") != "invert":
                print(f"OK {path}: {report['workload']} workload "
                      f"(engine {num['engine']}, backward error "
                      f"{num['rel_residual']:.3g}), "
                      f"{report['spike_count']} spikes -> "
                      f"{report['rung_count']} rungs, every rung "
                      f"causally explained")
            else:
                print(f"OK {path}: {len(num['pivot_block'])} supersteps "
                      f"traced (growth {num['growth_factor']:.1f}x, max "
                      f"pivot criterion {num['max_pivot_inv_norm']:.3g}"
                      f"), {report['spike_count']} spikes -> "
                      f"{report['rung_count']} rungs, every rung "
                      f"causally explained")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
