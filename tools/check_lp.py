#!/usr/bin/env python
"""Validate an ``--lp-demo`` report (ISSUE 17 CI satellite) — the
LP/QP-driver analogue of ``check_update.py``.

Usage: ``python tools/check_lp.py report.json [...]`` (or ``-`` for
stdin).  No jax import — this is the ``make lp-demo`` gate and runs
anywhere.  Exit codes: 0 = valid, 1 = bound/structure violations,
2 = SILENT DIVERGENCE (the alarm that must never be downgraded): a
driver that claims convergence its own iterate residuals cannot
re-derive, an update the ledger cannot account for as
``refreshed | re_inverted | gated`` or a typed error, a verification
solve whose agreement with the resident inverse failed without a typed
outcome, or a chaos run that did not bit-match the fault-free replay.

What a valid lp_demo report must prove (docs/WORKLOADS.md):

  * **convergence is re-derivable** — for every leg, the final
    iterate's KKT residual is finite, bit-identical to its own hex
    trace token, equal to the reported ``kkt_rel_final``, and at or
    below the reported solver-gate threshold whenever the leg claims
    ``converged`` (the checker never re-runs the solver — it re-judges
    the report's own numbers, so a doctored residual or flag cannot
    pass);
  * **every update accounted** — per leg, the outcome ledger sums
    exactly to the update count, the per-iterate outcome stream
    agrees with the ledger tally, and the objective matches the
    instance's constructed optimum to within the gate-scaled bound;
  * **verification solves agree** — every iterate that carries a
    verification solve passes the solve lane's κ-free gate AND the
    κ-scaled agreement test against the resident inverse's answer;
  * **the degradation ladder is real** — the zero-drift-budget probe
    re_inverted EVERY update (>= that many rungs fired) and still
    converged;
  * **the warm path is free** — ZERO compiles and ZERO plan-cache
    measurements after warmup on the driver legs, the chaos pass, and
    the batched-lane measurement;
  * **chaos proved durability** — >= 1 seeded ``replica_kill`` fired
    mid-run, every per-iteration outcome tuple matched the fault-free
    replay, and the final solution fingerprints are bit-identical;
  * **batching amortizes** — measured occupancy > 1 on the batched
    update lane, and the warm amortized per-update latency beats the
    one-per-launch path (the speedup is recorded either way; the demo
    acceptance requires > 1).
"""

from __future__ import annotations

import json
import math
import sys

OUTCOMES = ("refreshed", "re_inverted", "gated")

#: objective-vs-certificate slack: the driver's forward error scales
#: with the same eps·n·κ model the gate encodes; 1e3x covers the
#: constant the model hides without ever passing a wrong vertex.
OBJ_GATE_FACTOR = 1e3


def _ledger_total(ledger: dict) -> int:
    return sum(int(ledger.get(k, 0)) for k in OUTCOMES + ("error",))


def _check_leg(name: str, leg: dict, errs: list, stale: list) -> None:
    """Re-derive one driver leg's claims from its own iterate trail."""
    iterates = leg.get("iterates", [])
    if not iterates:
        stale.append(f"{name}: no iterate trail — convergence is "
                     f"unverifiable")
        return
    last = iterates[-1]
    kkt = last.get("kkt_rel")
    thr = last.get("kkt_threshold")
    try:
        hex_rel = float.fromhex(last.get("kkt_hex", ""))
    except (TypeError, ValueError):
        hex_rel = None
    if hex_rel is None or hex_rel != kkt:
        stale.append(f"{name}: final kkt_rel {kkt} does not bit-match "
                     f"its own hex trace token {last.get('kkt_hex')!r}")
    if leg.get("kkt_rel_final") != kkt:
        stale.append(f"{name}: reported kkt_rel_final "
                     f"{leg.get('kkt_rel_final')} != final iterate "
                     f"residual {kkt} — the summary drifted from its "
                     f"own trail")
    converged = bool(leg.get("converged", False))
    rederived = (isinstance(kkt, float) and isinstance(thr, float)
                 and math.isfinite(kkt) and kkt <= thr)
    if converged and not rederived:
        stale.append(f"{name}: claims converged but the final iterate "
                     f"residual ({kkt}) does not pass its own gate "
                     f"({thr}) — silent divergence")
    if not converged:
        errs.append(f"{name}: driver did not converge")

    # Every update accounted, and the iterate stream agrees with the
    # ledger it claims to summarize.
    ledger = leg.get("ledger", {})
    updates = int(leg.get("updates", -1))
    total = _ledger_total(ledger)
    if total != updates:
        stale.append(f"{name}: ledger accounts {total} of {updates} "
                     f"updates ({ledger}) — an update went silently "
                     f"unaccounted")
    tally = {}
    for r in iterates:
        if "outcome" in r:
            tally[r["outcome"]] = tally.get(r["outcome"], 0) + 1
    for o in OUTCOMES:
        if tally.get(o, 0) != int(ledger.get(o, 0)):
            stale.append(f"{name}: iterate outcome stream counts "
                         f"{tally} but the ledger says {ledger} — the "
                         f"ledger drifted from its own trail")
            break

    # Verification solves: the κ-free solve gate and the κ-scaled
    # agreement both re-judged from the recorded numbers.
    solves = 0
    for r in iterates:
        if "solve_rel" not in r:
            continue
        solves += 1
        if not (math.isfinite(r["solve_rel"])
                and r["solve_rel"] <= r.get("solve_threshold",
                                            float("nan"))):
            stale.append(f"{name} iterate {r.get('i')}: verification "
                         f"solve failed its own gate "
                         f"(rel {r['solve_rel']} vs "
                         f"{r.get('solve_threshold')})")
        if not (math.isfinite(r.get("agree_rel", float("nan")))
                and r["agree_rel"] <= r.get("agree_threshold",
                                            float("nan"))):
            stale.append(f"{name} iterate {r.get('i')}: resident "
                         f"inverse disagrees with the fresh solve "
                         f"beyond what κ explains "
                         f"(rel {r.get('agree_rel')} vs "
                         f"{r.get('agree_threshold')}) — a silently "
                         f"rotten inverse")
    if solves != int(leg.get("solves", -1)):
        errs.append(f"{name}: {solves} verification solves in the "
                    f"trail but the summary claims {leg.get('solves')}")

    # The certificate check: the instance carries its constructed
    # optimum; the reached objective must match it to the gate-scaled
    # bound (a wrong vertex/active-set converges the KKT residual too,
    # but not the objective).
    obj, ref = leg.get("objective"), leg.get("objective_ref")
    if (isinstance(obj, float) and isinstance(ref, float)
            and isinstance(thr, float) and math.isfinite(thr)):
        rel = abs(obj - ref) / (1.0 + abs(ref))
        bound = max(1e-8, OBJ_GATE_FACTOR * thr)
        if converged and not rel <= bound:
            stale.append(f"{name}: converged objective {obj} misses "
                         f"the instance certificate {ref} (rel {rel:.3e}"
                         f" > {bound:.3e}) — converged to the wrong "
                         f"point")
    else:
        errs.append(f"{name}: objective/certificate fields missing or "
                    f"non-numeric")


def check(report: dict) -> tuple[list[str], list[str]]:
    """Return (violations, divergence_violations); both empty = valid."""
    errs: list[str] = []
    stale: list[str] = []
    if report.get("metric") != "lp_demo":
        return ([f"not an lp_demo report (metric="
                 f"{report.get('metric')!r})"], [])

    legs = report.get("legs", {})
    for required in ("lp_well", "lp_ill", "qp_well", "qp_ill"):
        if required not in legs:
            errs.append(f"missing driver leg {required!r}")
    for name, leg in legs.items():
        _check_leg(name, leg, errs, stale)
    if "errors" not in report:
        errs.append("missing 'errors' field")
    for msg in report.get("errors", []):
        stale.append(f"typed driver failure mid-demo: {msg}")

    # ---- warm-path pins --------------------------------------------
    if report.get("compiles_after_warmup", 1) != 0:
        stale.append(f"{report.get('compiles_after_warmup')} "
                     f"compile(s) on the warm driver path — the "
                     f"zero-compile pin broke")
    if report.get("measurements_after_warmup", 1) != 0:
        errs.append(f"{report.get('measurements_after_warmup')} "
                    f"plan-cache measurement(s) on the driver path")

    # ---- the degradation ladder ------------------------------------
    probe = report.get("drift_probe", {})
    p_updates = int(probe.get("updates", 0))
    if (p_updates < 1
            or int(probe.get("ledger", {}).get("re_inverted", 0))
            != p_updates
            or probe.get("rungs_fired", 0) < p_updates):
        errs.append(f"the zero-drift-budget probe did not re_invert "
                    f"every update ({probe}) — the ladder is unproven")
    if not probe.get("converged", False):
        stale.append("the zero-drift-budget probe did not converge — "
                     "the re_invert rung handed the driver a bad "
                     "inverse")

    # ---- chaos durability (the exit-2 class) ------------------------
    chaos = report.get("chaos", {})
    if chaos.get("kills_injected", 0) < 1:
        errs.append("no replica_kill injected mid-run — the chaos leg "
                    "was vacuous")
    if chaos.get("deaths", 0) < chaos.get("kills_injected", 0):
        errs.append(f"{chaos.get('kills_injected')} kills but only "
                    f"{chaos.get('deaths')} deaths — a kill was "
                    f"swallowed")
    if not chaos.get("fingerprint_bitmatch", False):
        stale.append("final solution fingerprint diverged from the "
                     "fault-free replay")
    if chaos.get("iterates_matched", -1) != chaos.get("iterates_total",
                                                      -2):
        stale.append(f"only {chaos.get('iterates_matched')} of "
                     f"{chaos.get('iterates_total')} chaos iterates "
                     f"bit-matched the fault-free replay")
    if chaos.get("compiles_delta_after_warmup", 1) != 0:
        stale.append(f"{chaos.get('compiles_delta_after_warmup')} "
                     f"compile(s) during the chaos pass — warm "
                     f"replacements were not free")
    mism = report.get("mismatches", [{"missing": True}])
    if mism:
        stale.append(f"{len(mism)} chaos iterate(s) diverged from the "
                     f"fault-free replay: {mism[:3]}")

    # ---- the batched-lane amortization claim ------------------------
    bat = report.get("batched", {})
    if bat.get("occupancy", 0) <= 1:
        errs.append(f"batched update lane measured occupancy "
                    f"{bat.get('occupancy')} — the vmapped batch "
                    f"dimension never carried > 1 rider")
    if not bat.get("amortized_beats_one_per_launch", False):
        errs.append(f"warm batched amortized latency "
                    f"({bat.get('warm_batched_amortized_ms')} ms) did "
                    f"not beat one-per-launch "
                    f"({bat.get('warm_one_per_launch_ms')} ms), "
                    f"speedup {bat.get('speedup_x')}x")
    if bat.get("compiles_delta", 1) != 0:
        stale.append(f"{bat.get('compiles_delta')} compile(s) during "
                     f"the batched-lane measurement — the warm pin "
                     f"broke")

    if report.get("silent_divergence", True):
        stale.append("silent_divergence flagged by the demo itself")
    fleet_ledger = report.get("fleet_ledger", {})
    if fleet_ledger.get("outstanding", 1) != 0:
        stale.append(f"{fleet_ledger.get('outstanding')} request(s) "
                     f"outstanding after the drain — lost in flight")
    return errs, stale


def main(argv) -> int:
    if not argv:
        print("usage: check_lp.py report.json [...]", file=sys.stderr)
        return 1
    rc = 0
    for path in argv:
        try:
            if path == "-":
                report = json.load(sys.stdin)
            else:
                with open(path) as f:
                    report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable report ({e})",
                  file=sys.stderr)
            rc = max(rc, 1)
            continue
        errs, stale = check(report)
        for e in stale:
            print(f"SILENT-DIVERGENCE {path}: {e}", file=sys.stderr)
        for e in errs:
            print(f"FAIL {path}: {e}", file=sys.stderr)
        if stale:
            rc = 2
        elif errs:
            rc = max(rc, 1)
        else:
            legs = report["legs"]
            bat = report["batched"]
            iters = {k: v["iterations"] for k, v in legs.items()}
            print(f"OK {path}: 4 driver legs converged at n="
                  f"{report['n']} ({iters}), "
                  f"{report['chaos']['kills_injected']} kill(s) with "
                  f"bit-matched replay, drift probe re_inverted "
                  f"{report['drift_probe']['updates']} update(s), "
                  f"batched occupancy {bat['occupancy']} amortized "
                  f"{bat['warm_batched_amortized_ms']} ms vs "
                  f"{bat['warm_one_per_launch_ms']} ms "
                  f"({bat['speedup_x']}x), 0 compiles after warmup")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
