#!/usr/bin/env python
"""Validate a ``--comm-demo`` report (ISSUE 14).

Usage: ``python tools/check_comm.py report.json`` (or ``-`` for
stdin).  No jax import — this is the ``make comm-demo`` gate and runs
anywhere.

What a valid communication-observatory report must prove
(docs/OBSERVABILITY.md):

  * **the reconciliation invariant** — on every reconciliation leg
    (1D and 2D meshes, both gather modes, a grouped engine, a RAGGED
    problem size), the multiset of collectives the traced program
    actually issued (the compat-shim recording layer: kind × mesh axis
    × operand shape × dtype) EQUALS the layout-derived analytical
    inventory.  The checker re-derives the comparison from the
    report's own raw data — it never trusts the ``reconciled`` flag:
    an observed collective the model does not predict is an
    UNACCOUNTED collective; a predicted collective the trace never
    issued is a stripped/phantom entry.  Both are the exit-2 class.
  * **totals honesty** — the per-leg byte/message totals re-derive
    from the signature list (shape × dtype width × launches), so a
    report cannot claim totals its own inventory does not add up to.
  * **no silent drift** — the drift leg is judged; when its
    measured/projected ratio sits outside the stated band, a
    ``comm_drift`` event MUST exist in the embedded flight-recorder
    slice and the report's drift counters must agree.  An out-of-band
    ratio with no recorded event is a silent drift — exit 2.
  * the embedded black-box slice is gap-free (``dropped == 0``) and
    ``silent_comm`` agrees with the re-derivation.

Exit taxonomy (the check_fleet/check_slo convention): 0 = valid,
1 = unreadable/structurally invalid, 2 = an unaccounted collective or
a silent drift.
"""

from __future__ import annotations

import json
import sys

#: dtype widths for total re-derivation (the report's own
#: payload_bytes is cross-checked against these; an unknown dtype is a
#: structural error — the analytical model only emits these).
_ITEMSIZE = {
    "float32": 4, "float64": 8, "float16": 2, "bfloat16": 2,
    "int32": 4, "int64": 8, "complex64": 8, "complex128": 16,
}


def _nelems(shape) -> int:
    out = 1
    for s in shape:
        out *= int(s)
    return out


def _sig_key(d: dict) -> tuple:
    return (d["kind"], d["axis"], tuple(d["shape"]), d["dtype"])


def _check_leg(name: str, comm: dict, *, require_engine_observed: bool,
               errs: list, silent: list) -> None:
    sigs = comm.get("sigs") or []
    # -- totals re-derive from the inventory -------------------------
    payload = explicit = messages = 0
    for s in sigs:
        if s["dtype"] not in _ITEMSIZE:
            errs.append(f"{name}: unknown dtype {s['dtype']!r} in "
                        f"inventory")
            continue
        nb = _nelems(s["shape"]) * _ITEMSIZE[s["dtype"]]
        if nb != s.get("payload_bytes"):
            errs.append(f"{name}: sig {s['kind']}@{s['axis']} "
                        f"{s['shape']} claims {s.get('payload_bytes')} "
                        f"payload bytes, shape x width says {nb}")
        payload += nb * s["executed"]
        if not s.get("implicit"):
            explicit += nb * s["executed"]
            messages += s["executed"]
    tot = comm.get("totals") or {}
    if tot.get("payload_bytes") != payload:
        errs.append(f"{name}: totals.payload_bytes "
                    f"{tot.get('payload_bytes')} != inventory sum "
                    f"{payload}")
    if tot.get("messages") != messages:
        errs.append(f"{name}: totals.messages {tot.get('messages')} "
                    f"!= inventory sum {messages}")

    # -- the reconciliation invariant, re-derived ---------------------
    observed = comm.get("observed") or {}
    judged_engine = False
    for section, recs in observed.items():
        if recs is None:
            continue            # trace-cache hit: honestly un-judged
        if section == "engine":
            judged_engine = True
        want: dict[tuple, int] = {}
        for s in sigs:
            if (s.get("section") == section and not s.get("implicit")
                    and s["traced"]):
                k = _sig_key(s)
                want[k] = want.get(k, 0) + s["traced"]
        got: dict[tuple, int] = {}
        for r in recs:
            k = _sig_key(r)
            got[k] = got.get(k, 0) + int(r["count"])
        for k in sorted(set(want) | set(got), key=str):
            w, g = want.get(k, 0), got.get(k, 0)
            if g > w:
                silent.append(
                    f"{name}/{section}: UNACCOUNTED collective "
                    f"{k[0]}@{k[1]} {list(k[2])} {k[3]}: observed {g} "
                    f"vs analytical {w}")
            elif w > g:
                silent.append(
                    f"{name}/{section}: stripped/phantom collective "
                    f"{k[0]}@{k[1]} {list(k[2])} {k[3]}: analytical "
                    f"{w} vs observed {g}")
    if require_engine_observed and not judged_engine:
        errs.append(f"{name}: engine section was never observed (the "
                    f"reconciliation legs must capture a fresh trace)")
    if comm.get("reconciled") is not True and require_engine_observed:
        errs.append(f"{name}: reconciled={comm.get('reconciled')!r} "
                    f"(must be strictly true on a reconciliation leg)")


def check(report: dict) -> tuple[list[str], list[str]]:
    """Returns ``(errs, silent)``: structural violations (exit 1) and
    the exit-2 unaccounted-collective / silent-drift class."""
    errs: list[str] = []
    silent: list[str] = []
    if report.get("metric") != "comm_demo":
        return ([f"not a comm_demo report "
                 f"(metric={report.get('metric')!r})"], [])
    if not report.get("ragged"):
        errs.append("demo problem size is not ragged (n % m == 0): "
                    "the padded-tail inventory was never exercised")

    legs = report.get("legs") or []
    if len(legs) < 4:
        errs.append(f"only {len(legs)} reconciliation legs; need 1D + "
                    f"2D, both gather modes")
    seen = set()
    for leg in legs:
        comm = leg.get("comm") or {}
        mesh = comm.get("mesh", "")
        seen.add(("2d" if "x" in mesh else "1d",
                  bool(comm.get("gather"))))
        _check_leg(leg.get("name", "?"), comm,
                   require_engine_observed=True, errs=errs,
                   silent=silent)
    for want in (("1d", True), ("1d", False), ("2d", True),
                 ("2d", False)):
        if want not in seen:
            errs.append(f"missing reconciliation coverage: "
                        f"{want[0]} gather={want[1]}")
    # ISSUE 15: the demo must also reconcile the distributed SOLVE
    # engine (the [A | B] elimination's own inventory — a solve engine
    # without a reconciled leg is exactly the unaccounted-collective
    # class this gate exists for).
    if not any((leg.get("comm") or {}).get("engine") == "solve_sharded"
               for leg in legs):
        errs.append("missing reconciliation coverage: the distributed "
                    "solve leg (engine='solve_sharded')")
    # ISSUE 16: the probe-ahead engines reorder the schedule but must
    # keep the collective multiset identical — a demo without their
    # reconciled legs would let a lookahead-only extra collective ship
    # unaccounted.
    for la_engine, what in (("lookahead", "invert"),
                            ("solve_lookahead", "solve")):
        if not any((leg.get("comm") or {}).get("engine") == la_engine
                   for leg in legs):
            errs.append(f"missing reconciliation coverage: the "
                        f"probe-ahead {what} leg (engine="
                        f"'{la_engine}')")

    # -- drift leg ----------------------------------------------------
    drift_leg = report.get("drift_leg") or {}
    dcomm = drift_leg.get("comm") or {}
    _check_leg(drift_leg.get("name", "drift"), dcomm,
               require_engine_observed=False, errs=errs, silent=silent)
    drift = dcomm.get("drift") or {}
    if not drift.get("judged"):
        errs.append("drift leg was not judged (set_drift_policy("
                    "judge='always') is the demo's contract)")
    ratio = drift.get("comm_vs_projected")
    band = drift.get("band") or [0, 0]
    out_of_band = (isinstance(ratio, (int, float))
                   and not (band[0] <= ratio <= band[1]))
    if out_of_band != bool(drift.get("out_of_band")):
        errs.append(f"drift leg out_of_band={drift.get('out_of_band')}"
                    f" disagrees with ratio {ratio} vs band {band}")
    events = [e for e in (report.get("blackbox") or {}).get(
        "events", []) if e.get("kind") == "comm_drift"]
    if drift.get("judged") and out_of_band:
        if not events:
            silent.append(
                f"SILENT DRIFT: measured/projected ratio {ratio} is "
                f"outside the band {band} but no comm_drift event was "
                f"recorded in the flight-recorder slice")
        if not drift.get("event_recorded"):
            silent.append("drift record claims event_recorded=false "
                          "for an out-of-band judged ratio")
    if report.get("drift_events") != len(events):
        errs.append(f"report drift_events={report.get('drift_events')} "
                    f"!= {len(events)} comm_drift events in the slice")

    bb = report.get("blackbox") or {}
    if bb.get("dropped", 1) != 0:
        errs.append(f"flight-recorder slice dropped "
                    f"{bb.get('dropped')} events — reconstruction has "
                    f"gaps")
    if bool(report.get("silent_comm")) != bool(silent):
        errs.append(f"report silent_comm={report.get('silent_comm')} "
                    f"disagrees with the re-derived verdict "
                    f"({len(silent)} violations)")
    return errs, silent


def main(argv) -> int:
    if not argv:
        print("usage: check_comm.py report.json [...]", file=sys.stderr)
        return 1
    rc = 0
    for path in argv:
        try:
            if path == "-":
                report = json.load(sys.stdin)
            else:
                with open(path) as f:
                    report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable report ({e})",
                  file=sys.stderr)
            return 1
        errs, silent = check(report)
        for msg in errs:
            print(f"FAIL {path}: {msg}", file=sys.stderr)
        for msg in silent:
            print(f"SILENT {path}: {msg}", file=sys.stderr)
        if silent:
            rc = max(rc, 2)
        elif errs:
            rc = max(rc, 1)
        else:
            legs = report.get("legs") or []
            drift = ((report.get("drift_leg") or {}).get("comm")
                     or {}).get("drift") or {}
            print(f"OK {path}: {len(legs)} legs reconciled "
                  f"(observed == analytical), drift ratio "
                  f"{drift.get('comm_vs_projected'):.3g} "
                  f"{'recorded' if drift.get('event_recorded') else 'in band'}, "
                  f"{report.get('drift_events')} comm_drift event(s)")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
