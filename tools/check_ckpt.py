#!/usr/bin/env python
"""Validate a ``--ckpt-demo`` report (ISSUE 20 CI satellite).

Usage: ``python tools/check_ckpt.py report.json [...]`` (or ``-`` for
stdin).  No jax import — this is the ``make ckpt-demo`` gate and runs
anywhere.  Exit codes: 0 = valid, 1 = bound/structure violations,
2 = SILENT LOSS (the alarm that must never be downgraded): a resume
that diverged from the uninterrupted bits, a preemption that silently
recomputed from scratch past a durable checkpoint, a preempt event the
black box cannot pair with a resume or a typed refusal, a warm resume
that recompiled, or a checkpoint ledger that does not add up.

What a valid ckpt_demo report must prove (docs/RESILIENCE.md):

  * **resumes are bit-exact** — every leg's resumed result fingerprint
    equals the uninterrupted baseline's (the checker never re-runs the
    sweep — it compares the report's own witnesses, so a doctored
    fingerprint cannot pass);
  * **no silent from-scratch** — a leg preempted AFTER a durable
    checkpoint (``preempt_step >= 0``) must have ``resumed`` and
    re-entered at exactly that superstep; recomputing from step 0 past
    a durable token is the failure this tool exists to catch.
    (Preempted BEFORE anything durable, ``preempt_step == -1``, a
    from-scratch run is the CORRECT recovery — lost work is still
    under one cadence window.);
  * **lost work is bounded** — every re-executed segment spans at most
    ``cadence`` supersteps, so a preemption can never cost more than
    one cadence window;
  * **every preemption pairs** — each ``ckpt_preempted`` event in the
    embedded black box with a durable step is followed by a
    ``ckpt_resumed`` event for the same run at the same step;
  * **warm resumes are free** — zero segment compiles on every resume
    (the segment executables are keyed on static bounds; re-entering
    on the cadence grid reuses them);
  * **the ledger adds up** — ``written == resumed + discarded + live``
    re-derived from the reported counts, zero live tokens at demo end,
    zero corruptions, and the black-box event counts agree with the
    ledger (an event stream that drifts from its own ledger is how
    silent loss hides).
"""

from __future__ import annotations

import json
import sys

REQUIRED_LEGS = ("single_invert", "dist_solve", "lp_stream",
                 "fleet_kill")


def _check_leg(name: str, leg: dict, errs: list, loss: list) -> None:
    if not leg.get("bit_match", False):
        loss.append(
            f"{name}: resumed fingerprint {leg.get('resume_fp')!r} "
            f"diverged from the uninterrupted baseline "
            f"{leg.get('baseline_fp')!r} — a resume must be bit-exact")
    pre = leg.get("preempt_step", -1)
    if pre is None:
        pre = -1
    if pre >= 0:
        if not leg.get("resumed", False):
            loss.append(
                f"{name}: preempted with a durable checkpoint at "
                f"superstep {pre} but the recovery did not resume — "
                f"silent recompute-from-scratch")
        elif int(leg.get("resume_start_step", -1)) != int(pre):
            loss.append(
                f"{name}: resume re-entered at superstep "
                f"{leg.get('resume_start_step')} but the durable "
                f"checkpoint was at {pre} — work silently lost or "
                f"silently redone")
    cadence = int(leg.get("cadence", 0))
    if cadence < 1:
        errs.append(f"{name}: missing/invalid cadence")
    for seg in leg.get("resume_segments", []):
        t0, t1 = int(seg[0]), int(seg[1])
        if t1 - t0 > max(cadence, 1):
            loss.append(
                f"{name}: resumed segment ({t0}, {t1}) spans "
                f"{t1 - t0} supersteps > cadence {cadence} — the "
                f"lost-work bound is broken")
    if leg.get("resume_compiles", 1) != 0:
        loss.append(
            f"{name}: {leg.get('resume_compiles')} segment compile(s) "
            f"on the warm resume — the zero-compile pin broke")
    if name == "lp_stream" and not leg.get("kkt_trail_match", False):
        loss.append(
            "lp_stream: the resumed run's kkt_hex iterate trail does "
            "not bit-match the uninterrupted stream — the replay "
            "silently diverged")
    if name == "fleet_kill" and not leg.get("killed_replicas"):
        errs.append(
            "fleet_kill: no replica was killed mid-sweep — the "
            "kill-path leg was vacuous")


def _check_events(report: dict, errs: list, loss: list) -> None:
    events = report.get("blackbox", {}).get("events", [])
    if not events:
        errs.append("no embedded black-box slice — preempt/resume "
                    "pairing is unverifiable")
        return
    preempts = [e for e in events if e.get("kind") == "ckpt_preempted"]
    resumes = [e for e in events if e.get("kind") == "ckpt_resumed"]
    writes = [e for e in events if e.get("kind") == "ckpt_written"]
    corrupts = [e for e in events if e.get("kind") == "ckpt_corrupt"]
    if not preempts:
        errs.append("no ckpt_preempted event in the black box — the "
                    "demo never actually preempted anything")
    for i, e in enumerate(events):
        if e.get("kind") != "ckpt_preempted":
            continue
        step = int(e.get("step", -1))
        if step < 0:
            # Nothing durable: from-scratch recovery is correct.
            continue
        run = e.get("run_id")
        paired = any(
            r.get("run_id") == run and int(r.get("step", -2)) == step
            and events.index(r) > i
            for r in resumes)
        if not paired:
            loss.append(
                f"preempt of run {run!r} at durable superstep {step} "
                f"has no matching ckpt_resumed event — the checkpoint "
                f"was silently ignored")
    ledger = report.get("ledger", {})
    for kind, evs in (("written", writes), ("resumed", resumes),
                      ("corrupt", corrupts)):
        if int(ledger.get(kind, -1)) != len(evs):
            loss.append(
                f"ledger counts {ledger.get(kind)} {kind} but the "
                f"black box recorded {len(evs)} ckpt_{kind} event(s) "
                f"— the ledger drifted from its own event stream")


def check(report: dict) -> tuple[list[str], list[str]]:
    """Return (violations, silent_loss_violations); both empty = OK."""
    errs: list[str] = []
    loss: list[str] = []
    if report.get("metric") != "ckpt_demo":
        return ([f"not a ckpt_demo report (metric="
                 f"{report.get('metric')!r})"], [])
    legs = report.get("legs", {})
    for required in REQUIRED_LEGS:
        if required not in legs:
            errs.append(f"missing leg {required!r}")
    for name, leg in legs.items():
        _check_leg(name, leg, errs, loss)
    _check_events(report, errs, loss)

    ledger = report.get("ledger", {})
    w = int(ledger.get("written", -1))
    r = int(ledger.get("resumed", 0))
    d = int(ledger.get("discarded", 0))
    live = int(ledger.get("live", 0))
    if w != r + d + live:
        loss.append(f"checkpoint ledger does not add up: written {w} "
                    f"!= resumed {r} + discarded {d} + live {live}")
    if not ledger.get("invariant_holds", False):
        loss.append("the store's own invariant flag is false")
    if live != 0:
        errs.append(f"{live} live checkpoint token(s) at demo end — "
                    f"a run finished without consuming its token")
    if int(ledger.get("corrupt", 0)) != 0:
        errs.append(f"{ledger.get('corrupt')} corrupt checkpoint(s) "
                    f"during the demo — quarantine fired unexpectedly")
    if report.get("silent_loss", True):
        loss.append("silent_loss flagged by the demo itself")
    return errs, loss


def main(argv) -> int:
    if not argv:
        print("usage: check_ckpt.py report.json [...]", file=sys.stderr)
        return 1
    rc = 0
    for path in argv:
        try:
            if path == "-":
                report = json.load(sys.stdin)
            else:
                with open(path) as f:
                    report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable report ({e})",
                  file=sys.stderr)
            rc = max(rc, 1)
            continue
        errs, loss = check(report)
        for e in loss:
            print(f"SILENT-LOSS {path}: {e}", file=sys.stderr)
        for e in errs:
            print(f"FAIL {path}: {e}", file=sys.stderr)
        if loss:
            rc = 2
        elif errs:
            rc = max(rc, 1)
        else:
            legs = report["legs"]
            resumes = sum(1 for v in legs.values() if v.get("resumed"))
            print(f"OK {path}: {len(legs)} legs bit-matched at n="
                  f"{report['n']} cadence {report['cadence']} "
                  f"({resumes} resume(s), 0 resume compiles), ledger "
                  f"{report['ledger']['written']} written = "
                  f"{report['ledger']['resumed']} resumed + "
                  f"{report['ledger']['discarded']} discarded + 0 live")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
