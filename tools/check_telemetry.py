#!/usr/bin/env python
"""Validate telemetry export artifacts (the Makefile ``metrics-demo``
target's checker; ISSUE 4 satellite).

Usage: ``python tools/check_telemetry.py FILE [FILE ...]``

Each file is sniffed by content: a document starting with ``{`` is
checked as Chrome trace-event JSON, anything else as Prometheus text
exposition format.  Checks (all must pass; exit 1 with a message
otherwise):

  * Prometheus: every sample line parses as ``name[{labels}] value``,
    every metric family has a ``# TYPE`` line with a known type AND a
    non-empty ``# HELP`` line (both ways — a HELP for a family that
    exports no TYPE is a stale/typoed name; ISSUE 10 satellite), every
    family name lives in the ``tpu_jordan_`` namespace
    (``obs.metrics.NAME_RE``), and at least one sample exists.
  * Chrome trace: the document loads as JSON with a ``traceEvents``
    list, every event has a known phase (complete ``X`` events carry a
    numeric ``dur``; duration events come as matched ``B``/``E`` pairs
    per (pid, tid, name)), and at least one event exists.
  * Async journey lanes (ISSUE 8): nestable ``b``/``e``/``n`` events
    must carry an ``id`` (the request_id — the lane key Perfetto
    groups by), ``b``/``e`` must balance per (cat, id), every instant
    ``n`` must fall inside its lane's ``b``..``e`` bracket, and a lane
    in the ``tpu_jordan_request`` category must carry at least one
    hop instant (a request lane with no events explains nothing).
  * Pallas-path attribution honesty (ISSUE 6 satellite): an ``execute``
    event whose ``args.engine`` is a fused-kernel engine
    (``grouped_pallas*``) must not contain MODEL-attributed hot-loop
    phase children — the kernels give the host real brackets
    (``measured=True``/``source``), so a ``modeled=True`` pivot/permute/
    eliminate event nested inside such an execute span is a regression
    to the flops model and fails the check.
"""

from __future__ import annotations

import json
import re
import sys

NAME_RE = re.compile(r"^tpu_jordan_[a-z0-9_]+$")
SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?\s+(-?[0-9.eE+-]+|NaN|"
    r"[+-]?Inf)$")
_SUFFIXES = ("_sum", "_count")
_TYPES = {"counter", "gauge", "summary", "histogram", "untyped"}
#: The paper's hot-loop phases (obs.spans.PHASES) and the engines whose
#: execute spans must carry MEASURED (never modeled) phase children.
_PHASE_NAMES = {"pivot", "permute", "eliminate"}
_PALLAS_ENGINE_PREFIX = "grouped_pallas"


def check_prometheus(text: str, path: str) -> int:
    """Returns the sample count; raises AssertionError on any violation."""
    typed: set[str] = set()
    helped: set[str] = set()
    samples = 0
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            assert len(parts) == 4 and parts[3] in _TYPES, \
                f"{path}:{i}: malformed TYPE line: {line!r}"
            typed.add(parts[2])
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            assert len(parts) >= 4 and parts[3].strip(), \
                f"{path}:{i}: HELP line without text: {line!r}"
            helped.add(parts[2])
            continue
        if line.startswith("#"):
            continue
        m = SAMPLE_RE.match(line)
        assert m, f"{path}:{i}: unparseable sample line: {line!r}"
        name = m.group(1)
        family = name
        for suf in _SUFFIXES:
            if family.endswith(suf) and family[:-len(suf)] in typed:
                family = family[:-len(suf)]
                break
        assert NAME_RE.match(family), (
            f"{path}:{i}: metric {family!r} outside the tpu_jordan_ "
            f"namespace ({NAME_RE.pattern})")
        assert family in typed, \
            f"{path}:{i}: sample {name!r} has no preceding # TYPE line"
        float(m.group(3).replace("Inf", "inf").replace("NaN", "nan"))
        samples += 1
    assert samples > 0, f"{path}: no samples — empty scrape"
    # HELP next to TYPE, both ways (ISSUE 10 satellite): a family that
    # is typed but undocumented fails, as does a HELP line for a family
    # that exports no TYPE (a stale or typoed family name).
    unhelped = typed - helped
    assert not unhelped, (
        f"{path}: metric families with # TYPE but no # HELP line: "
        f"{sorted(unhelped)}")
    orphaned = helped - typed
    assert not orphaned, (
        f"{path}: # HELP lines for families with no # TYPE: "
        f"{sorted(orphaned)}")
    return samples


def check_chrome_trace(text: str, path: str) -> int:
    """Returns the event count; raises AssertionError on any violation."""
    doc = json.loads(text)
    events = doc["traceEvents"]
    assert isinstance(events, list) and events, \
        f"{path}: traceEvents missing or empty"
    open_be: dict = {}
    lanes: dict = {}
    for ev in events:
        ph = ev.get("ph")
        assert ph in {"X", "B", "E", "M", "i", "b", "e", "n"}, \
            f"{path}: unknown event phase {ph!r}: {ev}"
        if ph == "X":
            assert isinstance(ev.get("dur"), (int, float)), \
                f"{path}: complete event without numeric dur: {ev}"
            assert isinstance(ev.get("ts"), (int, float)), \
                f"{path}: complete event without numeric ts: {ev}"
        elif ph in ("B", "E"):
            key = (ev.get("pid"), ev.get("tid"), ev.get("name"))
            open_be[key] = open_be.get(key, 0) + (1 if ph == "B" else -1)
            assert open_be[key] >= 0, \
                f"{path}: E before B for {key}"
        elif ph in ("b", "e", "n"):
            # Async nestable lanes (the ISSUE 8 journey view): id is
            # the lane key — an async event without one renders on no
            # lane at all.
            assert ev.get("id") not in (None, ""), \
                f"{path}: async {ph!r} event without an id: {ev}"
            assert isinstance(ev.get("ts"), (int, float)), \
                f"{path}: async event without numeric ts: {ev}"
            lane = lanes.setdefault((ev.get("cat"), ev["id"]),
                                    {"b": [], "e": [], "n": []})
            lane[ph].append(float(ev["ts"]))
    bad = {k: v for k, v in open_be.items() if v != 0}
    assert not bad, f"{path}: unmatched B/E events: {bad}"
    for (cat, lane_id), tss in lanes.items():
        assert len(tss["b"]) == len(tss["e"]) >= 1, (
            f"{path}: async lane {lane_id!r} (cat {cat!r}) has "
            f"{len(tss['b'])} 'b' vs {len(tss['e'])} 'e' events — "
            f"unbalanced lane bracket")
        t0, t1 = min(tss["b"]), max(tss["e"])
        assert t0 <= t1, \
            f"{path}: async lane {lane_id!r} ends before it begins"
        for ts in tss["n"]:
            assert t0 - 1e-6 <= ts <= t1 + 1e-6, (
                f"{path}: async instant at ts {ts} outside lane "
                f"{lane_id!r}'s bracket [{t0}, {t1}] — the hop would "
                f"render off its request's row")
        if cat == "tpu_jordan_request":
            assert tss["n"], (
                f"{path}: request lane {lane_id!r} has no hop "
                f"instants — a journey that explains nothing")

    # Pallas-path attribution honesty: no modeled phase children inside
    # a fused-kernel engine's execute bracket.
    pallas_execs = [
        ev for ev in events
        if ev.get("ph") == "X" and ev.get("name") == "execute"
        and str(ev.get("args", {}).get("engine", ""))
        .startswith(_PALLAS_ENGINE_PREFIX)]
    for ex in pallas_execs:
        t0, t1 = ex["ts"], ex["ts"] + ex["dur"]
        for ev in events:
            if (ev.get("ph") == "X" and ev.get("name") in _PHASE_NAMES
                    and ev.get("pid") == ex.get("pid")
                    and ev.get("tid") == ex.get("tid")
                    and t0 <= ev.get("ts", -1) and
                    ev["ts"] + ev.get("dur", 0) <= t1 + 1e-6):
                assert not ev.get("args", {}).get("modeled"), (
                    f"{path}: modeled phase child {ev['name']!r} inside "
                    f"a {_PALLAS_ENGINE_PREFIX}* execute span — the "
                    f"Pallas path must emit measured brackets "
                    f"(obs/spans.attribute_phases_measured)")
    return len(events)


def main(argv=None) -> int:
    paths = (argv if argv is not None else sys.argv[1:])
    if not paths:
        print(__doc__, file=sys.stderr)
        return 1
    failures = 0
    for path in paths:
        try:
            with open(path) as f:
                text = f.read()
            if text.lstrip().startswith("{"):
                n = check_chrome_trace(text, path)
                print(f"{path}: OK chrome-trace ({n} events)")
            else:
                n = check_prometheus(text, path)
                print(f"{path}: OK prometheus ({n} samples)")
        except Exception as e:                   # noqa: BLE001
            print(f"{path}: FAIL — {e}", file=sys.stderr)
            failures += 1
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
