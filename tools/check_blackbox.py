#!/usr/bin/env python
"""Flight-recorder/journey validation (ISSUE 8): the shared causal-
chain rules ``check_chaos.py`` / ``check_fleet.py`` import (next to
themselves, no jax — runnable anywhere, the rules exist exactly once),
plus a standalone CLI for validating a RAW black-box dump
(``--blackbox-out`` / the automatic exit-2 emission):

    python tools/check_blackbox.py dump.json [...]

A raw dump passes when every journey in the retained window is
complete (submit -> terminal result, explanatory hops on typed
failures), every fault chains to its consequence, and every recorded
replica death is covered by a restart / counted failure / deliberate
breaker withholding.  The window-level ``dropped`` honesty counter is
reported but not failed — a long-lived ring legitimately evicts; the
EMBEDDED report slices are the ones that must be gap-free.

The validated contract (docs/OBSERVABILITY.md):

  * the embedded black-box slice is **gap-free** (``dropped == 0`` — an
    overflowed ring cannot prove reconstruction);
  * every request that entered the window is **reconstructible from the
    dump alone**: its journey starts at ``submit`` and ends at a
    terminal ``result`` (a submitted-never-resolved journey is the
    silent-loss signature);
  * every **typed failure explains itself**: its journey carries at
    least one explanatory hop (shed / requeue / reject /
    breaker_fast_fail / deadline / batch_failure / fault / retry) —
    a typed error with no causal trail is a reconstruction gap;
  * every **fault has its consequence**: each ``fault_injected`` event
    is followed (by recorder ``seq``) by the recovery-chain event its
    point promises (a kill by a death, a death by a restart or a
    deliberate withholding, an execute fault by a retry, ...).
"""

from __future__ import annotations

#: Journey hops that explain a typed failure (mirrors
#: ``tpu_jordan.obs.journey.EXPLANATORY_HOPS`` — duplicated here so the
#: checkers never import the package; ``tests/test_journey.py`` pins
#: the two sets equal).
EXPLANATORY_HOPS = frozenset({
    "shed", "requeue", "reject", "breaker_fast_fail",
    "deadline", "batch_failure", "fault", "retry",
})

#: fault point -> the event kinds that prove its causal consequence
#: (any one, later in seq order).  ``retry``/``batch_failure``/
#: ``deadline`` journey hops are folded in as pseudo-kinds
#: ``journey:<event>``.
FAULT_CONSEQUENCES = {
    "replica_kill": ("replica_death",),
    "compile": ("retry", "journey:batch_failure"),
    "execute": ("retry", "journey:batch_failure"),
    "dispatch": ("retry", "journey:batch_failure"),
    "result_corrupt_nan": ("retry", "recovery_rung",
                           "journey:batch_failure"),
    "measure": ("retry",),
    "plan_cache_write": ("plan_cache_write_failure",),
}


def journeys(events) -> dict:
    """Group the slice's ``journey`` events by request id (insertion
    order preserved — the recorder's seq order)."""
    out: dict = {}
    for e in events:
        if e.get("kind") != "journey" or "request_id" not in e:
            continue
        out.setdefault(str(e["request_id"]), []).append(e)
    return out


def ledger(events) -> dict:
    """Recompute the outcome ledger from raw journey events — the
    checker-side twin of ``obs.journey.outcome_ledger``, used to
    RECONCILE against the ledger a report embeds (any disagreement is
    drift between what the demo claims and what its own black box can
    prove)."""
    ok = err = 0
    typed: dict = {}
    gaps = []
    for rid, evs in journeys(events).items():
        terminal = next((e for e in reversed(evs)
                         if e.get("event") == "result"), None)
        if terminal is None:
            gaps.append(rid)
        elif terminal.get("outcome") == "ok":
            ok += 1
        else:
            err += 1
            name = str(terminal.get("error", "UnknownError"))
            typed[name] = typed.get(name, 0) + 1
    return {"submitted": ok + err + len(gaps), "ok": ok, "error": err,
            "typed_errors": dict(sorted(typed.items())),
            "gaps": sorted(gaps)}


def check_journeys(blackbox: dict, requests: int | None = None
                   ) -> list[str]:
    """The reconstruction rules over an embedded black-box slice;
    returns violations (empty = every request reconstructible)."""
    errs: list[str] = []
    if not isinstance(blackbox, dict) or "events" not in blackbox:
        return ["no black-box slice embedded in the report "
                "(reconstruction cannot be proven)"]
    if blackbox.get("dropped", 1) != 0:
        errs.append(f"black-box ring dropped "
                    f"{blackbox.get('dropped')} event(s) inside the "
                    f"window — reconstruction has gaps")
    events = blackbox["events"]
    js = journeys(events)
    if requests is not None and len(js) != requests:
        errs.append(f"{len(js)} request journeys in the black box but "
                    f"{requests} requests submitted — "
                    f"{requests - len(js)} request(s) left no trail")
    for rid, evs in js.items():
        names = [e.get("event") for e in evs]
        seqs = [e.get("seq", 0) for e in evs]
        if names[:1] != ["submit"]:
            errs.append(f"journey {rid} does not start at submit "
                        f"(events: {names[:4]}...)")
        if any(b <= a for a, b in zip(seqs, seqs[1:])):
            errs.append(f"journey {rid} events out of seq order")
        terminal = next((e for e in reversed(evs)
                         if e.get("event") == "result"), None)
        if terminal is None:
            errs.append(f"journey {rid} has no terminal result — "
                        f"submitted but never resolved (silent loss)")
            continue
        if terminal is not evs[-1]:
            errs.append(f"journey {rid} has events after its terminal "
                        f"result")
        if (terminal.get("outcome") != "ok"
                and not EXPLANATORY_HOPS.intersection(names)):
            errs.append(
                f"journey {rid} failed typed "
                f"({terminal.get('error')}) with NO explanatory hop "
                f"(one of {sorted(EXPLANATORY_HOPS)}) — a causal gap")
    return errs


def check_fault_chains(events) -> list[str]:
    """Every ``fault_injected`` event must be followed, in seq order,
    by the consequence its point promises — the fault → recovery causal
    chain, validated event-by-event instead of by counter deltas."""
    errs: list[str] = []
    later_kinds: list[tuple[int, str]] = []
    for e in events:
        kind = e.get("kind")
        if kind == "journey":
            later_kinds.append((e.get("seq", 0),
                                f"journey:{e.get('event')}"))
        elif kind is not None:
            later_kinds.append((e.get("seq", 0), kind))
    for e in events:
        if e.get("kind") != "fault_injected":
            continue
        point = e.get("point")
        wanted = FAULT_CONSEQUENCES.get(point)
        if wanted is None:
            continue                 # an unmapped point is not a gap
        seq = e.get("seq", 0)
        if not any(s > seq and k in wanted for s, k in later_kinds):
            errs.append(
                f"injected fault {point!r} (seq {seq}) has no recorded "
                f"consequence ({' | '.join(wanted)}) later in the "
                f"black box — the causal chain is broken")
    return errs


def check_death_coverage(events) -> list[str]:
    """Every recorded replica death must be followed by a restart, a
    counted restart failure, or a deliberate breaker withholding for
    its slot — a death with none is an abandoned slot the ledger
    could only see as degraded throughput."""
    errs: list[str] = []
    deaths = [e for e in events if e.get("kind") == "replica_death"]
    for d in deaths:
        slot, seq = d.get("slot"), d.get("seq", 0)
        covered = any(
            e.get("kind") in ("restart", "restart_failure",
                              "restart_withheld")
            and e.get("slot") == slot and e.get("seq", 0) > seq
            for e in events)
        if not covered:
            errs.append(f"replica death at slot {slot} (seq {seq}) has "
                        f"no restart / restart_failure / "
                        f"restart_withheld event after it — the "
                        f"supervision chain is broken")
    return errs


def reconcile_ledgers(report_ledger: dict, events) -> list[str]:
    """The embedded journey ledger must equal the one recomputed from
    the embedded events (same helper discipline, checked both sides)."""
    mine = ledger(events)
    errs = []
    for key in ("submitted", "ok", "error", "typed_errors", "gaps"):
        if report_ledger.get(key) != mine[key]:
            errs.append(f"journey_ledger[{key!r}] = "
                        f"{report_ledger.get(key)!r} but the embedded "
                        f"black box proves {mine[key]!r} — ledger "
                        f"drift")
    return errs


def check_dump(dump: dict) -> tuple[list[str], list[str]]:
    """Validate a RAW recorder dump; returns (violations, warnings).
    Eviction honesty: when ``dropped`` > 0 the ring legitimately lost
    the window's head, so journey-completeness rules (which would flag
    truncated journeys as gaps) are skipped with a warning — fault
    chains and death coverage still run over the retained window."""
    if dump.get("metric") != "blackbox":
        return ([f"not a blackbox dump (metric="
                 f"{dump.get('metric')!r})"], [])
    events = dump.get("events")
    if not isinstance(events, list):
        return (["dump has no events list"], [])
    warnings: list[str] = []
    errs: list[str] = []
    if dump.get("dropped", 0) > 0:
        warnings.append(f"ring evicted {dump['dropped']} event(s) "
                        f"before the retained window — journey "
                        f"completeness not checkable, validating "
                        f"fault chains over the retained window only")
    else:
        errs += check_journeys({"dropped": 0, "events": events})
    errs += check_fault_chains(events)
    errs += check_death_coverage(events)
    return errs, warnings


def main(argv) -> int:
    import json
    import sys

    if not argv:
        print("usage: check_blackbox.py dump.json [...]",
              file=sys.stderr)
        return 1
    rc = 0
    for path in argv:
        try:
            if path == "-":
                dump = json.load(sys.stdin)
            else:
                with open(path) as f:
                    dump = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable dump ({e})",
                  file=sys.stderr)
            rc = 1
            continue
        errs, warnings = check_dump(dump)
        for w in warnings:
            print(f"WARN {path}: {w}", file=sys.stderr)
        if errs:
            rc = 1
            for e in errs:
                print(f"FAIL {path}: {e}", file=sys.stderr)
        else:
            js = journeys(dump.get("events", []))
            led = ledger(dump.get("events", []))
            print(f"OK {path}: {dump.get('retained')} events retained "
                  f"({dump.get('recorded_total')} recorded, "
                  f"{dump.get('dropped')} dropped), {len(js)} "
                  f"journey(s) reconstructed ({led['ok']} ok, "
                  f"{led['error']} typed), causal chains intact")
    return rc


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
