#!/usr/bin/env python
"""Validate a ``--fleet-demo`` report (ISSUE 7 CI satellite) — the
fleet analogue of ``check_chaos.py``.

Usage: ``python tools/check_fleet.py report.json [...]`` (or ``-`` for
stdin).  No jax import — this is the ``make fleet-demo`` gate and runs
anywhere.  Exit codes: 0 = valid, 1 = bound/structure violations,
2 = SILENT LOSS (a response that neither bit-matched the fault-free
replay nor carried a typed error, or a request the ledger lost — the
alarm that must never be downgraded).

What a valid fleet report must prove (docs/FLEET.md):

  * chaos actually happened — >= 1 seeded ``replica_kill`` fired,
    every death was supervised (deaths >= kills, restarts cover the
    deaths that a closed restart breaker did not deliberately strand);
  * the warm rolling restart was FREE — ``tpu_jordan_compiles_total``
    delta == 0 after warmup across the whole chaos pass (replacement
    replicas compiled nothing: shared executor store) and zero
    plan-cache measurements (read-only pre-tuned plans);
  * zero silent errors — every chaos response bit-matched the
    fault-free replay or carried a typed error, the request ledger
    adds up exactly (submitted == resolved, outstanding == 0);
  * every request is RECONSTRUCTIBLE from the embedded black-box slice
    alone (ISSUE 8): the slice is gap-free, every submitted request's
    journey reaches a terminal result, every typed failure carries its
    shed/requeue/retry causal hops, every injected kill chains to a
    death and every death to a restart (or a deliberate breaker
    withholding), and the embedded journey ledger equals the one
    recomputed from the raw events — any break is the exit-2 class;
  * throughput held its bound — ``scaling_x >= scaling_floor`` (the
    floor is explicit in the report; >= 0.5 so it cannot be vacuous)
    at a bounded p99 (``fleet_p99_ms <= p99_bound_ms``), chaos p99
    included: a kill mid-stream must not wedge latency.
"""

from __future__ import annotations

import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)
import check_blackbox as _blackbox  # noqa: E402  (sibling, jax-free)

#: The floor below which a scaling bound proves nothing at all: a
#: fleet that HALVES throughput is broken whatever the hardware.
MIN_HONEST_SCALING_FLOOR = 0.5


def check(report: dict) -> tuple[list[str], list[str]]:
    """Return (violations, silent_loss_violations); both empty = valid."""
    errs: list[str] = []
    silent: list[str] = []
    if report.get("metric") != "fleet_demo":
        return ([f"not a fleet_demo report (metric="
                 f"{report.get('metric')!r})"], [])

    chaos = report.get("chaos", {})
    ledger = report.get("ledger", {})
    thr = report.get("throughput", {})
    pc = report.get("plan_cache", {})

    # ---- the kill ledger -------------------------------------------
    kills = chaos.get("kills_injected", 0)
    deaths = chaos.get("deaths", 0)
    restarts = chaos.get("restarts", 0)
    if kills <= 0:
        errs.append("no replica_kill injected — the chaos run was "
                    "vacuous")
    if deaths < kills:
        errs.append(f"{kills} kills injected but only {deaths} replica "
                    f"deaths recorded — a kill was swallowed")
    if restarts < 1:
        errs.append("no supervisor restart happened — the warm "
                    "rolling-restart path was never exercised")
    covered = (restarts + chaos.get("restart_failures", 0)
               + chaos.get("stranded_by_breaker", 0))
    if covered < deaths:
        errs.append(f"{deaths} deaths but only {restarts} restarts + "
                    f"{chaos.get('restart_failures', 0)} counted "
                    f"restart failures + "
                    f"{chaos.get('stranded_by_breaker', 0)} breaker-"
                    f"stranded slots — a dead slot was silently "
                    f"abandoned")

    # ---- the zero-compile / zero-measurement warm-restart pin ------
    if chaos.get("compiles_delta_after_warmup", 1) != 0:
        errs.append(f"replacement replicas compiled "
                    f"{chaos.get('compiles_delta_after_warmup')} "
                    f"executable(s) — the warm rolling restart was not "
                    f"free (shared-store pin broken)")
    if pc.get("measurements", 1) != 0:
        errs.append(f"{pc.get('measurements')} plan-cache "
                    f"measurement(s) during serving — the read-only "
                    f"pre-tuned cache pin broke")
    if not pc.get("read_only", False):
        errs.append("fleet plan cache was not opened read-only")

    # ---- zero silent errors (the exit-2 class) ---------------------
    requests = report.get("requests", 0)
    matched = report.get("matched_bitwise", 0)
    typed = sum(report.get("typed_errors", {}).values())
    mism = report.get("mismatches", [{"missing": True}])
    if mism:
        silent.append(f"{len(mism)} response(s) diverged from the "
                      f"fault-free replay without a typed error: "
                      f"{mism[:3]}")
    if matched + typed + len(mism) != requests:
        silent.append(f"response ledger does not add up: {matched} "
                      f"matched + {typed} typed + {len(mism)} "
                      f"mismatched != {requests} requests")
    if ledger.get("outstanding", 1) != 0:
        silent.append(f"{ledger.get('outstanding')} request(s) "
                      f"outstanding after the drain — lost in flight")
    if (ledger.get("resolved_ok", -1) + ledger.get("resolved_error", -1)
            != ledger.get("submitted", 0)):
        silent.append(f"fleet ledger does not add up: {ledger}")
    if report.get("silent_loss", True):
        silent.append("silent_loss flagged by the demo itself")

    # ---- black-box reconstruction (ISSUE 8, the exit-2 class) ------
    # Every request of the chaos pass must be reconstructible from the
    # embedded flight-recorder slice ALONE: gap-free ring, a complete
    # journey per request, explanatory hops on every typed failure,
    # fault -> death -> restart causal chains, and a journey ledger
    # that matches the raw events.
    bb = report.get("blackbox")
    silent += _blackbox.check_journeys(bb, requests=requests)
    if isinstance(bb, dict) and "events" in bb:
        events = bb["events"]
        silent += _blackbox.check_fault_chains(events)
        silent += _blackbox.check_death_coverage(events)
        silent += _blackbox.reconcile_ledgers(
            report.get("journey_ledger", {}), events)
        jl = _blackbox.ledger(events)
        if jl["error"] != typed:
            silent.append(f"black box proves {jl['error']} typed "
                          f"failure(s) but the response ledger counted "
                          f"{typed}")

    # ---- throughput + latency bounds -------------------------------
    floor = thr.get("scaling_floor", 0)
    if floor < MIN_HONEST_SCALING_FLOOR:
        errs.append(f"scaling_floor {floor} < "
                    f"{MIN_HONEST_SCALING_FLOOR} — the bound is "
                    f"vacuous")
    if thr.get("scaling_x", 0) < floor:
        errs.append(f"throughput scaling {thr.get('scaling_x')}x "
                    f"below the report's own floor {floor}x")
    bound = thr.get("p99_bound_ms", 0)
    if bound <= 0:
        errs.append("p99_bound_ms missing/zero — the latency bound is "
                    "vacuous")
    for key in ("fleet_p99_ms", "chaos_p99_ms"):
        if thr.get(key, bound + 1) > bound:
            errs.append(f"{key} {thr.get(key)} exceeds the bound "
                        f"{bound} ms")
    return errs, silent


def main(argv) -> int:
    if not argv:
        print("usage: check_fleet.py report.json [...]", file=sys.stderr)
        return 1
    rc = 0
    for path in argv:
        try:
            if path == "-":
                report = json.load(sys.stdin)
            else:
                with open(path) as f:
                    report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable report ({e})", file=sys.stderr)
            rc = max(rc, 1)
            continue
        errs, silent = check(report)
        for e in silent:
            print(f"SILENT-LOSS {path}: {e}", file=sys.stderr)
        for e in errs:
            print(f"FAIL {path}: {e}", file=sys.stderr)
        if silent:
            rc = 2
        elif errs:
            rc = max(rc, 1)
        else:
            chaos = report["chaos"]
            thr = report["throughput"]
            nj = len(_blackbox.journeys(
                report.get("blackbox", {}).get("events", [])))
            print(f"OK {path}: {report['requests']} requests x "
                  f"{report['replicas']} replicas, "
                  f"{chaos['kills_injected']} kill(s) -> "
                  f"{chaos['restarts']:.0f} warm restart(s) "
                  f"({chaos['reroutes']:.0f} re-queued), 0 compiles "
                  f"after warmup, {report['matched_bitwise']} "
                  f"bit-matched the fault-free replay, scaling "
                  f"{thr['scaling_x']}x >= {thr['scaling_floor']}x, "
                  f"{nj}/{report['requests']} journeys reconstructed "
                  f"from the black box, 0 silent")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
