#!/usr/bin/env python
"""Validate an ``--autoscale-demo`` report (ISSUE 18 satellite) — the
autoscaler analogue of ``check_fleet.py``.

Usage: ``python tools/check_autoscale.py report.json [...]`` (or ``-``
for stdin).  No jax import — this is the ``make autoscale-demo`` gate
and runs anywhere.  Exit codes: 0 = valid, 1 = bound/structure
violations, 2 = a SILENT P99 BREACH or an UNEXPLAINED SCALE ACTION —
the alarm class that must never be downgraded.

The checker's job is re-derivation, not trust: every scale/drain/
pre-shed decision in the report must be re-derivable from the burn
evidence the autoscaler recorded alongside it:

  * every ``scale_up`` (and every withheld one) carries >= 1 paging
    objective whose window pairs ACTUALLY page by the recorded numbers
    (long burn > threshold AND short burn > threshold, with each burn
    equal to error_rate / error_budget) — an action whose evidence
    does not re-derive is exit 2;
  * every ``scale_withheld`` shows the ledger at/over its budget
    (``live_bytes >= scale_budget_bytes`` — the capacity veto held);
  * every ``drain`` shows ``idle_s >= idle_after_s``, lands at/above
    the floor, and its tick saw NO risk signal (never drain into a
    burn);
  * ``pre_shed_on`` carries paging or p99-risk evidence (each p99-risk
    entry re-derives: p99_ms >= frac x target); ``pre_shed_off``
    carries neither;
  * any tick that saw risk while pre-shed stayed OFF and no capacity
    action answered it is the silent-breach class (exit 2), and the
    report's own ``silent_p99_breach`` flag must agree with the
    re-derivation;
  * the in-memory action list, the flight-recorder ``autoscale``
    events, and the ``tpu_jordan_autoscale_actions_total`` deltas must
    all tell the same story, and the counted ``shed{reason=pre_shed}``
    must equal the journey-hopped pre-shed rejections in the black-box
    slice — typed, counted, journey-hopped, or it didn't happen.

Vacuity guards (exit 1): the demo must actually show a scale-up, a
drain back to the floor, a pre-shed engage/release cycle, deadline
burn in the burst, and a clean recovery wave.
"""

from __future__ import annotations

import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)

#: Relative tolerance when re-deriving burn = error_rate/budget from a
#: report rounded for JSON (the demo rounds to 6 decimals).
REDERIVE_RTOL = 1e-3

#: The capacity-action kinds that align 1:1 with non-null tick actions
#: (pre-shed flips are flag reconciliations, not capacity steps).
CAPACITY_ACTIONS = ("scale_up", "scale_withheld", "drain")


def _pages(window: dict) -> bool:
    """Re-derive one window pair's page decision from its numbers."""
    thr = window.get("threshold", float("inf"))
    try:
        return (window["long"]["burn_rate"] > thr
                and window["short"]["burn_rate"] > thr)
    except (KeyError, TypeError):
        return False


def _burn_consistent(window: dict, budget: float) -> bool:
    """Each recorded burn must equal error_rate / error_budget (the
    definition, not a new number the report could invent)."""
    if not budget or budget <= 0:
        return False
    for half in ("long", "short"):
        w = window.get(half)
        if not isinstance(w, dict):
            return False
        expect = w.get("error_rate", 0.0) / budget
        got = w.get("burn_rate")
        if got is None or abs(got - expect) > REDERIVE_RTOL * max(
                1.0, abs(expect)):
            return False
    return True


def _check_paging_evidence(tag: str, paging: list) -> list[str]:
    """The exit-2 re-derivation for one action's paging evidence."""
    bad = []
    if not paging:
        bad.append(f"{tag}: no paging objective in evidence — the "
                   f"action is unexplained")
        return bad
    for obj in paging:
        wins = obj.get("windows", [])
        budget = obj.get("error_budget", 0.0)
        if not wins:
            bad.append(f"{tag}: objective {obj.get('name')!r} pages "
                       f"with zero window pairs")
        for w in wins:
            if not _pages(w):
                bad.append(
                    f"{tag}: objective {obj.get('name')!r} window "
                    f"{w.get('threshold')}x does not actually page by "
                    f"its own numbers (long "
                    f"{w.get('long', {}).get('burn_rate')}, short "
                    f"{w.get('short', {}).get('burn_rate')})")
            if not _burn_consistent(w, budget):
                bad.append(
                    f"{tag}: objective {obj.get('name')!r} burn rates "
                    f"are not error_rate/error_budget "
                    f"(budget {budget}) — doctored evidence")
    return bad


def check(report: dict) -> tuple[list[str], list[str]]:
    """Return (violations, alarm_violations); both empty = valid."""
    errs: list[str] = []
    silent: list[str] = []
    if report.get("metric") != "autoscale_demo":
        return ([f"not an autoscale_demo report (metric="
                 f"{report.get('metric')!r})"], [])

    cfg = report.get("config", {})
    floor = report.get("floor", 1)
    ceiling = report.get("ceiling", floor)
    idle_after_s = cfg.get("idle_after_s", float("inf"))
    frac = cfg.get("preshed_p99_frac", 1.0)
    actions = report.get("actions", [])
    ticks = report.get("ticks", [])
    phases = report.get("phases", {})

    # ---- vacuity guards (the demo must demonstrate the loop) -------
    kinds = [a.get("action") for a in actions]
    for needed in ("scale_up", "drain", "pre_shed_on", "pre_shed_off"):
        if needed not in kinds:
            errs.append(f"no {needed} action — the demo never "
                        f"exercised that leg of the control loop")
    burst_waves = phases.get("burst", {}).get("waves", [])
    if not any(w.get("typed_errors", {}).get("DeadlineExceededError")
               for w in burst_waves):
        errs.append("no DeadlineExceededError in the burst — the burn "
                    "source never fired, the paging was not this "
                    "demo's doing")
    recovery = phases.get("recovery", {})
    if recovery.get("ok", 0) < 1 or recovery.get("typed_errors"):
        errs.append(f"recovery wave did not serve cleanly: {recovery}")
    traj = report.get("ready_trajectory", [])
    if traj and (max(traj) > ceiling or min(traj) < floor):
        errs.append(f"ready trajectory {traj} escaped "
                    f"[{floor}, {ceiling}]")
    if traj and traj[-1] != floor:
        errs.append(f"fleet ended at {traj[-1]} replicas, not the "
                    f"floor {floor} — the drain never completed")

    # ---- per-action re-derivation (the exit-2 class) ---------------
    for i, a in enumerate(actions):
        kind = a.get("action")
        tag = f"action[{i}] {kind}"
        ev = a.get("evidence", {})
        before, after = a.get("ready_before"), a.get("ready_after")
        if kind == "scale_up":
            silent += _check_paging_evidence(tag, ev.get("paging", []))
            if after != before + 1 or after > ceiling:
                silent.append(f"{tag}: ready {before} -> {after} is "
                              f"not one step up within ceiling "
                              f"{ceiling}")
        elif kind == "scale_withheld":
            silent += _check_paging_evidence(tag, ev.get("paging", []))
            budget = ev.get("scale_budget_bytes")
            if budget is None or ev.get("live_bytes", -1) < budget:
                silent.append(f"{tag}: withheld without the ledger at "
                              f"its budget (live {ev.get('live_bytes')}"
                              f" vs budget {budget})")
            if after != before:
                silent.append(f"{tag}: a WITHHELD action changed ready "
                              f"{before} -> {after}")
        elif kind == "drain":
            if ev.get("idle_s", -1.0) < idle_after_s:
                silent.append(f"{tag}: drained at idle_s="
                              f"{ev.get('idle_s')} < idle_after_s="
                              f"{idle_after_s} — unexplained drain")
            if after != before - 1 or after < floor:
                silent.append(f"{tag}: ready {before} -> {after} is "
                              f"not one step down at/above floor "
                              f"{floor}")
        elif kind == "pre_shed_on":
            p99 = ev.get("p99_risk", [])
            if not ev.get("paging") and not p99:
                silent.append(f"{tag}: pre-shed engaged with neither "
                              f"paging nor p99 risk in evidence")
            for r in p99:
                if r.get("p99_ms", -1) < frac * r.get(
                        "p99_target_ms", float("inf")):
                    silent.append(f"{tag}: p99 risk entry does not "
                                  f"re-derive ({r})")
        elif kind == "pre_shed_off":
            if ev.get("paging") or ev.get("p99_risk"):
                silent.append(f"{tag}: pre-shed released while "
                              f"evidence still shows risk: {ev}")
        elif kind == "pre_shed_vetoed":
            # ISSUE 19: a withheld p99-driven shed must carry BOTH the
            # p99 risk it answered and the skew-judge verdict that
            # vetoed it (a suspected straggler replica with a spread
            # above threshold).
            veto = ev.get("skew_veto") or {}
            if not ev.get("p99_risk"):
                silent.append(f"{tag}: vetoed with no p99 risk in "
                              f"evidence — nothing was withheld")
            if veto.get("replica") is None or not isinstance(
                    veto.get("spread"), (int, float)) \
                    or veto.get("spread", 0) <= veto.get(
                        "threshold", float("inf")):
                silent.append(f"{tag}: veto evidence does not "
                              f"re-derive (needs a suspected replica "
                              f"and spread > threshold): {veto}")
            if after != before:
                silent.append(f"{tag}: a VETOED shed changed ready "
                              f"{before} -> {after}")
        else:
            silent.append(f"{tag}: unknown action kind")

    # ---- tick/action alignment: drains must not answer a burn ------
    tick_actions = [t for t in ticks if t.get("action")]
    cap_actions = [a for a in actions
                   if a.get("action") in CAPACITY_ACTIONS]
    if [t["action"] for t in tick_actions] != [a["action"]
                                               for a in cap_actions]:
        silent.append(
            f"tick action trail {[t['action'] for t in tick_actions]} "
            f"!= recorded capacity actions "
            f"{[a['action'] for a in cap_actions]}")
    else:
        for t in tick_actions:
            if t["action"] == "drain" and (t.get("paging")
                                           or t.get("p99_risk")):
                silent.append(f"drain at t={t.get('t')} while the "
                              f"tick itself saw risk signals "
                              f"(paging={t.get('paging')}, "
                              f"p99_risk={t.get('p99_risk')})")

    # ---- the silent-breach re-derivation (the namesake alarm) ------
    # A skew-vetoed tick (ISSUE 19) is the one sanctioned exception:
    # the fleet-skew judge attributed the p99 risk to one suspected
    # straggler replica, and the tick carries the veto evidence —
    # shedding the whole fleet would have been the wrong actuator.
    rederived = any(
        (t.get("paging") or t.get("p99_risk"))
        and not t.get("pre_shed")
        and t.get("action") not in ("scale_up", "scale_withheld")
        and not t.get("skew_veto", False)
        for t in ticks)
    if rederived:
        silent.append("a tick saw risk signals with pre-shed OFF and "
                      "no capacity action — SILENT P99 BREACH")
    if bool(report.get("silent_p99_breach", True)) != rederived:
        silent.append(f"report's silent_p99_breach="
                      f"{report.get('silent_p99_breach')} disagrees "
                      f"with the tick re-derivation ({rederived})")

    # ---- black-box / counter reconciliation ------------------------
    bb = report.get("blackbox")
    if not isinstance(bb, dict) or "events" not in bb:
        silent.append("no black-box slice embedded — the decisions "
                      "are unreconstructible")
    else:
        events = bb["events"]
        bb_actions = [e for e in events if e.get("kind") == "autoscale"]
        if [e.get("action") for e in bb_actions] != kinds:
            silent.append(
                f"flight-recorder autoscale trail "
                f"{[e.get('action') for e in bb_actions]} != report "
                f"actions {kinds} — the two stories diverge")
        preshed_hops = sum(
            1 for e in events
            if e.get("kind") == "journey" and e.get("event") == "shed"
            and e.get("reason") == "pre_shed")
        counted = report.get("pre_shed_count", -1)
        if counted != preshed_hops:
            silent.append(f"shed{{reason=pre_shed}} counted {counted} "
                          f"but the black box journey-hopped "
                          f"{preshed_hops} — a shed went uncounted "
                          f"or unhopped")
        if kinds.count("pre_shed_on") > 0 and preshed_hops == 0:
            errs.append("pre-shed engaged but shed zero requests — "
                        "the front door never exercised the flag")

    # ---- the fleet ledger must still add up ------------------------
    ledger = report.get("ledger", {})
    if ledger.get("outstanding", 1) != 0:
        silent.append(f"{ledger.get('outstanding')} request(s) "
                      f"outstanding after the drain — lost in flight")
    if (ledger.get("resolved_ok", -1) + ledger.get("resolved_error", -1)
            != ledger.get("submitted", 0)):
        silent.append(f"fleet ledger does not add up: {ledger}")
    return errs, silent


def main(argv) -> int:
    if not argv:
        print("usage: check_autoscale.py report.json [...]",
              file=sys.stderr)
        return 1
    rc = 0
    for path in argv:
        try:
            if path == "-":
                report = json.load(sys.stdin)
            else:
                with open(path) as f:
                    report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable report ({e})",
                  file=sys.stderr)
            rc = max(rc, 1)
            continue
        errs, silent = check(report)
        for e in silent:
            print(f"ALARM {path}: {e}", file=sys.stderr)
        for e in errs:
            print(f"FAIL {path}: {e}", file=sys.stderr)
        if silent:
            rc = 2
        elif errs:
            rc = max(rc, 1)
        else:
            kinds = report.get("actions_by_kind", {})
            print(f"OK {path}: {len(report.get('ticks', []))} ticks, "
                  f"{kinds.get('scale_up', 0)} scale-up(s) + "
                  f"{kinds.get('drain', 0)} drain(s) + "
                  f"{kinds.get('scale_withheld', 0)} withheld, "
                  f"pre-shed cycle "
                  f"{kinds.get('pre_shed_on', 0)}/"
                  f"{kinds.get('pre_shed_off', 0)}, "
                  f"{report.get('pre_shed_count', 0)} typed pre-sheds, "
                  f"every action re-derived from its burn evidence, "
                  f"0 silent breaches")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
