#!/usr/bin/env python
"""Validate a ``--chaos-demo`` report (ISSUE 5 CI satellite).

Usage: ``python tools/check_chaos.py report.json [...]`` (or ``-`` for
stdin).  No jax import — this is the ``make chaos-demo`` gate and runs
anywhere.

What a valid chaos report must prove (docs/RESILIENCE.md):

  * chaos actually happened — ``injected`` > 0, and every fault KIND
    the demo promises (compile, execute, result_corrupt_nan,
    plan_cache_write) actually fired;
  * nothing silent — every injected fault is accounted for as retried,
    degraded, or typed-error (``unaccounted == 0``), and
    ``silent_corruption`` is false;
  * the replay pin held — zero ``mismatches``: every response either
    bit-matched the fault-free run of the same request or carried a
    typed error;
  * the response ledger adds up — matched + typed errors == requests;
  * every request of the chaos pass is reconstructible from the
    embedded black-box slice alone (ISSUE 8): a gap-free ring, a
    complete journey per request, and every injected fault chained —
    event by event, not by counter deltas — to the retry / recovery
    rung / degradation it caused.
"""

from __future__ import annotations

import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)
import check_blackbox as _blackbox  # noqa: E402  (sibling, jax-free)

REQUIRED_POINTS = ("compile", "execute", "result_corrupt_nan",
                   "plan_cache_write")


def check(report: dict) -> list[str]:
    """Return a list of violations (empty = valid)."""
    errs = []
    if report.get("metric") != "chaos_demo":
        return [f"not a chaos_demo report (metric="
                f"{report.get('metric')!r})"]
    acct = report.get("accounting", {})
    faults = report.get("faults", {})
    by_point = faults.get("injected_by_point", {})

    if acct.get("injected", 0) <= 0:
        errs.append("no faults injected — the chaos run was vacuous")
    for point in REQUIRED_POINTS:
        if by_point.get(point, 0) <= 0:
            errs.append(f"required fault point {point!r} never fired "
                        f"(schedule horizon vs actual call count?)")
    # Fault-event units: injected == retried + degraded + terminal
    # batch failures for an honest run.  A POSITIVE remainder is a
    # silently absorbed fault; a negative one means a real (uninjected)
    # transient also fired — noisy, but nothing was swallowed.
    if acct.get("unaccounted", 1) > 0:
        errs.append(f"{acct.get('unaccounted')} injected fault(s) "
                    f"unaccounted (not retried, degraded, or a counted "
                    f"terminal failure) — silent fault absorption")
    if report.get("silent_corruption", True):
        errs.append("silent_corruption flagged by the demo itself")
    mism = report.get("mismatches", [{"missing": True}])
    if mism:
        errs.append(f"{len(mism)} response(s) diverged from the "
                    f"fault-free replay without a typed error: "
                    f"{mism[:3]}")
    requests = report.get("requests", 0)
    matched = report.get("matched_bitwise", 0)
    typed = sum(report.get("typed_errors", {}).values())
    if matched + typed + len(mism) != requests:
        errs.append(f"response ledger does not add up: {matched} matched "
                    f"+ {typed} typed + {len(mism)} mismatched != "
                    f"{requests} requests")

    # ---- black-box reconstruction (ISSUE 8) ------------------------
    bb = report.get("blackbox")
    errs += _blackbox.check_journeys(bb, requests=requests)
    if isinstance(bb, dict) and "events" in bb:
        errs += _blackbox.check_fault_chains(bb["events"])
        errs += _blackbox.reconcile_ledgers(
            report.get("journey_ledger", {}), bb["events"])
    return errs


def main(argv) -> int:
    if not argv:
        print("usage: check_chaos.py report.json [...]", file=sys.stderr)
        return 2
    rc = 0
    for path in argv:
        try:
            if path == "-":
                report = json.load(sys.stdin)
            else:
                with open(path) as f:
                    report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable report ({e})", file=sys.stderr)
            rc = 1
            continue
        errs = check(report)
        if errs:
            rc = 1
            for e in errs:
                print(f"FAIL {path}: {e}", file=sys.stderr)
        else:
            acct = report["accounting"]
            print(f"OK {path}: {report['requests']} requests, "
                  f"{acct['injected']} faults injected "
                  f"({acct['retried']:.0f} retried, "
                  f"{acct['degraded']:.0f} degraded, "
                  f"{acct['terminal_failures']:.0f} terminal), "
                  f"{report['matched_bitwise']} bit-matched the "
                  f"fault-free replay, "
                  f"{len(_blackbox.journeys(report.get('blackbox', {}).get('events', [])))}"
                  f"/{report['requests']} journeys reconstructed, "
                  f"0 silent")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
