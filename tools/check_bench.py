#!/usr/bin/env python
"""Variance-aware BENCH trajectory regression sentinel (ISSUE 10
tentpole part 3).

Usage: ``python tools/check_bench.py BENCH_r01.json BENCH_r02.json ...``
(trajectory order = argument order; a shell glob sorts round files
correctly).  No jax import — this is the ``make bench-check`` gate and
runs anywhere.

The r04→r05 4096² dip burned a whole diagnosis round because nothing
watched the BENCH_r*.json trajectory — and the dip turned out to be
single-sample session-lottery noise (BASELINE.md).  This sentinel
generalizes the PR 6 dip guard from one hardcoded row to every
steady-state row of the trajectory, with the same variance discipline:

  * **steady-state only, never first-call** — compared rows are the
    ``*_gflops`` keys (slope-derived per-call rates on the cached
    executable) and the headline ``value``; ``first_call_compile_
    inclusive_s`` keys are never compared (a compile-time change is
    not an execution regression — the exact conflation PR 4 separated
    the rows to prevent);
  * **flag only what the rows' own spread cannot explain** — a
    shortfall beyond ``--tolerance-pct`` (default 10) against the best
    prior round is a regression ONLY when the latest row carries
    robust-capture stats (``spread_pct`` / ``variance_flag``) showing
    a quiet session (< ``--high-variance-pct``, default 10) on BOTH
    ends of the comparison.  A noisy session explains its own dip; a
    row WITHOUT spread stats (every pre-ISSUE-4 round — the diagnosed
    r04→r05 class) is UNKNOWN, not regressed: a single-sample capture
    cannot distinguish noise from regression, which is precisely why
    it must not page (backfill tolerance, ISSUE 10 satellite);
  * **rows compare like-for-like by key** — a config change renames
    its key (``m256`` vs ``m384``), so tuning migrations never diff
    against each other.

Environment fingerprints (``extra.env``: jax/jaxlib versions, device
kind, host cores — recorded by bench.py since ISSUE 10) are printed as
context; missing in old rounds = unknown, never a gate.

Exit taxonomy (the check_fleet/check_slo convention): 0 = trajectory
healthy (or nothing comparable), 1 = unreadable/unjudgeable input,
2 = an unexplained steady-state regression.
"""

from __future__ import annotations

import json
import re
import sys

TOLERANCE_PCT = 10.0        # shortfall below this is never flagged
HIGH_VARIANCE_PCT = 10.0    # spread at/above this explains any dip

_N_RE = re.compile(r"(\d{3,})")


def load_round(path: str) -> dict | None:
    """One BENCH_r*.json -> its bench row {"metric", "value", "extra"}
    or None when the round carries no parseable row (recorded rc != 0
    runs keep their file but have nothing to compare)."""
    with open(path) as f:
        doc = json.load(f)
    row = doc.get("parsed")
    if isinstance(row, dict) and "metric" in row and "value" in row:
        return row
    # Fallback: the last JSON line of the captured tail (the bench
    # prints exactly one).
    for line in reversed(doc.get("tail", "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict) and "metric" in row:
                return row
    return None


#: Accounting-class key suffixes (ISSUE 10/13/14): numbers that
#: describe WHAT the compiler, the capacity ledger, or the comm
#: observatory counted, not how fast the same execution ran —
#: ``*_xla_gflops`` (compiler flop recounts) and the ``*_bytes``
#: fields (``peak_hbm_bytes`` / ``resident_handle_bytes`` /
#: ``*_comm_bytes``: a jaxlib layout change, a dtype/bucket change, or
#: a collective-inventory change re-prices the same execution), plus
#: ``*_overlap_frac`` (ISSUE 16: the probe-ahead rows' modeled
#: probe-overlap headroom — a cost-model re-weighting re-prices the
#: same schedule), plus the ISSUE 19 work-observatory fields
#: ``*_work_skew`` / ``*_ragged_penalty`` (layout-exact imbalance
#: factor and padding penalty — a layout/block-size change re-prices
#: the same solve), plus the ISSUE 20 checkpoint field ``*_cadence``
#: (the superstep checkpoint interval the ``ckpt_overhead`` row ran
#: at: a cadence retune re-prices the same sweep — the overhead RATE
#: still pages, the knob that produced it never does).  Never compared
#: across rounds — the first-call separation principle applied to
#: accounting.
ACCOUNTING_SUFFIXES = ("_xla_gflops", "_bytes", "_overlap_frac",
                       "_work_skew", "_ragged_penalty", "_cadence")

#: Rate-class suffixes: slope-derived achieved rates on the cached
#: executable — the keys the sentinel compares and pages on.
#: ``*_gbps`` (ISSUE 14: achieved interconnect GB/s, the mesh
#: bandwidth sentinel) pages exactly like a ``*_gflops`` shortfall.
RATE_SUFFIXES = ("_gflops", "_gbps")


def is_accounting_key(key: str) -> bool:
    return key.endswith(ACCOUNTING_SUFFIXES)


def comparable_keys(row: dict) -> dict[str, float]:
    """The steady-state rate keys of one round: the headline ``value``
    (under its metric name) plus every numeric ``*_gflops``/``*_gbps``
    extra.  First-call keys never appear here by construction, and
    neither do the accounting-class rows (:func:`is_accounting_key`):
    the ``*_xla_gflops`` recounts and the ``*_bytes`` capacity/comm
    fields describe the same execution differently priced — a compiler
    or accounting change must not page as an execution regression (the
    same separation principle that keeps first-call times out)."""
    out = {}
    if isinstance(row.get("value"), (int, float)):
        out[str(row.get("metric", "value"))] = float(row["value"])
    for k, v in (row.get("extra") or {}).items():
        if (k.endswith(RATE_SUFFIXES) and not is_accounting_key(k)
                and isinstance(v, (int, float))):
            out[k] = float(v)
    return out


def _base_tokens(key: str) -> set[str]:
    """Digit-stripped ``_``-tokens: ``grouped2`` and ``grouped`` count
    as the SAME configuration token, so a grouped row's fuzzy lookup
    can never bind to the plain row's stats just because their numeric
    suffixes differ."""
    out = set()
    for tok in key.split("_"):
        base = tok.rstrip("0123456789")
        if base:
            out.add(base)
    return out


def _variance_context(key: str, row: dict) -> tuple[float | None, bool]:
    """(spread_pct, variance_flag) for one rate key, best effort:

      1. exact stem (``<key-minus-_gflops>_spread_pct``);
      2. the historical SUFFIX style (``spread_pct_<n>`` /
         ``variance_flag_<n>`` — how the 16384 scale row used to
         record its stats);
      3. the closest sibling among ``*_spread_pct`` keys carrying the
         same problem size, scored by shared digit-stripped tokens
         first (``grouped2`` matches ``grouped``, never the plain
         sibling), then longest common prefix.

    None = the round recorded no robust-capture stats for this row
    (pre-ISSUE-4 rounds) — unknown, not quiet."""
    extra = row.get("extra") or {}
    for suffix in RATE_SUFFIXES:
        if key.endswith(suffix):
            stem = key[:-len(suffix)]
            if f"{stem}_spread_pct" in extra:
                return (float(extra[f"{stem}_spread_pct"]),
                        bool(extra.get(f"{stem}_variance_flag")))
    m = _N_RE.search(key)
    n_tok = m.group(1) if m else None
    if n_tok is not None and f"spread_pct_{n_tok}" in extra:
        return (float(extra[f"spread_pct_{n_tok}"]),
                bool(extra.get(f"variance_flag_{n_tok}")))
    key_toks = _base_tokens(key)
    best = None
    for k2 in extra:
        if not k2.endswith("_spread_pct"):
            continue
        if n_tok is not None and n_tok not in k2:
            continue
        lcp = 0
        for a, b in zip(key, k2):
            if a != b:
                break
            lcp += 1
        toks = len(key_toks
                   & (_base_tokens(k2) - {"spread", "pct"}))
        score = (toks, lcp, -len(k2))
        if best is None or score > best[0]:
            best = (score, k2)
    if best is None:
        return None, False
    stem = best[1][:-len("_spread_pct")]
    return (float(extra[best[1]]),
            bool(extra.get(f"{stem}_variance_flag")))


def check_trajectory(rounds: list[tuple[str, dict]],
                     tolerance_pct: float = TOLERANCE_PCT,
                     high_variance_pct: float = HIGH_VARIANCE_PCT
                     ) -> tuple[list[str], list[str], list[str]]:
    """Compare the LAST round against the best prior value per key.
    Returns ``(regressions, warnings, notes)`` — regressions are the
    exit-2 class."""
    regressions, warnings, notes = [], [], []
    if len(rounds) < 2:
        notes.append(f"{len(rounds)} usable round(s) — nothing to "
                     f"compare yet")
        return regressions, warnings, notes
    latest_name, latest = rounds[-1]
    latest_keys = comparable_keys(latest)
    for key, val in sorted(latest_keys.items()):
        prior = [(name, comparable_keys(row)[key], row)
                 for name, row in rounds[:-1]
                 if key in comparable_keys(row)]
        if not prior:
            notes.append(f"{key}: new row in {latest_name} (no prior "
                         f"round to compare)")
            continue
        ref_name, ref, ref_row = max(prior, key=lambda p: p[1])
        if ref <= 0:
            continue
        shortfall = 100.0 * (1.0 - val / ref)
        ctx = (f"{key}: {val:.1f} vs best {ref:.1f} ({ref_name}), "
               f"{shortfall:+.1f}% shortfall")
        if shortfall <= tolerance_pct:
            continue
        spread, vflag = _variance_context(key, latest)
        ref_spread, ref_vflag = _variance_context(key, ref_row)
        if spread is None:
            warnings.append(
                f"{ctx} — UNKNOWN: the {latest_name} row carries no "
                f"spread stats (single-sample capture?), cannot "
                f"distinguish noise from regression")
        elif vflag or spread >= high_variance_pct:
            warnings.append(
                f"{ctx} — explained by the session's own variance "
                f"(spread {spread:.1f}%"
                f"{', variance_flag' if vflag else ''})")
        elif ref_spread is not None and (ref_vflag
                                         or ref_spread >= high_variance_pct):
            warnings.append(
                f"{ctx} — the {ref_name} high-water mark itself was "
                f"noisy (spread {ref_spread:.1f}%"
                f"{', variance_flag' if ref_vflag else ''})")
        else:
            regressions.append(
                f"{ctx} — spread {spread:.1f}% cannot explain it: "
                f"unexplained steady-state regression")
    env = (latest.get("extra") or {}).get("env")
    if isinstance(env, dict):
        notes.append(f"{latest_name} env: jax {env.get('jax')} / "
                     f"jaxlib {env.get('jaxlib')}, "
                     f"{env.get('device_kind')} x"
                     f"{env.get('device_count')}, "
                     f"{env.get('host_cpu_count')} host cores")
    else:
        notes.append(f"{latest_name} env: unknown (pre-ISSUE-10 row)")
    return regressions, warnings, notes


def main(argv) -> int:
    args = [a for a in argv if not a.startswith("--")]
    tol, hivar = TOLERANCE_PCT, HIGH_VARIANCE_PCT
    for a in argv:
        if a.startswith("--tolerance-pct="):
            tol = float(a.split("=", 1)[1])
        elif a.startswith("--high-variance-pct="):
            hivar = float(a.split("=", 1)[1])
        elif a.startswith("--"):
            print(f"unknown flag {a}", file=sys.stderr)
            return 1
    if not args:
        print("usage: check_bench.py BENCH_r01.json BENCH_r02.json ...",
              file=sys.stderr)
        return 1
    rounds = []
    for path in args:
        try:
            row = load_round(path)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable round ({e})", file=sys.stderr)
            return 1
        if row is None:
            print(f"note: {path} carries no bench row (failed run?) — "
                  f"skipped", file=sys.stderr)
            continue
        rounds.append((path, row))
    if not rounds:
        print("FAIL: no usable rounds", file=sys.stderr)
        return 1
    if args and rounds and rounds[-1][0] != args[-1]:
        print(f"FAIL: the latest round {args[-1]} is unjudgeable",
              file=sys.stderr)
        return 1
    regressions, warnings, notes = check_trajectory(rounds, tol, hivar)
    for msg in notes:
        print(f"note: {msg}")
    for msg in warnings:
        print(f"warn: {msg}")
    for msg in regressions:
        print(f"REGRESSION: {msg}", file=sys.stderr)
    if regressions:
        return 2
    n_keys = len(comparable_keys(rounds[-1][1]))
    print(f"OK: {len(rounds)} rounds, {n_keys} steady-state rows in "
          f"{rounds[-1][0]}, {len(warnings)} variance-explained/unknown "
          f"dips, 0 unexplained regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
