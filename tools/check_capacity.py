#!/usr/bin/env python
"""Validate a ``--capacity-demo`` report (ISSUE 13 CI satellite) — the
capacity observatory's reconciliation gate, the memory analogue of
``check_update.py``.

Usage: ``python tools/check_capacity.py report.json [...]`` (or ``-``
for stdin).  No jax import — this is the ``make capacity-demo`` gate
and runs anywhere.  Exit codes: 0 = valid, 1 = bound/structure
violations, 2 = UNMETERED RESIDENCY or a SILENT EVICTION (the alarm
that must never be downgraded): a metered byte class whose ledger does
not reconcile (``bytes_created != bytes_live + bytes_evicted`` —
resident bytes nothing accounts for), or a budget eviction with no
recorded ``capacity_eviction`` budget event (residency that vanished
without evidence).

What a valid capacity report must prove (docs/OBSERVABILITY.md):

  * **every metered class reconciles** — for each ``kind == metered``
    component in the ledger, bytes_created == bytes_live +
    bytes_evicted (the exit-2 class: unmetered residency);
  * **every budget eviction is explained** — the demo's budget-eviction
    count equals the ``capacity_eviction`` events with
    ``cause == budget`` in the embedded black-box slice, each carrying
    ``handle_id``/``nbytes``/``budget_bytes``, and each paired with a
    ``capacity_evict`` journey hop on the admitting request (exit 2:
    a silent evict-without-event);
  * **admission is typed** — the all-pinned over-budget resident invert
    raised ``CapacityExceededError`` (counted), and an update against
    the evicted handle was the typed ``UnknownHandleError`` — never a
    silently stale serve;
  * **the warm path is free with metering on** — ZERO compiles and
    ZERO plan-cache measurements on the whole capacity path after
    warmup (the PR 3/7 pins hold with the observatory on by default);
  * **lanes were projected before they were paid for** — a non-empty
    ``projected_lanes`` block with positive byte projections.
"""

from __future__ import annotations

import json
import sys


def check(report: dict) -> tuple[list[str], list[str]]:
    """Return (violations, unmetered_violations); both empty = valid."""
    errs: list[str] = []
    silent: list[str] = []
    if report.get("metric") != "capacity_demo":
        return ([f"not a capacity_demo report (metric="
                 f"{report.get('metric')!r})"], [])

    # ---- ledger reconciliation (the exit-2 class) -------------------
    components = (report.get("ledger") or {}).get("components") or {}
    if not components:
        silent.append("report carries no capacity ledger — nothing "
                      "accounts for resident bytes")
    metered = 0
    for name, doc in sorted(components.items()):
        if doc.get("kind") != "metered":
            continue
        metered += 1
        created = int(doc.get("bytes_created", -1))
        live = int(doc.get("bytes_live", 0))
        evicted = int(doc.get("bytes_evicted", 0))
        if created != live + evicted:
            silent.append(
                f"component {name!r} does not reconcile: "
                f"bytes_created {created} != bytes_live {live} + "
                f"bytes_evicted {evicted} — unmetered residency")
    for name in ("handles", "executor_lanes"):
        if name not in components:
            silent.append(f"byte class {name!r} missing from the "
                          f"ledger — its residency is unmetered")
    if report.get("unmetered_components"):
        silent.append(f"demo itself flagged unmetered components: "
                      f"{report['unmetered_components']}")

    # ---- every budget eviction explained (the exit-2 class) ---------
    budget_evictions = int(report.get("budget_evictions", 0))
    events = report.get("evictions") or []
    budget_events = [e for e in events if e.get("cause") == "budget"]
    if budget_evictions < 1:
        errs.append("no budget eviction happened — the actuation leg "
                    "was vacuous")
    if budget_evictions != len(budget_events):
        silent.append(
            f"{budget_evictions} budget eviction(s) but "
            f"{len(budget_events)} recorded capacity_eviction budget "
            f"event(s) — an eviction without evidence is a silent "
            f"evict")
    for e in budget_events:
        missing = [k for k in ("handle_id", "nbytes", "budget_bytes")
                   if k not in e]
        if missing:
            silent.append(f"budget eviction event {e} lacks {missing} "
                          f"— unexplained")
    hops = int(report.get("journey_evict_hops", 0))
    if hops < len(budget_events):
        silent.append(
            f"{len(budget_events)} budget eviction(s) but only {hops} "
            f"capacity_evict journey hop(s) — an eviction not "
            f"attributable to the request that forced it")

    # ---- typed admission control ------------------------------------
    overflow = report.get("typed_overflow") or {}
    if not overflow.get("raised"):
        errs.append(f"the all-pinned over-budget resident invert did "
                    f"not raise CapacityExceededError "
                    f"(got {overflow.get('error')!r})")
    if overflow.get("refusals", 0) < 1:
        errs.append("no admission refusal counted "
                    "(tpu_jordan_capacity_exceeded_total)")
    if report.get("update_after_evict_typed") != "UnknownHandleError":
        silent.append(
            f"an update against the evicted handle was "
            f"{report.get('update_after_evict_typed')!r}, not the "
            f"typed UnknownHandleError — a silently stale serve")

    # ---- warm pins with metering on ---------------------------------
    if report.get("compiles_on_capacity_path", 1) != 0:
        errs.append(f"{report.get('compiles_on_capacity_path')} "
                    f"compile(s) on the warm capacity path — the "
                    f"zero-compile pin broke with metering on")
    if report.get("measurements", 1) != 0:
        errs.append(f"{report.get('measurements')} plan-cache "
                    f"measurement(s) on the capacity path")

    # ---- projections before compiles --------------------------------
    projected = report.get("projected_lanes") or {}
    if not projected or any(int(v) <= 0 for v in projected.values()):
        errs.append(f"lane byte projections missing or non-positive "
                    f"({projected}) — operators cannot see what a "
                    f"bucket costs to open")

    if report.get("silent_capacity", True):
        silent.append("silent_capacity flagged by the demo itself")
    return errs, silent


def main(argv) -> int:
    if not argv:
        print("usage: check_capacity.py report.json [...]",
              file=sys.stderr)
        return 1
    rc = 0
    for path in argv:
        try:
            if path == "-":
                report = json.load(sys.stdin)
            else:
                with open(path) as f:
                    report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable report ({e})",
                  file=sys.stderr)
            rc = max(rc, 1)
            continue
        errs, silent = check(report)
        for e in silent:
            print(f"UNMETERED {path}: {e}", file=sys.stderr)
        for e in errs:
            print(f"FAIL {path}: {e}", file=sys.stderr)
        if silent:
            rc = 2
        elif errs:
            rc = max(rc, 1)
        else:
            comps = report["ledger"]["components"]
            handles = comps.get("handles", {})
            lanes = comps.get("executor_lanes", {})
            print(f"OK {path}: handles "
                  f"{handles.get('bytes_live')}/"
                  f"{handles.get('bytes_created')} bytes live/created "
                  f"(high water {handles.get('high_water_bytes')}), "
                  f"lanes {lanes.get('bytes_live')} bytes over "
                  f"{lanes.get('entries')} executable(s), "
                  f"{report['budget_evictions']} budget eviction(s) "
                  f"all event-explained, typed overflow raised, "
                  f"0 compiles on the warm path")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
