#!/usr/bin/env python
"""Validate an SLO burn-rate report (ISSUE 8; the ``make slo-demo``
gate).

Usage: ``python tools/check_slo.py report.json [...]`` (or ``-`` for
stdin).  Accepts either a bare ``slo_report`` document
(``obs.slo.SLOMonitor.evaluate()``) or a ``fleet_demo`` report carrying
one under ``"slo"`` (the ``--slo-report`` leg).  No jax — runs
anywhere.

What a valid SLO report must prove (docs/OBSERVABILITY.md):

  * structure — >= 1 objective, each with >= 1 window pair, each pair
    with a positive threshold and ``long_window > short_window``;
  * the burn-rate math is INTERNALLY CONSISTENT — for every window,
    ``error_rate == errors / requests`` (0 when no traffic) and
    ``burn_rate == error_rate / error_budget``, recomputed here from
    the window's own counts (a report whose arithmetic does not
    reproduce is doctored or buggy);
  * the page decision follows the multi-window AND rule — ``page`` is
    true iff BOTH the long and the short window burn above the pair's
    threshold;
  * the verdicts roll up honestly — an objective is ``healthy`` iff it
    is not paging and its p99 objective holds; the report-level
    ``healthy`` is the AND over objectives.

Exit codes: 0 = valid, 1 = structural/consistency violations,
2 = the report is PAGING (healthy=false) — distinct so CI can treat
"the math is wrong" and "the fleet is burning budget" differently.
"""

from __future__ import annotations

import json
import sys

#: |measured - recomputed| tolerance: reports round burn rates to 4
#: decimals and error rates to 6.
EPS = 5e-4


def _check_window(w: dict, budget: float, tag: str) -> list[str]:
    errs = []
    reqs, errors = w.get("requests", -1), w.get("errors", -1)
    if reqs < 0 or errors < 0 or errors > reqs:
        errs.append(f"{tag}: bad counts (requests={reqs}, "
                    f"errors={errors})")
        return errs
    want_rate = (errors / reqs) if reqs else 0.0
    if abs(w.get("error_rate", -1) - want_rate) > EPS:
        errs.append(f"{tag}: error_rate {w.get('error_rate')} != "
                    f"{errors}/{reqs}")
    want_burn = want_rate / budget
    if abs(w.get("burn_rate", -1) - want_burn) > max(EPS, EPS * want_burn):
        errs.append(f"{tag}: burn_rate {w.get('burn_rate')} != "
                    f"error_rate/budget = {round(want_burn, 4)}")
    return errs


def check(report: dict) -> tuple[list[str], bool]:
    """Returns (violations, paging); valid = no violations."""
    if report.get("metric") == "fleet_demo":
        report = report.get("slo") or {}
    if report.get("metric") != "slo_report":
        return ([f"not an slo_report (metric="
                 f"{report.get('metric')!r})"], False)
    errs: list[str] = []
    objectives = report.get("objectives", [])
    if not objectives:
        errs.append("no objectives — the SLO evaluation was vacuous")
    healthy_roll = True
    for obj in objectives:
        name = obj.get("name", "?")
        budget = obj.get("error_budget", 0)
        if not (0 < budget < 1):
            errs.append(f"{name}: error_budget {budget} outside (0, 1)")
            continue
        target = obj.get("availability_target", 0)
        if abs((1.0 - target) - budget) > EPS:
            errs.append(f"{name}: budget {budget} != 1 - availability "
                        f"target {target}")
        pairs = obj.get("windows", [])
        if not pairs:
            errs.append(f"{name}: no window pairs")
        paging_roll = False
        for i, pair in enumerate(pairs):
            thr = pair.get("threshold", 0)
            if thr <= 0:
                errs.append(f"{name}[{i}]: threshold {thr} <= 0")
            long_w, short_w = pair.get("long", {}), pair.get("short", {})
            if long_w.get("window_s", 0) <= short_w.get("window_s", 1):
                errs.append(f"{name}[{i}]: long window "
                            f"{long_w.get('window_s')}s not longer than "
                            f"short {short_w.get('window_s')}s")
            errs += _check_window(long_w, budget, f"{name}[{i}].long")
            errs += _check_window(short_w, budget, f"{name}[{i}].short")
            want_page = (long_w.get("burn_rate", 0) > thr
                         and short_w.get("burn_rate", 0) > thr)
            if bool(pair.get("page")) != want_page:
                errs.append(f"{name}[{i}]: page={pair.get('page')} "
                            f"contradicts the multi-window AND rule "
                            f"(long {long_w.get('burn_rate')}, short "
                            f"{short_w.get('burn_rate')}, threshold "
                            f"{thr})")
            paging_roll = paging_roll or want_page
        if bool(obj.get("paging")) != paging_roll:
            errs.append(f"{name}: paging={obj.get('paging')} "
                        f"contradicts its own window pairs")
        p99, p99_target = obj.get("p99_ms"), obj.get("p99_target_ms")
        want_p99_ok = (p99_target is None or p99 is None
                       or p99 <= p99_target)
        if bool(obj.get("p99_ok")) != want_p99_ok:
            errs.append(f"{name}: p99_ok={obj.get('p99_ok')} "
                        f"contradicts p99 {p99} vs target {p99_target}")
        want_healthy = (not paging_roll) and want_p99_ok
        if bool(obj.get("healthy")) != want_healthy:
            errs.append(f"{name}: healthy={obj.get('healthy')} "
                        f"contradicts paging/p99")
        healthy_roll = healthy_roll and want_healthy
    if bool(report.get("healthy")) != healthy_roll:
        errs.append(f"report healthy={report.get('healthy')} "
                    f"contradicts the AND over its objectives")
    return errs, not healthy_roll


def main(argv) -> int:
    if not argv:
        print("usage: check_slo.py report.json [...]", file=sys.stderr)
        return 1
    rc = 0
    for path in argv:
        try:
            if path == "-":
                report = json.load(sys.stdin)
            else:
                with open(path) as f:
                    report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable report ({e})", file=sys.stderr)
            rc = max(rc, 1)
            continue
        errs, paging = check(report)
        if errs:
            rc = max(rc, 1)
            for e in errs:
                print(f"FAIL {path}: {e}", file=sys.stderr)
        elif paging:
            rc = max(rc, 2)
            print(f"PAGING {path}: the report is internally consistent "
                  f"and the fleet IS burning error budget past its "
                  f"thresholds", file=sys.stderr)
        else:
            slo = (report.get("slo") if report.get("metric") ==
                   "fleet_demo" else report) or report
            n = len(slo.get("objectives", []))
            print(f"OK {path}: {n} objective(s) evaluated over "
                  f"{slo.get('samples')} samples, burn-rate math "
                  f"reproduces, nothing paging")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
