#!/usr/bin/env python
"""Validate an ``--update-demo`` report (ISSUE 12 CI satellite) — the
resident-inverse analogue of ``check_fleet.py``.

Usage: ``python tools/check_update.py report.json [...]`` (or ``-``
for stdin).  No jax import — this is the ``make update-demo`` gate and
runs anywhere.  Exit codes: 0 = valid, 1 = bound/structure violations,
2 = a SILENTLY STALE INVERSE (the alarm that must never be
downgraded): a resident inverse that diverged from the fault-free
replay, failed the residual gate against a from-scratch solve of the
mutated matrix without a typed outcome, or an update the ledger cannot
account for as ``refreshed | re_inverted | gated`` or a typed error.

What a valid update report must prove (docs/WORKLOADS.md):

  * **every update accounted** — the serve AND chaos ledgers each sum
    exactly to the stream length across
    refreshed / re_inverted / gated / typed-error, with at least one
    ``refreshed`` (the O(n²k) path actually ran) and at least one
    ``gated`` (the rank-destroying mutation was typed, never garbage);
  * **the degradation ladder is real** — the forced zero-drift-budget
    probe re_inverted (>= 1 rung fired);
  * **the warm path is free** — ZERO compiles and ZERO plan-cache
    measurements on the serve update path after warmup, and ZERO
    compiles across the whole chaos pass (kills + warm replacements
    included — the PR 7 shared-store pin, extended to update lanes);
  * **the perf claim holds** — warm update latency strictly beats warm
    re-invert at the same bucket, and the update executable's own XLA
    ``cost_analysis`` FLOPs are strictly below the fresh-invert
    executable's (k ≤ n/8 is the documented regime; both numbers are
    in the report, compared when the backend exposed them);
  * **chaos proved durability** — >= 1 seeded ``replica_kill`` fired
    mid-update-stream, the post-kill resident inverse bit-matches the
    fault-free replay, and it passes the residual gate evaluated
    against the true mutated matrix (the from-scratch verification).
"""

from __future__ import annotations

import json
import sys

OUTCOMES = ("refreshed", "re_inverted", "gated")


def _ledger_total(ledger: dict) -> int:
    return sum(int(ledger.get(k, 0)) for k in OUTCOMES + ("error",))


def check(report: dict) -> tuple[list[str], list[str]]:
    """Return (violations, stale_violations); both empty = valid."""
    errs: list[str] = []
    stale: list[str] = []
    if report.get("metric") != "update_demo":
        return ([f"not an update_demo report (metric="
                 f"{report.get('metric')!r})"], [])

    updates = int(report.get("updates", 0))
    serve = report.get("serve", {})
    chaos = report.get("chaos", {})
    lat = report.get("latency", {})
    hw = report.get("hwcost", {})
    ver = report.get("verification", {})

    # ---- the accounting ledgers (the exit-2 class) ------------------
    for name, ledger in (("serve", serve.get("ledger", {})),
                         ("chaos", chaos.get("ledger", {}))):
        total = _ledger_total(ledger)
        if total != updates:
            stale.append(f"{name} ledger accounts {total} of {updates} "
                         f"updates ({ledger}) — an update went silently "
                         f"unaccounted")
        if ledger.get("refreshed", 0) < 1:
            errs.append(f"{name} ledger shows no 'refreshed' update — "
                        f"the O(n²k) path never ran")
        if ledger.get("gated", 0) + ledger.get("error", 0) < 1:
            errs.append(f"{name} ledger shows no gated/typed outcome — "
                        f"the rank-destroying mutation was not typed")

    rung = serve.get("drift_rung", {})
    if rung.get("outcome") != "re_inverted" or rung.get("rungs_fired",
                                                        0) < 1:
        errs.append(f"the forced zero-drift-budget probe did not fire "
                    f"the re_invert rung ({rung}) — the ladder is "
                    f"unproven")

    # ---- warm-path pins --------------------------------------------
    if serve.get("compiles_on_update_path", 1) != 0:
        stale.append(f"{serve.get('compiles_on_update_path')} "
                     f"compile(s) on the warm serve update path — the "
                     f"zero-compile pin broke")
    if serve.get("measurements", 1) != 0:
        errs.append(f"{serve.get('measurements')} plan-cache "
                    f"measurement(s) on the update path")
    if chaos.get("compiles_delta_after_warmup", 1) != 0:
        stale.append(f"{chaos.get('compiles_delta_after_warmup')} "
                     f"compile(s) during the chaos pass — warm "
                     f"replacements were not free")

    # ---- the perf claims -------------------------------------------
    if not lat.get("update_beats_reinvert", False):
        errs.append(f"warm update latency "
                    f"({lat.get('warm_update_ms')} ms) did not beat "
                    f"warm re-invert ({lat.get('warm_reinvert_ms')} ms)")
    below = hw.get("flops_below_invert")
    if below is False:
        errs.append(f"update executable cost_analysis FLOPs "
                    f"({hw.get('update_executable_flops')}) NOT below "
                    f"the fresh-invert executable's "
                    f"({hw.get('invert_executable_flops')}) at "
                    f"k/n={hw.get('k_over_n')}")
    elif below is None:
        print("note: backend exposed no cost_analysis — FLOP pin "
              "unjudgeable (not failed)", file=sys.stderr)

    # ---- chaos durability (the exit-2 class) ------------------------
    if chaos.get("kills_injected", 0) < 1:
        errs.append("no replica_kill injected mid-update-stream — the "
                    "chaos leg was vacuous")
    if chaos.get("deaths", 0) < chaos.get("kills_injected", 0):
        errs.append(f"{chaos.get('kills_injected')} kills but only "
                    f"{chaos.get('deaths')} deaths — a kill was "
                    f"swallowed")
    if not chaos.get("final_inverse_bitmatch_replay", False):
        stale.append("post-kill resident inverse bits diverged from "
                     "the fault-free replay")
    mism = report.get("mismatches", [{"missing": True}])
    if mism:
        stale.append(f"{len(mism)} update outcome(s) diverged from the "
                     f"fault-free replay: {mism[:3]}")
    if not ver.get("gate_passes", False):
        stale.append(f"the post-kill resident inverse FAILS the "
                     f"residual gate against the mutated matrix "
                     f"(rel {ver.get('resident_rel_residual')} vs "
                     f"threshold {ver.get('gate_threshold')}) with no "
                     f"typed outcome — a silently stale inverse")
    if report.get("silent_stale", True):
        stale.append("silent_stale flagged by the demo itself")
    fleet_ledger = report.get("fleet_ledger", {})
    if fleet_ledger.get("outstanding", 1) != 0:
        stale.append(f"{fleet_ledger.get('outstanding')} request(s) "
                     f"outstanding after the drain — lost in flight")
    return errs, stale


def main(argv) -> int:
    if not argv:
        print("usage: check_update.py report.json [...]",
              file=sys.stderr)
        return 1
    rc = 0
    for path in argv:
        try:
            if path == "-":
                report = json.load(sys.stdin)
            else:
                with open(path) as f:
                    report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable report ({e})",
                  file=sys.stderr)
            rc = max(rc, 1)
            continue
        errs, stale = check(report)
        for e in stale:
            print(f"STALE-INVERSE {path}: {e}", file=sys.stderr)
        for e in errs:
            print(f"FAIL {path}: {e}", file=sys.stderr)
        if stale:
            rc = 2
        elif errs:
            rc = max(rc, 1)
        else:
            lat = report["latency"]
            hw = report["hwcost"]
            print(f"OK {path}: {report['updates']} updates x rank "
                  f"{report['rank']} at n={report['n']}, ledger "
                  f"{report['serve']['ledger']}, warm update "
                  f"{lat['warm_update_ms']} ms vs re-invert "
                  f"{lat['warm_reinvert_ms']} ms "
                  f"({lat['speedup_x']}x), executable FLOPs ratio "
                  f"{hw.get('update_vs_invert_flops')}, "
                  f"{report['chaos']['kills_injected']} kill(s) with "
                  f"bit-matched post-kill inverse, 0 compiles after "
                  f"warmup")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
