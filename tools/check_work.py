#!/usr/bin/env python
"""Validate a ``--work-demo`` report (ISSUE 19).

Usage: ``python tools/check_work.py report.json`` (or ``-`` for
stdin).  No jax import — this is the ``make work-demo`` gate and runs
anywhere.

What a valid work-observatory report must prove
(docs/OBSERVABILITY.md):

  * **the reconciliation invariant** — on every solve leg (1D and 2D
    meshes, invert and solve workloads, a RAGGED size and an ALIGNED
    size) the per-(worker, phase) analytical FLOP inventory re-derives
    EXACTLY from the layout math in this file (cyclic ownership ×
    live-column window × workload convention) and sums EXACTLY to the
    engine's convention total (invert ``2n³``, solve ``n³ + n²k`` —
    integer arithmetic, no tolerance).  The checker never trusts the
    ``exact`` flag: a worker share the layout math does not predict is
    UNACCOUNTED work — the exit-2 class.
  * **the hwcost pin** — each leg's ``devices × cost_analysis
    per-device`` FLOPs sit inside the stated band around the TRACED
    executed-work model, and the model itself re-derives from the
    leg's (engine, N, m, k, unroll, group, pc).  An out-of-band ratio
    the report stamps ``within: true`` is unaccounted work.
  * **penalty honesty** — the aligned leg's ragged penalty is exactly
    ``0.0``; every ragged penalty re-derives from the padded/ideal
    executed-model quotient.
  * **supported straggler verdicts** — each fleet leg's verdict
    re-derives from its own evidence (normalized p99 spread vs the
    stated threshold); a ``suspected`` verdict MUST have a
    ``straggler_suspected`` event naming the same replica in the
    embedded flight-recorder slice, and a layout-attributed spread
    must stay clean.  A verdict the evidence can't support is the
    exit-2 class.
  * the embedded black-box slice is gap-free (``dropped == 0``) and
    ``silent_work`` agrees with the re-derivation.

Exit taxonomy (the check_comm/check_fleet convention): 0 = valid,
1 = unreadable/structurally invalid, 2 = unaccounted work or an
unsupported straggler verdict.
"""

from __future__ import annotations

import json
import sys

#: Engines with a registered inventory (obs/work.INVENTORY_ENGINES —
#: mirrored here so the gate needs no tpu_jordan import).
KNOWN_ENGINES = {
    "inplace", "grouped", "swapfree", "augmented", "solve_sharded",
    "lookahead", "solve_lookahead",
}


def _sig(v: float) -> float:
    return float(f"{float(v):.4g}")


def _close(a, b, tol: float = 1e-6) -> bool:
    if a is None or b is None:
        return a is b
    a, b = float(a), float(b)
    return abs(a - b) <= tol * max(1.0, abs(a), abs(b))


# ---------------------------------------------------------------------
# The analytical model, re-derived from scratch (obs/work.py's math,
# independently restated — the whole point of the gate).
# ---------------------------------------------------------------------


def _heights(n: int, m: int) -> list[int]:
    tu = -(-n // m)
    return [m] * (tu - 1) + [n - (tu - 1) * m]


def _cyclic_sums(h: list[int], p: int) -> list[int]:
    out = [0] * p
    for r, hr in enumerate(h):
        out[r % p] += hr
    return out


def _convention(n: int, workload: str, k: int) -> int:
    if workload == "invert":
        return 2 * n ** 3
    if workload == "solve":
        return n ** 3 + n ** 2 * k
    raise ValueError(f"unknown workload {workload!r}")


def _inventory_1d(n: int, m: int, p: int, workload: str, k: int):
    h = _heights(n, m)
    r_sum = _cyclic_sums(h, p)
    per = {str(w): {"pivot": 0, "eliminate": 0} for w in range(p)}
    steps = []
    cum = 0
    for t, ht in enumerate(h):
        if workload == "invert":
            f = 2 * ht * n
        else:
            w_prev = n - cum
            cum += ht
            f = ht * (w_prev + (n - cum) + k)
        owner = t % p
        tot = 0
        for w in range(p):
            piv = f * ht if w == owner else 0
            elim = f * (r_sum[w] - (ht if w == owner else 0))
            per[str(w)]["pivot"] += piv
            per[str(w)]["eliminate"] += elim
            tot += piv + elim
        steps.append(tot)
    return per, steps


def _inventory_2d(n: int, m: int, pr: int, pc: int, workload: str,
                  k: int):
    h = _heights(n, m)
    r_sum = _cyclic_sums(h, pr)
    s_sum = _cyclic_sums(h, pc)
    kc = [len(range(c, k, pc)) for c in range(pc)]
    per = {f"{wr},{wc}": {"pivot": 0, "eliminate": 0}
           for wr in range(pr) for wc in range(pc)}
    steps = []
    pref = [0] * pc
    for t, ht in enumerate(h):
        tc = t % pc
        pref[tc] += ht
        tot = 0
        for wc in range(pc):
            if workload == "invert":
                f = 2 * ht * s_sum[wc]
            else:
                colw = 2 * (s_sum[wc] - pref[wc])
                colw += (ht if wc == tc else 0) + kc[wc]
                f = ht * colw
            owner = t % pr
            for wr in range(pr):
                piv = f * ht if wr == owner else 0
                elim = f * (r_sum[wr] - (ht if wr == owner else 0))
                cell = per[f"{wr},{wc}"]
                cell["pivot"] += piv
                cell["eliminate"] += elim
                tot += piv + elim
        steps.append(tot)
    return per, steps


def _executed_model(engine: str, workload: str, *, N: int, m: int,
                    k: int, unroll: bool, pc: int) -> float:
    nr = N // m
    if workload == "invert":
        width = 2 * N if engine == "augmented" else N
        return 2.0 * N * N * width
    if not unroll:
        return 2.0 * N * N * (N + k * pc)
    total = 0.0
    for t in range(nr):
        if pc > 1:
            bc1 = nr // pc
            live = pc * (bc1 - t // pc) * m
        else:
            live = N - t * m
        total += 2.0 * m * N * (live + k * pc)
    return total


# ---------------------------------------------------------------------
# Per-leg re-derivation.
# ---------------------------------------------------------------------


def _check_solve_leg(name: str, work: dict, errs: list,
                     silent: list) -> None:
    engine = work.get("engine")
    if engine not in KNOWN_ENGINES:
        errs.append(f"{name}: unknown engine {engine!r} (no registered "
                    f"inventory)")
        return
    n, m = int(work["n"]), int(work["block_size"])
    workload, k = work["workload"], int(work.get("rhs") or 0)
    workers = work.get("workers")
    two_d = isinstance(workers, list)
    if two_d:
        pr, pc = int(workers[0]), int(workers[1])
        per, steps = _inventory_2d(n, m, pr, pc, workload, k)
    else:
        pc = 1
        per, steps = _inventory_1d(n, m, int(workers), workload, k)

    convention = _convention(n, workload, k)
    tot = work.get("totals") or {}
    if tot.get("convention_flops") != convention:
        errs.append(f"{name}: convention_flops "
                    f"{tot.get('convention_flops')} != {workload} "
                    f"convention {convention}")

    # -- the reconciliation invariant, re-derived ---------------------
    got = work.get("per_worker") or {}
    for w in sorted(set(per) | set(got)):
        mine, theirs = per.get(w), got.get(w)
        if mine is None:
            silent.append(f"{name}: UNACCOUNTED worker {w!r}: the "
                          f"layout owns no such worker")
            continue
        if theirs is None:
            silent.append(f"{name}: worker {w!r} missing from the "
                          f"report (layout says {sum(mine.values())} "
                          f"FLOPs)")
            continue
        for phase in ("pivot", "eliminate"):
            if int(theirs.get(phase, -1)) != mine[phase]:
                silent.append(
                    f"{name}: worker {w} {phase} FLOPs "
                    f"{theirs.get(phase)} != layout-derived "
                    f"{mine[phase]}")
        flops = mine["pivot"] + mine["eliminate"]
        if theirs.get("flops") != flops:
            silent.append(f"{name}: worker {w} flops "
                          f"{theirs.get('flops')} != pivot+eliminate "
                          f"{flops}")
        share = _sig(flops / float(convention))
        if not _close(theirs.get("share"), share):
            silent.append(f"{name}: worker {w} share "
                          f"{theirs.get('share')} != {share}")
    accounted = sum(d["pivot"] + d["eliminate"] for d in per.values())
    if accounted != convention:
        silent.append(f"{name}: layout inventory sums to {accounted} "
                      f"!= convention {convention} (checker model "
                      f"violation — file a bug)")
    if tot.get("accounted_flops") != accounted:
        silent.append(f"{name}: accounted_flops "
                      f"{tot.get('accounted_flops')} != inventory sum "
                      f"{accounted}")
    if tot.get("exact") is not True:
        silent.append(f"{name}: exact={tot.get('exact')!r} — the "
                      f"shares do not sum to the convention total")
    if list(work.get("per_superstep") or []) != steps:
        silent.append(f"{name}: per_superstep series diverges from "
                      f"the layout-derived schedule")

    # -- skew / penalty re-derivation ---------------------------------
    worker_flops = [d["pivot"] + d["eliminate"] for d in per.values()]
    mean = sum(worker_flops) / len(worker_flops)
    skew = _sig(max(worker_flops) / mean) if mean else 1.0
    if not _close(tot.get("skew"), skew):
        errs.append(f"{name}: skew {tot.get('skew')} != re-derived "
                    f"{skew}")
    N = int(work["padded_n"])
    unroll = bool(work.get("unroll"))
    executed = _executed_model(engine, workload, N=N, m=m, k=k,
                               unroll=unroll, pc=pc)
    ideal = _executed_model(engine, workload, N=n, m=m, k=k,
                            unroll=unroll, pc=pc)
    if not _close(tot.get("executed_model_flops"), executed):
        errs.append(f"{name}: executed_model_flops "
                    f"{tot.get('executed_model_flops')} != re-derived "
                    f"{executed}")
    penalty = _sig(executed / ideal - 1.0) if ideal else 0.0
    if not _close(tot.get("ragged_penalty"), penalty):
        errs.append(f"{name}: ragged_penalty {tot.get('ragged_penalty')}"
                    f" != re-derived {penalty}")

    # -- the hwcost pin ------------------------------------------------
    xla = work.get("xla") or {}
    if not xla.get("available"):
        errs.append(f"{name}: no cost_analysis attribution (the demo "
                    f"legs run real sharded executables — the pin must "
                    f"be judged)")
        return
    total_fl = float(xla.get("per_device_flops", 0)) \
        * int(xla.get("devices", 0))
    if not _close(xla.get("total_flops"), total_fl):
        errs.append(f"{name}: xla.total_flops {xla.get('total_flops')} "
                    f"!= per_device × devices {total_fl}")
    nr = int(work.get("padded_supersteps") or 0)
    model = executed
    if not unroll and nr:
        group = int(work.get("group") or 0)
        traced = min(group, nr) if group > 1 else 1
        model = model * traced / nr
    if not _close(xla.get("model_traced_flops"), model):
        errs.append(f"{name}: xla.model_traced_flops "
                    f"{xla.get('model_traced_flops')} != re-derived "
                    f"{model}")
    band = xla.get("band") or [0, 0]
    ratio = total_fl / model if model > 0 else None
    if not _close(xla.get("xla_vs_model"), None if ratio is None
                  else _sig(ratio), tol=1e-3):
        errs.append(f"{name}: xla_vs_model {xla.get('xla_vs_model')} "
                    f"!= re-derived {ratio}")
    within = ratio is not None and band[0] <= ratio <= band[1]
    if bool(xla.get("within")) != within:
        silent.append(f"{name}: UNACCOUNTED work — xla ratio {ratio} "
                      f"vs band {band} says within={within} but the "
                      f"report stamps {xla.get('within')}")
    elif not within:
        silent.append(f"{name}: UNACCOUNTED work — devices × "
                      f"cost_analysis {total_fl} is outside the band "
                      f"{band} around the traced model {model}")


def _check_fleet_leg(leg: dict, bb_events: list, errs: list,
                     silent: list) -> None:
    name = leg.get("name", "fleet?")
    verdict = leg.get("verdict") or {}
    thr = verdict.get("threshold")
    if not isinstance(thr, (int, float)) or thr <= 1:
        errs.append(f"{name}: verdict has no usable threshold "
                    f"({thr!r})")
        return
    p99 = verdict.get("p99_ms") or {}
    expected = verdict.get("expected")
    norm = {}
    for rep, v in p99.items():
        if v is None or v <= 0:
            continue
        e = float(expected.get(rep, 1.0)) if expected else 1.0
        norm[rep] = float(v) / (e if e > 0 else 1.0)
    for rep, v in norm.items():
        if not _close(verdict.get("normalized", {}).get(rep), _sig(v),
                      tol=1e-3):
            silent.append(f"{name}: normalized p99 for replica {rep} "
                          f"{verdict.get('normalized', {}).get(rep)} "
                          f"!= evidence-derived {_sig(v)}")
    if len(norm) < 2:
        judged, suspected, spread = False, False, None
    else:
        judged = True
        worst = max(norm, key=lambda r: norm[r])
        spread = norm[worst] / min(norm.values())
        suspected = spread > thr
        if suspected and verdict.get("replica") != worst:
            silent.append(f"{name}: verdict blames replica "
                          f"{verdict.get('replica')!r} but the "
                          f"evidence's worst replica is {worst!r}")
    if bool(verdict.get("judged")) != judged:
        errs.append(f"{name}: judged={verdict.get('judged')} but the "
                    f"evidence has {len(norm)} usable replicas")
    if spread is not None and not _close(verdict.get("spread"),
                                         _sig(spread), tol=1e-3):
        silent.append(f"{name}: spread {verdict.get('spread')} != "
                      f"evidence-derived {_sig(spread)}")
    if bool(verdict.get("suspected")) != suspected:
        silent.append(
            f"{name}: UNSUPPORTED VERDICT — suspected="
            f"{verdict.get('suspected')} but the normalized spread "
            f"{spread} vs threshold {thr} says {suspected}")
    if "expect_suspected" in leg and \
            bool(leg["expect_suspected"]) != suspected:
        silent.append(f"{name}: the leg's contract expects suspected="
                      f"{leg['expect_suspected']} and the evidence "
                      f"says {suspected}")
    if suspected:
        hits = [e for e in bb_events
                if e.get("kind") == "straggler_suspected"
                and e.get("replica") == verdict.get("replica")]
        if not hits:
            silent.append(
                f"{name}: SILENT STRAGGLER — the verdict suspects "
                f"replica {verdict.get('replica')!r} but no "
                f"straggler_suspected event for it exists in the "
                f"flight-recorder slice")


# ---------------------------------------------------------------------
# The report-level contract.
# ---------------------------------------------------------------------

#: Solve-leg coverage the demo must ship (mesh kind × workload) plus
#: the aligned penalty pin.
_REQUIRED_LEGS = {
    ("1d", "invert"), ("2d", "invert"), ("1d", "solve"),
    ("2d", "solve"),
}


def check(report: dict) -> tuple[list[str], list[str]]:
    """Returns ``(errs, silent)``: structural violations (exit 1) and
    the exit-2 unaccounted-work / unsupported-verdict class."""
    errs: list[str] = []
    silent: list[str] = []
    if report.get("metric") != "work_demo":
        return ([f"not a work_demo report "
                 f"(metric={report.get('metric')!r})"], [])
    if not report.get("ragged"):
        errs.append("demo problem size is not ragged (n % m == 0): the "
                    "padded-tail shares were never exercised")

    legs = report.get("legs") or []
    seen = set()
    aligned = None
    for leg in legs:
        work = leg.get("work") or {}
        two_d = isinstance(work.get("workers"), list)
        seen.add(("2d" if two_d else "1d", work.get("workload")))
        if work.get("n") == work.get("block_size", 0) * 8 and not two_d:
            aligned = leg
        _check_solve_leg(leg.get("name", "?"), work, errs, silent)
    for want in sorted(_REQUIRED_LEGS):
        if want not in seen:
            errs.append(f"missing reconciliation coverage: {want[0]} "
                        f"{want[1]} leg")
    if aligned is None:
        errs.append("missing the aligned leg (n % m == 0, p | Nr): the "
                    "penalty==0 pin was never exercised")
    else:
        pen = (aligned.get("work", {}).get("totals") or {}).get(
            "ragged_penalty")
        if pen != 0.0:
            silent.append(
                f"{aligned.get('name')}: aligned ragged_penalty {pen} "
                f"!= 0.0 — phantom padding work on an aligned shape")
    if bool(report.get("penalty_nonzero_aligned")) != \
            bool(aligned is not None and (aligned.get("work", {})
                 .get("totals") or {}).get("ragged_penalty") != 0.0):
        errs.append("penalty_nonzero_aligned disagrees with the "
                    "aligned leg's own totals")

    # -- fleet legs ----------------------------------------------------
    bb = report.get("blackbox") or {}
    bb_events = bb.get("events") or []
    fleet_legs = report.get("fleet_legs") or []
    names = {leg.get("name") for leg in fleet_legs}
    for want in ("fleet_straggler_suspected",
                 "fleet_skew_layout_attributed",
                 "fleet_straggler_recovered"):
        if want not in names:
            errs.append(f"missing fleet leg: {want}")
    for leg in fleet_legs:
        _check_fleet_leg(leg, bb_events, errs, silent)
    n_susp = sum(1 for e in bb_events
                 if e.get("kind") == "straggler_suspected")
    n_clear = sum(1 for e in bb_events
                  if e.get("kind") == "straggler_cleared")
    if report.get("straggler_events") != n_susp:
        errs.append(f"report straggler_events="
                    f"{report.get('straggler_events')} != {n_susp} in "
                    f"the slice")
    if report.get("cleared_events") != n_clear:
        errs.append(f"report cleared_events="
                    f"{report.get('cleared_events')} != {n_clear} in "
                    f"the slice")
    if "fleet_straggler_recovered" in names and n_clear < 1:
        silent.append("the recovery leg ran but no straggler_cleared "
                      "event exists — the clear transition was never "
                      "recorded")
    fleet = report.get("fleet") or {}
    if fleet.get("veto_after_recovery") is not None:
        errs.append("veto_after_recovery is still set — a recovered "
                    "fleet must not keep vetoing the autoscaler")

    if bb.get("dropped", 1) != 0:
        errs.append(f"flight-recorder slice dropped {bb.get('dropped')} "
                    f"events — reconstruction has gaps")
    if bool(report.get("silent_work")) != bool(silent):
        errs.append(f"report silent_work={report.get('silent_work')} "
                    f"disagrees with the re-derived verdict "
                    f"({len(silent)} violations)")
    return errs, silent


def main(argv) -> int:
    if not argv:
        print("usage: check_work.py report.json [...]", file=sys.stderr)
        return 1
    rc = 0
    for path in argv:
        try:
            if path == "-":
                report = json.load(sys.stdin)
            else:
                with open(path) as f:
                    report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL {path}: unreadable report ({e})",
                  file=sys.stderr)
            return 1
        errs, silent = check(report)
        for msg in errs:
            print(f"FAIL {path}: {msg}", file=sys.stderr)
        for msg in silent:
            print(f"SILENT {path}: {msg}", file=sys.stderr)
        if silent:
            rc = max(rc, 2)
        elif errs:
            rc = max(rc, 1)
        else:
            legs = report.get("legs") or []
            print(f"OK {path}: {len(legs)} solve legs reconciled "
                  f"(shares == layout math == convention total, "
                  f"hwcost pin in band), "
                  f"{report.get('straggler_events')} straggler "
                  f"event(s) supported by evidence, "
                  f"{report.get('cleared_events')} cleared")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
