"""CLI entry: ``python -m tpu_jordan n m [file]``.

Mirrors the reference's argv contract and exit codes (main.cpp:65-93):
positional ``n m [file]``, usage message and exit 1 on bad args, exit 2 on
solve failure (file errors, singular matrix), 0 on success.  Extra
TPU-relevant knobs are optional flags so the positional contract is intact.
"""

from __future__ import annotations

import argparse
import os
import sys

import jax.numpy as jnp

from .tuning.registry import ENGINES


def _workers_arg(s: str):
    """'8' -> 8 workers on a 1D mesh; '2x4' -> a (2, 4) 2D mesh."""
    if "x" in s:
        pr, pc = s.split("x", 1)
        return (int(pr), int(pc))
    return int(s)


def _write_telemetry(metrics_out, trace_json, telemetry) -> None:
    """``--metrics-out`` / ``--trace-json`` emission — runs on every
    exit path (a failed solve's partial telemetry is still evidence).
    Write failures warn on stderr but never mask the run's own exit
    code/diagnostic (the documented 0/1/2 taxonomy, main.cpp:77-85)."""
    try:
        if metrics_out:
            from .obs.export import write_metrics

            write_metrics(metrics_out)
        if trace_json and telemetry is not None:
            from .obs.export import write_chrome_trace
            from .obs.recorder import RECORDER

            # The journey slice rides the span trace (ISSUE 8): one
            # async Perfetto lane per request_id next to the phase
            # spans, from the always-on flight recorder.
            write_chrome_trace(
                trace_json, telemetry,
                journey_events=RECORDER.events(kind="journey"))
    except OSError as e:
        print(f"warning: telemetry export failed: {e}", file=sys.stderr)


def _write_capacity(path) -> None:
    """``--capacity-report`` emission — the process-wide capacity
    snapshot (obs/capacity.py), written on every exit path with the
    same never-mask-the-exit-code discipline as ``_write_telemetry``.
    The snapshot re-probes the device live-bytes watermark (ISSUE 13
    satellite) on backends that report it."""
    if not path:
        return
    try:
        from .obs.capacity import write_report

        write_report(path)
    except OSError as e:
        print(f"warning: capacity report failed: {e}", file=sys.stderr)


def _write_comm(path) -> None:
    """``--comm-report`` emission — the process-wide communication
    snapshot (obs/comm.py: the last distributed solve's per-phase
    collective accounting + reconciliation/drift record and the
    tpu_jordan_comm_* counters), written on every exit path with the
    same never-mask-the-exit-code discipline as ``_write_telemetry``."""
    if not path:
        return
    try:
        from .obs.comm import write_report

        write_report(path)
    except OSError as e:
        print(f"warning: comm report failed: {e}", file=sys.stderr)


def _write_work(path) -> None:
    """``--work-report`` emission — the process-wide work snapshot
    (obs/work.py: the last distributed solve's per-worker analytical
    FLOP shares, skew and ragged-penalty record plus the
    tpu_jordan_work_* gauges and straggler counter), written on every
    exit path with the same never-mask-the-exit-code discipline as
    ``_write_telemetry``."""
    if not path:
        return
    try:
        from .obs.work import write_report

        write_report(path)
    except OSError as e:
        print(f"warning: work report failed: {e}", file=sys.stderr)


def _write_blackbox(path) -> None:
    """Dump the always-on flight recorder (ISSUE 8): on demand via
    ``--blackbox-out``, and AUTOMATICALLY on every exit-2 path — the
    black box exists precisely for the runs that end in the failure
    taxonomy's "runtime failure" class.  Same never-mask-the-exit-code
    discipline as ``_write_telemetry``."""
    try:
        from .obs.recorder import RECORDER

        RECORDER.write(path)
        print(f"flight recorder dumped to {path} "
              f"({RECORDER.total} events recorded)", file=sys.stderr)
    except OSError as e:
        print(f"warning: blackbox dump failed: {e}", file=sys.stderr)


def main(argv=None) -> int:
    """Parse-and-run wrapper: the run itself is ``_main``; on the way
    out, the always-on flight recorder is dumped when the caller asked
    for it (``--blackbox-out``) or when the run ends in the exit-2
    runtime-failure class — a crash-forensics artifact for exactly the
    runs that need one (docs/OBSERVABILITY.md)."""
    state: dict = {"blackbox_out": None}
    rc = _main(argv, state)
    if state["blackbox_out"] or rc == 2:
        import tempfile

        _write_blackbox(state["blackbox_out"]
                        or os.path.join(tempfile.gettempdir(),
                                        "tpu_jordan_blackbox.json"))
    return rc


def _main(argv, state) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_jordan",
        usage="python -m tpu_jordan n m [file]",
        description="Distributed block Gauss-Jordan matrix inversion on TPU.",
    )
    ap.add_argument("n", type=int, help="matrix dimension")
    ap.add_argument("m", type=int, help="pivot block size")
    ap.add_argument("file", nargs="?", default=None, help="matrix file")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "float64", "bfloat16", "float16",
                             "complex64"],
                    help="storage dtype (complex64, ISSUE 11: "
                         "first-class on --workload solve/lstsq and on "
                         "the augmented invert engine)")
    ap.add_argument("--precision", default="highest",
                    choices=["highest", "high", "default", "mixed"],
                    help="matmul precision for the elimination sweeps; "
                         "'mixed' = HIGH sweeps + >=2 HIGHEST "
                         "Newton-Schulz refinement steps "
                         "(benchmarks/PHASES.md)")
    ap.add_argument("--generator", default="absdiff",
                    choices=["absdiff", "hilbert", "rand", "kms",
                             "crand"],
                    help="matrix generator when no file is given "
                         "(hilbert = the reference's -DHILBERT build; "
                         "rand = deterministic uniform [-1,1), the "
                         "well-conditioned scale fixture; kms = the "
                         "0.25^|i-j| SPD fixture for --assume spd; "
                         "crand = deterministic complex uniform, "
                         "complex dtypes only)")
    ap.add_argument("--workload", default="invert",
                    choices=["invert", "solve", "lstsq"],
                    help="what to compute (ISSUE 11, docs/WORKLOADS.md)"
                         ": 'invert' = the historical A^-1 path; "
                         "'solve' = X = A^-1 B by Gauss-Jordan on "
                         "[A | B] with no inverse ever formed (~half "
                         "the FLOPs, gated on the k-free ||AX - B|| "
                         "backward error); 'lstsq' = argmin ||Ax - b|| "
                         "via the normal equations through the SPD "
                         "fast path (A is n x n//2, overdetermined "
                         "2:1).  solve/lstsq run single-device with "
                         "engine auto (the workload-scoped tuner "
                         "ladder)")
    ap.add_argument("--rhs", type=int, default=1, metavar="K",
                    help="--workload solve/lstsq: number of "
                         "right-hand-side columns (default 1)")
    ap.add_argument("--assume", default="general",
                    choices=["general", "spd"],
                    help="--workload solve: 'spd' promises A is "
                         "symmetric/Hermitian positive definite and "
                         "takes the pivot-free fast path (skips the "
                         "condition-based pivot probe — pair with "
                         "--generator kms); unsound on general "
                         "matrices")
    ap.add_argument("--refine", type=int, default=0,
                    help="Newton-Schulz refinement steps")
    ap.add_argument("--engine", default="auto",
                    choices=list(ENGINES),
                    help="elimination engine: 'auto' = autotuned "
                         "selection (plan cache -> registry cost "
                         "ranking -> --tune measured tuning; "
                         "docs/TUNING.md); 'grouped' = delayed "
                         "group updates, the measured winner for "
                         "well-conditioned matrices at n >= 8192 with "
                         "m=128 (driver.resolve_engine documents the "
                         "measured dispatch policy); 'augmented' = the "
                         "4N^3 reference-parity path; 'swapfree' = the "
                         "implicit-permutation distributed engine (no "
                         "row-swap broadcast, no per-step 2D unscramble, "
                         "bucketed-ppermute deferred repairs — the "
                         "pod-scale comm design; distributed, either "
                         "gather mode incl. --no-gather)")
    ap.add_argument("--group", type=int, default=0,
                    help="panels per delayed-group update (implies "
                         "--engine grouped when > 1; grouped default 2)")
    ap.add_argument("--tune", action="store_true",
                    help="--engine auto only: measure the registry's "
                         "cost-pruned engine candidates at this "
                         "(n, dtype, mesh, gather) point with the robust "
                         "core (median-of-k, IQR outlier rejection) and "
                         "run the fastest; combine with --plan-cache to "
                         "persist the plan (docs/TUNING.md)")
    ap.add_argument("--plan-cache", default=None, metavar="PATH",
                    help="--engine auto only: versioned JSON plan cache "
                         "consulted before any cost ranking or "
                         "measurement (a warm hit performs zero "
                         "measurements) and updated after selection; "
                         "corrupt/version-stale files fall back to "
                         "cost-model ranking")
    ap.add_argument("--workers", type=_workers_arg, default=1,
                    help="devices in the mesh: an integer for the 1D "
                         "row-cyclic layout (the reference's mpirun -np), "
                         "or PRxPC (e.g. 2x4) for the 2D block-cyclic "
                         "layout")
    ap.add_argument("--distributed", action="store_true",
                    help="call jax.distributed.initialize for multi-host "
                         "TPU slices before any device use (the analog of "
                         "MPI_Init, main.cpp:69; no-op on a single host)")
    ap.add_argument("--sleep", type=int, default=0, metavar="SECONDS",
                    help="sleep after printing the pid, before any device "
                         "work — attach-a-debugger window (the reference's "
                         "-DSLEEP startup hook, main.cpp:8,70-72; useful "
                         "for multi-host runs where each process must be "
                         "attached separately)")
    ap.add_argument("--gather", default=True,
                    action=argparse.BooleanOptionalAction,
                    help="--no-gather keeps the inverse as sharded cyclic "
                         "blocks (distributed runs; generator or file "
                         "input): the O(n^2/workers) per-device memory "
                         "mode for north-star sizes")
    ap.add_argument("--batch", type=int, default=1,
                    help="invert a batch of B generated matrices in one "
                         "vmapped computation (the north-star batch "
                         "capability; generator input only, single "
                         "device; B distinct matrices via per-element "
                         "index offsets)")
    ap.add_argument("--numerics", default="off",
                    choices=["off", "summary", "trace"],
                    help="per-solve numerical health record (ISSUE 10, "
                         "docs/OBSERVABILITY.md): 'summary' reports "
                         "rel_residual/kappa from what the solve "
                         "already returns; 'trace' adds the full "
                         "per-superstep record (chosen pivot block, "
                         "its inverse inf-norm — the paper's selection "
                         "criterion — candidate spread, element-growth "
                         "watermark) from the instrumented unrolled "
                         "engines (single-device; --workload solve "
                         "traces the [A | B] elimination the same "
                         "way, pivot sequence pinned equal — the SPD "
                         "fast path has no probe to trace and "
                         "refuses typed).  Both mirror into "
                         "the tpu_jordan_pivot_condition/"
                         "growth_factor/residual histograms and spike "
                         "the flight recorder before any recovery "
                         "rung; 'off' (default) costs nothing")
    ap.add_argument("--numerics-demo", action="store_true",
                    help="run the numerics-observatory acceptance demo "
                         "(obs/numerics.numerics_demo): one seeded "
                         "ill-conditioned bf16 solve, traced — the "
                         "residual gate fails, refine diverges, the "
                         "fp32 re-solve recovers — and print ONE JSON "
                         "line proving every degradation rung was "
                         "causally preceded by a numerics_spike event "
                         "in the flight recorder (exit 2 on an "
                         "unexplained rung; tools/check_numerics.py "
                         "validates the report).  n is the fixture "
                         "size, m the block size; --chaos-seed seeds "
                         "the fixture")
    ap.add_argument("--serve-demo", action="store_true",
                    help="run the dynamic-batching inversion service "
                         "demo (tpu_jordan.serve.JordanService): mixed "
                         "request sizes cycling through n/2^k across "
                         ">= 3 shape buckets, micro-batched through the "
                         "bucketed AOT executable cache, then print ONE "
                         "JSON line of per-bucket stats (occupancy, "
                         "latency percentiles, compile + plan-cache "
                         "measurement counters; docs/SERVING.md); n is "
                         "the largest request size, m the block-size "
                         "hint; single device, generator input only")
    ap.add_argument("--chaos-demo", action="store_true",
                    help="serve the SAME deterministic mixed request "
                         "stream twice — fault-free, then under a "
                         "seeded FaultPlan injecting compile failures, "
                         "transient execute errors, NaN result "
                         "corruption, and plan-cache write failures "
                         "(tpu_jordan.resilience; docs/RESILIENCE.md) — "
                         "and print ONE JSON line proving every "
                         "response bit-matched the fault-free replay "
                         "or carried a typed error, with every "
                         "injected fault accounted for as retried, "
                         "degraded, or typed-error (exit 2 on any "
                         "silent corruption; tools/check_chaos.py "
                         "validates the report)")
    ap.add_argument("--chaos-seed", type=int, default=0, metavar="S",
                    help="--chaos-demo/--fleet-demo: FaultPlan + "
                         "request-stream seed (default 0; same seed = "
                         "identical chaos)")
    ap.add_argument("--fleet-demo", action="store_true",
                    help="run the supervised replica-pool acceptance "
                         "demo (tpu_jordan.fleet.JordanFleet; "
                         "docs/FLEET.md): single-replica vs N-replica "
                         "throughput on the same deterministic mixed "
                         "stream, then the SAME stream under a seeded "
                         "replica_kill — the supervisor warm-replaces "
                         "each victim against the shared executor "
                         "store + read-only pre-tuned plan cache (zero "
                         "compiles, zero measurements) and the router "
                         "re-queues its queued work; prints ONE JSON "
                         "line proving every response bit-matched the "
                         "fault-free replay or carried a typed error "
                         "(exit 2 on any silent loss; "
                         "tools/check_fleet.py validates the report)")
    ap.add_argument("--autoscale-demo", action="store_true",
                    help="run the SLO-driven autoscaler acceptance "
                         "demo (tpu_jordan.fleet.FleetAutoscaler; "
                         "ISSUE 18, docs/FLEET.md): one seeded "
                         "burst->idle->recovery trace through a "
                         "floor-sized fleet — sustained deadline burn "
                         "pages the burn-rate monitor, which scales "
                         "the pool toward --replicas (the ceiling) "
                         "and pre-sheds new submissions typed at the "
                         "router; the idle phase drains back to the "
                         "floor; prints ONE JSON line carrying every "
                         "decision WITH the burn evidence it was "
                         "derived from (exit 2 on a silent p99 "
                         "breach; tools/check_autoscale.py re-derives "
                         "every action)")
    ap.add_argument("--update-demo", action="store_true",
                    help="run the resident-inverse update acceptance "
                         "demo (tpu_jordan.serve.update_demo; ISSUE 12, "
                         "docs/WORKLOADS.md): a warmed service creates "
                         "a resident handle (invert(resident=True)) "
                         "and streams --updates rank-K (--rank) "
                         "Sherman-Morrison-Woodbury mutations through "
                         "the O(n^2 k) update lane (one deliberately "
                         "rank-destroying mutation mid-stream -> typed "
                         "'gated'; a zero-drift-budget burst -> the "
                         "'re_invert' rung), measures warm update vs "
                         "warm re-invert latency + executable "
                         "cost_analysis FLOPs, then replays the same "
                         "stream through an N-replica fleet under a "
                         "seeded replica_kill — the post-kill resident "
                         "inverse must bit-match the fault-free replay "
                         "and gate-verify against a from-scratch solve "
                         "of the mutated matrix; prints ONE JSON line "
                         "(exit 2 = a silently stale inverse; "
                         "tools/check_update.py validates)")
    ap.add_argument("--capacity-demo", action="store_true",
                    help="run the capacity-observatory acceptance demo "
                         "(tpu_jordan.obs.capacity.capacity_demo; "
                         "ISSUE 13, docs/OBSERVABILITY.md): a warmed "
                         "service under a resident-handle byte budget "
                         "— lane bytes projected BEFORE compiling, "
                         "resident creates fill the budget, the next "
                         "create evicts the least-recently-served "
                         "handle (journey hop + capacity_eviction "
                         "event), an all-pinned admission is the typed "
                         "CapacityExceededError at submit, and the "
                         "ledger reconciles bytes_created == "
                         "bytes_live + bytes_evicted per class; prints "
                         "ONE JSON line (exit 2 = unmetered residency "
                         "or a silent eviction; "
                         "tools/check_capacity.py validates).  n is "
                         "the handle size, m the block size; "
                         "--chaos-seed seeds the fixtures")
    ap.add_argument("--comm-demo", action="store_true",
                    help="run the communication-observatory acceptance "
                         "demo (tpu_jordan.obs.comm.comm_demo; "
                         "ISSUE 14, docs/OBSERVABILITY.md): five tiny "
                         "distributed solves — 1D and 2D meshes, both "
                         "gather modes, a grouped engine, a ragged "
                         "problem size — each reconciling the "
                         "collective multiset the traced program "
                         "actually issued (the compat-shim recording "
                         "layer) against the layout-derived analytical "
                         "inventory, plus one deliberate "
                         "measured-vs-projected drift leg whose "
                         "out-of-band ratio must be a RECORDED "
                         "comm_drift event; prints ONE JSON line "
                         "(exit 2 = an unaccounted collective or a "
                         "silent drift; tools/check_comm.py "
                         "validates).  n is the problem size, m the "
                         "block size; runs on a forced 8-device "
                         "virtual CPU mesh when needed")
    ap.add_argument("--work-demo", action="store_true",
                    help="run the work-observatory acceptance demo "
                         "(tpu_jordan.obs.work.work_demo; ISSUE 19, "
                         "docs/OBSERVABILITY.md): six tiny distributed "
                         "solves — 1D and 2D meshes, invert and solve "
                         "workloads, a ragged size and an aligned size "
                         "— each leg's per-worker analytical FLOP "
                         "shares summing EXACTLY to the engine's "
                         "convention total and its executable judged "
                         "against cost_analysis, plus the fleet-skew "
                         "legs (a synthetic straggler that must become "
                         "a recorded straggler_suspected event, a "
                         "layout-attributed spread that must stay "
                         "clean, and the recovery transition); prints "
                         "ONE JSON line (exit 2 = unaccounted work or "
                         "an unsupported straggler verdict; "
                         "tools/check_work.py re-derives every share "
                         "from the layout math).  n is the problem "
                         "size, m the block size; runs on a forced "
                         "8-device virtual CPU mesh when needed")
    ap.add_argument("--work-report", default=None, metavar="PATH",
                    help="write the process-wide work snapshot (the "
                         "last distributed solve's per-worker "
                         "analytical FLOP shares, skew and "
                         "ragged-penalty record plus the "
                         "tpu_jordan_work_* gauges and the straggler "
                         "counter) as one JSON document on exit "
                         "(docs/OBSERVABILITY.md)")
    ap.add_argument("--lp-demo", action="store_true",
                    help="run the LP/QP optimization-driver acceptance "
                         "demo (tpu_jordan.lpqp.lp_demo; ISSUE 17, "
                         "docs/WORKLOADS.md): four seeded driver runs "
                         "(LP well/ill via revised simplex, QP well/ill "
                         "via primal active-set) stream correlated "
                         "invert(resident=True) + rank-k update + "
                         "verification-solve traffic through a warmed "
                         "replica fleet, convergence judged by the "
                         "solver's OWN eps*n*kappa residual gate; plus "
                         "a zero-drift-budget probe (every update rides "
                         "the re_invert rung), a seeded replica_kill "
                         "chaos run that must bit-match its fault-free "
                         "replay, and the batched update-lane "
                         "amortization measurement (--batch-cap "
                         "distinct handles fused into one vmapped "
                         "launch, warm per-update latency at occupancy "
                         "> 1 vs one-per-launch); prints ONE JSON line "
                         "(exit 2 = silent divergence; "
                         "tools/check_lp.py re-derives convergence "
                         "from the iterate residuals).  n is the "
                         "LP/QP dimension, m the block-size hint; "
                         "--chaos-seed seeds instances and faults; "
                         "requires --dtype float64")
    ap.add_argument("--ckpt-demo", action="store_true",
                    help="run the preemption-safety acceptance demo "
                         "(tpu_jordan.resilience.ckpt_demo; ISSUE 20, "
                         "docs/RESILIENCE.md): four legs over one "
                         "checkpoint store — a single-device invert "
                         "and a 1D sharded solve each preempted "
                         "mid-sweep by the seeded preempt fault and "
                         "resumed from the last durable superstep "
                         "checkpoint, a resumable LP stream replayed "
                         "to its identical kkt fingerprint trail, and "
                         "a fleet leg whose serving replica is KILLED "
                         "mid-sweep (the router re-queues with a "
                         "ckpt_resume hop) — every resume must "
                         "bit-match the uninterrupted run with zero "
                         "segment compiles, lost work bounded by the "
                         "cadence, and the store ledger must add up "
                         "(written == resumed + discarded + live); "
                         "prints ONE JSON line (exit 2 = silent loss; "
                         "tools/check_ckpt.py validates).  n is the "
                         "problem size, m the block size; --chaos-seed "
                         "seeds fixtures and the preempt schedule; "
                         "runs on a forced 8-device virtual CPU mesh "
                         "when needed")
    ap.add_argument("--ckpt-dir", default=None, metavar="PATH",
                    help="--ckpt-demo: directory for the checkpoint "
                         "store (default: a temp dir deleted after); "
                         "pass a path to inspect the checkpoint files "
                         "and ledger.json afterwards")
    ap.add_argument("--comm-report", default=None, metavar="PATH",
                    help="write the process-wide communication "
                         "snapshot (the last distributed solve's "
                         "per-phase collective accounting + "
                         "reconciliation/drift record and the "
                         "tpu_jordan_comm_* counters) as one JSON "
                         "document on exit (docs/OBSERVABILITY.md)")
    ap.add_argument("--capacity-report", default=None, metavar="PATH",
                    help="write the process-wide capacity snapshot "
                         "(tpu_jordan_capacity_*: resident handles, "
                         "compiled executor lanes, plan cache, "
                         "flight-recorder ring, device live-bytes "
                         "watermark — with high-water marks and the "
                         "per-class created == live + evicted "
                         "reconciliation) as one JSON document on "
                         "exit (docs/OBSERVABILITY.md)")
    ap.add_argument("--rank", type=int, default=32, metavar="K",
                    help="--update-demo: rank of each mutation "
                         "(default 32; the FLOP/latency wins need "
                         "k <= n/8)")
    ap.add_argument("--updates", type=int, default=8, metavar="M",
                    help="--update-demo: mutations per stream "
                         "(default 8; >= 3 so the ledger shows "
                         "refreshed + gated outcomes)")
    ap.add_argument("--replicas", type=int, default=3, metavar="N",
                    help="--fleet-demo/--update-demo: replica slots in "
                         "the pool (default 3; >= 2)")
    ap.add_argument("--kills", type=int, default=2, metavar="K",
                    help="--fleet-demo: seeded replica_kill injections "
                         "(default 2)")
    ap.add_argument("--scaling-floor", type=float, default=None,
                    metavar="X", help="--fleet-demo: minimum "
                         "fleet/single throughput ratio the checker "
                         "enforces (default 0.6 — the shared-device "
                         "in-process floor; pass e.g. 2.5 on parallel "
                         "hardware for the ~Nx claim)")
    ap.add_argument("--serve-requests", type=int, default=64,
                    metavar="R", help="--serve-demo/--chaos-demo/"
                                      "--fleet-demo: concurrent "
                                      "requests to submit (default 64)")
    ap.add_argument("--batch-cap", type=int, default=8, metavar="B",
                    help="--serve-demo/--chaos-demo/--fleet-demo: max "
                         "requests fused per executable launch "
                         "(default 8)")
    ap.add_argument("--max-wait-ms", type=float, default=2.0,
                    metavar="MS", help="--serve-demo/--chaos-demo/"
                                       "--fleet-demo: micro-batcher "
                                       "deadline — how long the oldest "
                                       "request waits for batch-mates "
                                       "(default 2.0)")
    ap.add_argument("--metrics-out", default=None, metavar="PATH",
                    help="write the process-wide tpu_jordan_* metrics "
                         "registry (solves, compiles, plan-cache "
                         "hits/misses, serve counters, latency "
                         "percentiles) as Prometheus text format on "
                         "exit (docs/OBSERVABILITY.md)")
    ap.add_argument("--trace-json", default=None, metavar="PATH",
                    help="record the run's span tree (solve: select/"
                         "load/compile/execute/gather/residual + "
                         "model-attributed hot-loop phases; serve: "
                         "per-batch compile/execute) and write it as "
                         "Chrome trace-event JSON — open in Perfetto "
                         "(ui.perfetto.dev) or chrome://tracing; "
                         "serve/fleet runs add one async lane per "
                         "request_id (the journey view)")
    ap.add_argument("--blackbox-out", default=None, metavar="PATH",
                    help="dump the always-on flight recorder (the "
                         "bounded ring of structured fleet events: "
                         "route/shed/requeue decisions, kills, "
                         "restarts, breaker transitions, recovery "
                         "rungs, injected faults, every per-request "
                         "journey hop) as one JSON document on exit; "
                         "without this flag the dump still happens "
                         "automatically on any exit-2 path "
                         "(docs/OBSERVABILITY.md)")
    ap.add_argument("--slo-report", action="store_true",
                    help="--fleet-demo: embed a multi-window burn-rate "
                         "SLO evaluation (availability per bucket + "
                         "fleet-wide, demo-scaled window pairs) in the "
                         "report, validated by tools/check_slo.py")
    ap.add_argument("--quiet", action="store_true")
    try:
        args = ap.parse_args(argv)
        if args.n <= 0 or args.m <= 0:
            raise ValueError("n and m must be positive")
        w = args.workers
        if (w <= 0 if isinstance(w, int) else w[0] <= 0 or w[1] <= 0):
            raise ValueError("workers must be positive")
        if args.sleep < 0:
            raise ValueError("--sleep must be non-negative")
        if args.serve_requests < 1 or args.batch_cap < 1:
            raise ValueError("--serve-requests/--batch-cap must be >= 1")
        if args.rank < 1 or args.updates < 3:
            raise ValueError("--rank must be >= 1 and --updates >= 3")
        if args.rhs < 1:
            raise ValueError("--rhs must be >= 1")
        if args.max_wait_ms < 0:
            raise ValueError("--max-wait-ms must be non-negative")
    except SystemExit as e:
        if e.code == 0:      # --help / --version are not usage errors
            return 0
        print("usage: python -m tpu_jordan n m [<file>]", file=sys.stderr)
        return 1
    except ValueError:
        # usage error -> exit 1 like the reference (main.cpp:77-85)
        print("usage: python -m tpu_jordan n m [<file>]", file=sys.stderr)
        return 1
    state["blackbox_out"] = args.blackbox_out

    if os.environ.get("JAX_PLATFORMS"):
        # Honor JAX_PLATFORMS even when the interpreter preloaded jax
        # before the CLI ran (e.g. via sitecustomize, which freezes the
        # platform choice before the env var can take effect).  A no-op
        # when they already agree.
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    if args.sleep:
        # The reference's -DSLEEP hook (main.cpp:8,70-72): pause at launch
        # so a debugger can attach to each process before any real work.
        import time

        print(f"pid {os.getpid()} sleeping {args.sleep}s", flush=True)
        time.sleep(args.sleep)

    if args.distributed:
        # Must run before the first backend use so every host process joins
        # the same slice-wide device view (mirrors MPI_Init being argv's
        # first consumer, main.cpp:69).
        from .parallel.mesh import distributed_init

        distributed_init()

    if args.dtype == "float64":
        # fp64 parity path (CPU): JAX demotes to fp32 unless x64 is on.
        import jax

        jax.config.update("jax_enable_x64", True)

    from .driver import SingularMatrixError, UsageError, solve, solve_batch
    from .io import MatrixReadError
    from .parallel.mesh import MeshSizeError
    from .serve.batcher import ServiceClosedError, ServiceOverloadedError

    telemetry = None
    if args.metrics_out or args.trace_json:
        # One span collector for the whole run (ISSUE 4); the metrics
        # registry is process-wide and needs no handle.
        from .obs.spans import Telemetry

        telemetry = Telemetry()
    try:
        # Misapplied-flag discipline (the CLI's own contract): workload
        # flags on the default invert workload are typed usage errors,
        # never silently dropped — a user asking for the SPD fast path
        # or a multi-RHS solve must not get invert numbers.
        if args.workload == "invert" and args.assume != "general":
            raise UsageError("--assume applies to --workload solve "
                             "(the pivot-free SPD fast path)")
        if args.workload == "invert" and args.rhs != 1:
            raise UsageError("--rhs applies to --workload solve/lstsq")
        if not args.update_demo and (args.rank != 32 or args.updates != 8):
            raise UsageError("--rank/--updates apply to --update-demo "
                             "(the resident-inverse update acceptance "
                             "run)")
        if args.ckpt_dir is not None and not args.ckpt_demo:
            raise UsageError("--ckpt-dir applies to --ckpt-demo (the "
                             "preemption-safety acceptance run's "
                             "checkpoint store location)")
        if (args.generator == "crand"
                and jnp.dtype(args.dtype).kind != "c"):
            raise UsageError("--generator crand is complex-valued; a "
                             "real --dtype would silently discard the "
                             "imaginary part (use --dtype complex64)")
        if args.autoscale_demo:
            # Autoscaler demo (ISSUE 18): the fleet-demo restriction
            # shape (single device per replica, deterministic seeded
            # traffic, gathered) and the same 0/1/2 taxonomy — exit 2
            # IS the silent-p99-breach alarm (a tick that saw risk
            # signals while pre-shed stayed off and no capacity action
            # answered it).
            if (args.serve_demo or args.chaos_demo or args.fleet_demo
                    or args.numerics_demo or args.update_demo
                    or args.capacity_demo or args.comm_demo
                    or args.lp_demo or args.work_demo):
                raise UsageError("--autoscale-demo is a distinct mode; "
                                 "pick one demo")
            if args.file is not None or args.workers != 1 or not args.gather:
                raise UsageError(
                    "--autoscale-demo runs single-device replicas "
                    "against its own seeded burst trace; file input, "
                    "--workers and --no-gather do not apply")
            if args.batch > 1 or args.tune or args.group != 0:
                raise UsageError("--autoscale-demo takes no "
                                 "--batch/--tune/--group")
            if args.engine != "auto" or args.refine:
                raise UsageError("--autoscale-demo resolves engines "
                                 "through the cost-only ladder; "
                                 "--engine/--refine do not apply")
            if args.workload != "invert" or args.rhs != 1:
                raise UsageError("--autoscale-demo streams invert "
                                 "requests; --workload/--rhs do not "
                                 "apply")
            if args.numerics != "off":
                raise UsageError("--autoscale-demo's burn-evidence "
                                 "semantics are pinned; --numerics "
                                 "does not apply")
            if args.slo_report or args.plan_cache is not None:
                raise UsageError("--slo-report/--plan-cache do not "
                                 "apply to --autoscale-demo (it builds "
                                 "its own demo-scaled monitor)")
            if args.replicas < 2:
                raise UsageError("--autoscale-demo needs --replicas "
                                 ">= 2 (the scale-up ceiling; the "
                                 "floor is 1)")
            if args.kills != 2 or args.scaling_floor is not None:
                raise UsageError("--kills/--scaling-floor are "
                                 "--fleet-demo flags; the autoscaler "
                                 "demo injects no faults")
            import json as _json

            from .fleet.autoscaler import autoscale_demo

            report = autoscale_demo(
                n=args.n, requests=args.serve_requests, floor=1,
                ceiling=args.replicas, batch_cap=args.batch_cap,
                max_wait_ms=args.max_wait_ms, seed=args.chaos_seed,
                block_size=args.m, dtype=jnp.dtype(args.dtype),
                telemetry=telemetry)
            if args.quiet:
                report.pop("slo_final", None)
            print(_json.dumps(report))
            if report["silent_p99_breach"]:
                print("silent p99 breach: a tick saw risk signals "
                      "with pre-shed off and no capacity action",
                      file=sys.stderr)
                return 2
            return 0
        if args.lp_demo:
            # LP/QP driver demo (ISSUE 17): the update-demo restriction
            # shape (single device, deterministic seeded instances,
            # gathered) and the same 0/1/2 taxonomy — exit 2 IS the
            # silent-divergence alarm (a driver that claims
            # convergence its own iterate residuals cannot re-derive,
            # an unaccounted update, or a chaos run that did not
            # bit-match its fault-free replay).
            if (args.serve_demo or args.chaos_demo or args.fleet_demo
                    or args.numerics_demo or args.update_demo
                    or args.capacity_demo or args.comm_demo
                    or args.work_demo):
                raise UsageError("--lp-demo, --comm-demo, --work-demo, "
                                 "--capacity-demo, --update-demo, "
                                 "--fleet-demo, --chaos-demo, "
                                 "--serve-demo and --numerics-demo "
                                 "are distinct modes; pick one")
            if args.file is not None or args.workers != 1 or not args.gather:
                raise UsageError(
                    "--lp-demo runs on a single device against its "
                    "own seeded LP/QP instances; file input, "
                    "--workers and --no-gather do not apply")
            if args.batch > 1 or args.tune or args.group != 0:
                raise UsageError("--lp-demo takes no "
                                 "--batch/--tune/--group")
            if args.engine != "auto" or args.refine:
                raise UsageError("--lp-demo resolves its lanes "
                                 "through the cost-only ladder; "
                                 "--engine/--refine do not apply")
            if args.workload != "invert" or args.rhs != 1:
                raise UsageError("--lp-demo streams its own resident-"
                                 "invert + update + solve mix; "
                                 "--workload/--rhs do not apply")
            if args.numerics != "off":
                raise UsageError("--lp-demo's convergence "
                                 "re-derivation semantics are pinned; "
                                 "--numerics does not apply")
            if args.slo_report or args.plan_cache is not None:
                raise UsageError("--slo-report/--plan-cache do not "
                                 "apply to --lp-demo")
            if args.serve_requests != 64 or args.max_wait_ms != 2.0:
                raise UsageError("--lp-demo issues the drivers' own "
                                 "sequential request stream; "
                                 "--serve-requests/--max-wait-ms do "
                                 "not apply (--batch-cap IS honored: "
                                 "it sizes the batched update lane)")
            if args.scaling_floor is not None:
                raise UsageError("--scaling-floor is a --fleet-demo "
                                 "flag (the throughput-ratio floor); "
                                 "--lp-demo measures batched-lane "
                                 "amortization instead")
            if args.replicas < 2:
                raise UsageError("--lp-demo needs --replicas >= 2")
            if args.kills < 1:
                raise UsageError("--lp-demo needs --kills >= 1")
            if args.batch_cap < 2:
                raise UsageError("--lp-demo's batched update lanes "
                                 "measure amortization at occupancy "
                                 "> 1; --batch-cap must be >= 2")
            if args.dtype != "float64":
                raise UsageError("--lp-demo iterates Bland pricing / "
                                 "active-set multipliers on the "
                                 "resident inverse; float32 "
                                 "reduced-cost noise makes the "
                                 "termination tests ill-posed — pass "
                                 "--dtype float64")
            import json as _json

            from .lpqp.demo import lp_demo

            report = lp_demo(n=args.n, block_size=args.m,
                             seed=args.chaos_seed,
                             replicas=args.replicas, kills=args.kills,
                             batch_cap=args.batch_cap,
                             dtype=jnp.dtype(args.dtype),
                             telemetry=telemetry)
            if args.quiet:
                report["chaos"]["faults"].pop("log", None)
            print(_json.dumps(report))
            if report["silent_divergence"]:
                print(f"silent divergence: "
                      f"errors={report['errors']}, "
                      f"mismatches={len(report['mismatches'])}",
                      file=sys.stderr)
                return 2
            return 0
        if args.comm_demo:
            # Comm demo (ISSUE 14): the capacity-demo restriction
            # shape (fixed internal legs, deterministic fixtures) and
            # the same 0/1/2 taxonomy — exit 2 IS the
            # unaccounted-collective / silent-drift alarm.
            if (args.serve_demo or args.chaos_demo or args.fleet_demo
                    or args.numerics_demo or args.update_demo
                    or args.capacity_demo or args.work_demo):
                raise UsageError("--comm-demo, --work-demo, "
                                 "--capacity-demo, "
                                 "--update-demo, --fleet-demo, "
                                 "--chaos-demo, --serve-demo and "
                                 "--numerics-demo are distinct modes; "
                                 "pick one")
            if args.file is not None or args.workers != 1 or not args.gather:
                raise UsageError(
                    "--comm-demo builds its own 1D/2D meshes (forced "
                    "virtual CPU devices when needed); file input, "
                    "--workers and --no-gather do not apply")
            if args.batch > 1 or args.tune or args.group != 0:
                raise UsageError("--comm-demo takes no "
                                 "--batch/--tune/--group")
            if args.engine != "auto" or args.refine:
                raise UsageError("--comm-demo runs a fixed engine-leg "
                                 "set (inplace/grouped/swapfree, both "
                                 "layouts); --engine/--refine do not "
                                 "apply")
            if args.workload != "invert":
                raise UsageError("--comm-demo reconciles the "
                                 "distributed invert engines; "
                                 "--workload does not apply")
            if args.numerics != "off":
                raise UsageError("--comm-demo's reconciliation "
                                 "semantics are pinned; --numerics "
                                 "does not apply")
            if args.slo_report or args.plan_cache is not None:
                raise UsageError("--slo-report/--plan-cache do not "
                                 "apply to --comm-demo")
            if (args.serve_requests != 64 or args.batch_cap != 8
                    or args.max_wait_ms != 2.0):
                raise UsageError("--comm-demo runs driver solves, not "
                                 "the service; --serve-requests/"
                                 "--batch-cap/--max-wait-ms do not "
                                 "apply")
            if (args.replicas != 3 or args.kills != 2
                    or args.scaling_floor is not None):
                raise UsageError("--replicas/--kills/--scaling-floor "
                                 "are --fleet-demo/--update-demo "
                                 "flags; --comm-demo runs one process")
            import json as _json

            from .obs.comm import comm_demo

            # --dtype / --generator are honored, not dropped: the
            # inventories' byte figures scale with dtype width, so a
            # float64 demo reconciles float64 inventories (complex is
            # a typed refusal inside comm_demo — distributed engines
            # are real-dtype).
            report = comm_demo(n=args.n, block_size=args.m,
                               seed=args.chaos_seed,
                               dtype=jnp.dtype(args.dtype),
                               generator=args.generator)
            print(_json.dumps(report))
            if report["silent_comm"]:
                print(f"silent communication accounting violation: "
                      f"unreconciled={report['unreconciled']}, "
                      f"mismatches={len(report['mismatches'])}, "
                      f"drift_events={report['drift_events']}",
                      file=sys.stderr)
                return 2
            return 0
        if args.work_demo:
            # Work demo (ISSUE 19): the comm-demo restriction shape
            # (fixed internal legs, deterministic fixtures) and the
            # same 0/1/2 taxonomy — exit 2 IS the unaccounted-work /
            # unsupported-straggler-verdict alarm.
            if (args.serve_demo or args.chaos_demo or args.fleet_demo
                    or args.numerics_demo or args.update_demo
                    or args.capacity_demo):
                raise UsageError("--work-demo, --capacity-demo, "
                                 "--update-demo, --fleet-demo, "
                                 "--chaos-demo, --serve-demo and "
                                 "--numerics-demo are distinct modes; "
                                 "pick one")
            if args.file is not None or args.workers != 1 or not args.gather:
                raise UsageError(
                    "--work-demo builds its own 1D/2D meshes (forced "
                    "virtual CPU devices when needed); file input, "
                    "--workers and --no-gather do not apply")
            if args.batch > 1 or args.tune or args.group != 0:
                raise UsageError("--work-demo takes no "
                                 "--batch/--tune/--group")
            if args.engine != "auto" or args.refine:
                raise UsageError("--work-demo runs a fixed engine-leg "
                                 "set (inplace/swapfree/solve_sharded, "
                                 "both layouts); --engine/--refine do "
                                 "not apply")
            if args.workload != "invert" or args.rhs != 1:
                raise UsageError("--work-demo accounts both workloads "
                                 "on its own legs; --workload/--rhs do "
                                 "not apply")
            if args.numerics != "off":
                raise UsageError("--work-demo's reconciliation "
                                 "semantics are pinned; --numerics "
                                 "does not apply")
            if args.slo_report or args.plan_cache is not None:
                raise UsageError("--slo-report/--plan-cache do not "
                                 "apply to --work-demo")
            if (args.serve_requests != 64 or args.batch_cap != 8
                    or args.max_wait_ms != 2.0):
                raise UsageError("--work-demo runs driver solves and "
                                 "synthetic fleet stats, not the "
                                 "service; --serve-requests/"
                                 "--batch-cap/--max-wait-ms do not "
                                 "apply")
            if (args.replicas != 3 or args.kills != 2
                    or args.scaling_floor is not None):
                raise UsageError("--replicas/--kills/--scaling-floor "
                                 "are --fleet-demo/--update-demo "
                                 "flags; --work-demo runs one process")
            import json as _json

            from .obs.work import work_demo

            # --dtype / --generator are honored, not dropped (complex
            # is a typed refusal inside work_demo — the distributed
            # engines are real-dtype).
            report = work_demo(n=args.n, block_size=args.m,
                               seed=args.chaos_seed,
                               dtype=jnp.dtype(args.dtype),
                               generator=args.generator)
            print(_json.dumps(report))
            if report["silent_work"]:
                print(f"silent work accounting violation: "
                      f"unaccounted={report['unaccounted']}, "
                      f"xla_unreconciled={report['xla_unreconciled']}, "
                      f"verdict_wrong={report['verdict_wrong']}",
                      file=sys.stderr)
                return 2
            return 0
        if args.ckpt_demo:
            # Checkpoint demo (ISSUE 20): the work-demo restriction
            # shape (fixed internal legs, deterministic fixtures and
            # preempt schedules) and the same 0/1/2 taxonomy — exit 2
            # IS the silent-loss alarm (a divergent resume, a durable
            # checkpoint silently ignored, or a ledger that does not
            # add up).
            if (args.serve_demo or args.chaos_demo or args.fleet_demo
                    or args.numerics_demo or args.update_demo
                    or args.capacity_demo or args.comm_demo
                    or args.work_demo or args.lp_demo):
                raise UsageError("--ckpt-demo, --lp-demo, --work-demo, "
                                 "--comm-demo, --capacity-demo, "
                                 "--update-demo, --fleet-demo, "
                                 "--chaos-demo, --serve-demo and "
                                 "--numerics-demo are distinct modes; "
                                 "pick one")
            if args.file is not None or args.workers != 1 or not args.gather:
                raise UsageError(
                    "--ckpt-demo builds its own 1D mesh and fleet "
                    "(forced virtual CPU devices when needed); file "
                    "input, --workers and --no-gather do not apply")
            if args.batch > 1 or args.tune or args.group != 0:
                raise UsageError("--ckpt-demo takes no "
                                 "--batch/--tune/--group")
            if args.engine != "auto" or args.refine:
                raise UsageError("--ckpt-demo runs a fixed engine-leg "
                                 "set (fori single-device and 1D "
                                 "sharded); --engine/--refine do not "
                                 "apply")
            if args.workload != "invert":
                raise UsageError("--ckpt-demo checkpoints both "
                                 "workloads on its own legs; "
                                 "--workload does not apply")
            if args.numerics != "off":
                raise UsageError("--ckpt-demo's bit-match semantics "
                                 "are pinned; --numerics does not "
                                 "apply")
            if args.slo_report or args.plan_cache is not None:
                raise UsageError("--slo-report/--plan-cache do not "
                                 "apply to --ckpt-demo")
            if (args.serve_requests != 64 or args.batch_cap != 8
                    or args.max_wait_ms != 2.0):
                raise UsageError("--ckpt-demo runs checkpointed "
                                 "sweeps, not the batched service; "
                                 "--serve-requests/--batch-cap/"
                                 "--max-wait-ms do not apply")
            if (args.replicas != 3 or args.kills != 2
                    or args.scaling_floor is not None):
                raise UsageError("--replicas/--kills/--scaling-floor "
                                 "are --fleet-demo/--update-demo "
                                 "flags; --ckpt-demo's kill leg is "
                                 "fixed at one kill on a 2-replica "
                                 "fleet")
            if jnp.dtype(args.dtype).kind == "c":
                raise UsageError("--ckpt-demo checkpoints the "
                                 "DISTRIBUTED engines and complex "
                                 "dtypes run single-device; use a "
                                 "real dtype")
            import json as _json

            from .resilience.ckpt_demo import ckpt_demo

            report = ckpt_demo(n=args.n, block_size=args.m,
                               seed=args.chaos_seed,
                               ckpt_dir=args.ckpt_dir)
            if args.quiet:
                # The checker needs the legs, ledger and blackbox
                # slice; nothing to trim beyond per-event noise.
                report["blackbox"]["events"] = [
                    e for e in report["blackbox"]["events"]
                    if str(e.get("kind", "")).startswith(
                        ("ckpt_", "fault_", "replica_"))]
            print(_json.dumps(report))
            if report["silent_loss"]:
                print(f"silent checkpoint loss: legs="
                      f"{ {k: v['bit_match'] for k, v in report['legs'].items()} }, "
                      f"ledger={report['ledger']}", file=sys.stderr)
                return 2
            return 0
        if args.capacity_demo:
            # Capacity demo (ISSUE 13): the numerics-demo restriction
            # shape (single device, deterministic seeded fixtures,
            # gathered) and the same 0/1/2 taxonomy — exit 2 IS the
            # unmetered-residency alarm (a byte class whose ledger
            # does not reconcile, or a budget eviction with no
            # recorded budget event).
            if (args.serve_demo or args.chaos_demo or args.fleet_demo
                    or args.numerics_demo or args.update_demo):
                raise UsageError("--capacity-demo, --update-demo, "
                                 "--fleet-demo, --chaos-demo, "
                                 "--serve-demo and --numerics-demo are "
                                 "distinct modes; pick one")
            if args.file is not None or args.workers != 1 or not args.gather:
                raise UsageError(
                    "--capacity-demo runs on a single device (gathered "
                    "output, deterministic seeded fixtures)")
            if args.batch > 1 or args.tune or args.group != 0:
                raise UsageError("--capacity-demo takes no "
                                 "--batch/--tune/--group")
            if args.workload != "invert":
                raise UsageError("--capacity-demo streams resident-"
                                 "invert + update requests; --workload "
                                 "does not apply")
            if args.numerics != "off":
                raise UsageError("--capacity-demo's ledger semantics "
                                 "are pinned; --numerics does not "
                                 "apply")
            if args.slo_report:
                raise UsageError("--slo-report is a --fleet-demo leg "
                                 "(the burn-rate monitor evaluates the "
                                 "fleet's request-outcome series)")
            if args.plan_cache is not None:
                raise UsageError("--capacity-demo resolves its lanes "
                                 "through the cost-only ladder; "
                                 "--plan-cache does not apply")
            if (args.serve_requests != 64 or args.batch_cap != 8
                    or args.max_wait_ms != 2.0):
                raise UsageError("--capacity-demo streams its own "
                                 "fixed resident-invert/update mix "
                                 "(cap-1 lanes); --serve-requests/"
                                 "--batch-cap/--max-wait-ms do not "
                                 "apply")
            if (args.replicas != 3 or args.kills != 2
                    or args.scaling_floor is not None):
                raise UsageError("--replicas/--kills/--scaling-floor "
                                 "are --fleet-demo/--update-demo "
                                 "flags; --capacity-demo runs one "
                                 "service under a handle budget")
            import json as _json

            from .obs.capacity import capacity_demo

            report = capacity_demo(n=args.n, block_size=args.m,
                                   seed=args.chaos_seed,
                                   dtype=jnp.dtype(args.dtype))
            if args.quiet:
                # The checker needs the ledger and the blackbox slice;
                # the per-handle numerics snapshot is operator color.
                report.pop("handles", None)
            print(_json.dumps(report))
            if report["silent_capacity"]:
                print(f"silent capacity violation: unmetered="
                      f"{report['unmetered_components']}, "
                      f"budget_evictions={report['budget_evictions']} "
                      f"vs {len(report['evictions'])} recorded "
                      f"events", file=sys.stderr)
                return 2
            return 0
        if args.update_demo:
            # Update demo (ISSUE 12): the fleet-demo restriction shape
            # (single device, deterministic seeded fixtures, gathered)
            # and the same 0/1/2 taxonomy — exit 2 IS the
            # silently-stale-inverse alarm (a resident inverse that
            # diverged from the fault-free replay, failed the gate
            # against a from-scratch solve of the mutated matrix
            # without a typed outcome, or an unaccounted update).
            if (args.serve_demo or args.chaos_demo or args.fleet_demo
                    or args.numerics_demo):
                raise UsageError("--update-demo, --fleet-demo, "
                                 "--chaos-demo, --serve-demo and "
                                 "--numerics-demo are distinct modes; "
                                 "pick one")
            if args.file is not None or args.workers != 1 or not args.gather:
                raise UsageError(
                    "--update-demo runs on a single device (gathered "
                    "output, deterministic seeded fixtures)")
            if args.batch > 1 or args.tune:
                raise UsageError("--update-demo takes no --batch/--tune")
            if args.group != 0 or args.engine == "swapfree":
                raise UsageError("--update-demo engines are "
                                 "single-device (auto resolution); "
                                 "--group does not apply")
            if args.workload != "invert":
                raise UsageError("--update-demo streams resident-invert"
                                 " + update requests; --workload does "
                                 "not apply")
            if args.numerics != "off":
                raise UsageError("--update-demo's replay-compare "
                                 "semantics are pinned; --numerics "
                                 "does not apply")
            if args.slo_report:
                raise UsageError("--slo-report is a --fleet-demo leg "
                                 "(the burn-rate monitor evaluates the "
                                 "fleet's request-outcome series)")
            if (args.serve_requests != 64 or args.batch_cap != 8
                    or args.max_wait_ms != 2.0):
                raise UsageError("--update-demo streams --updates "
                                 "sequential mutations (cap-1 lanes); "
                                 "--serve-requests/--batch-cap/"
                                 "--max-wait-ms do not apply")
            if args.plan_cache is not None or args.scaling_floor is not None:
                raise UsageError("--update-demo resolves its lanes "
                                 "through the cost-only ladder and "
                                 "measures update-vs-reinvert latency "
                                 "directly; --plan-cache/"
                                 "--scaling-floor do not apply")
            if args.replicas < 2:
                raise UsageError("--update-demo needs --replicas >= 2")
            if args.kills < 1:
                raise UsageError("--update-demo needs --kills >= 1")
            if args.rank > args.n // 8:
                raise UsageError("--update-demo needs --rank <= n/8 "
                                 "(the documented regime where the "
                                 "update executable's FLOPs beat the "
                                 "fresh invert's)")
            import json as _json

            from .serve import update_demo

            report = update_demo(
                n=args.n, block_size=args.m, rank=args.rank,
                updates=args.updates, replicas=args.replicas,
                kills=args.kills, seed=args.chaos_seed,
                dtype=jnp.dtype(args.dtype), telemetry=telemetry)
            if args.quiet:
                report["chaos"]["faults"].pop("log", None)
            print(_json.dumps(report))
            if report["silent_stale"]:
                print(f"silently stale resident inverse: "
                      f"{len(report['mismatches'])} mismatches, "
                      f"gate_passes="
                      f"{report['verification']['gate_passes']}",
                      file=sys.stderr)
                return 2
            return 0
        if args.fleet_demo:
            # Fleet demo: the --chaos-demo restrictions (single device,
            # deterministic fixtures, gathered) and the same 0/1/2
            # taxonomy — exit 2 IS the silent-loss alarm (a response
            # that neither bit-matched the fault-free replay nor
            # carried a typed error, or a request the ledger lost).
            if args.serve_demo or args.chaos_demo or args.numerics_demo:
                raise UsageError("--fleet-demo, --chaos-demo, "
                                 "--serve-demo and --numerics-demo are "
                                 "distinct modes; pick one")
            if args.numerics != "off":
                raise UsageError("--fleet-demo's replay-compare "
                                 "semantics are pinned; --numerics "
                                 "does not apply (use --serve-demo "
                                 "--numerics summary, or solve with "
                                 "--numerics)")
            if args.workload != "invert":
                raise UsageError("--fleet-demo streams invert "
                                 "requests; --workload does not apply")
            if args.file is not None or args.workers != 1 or not args.gather:
                raise UsageError(
                    "--fleet-demo runs on a single device (gathered "
                    "output, deterministic built-in fixtures)")
            if args.batch > 1 or args.tune:
                raise UsageError("--fleet-demo takes no --batch/--tune")
            if args.group != 0 or args.engine == "swapfree":
                raise UsageError("--fleet-demo engines are single-device "
                                 "(auto resolution); --group does not "
                                 "apply")
            if args.replicas < 2:
                raise UsageError("--fleet-demo needs --replicas >= 2")
            if args.kills < 1:
                raise UsageError("--fleet-demo needs --kills >= 1")
            import json as _json

            from .fleet import fleet_demo

            report = fleet_demo(
                n=args.n, replicas=args.replicas,
                requests=args.serve_requests, batch_cap=args.batch_cap,
                max_wait_ms=args.max_wait_ms, kills=args.kills,
                seed=args.chaos_seed, block_size=args.m,
                dtype=jnp.dtype(args.dtype), plan_cache=args.plan_cache,
                scaling_floor=args.scaling_floor, telemetry=telemetry,
                slo_report=args.slo_report)
            if args.quiet:
                report["chaos"]["faults"].pop("log", None)
            print(_json.dumps(report))
            if report["silent_loss"]:
                print(f"silent loss under replica_kill chaos: "
                      f"{len(report['mismatches'])} mismatches, "
                      f"ledger {report['ledger']}", file=sys.stderr)
                return 2
            return 0
        if args.slo_report:
            raise UsageError("--slo-report is a --fleet-demo leg "
                             "(the burn-rate monitor evaluates the "
                             "fleet's request-outcome series)")
        if args.numerics_demo:
            # Numerics demo (ISSUE 10): the same 0/1/2 taxonomy as the
            # chaos/fleet demos — exit 2 IS the unexplained-rung alarm
            # (a recovery rung with no causally preceding
            # numerics_spike event in the flight recorder).
            if args.serve_demo or args.chaos_demo:
                raise UsageError("--numerics-demo, --chaos-demo and "
                                 "--serve-demo are distinct modes; "
                                 "pick one")
            if args.file is not None or args.workers != 1 or not args.gather:
                raise UsageError(
                    "--numerics-demo runs on a single device (gathered "
                    "output, seeded built-in ill-conditioned fixture)")
            if args.batch > 1 or args.tune or args.group != 0:
                raise UsageError("--numerics-demo takes no "
                                 "--batch/--tune/--group")
            import json as _json

            from .obs.numerics import numerics_demo

            report = numerics_demo(n=args.n, block_size=args.m,
                                   seed=args.chaos_seed,
                                   workload=args.workload)
            print(_json.dumps(report))
            if report["silent_rung"]:
                print(f"unexplained degradation rung(s): "
                      f"{report['unexplained_rungs']} — no causally "
                      f"preceding numerics_spike", file=sys.stderr)
                return 2
            return 0
        if args.chaos_demo:
            # Chaos demo: same restrictions as --serve-demo (single
            # device, generator-free deterministic fixtures, gathered),
            # same 0/1/2 taxonomy — exit 2 IS the silent-corruption
            # alarm (a response that neither bit-matched the fault-free
            # replay nor carried a typed error, or an unaccounted
            # injected fault).
            if args.serve_demo:
                raise UsageError("--chaos-demo and --serve-demo are "
                                 "distinct modes; pick one")
            if args.file is not None or args.workers != 1 or not args.gather:
                raise UsageError(
                    "--chaos-demo runs on a single device (gathered "
                    "output, deterministic built-in fixtures)")
            if args.batch > 1 or args.tune:
                raise UsageError("--chaos-demo takes no --batch/--tune")
            if args.numerics != "off":
                raise UsageError("--chaos-demo's replay-compare "
                                 "semantics are pinned; --numerics "
                                 "does not apply (use --serve-demo "
                                 "--numerics summary, or solve with "
                                 "--numerics)")
            if args.group != 0 or args.engine == "swapfree":
                raise UsageError("--chaos-demo engines are single-device "
                                 "(auto resolution); --group does not "
                                 "apply")
            if args.workload != "invert":
                raise UsageError("--chaos-demo streams invert "
                                 "requests; --workload does not apply")
            import json as _json

            from .serve import chaos_demo

            report = chaos_demo(
                n=args.n, block_size=args.m, requests=args.serve_requests,
                batch_cap=args.batch_cap, max_wait_ms=args.max_wait_ms,
                seed=args.chaos_seed, dtype=jnp.dtype(args.dtype),
                plan_cache=args.plan_cache, telemetry=telemetry)
            if args.quiet:
                report["faults"].pop("log", None)
            print(_json.dumps(report))
            if report["silent_corruption"]:
                print(f"silent corruption under chaos: "
                      f"{len(report['mismatches'])} mismatches, "
                      f"{report['accounting']['unaccounted']} "
                      f"unaccounted faults", file=sys.stderr)
                return 2
            return 0
        if args.serve_demo:
            # The serving demo: single-device, generator input,
            # gathered output — same shape of restrictions as --batch
            # (exit 1 on bad combos, main.cpp:77-85 taxonomy).
            if args.file is not None or not args.gather:
                raise UsageError(
                    "--serve-demo requires generator input (gathered "
                    "output); --workers W serves the LARGEST size "
                    "through a W-device mesh lane (ISSUE 18)")
            if args.batch > 1:
                raise UsageError("--serve-demo and --batch are distinct "
                                 "modes; pick one")
            if args.tune:
                raise UsageError("--serve-demo resolves engines through "
                                 "the cost-only ladder (optionally a "
                                 "--plan-cache); --tune does not apply")
            if args.group != 0 or args.engine == "swapfree":
                raise UsageError("--serve-demo engines are single-device "
                                 "(auto/inplace/grouped/augmented); "
                                 "--group does not apply")
            if args.workload != "invert":
                raise UsageError("--serve-demo streams invert "
                                 "requests; submit(a, b) is the solve "
                                 "serve surface (docs/WORKLOADS.md)")
            import json as _json

            from .serve import serve_demo

            report = serve_demo(
                n=args.n, block_size=args.m,
                requests=args.serve_requests, batch_cap=args.batch_cap,
                max_wait_ms=args.max_wait_ms, engine=args.engine,
                plan_cache=args.plan_cache,
                dtype=jnp.dtype(args.dtype), generator=args.generator,
                telemetry=telemetry, numerics=args.numerics,
                workers=args.workers)
            if args.quiet:
                report.pop("stats", None)
            print(_json.dumps(report))
            if report["singular"]:
                # Same taxonomy as the one-shot path: a singular solve
                # is a runtime failure, exit 2 (main.cpp:435-437).  The
                # prose goes to stderr — stdout stays the documented
                # single JSON line.
                print(f"singular matrix ({report['singular']} requests "
                      f"flagged)", file=sys.stderr)
                return 2
            return 0
        if args.workload != "invert":
            # The solve workloads (ISSUE 11, docs/WORKLOADS.md): the
            # --batch-style restriction shape — single device, gathered,
            # engine resolved through the workload-scoped auto ladder
            # (the --engine invert vocabulary does not apply).
            if args.serve_demo or args.batch > 1:
                raise UsageError("--workload solve/lstsq and "
                                 "--serve-demo/--batch are distinct "
                                 "modes; pick one (the service accepts "
                                 "solve requests via submit(a, b))")
            if args.workload == "lstsq" and (args.workers != 1
                                             or not args.gather):
                raise UsageError("--workload lstsq runs on a single "
                                 "device (gathered output); --workload "
                                 "solve is the distributed one")
            if args.engine != "auto" or args.group != 0:
                raise UsageError("--workload solve/lstsq resolve their "
                                 "engine through the workload-scoped "
                                 "auto ladder (optionally --tune/"
                                 "--plan-cache); --engine/--group name "
                                 "invert engines and do not apply")
            if args.refine:
                raise UsageError("--refine is Newton-Schulz on an "
                                 "INVERSE; the solve workloads gate on "
                                 "||AX - B|| and recover via their own "
                                 "ladder (attach a policy)")
            from .io import read_matrix_file
            from .linalg import lstsq as _lstsq
            from .linalg import solve_system as _solve_system
            from .ops import generate

            dtype = jnp.dtype(args.dtype)
            rgen = "crand" if dtype.kind == "c" else "rand"
            bmat = generate(rgen, (args.n, args.rhs), dtype,
                            row_offset=args.n)
            if args.workload == "solve":
                if args.file is not None:
                    amat = read_matrix_file(args.file, args.n, dtype)
                else:
                    amat = generate(args.generator, (args.n, args.n),
                                    dtype)
                # --workers routes the distributed [A | B] elimination
                # (ISSUE 15) through engine="auto" exactly like invert:
                # the workload-scoped tuner resolves distributed points
                # to solve_sharded, Nr > MAX_UNROLL_NR single-device
                # points to the fori engine.
                result = _solve_system(
                    amat, bmat, block_size=args.m, dtype=dtype,
                    assume=args.assume, engine="auto",
                    workers=args.workers, gather=args.gather,
                    tune=args.tune,
                    plan_cache=args.plan_cache, telemetry=telemetry,
                    numerics=args.numerics, verbose=not args.quiet)
            else:
                if args.file is not None:
                    raise UsageError("--workload lstsq is "
                                     "generator-input only (the matrix "
                                     "file format is square)")
                if args.assume != "general":
                    raise UsageError("--assume applies to --workload "
                                     "solve (lstsq's normal equations "
                                     "are SPD by construction)")
                cols = max(1, args.n // 2)
                amat = generate(args.generator, (args.n, cols), dtype)
                res = _lstsq(amat, bmat, block_size=args.m, dtype=dtype,
                             engine="auto", tune=args.tune,
                             plan_cache=args.plan_cache,
                             telemetry=telemetry, numerics=args.numerics,
                             verbose=not args.quiet)
                if res.rank_deficient:
                    print("rank deficient (singular normal equations)",
                          file=sys.stderr)
                    return 2
                result = res.inner
                result.plan = res.plan
        elif args.batch > 1:
            if args.file is not None or args.workers != 1 or not args.gather:
                raise UsageError(
                    "--batch requires generator input on a single device "
                    "(gathered output)")
            if args.engine != "auto" or args.group != 0:
                # Batched grouped is a measured negative result
                # (benchmarks/PHASES.md): vmapped eager side updates cost
                # more than the thin-matmul penalty they remove at
                # batch-relevant n.
                raise UsageError("--batch uses the batched engine; "
                                 "--engine/--group do not apply")
            if args.tune or args.plan_cache:
                raise UsageError("--batch uses the batched engine; "
                                 "--tune/--plan-cache do not apply")
            if args.numerics != "off":
                raise UsageError("--numerics applies to single solves "
                                 "(the batched engine is one fused "
                                 "vmapped executable — no per-superstep "
                                 "host visibility)")
            result = solve_batch(
                n=args.n,
                block_size=args.m,
                batch=args.batch,
                generator=args.generator,
                dtype=jnp.dtype(args.dtype),
                refine=args.refine,
                precision=args.precision,
                verbose=not args.quiet,
                telemetry=telemetry,
            )
        else:
            result = solve(
                n=args.n,
                block_size=args.m,
                file=args.file,
                generator=args.generator,
                dtype=jnp.dtype(args.dtype),
                refine=args.refine,
                workers=args.workers,
                verbose=not args.quiet,
                gather=args.gather,
                precision=args.precision,
                engine=args.engine,
                group=args.group,
                tune=args.tune,
                plan_cache=args.plan_cache,
                telemetry=telemetry,
                numerics=args.numerics,
            )
    except FileNotFoundError:
        print(f"cannot open {args.file}")
        return 2
    except MatrixReadError:
        print(f"cannot read {args.file}")
        return 2
    except SingularMatrixError:
        print("singular matrix")
        return 2
    except MeshSizeError as e:
        # --workers exceeding the device count: the analog of mpirun -np
        # failing to launch — a runtime error, not a crash.
        print(e, file=sys.stderr)
        return 2
    except (ServiceOverloadedError, ServiceClosedError) as e:
        # Serving runtime failures (backpressure/shutdown races in the
        # demo) are runtime errors like a failed launch, not usage.
        print(e, file=sys.stderr)
        return 2
    except UsageError as e:
        # invalid flag combinations (e.g. --no-gather on the
        # single-device path) -> exit 1 (main.cpp:77-85).
        print(e, file=sys.stderr)
        return 1
    finally:
        _write_telemetry(args.metrics_out, args.trace_json, telemetry)
        _write_capacity(args.capacity_report)
        _write_comm(args.comm_report)
        _write_work(args.work_report)
    if args.quiet:
        print(f"glob_time: {result.elapsed:.2f}")
        print(f"residual: {result.residual:e}")
    elif result.plan is not None:
        # Surface what the autotuner ran (and from which ladder rung) so
        # --engine auto is never a black box.
        print(f"engine: {result.engine} "
              f"(auto, {result.plan.source} plan)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
