"""Preemption-safe execution: superstep checkpoint/resume (ISSUE 20).

The paper's elimination is an all-or-nothing monolith: a worker lost at
superstep 37 of 64 throws away 37 supersteps (``Jordan``,
main.cpp:953-1204 has no recovery path at all — MPI aborts).  On
preemptible pods that is THE availability gap.  This module adds the
recover-without-recompute discipline:

* The elimination state is **RNG-free and closed by construction**:
  the padded working set ([A|I] for inverts, (A, X) for solves), the
  ``singular`` evidence accumulated so far, the (Nr,) int32 row-swap
  record, and the superstep index ``t`` fully determine every later
  superstep.  Snapshotting exactly that tuple at a cadence boundary
  and re-entering at step ``t`` replays the identical arithmetic.
* The engines gained **segment executables** (``solve_segment*``,
  ``invert_segment*`` in ops/linalg; ``*_segment`` entries in the 1D/2D
  parallel modules): supersteps [t0, t1) as one jitted call, carry in /
  carry out, the unscramble epilogue moved to its own finalize
  executable.  Each segment replays the monolithic per-step arithmetic
  and collective schedule verbatim, so the concatenation of segments
  — and therefore a resume — **bit-matches the uninterrupted run**
  (the ISSUE 16 reordered-arithmetic discipline, pinned by
  tests/test_checkpoint.py and ``tools/check_ckpt.py``).
* Snapshots go to a host-side :class:`CheckpointStore`: one
  self-describing file per run (magic + JSON header + npz payload),
  **content-checksummed** (sha256 over the payload) and **atomic**
  (tmp + ``os.replace``, the plan-cache idiom) — a torn write can
  never be mistaken for a checkpoint.  A corrupt, truncated, or
  key-mismatched entry is a **typed refusal**
  (:class:`CheckpointCorruptError` / :class:`CheckpointMismatchError`),
  never a silent resume and never a silent from-scratch recompute.
* The ledger invariant ``written == resumed + discarded + live`` is
  maintained per store and persisted (``ledger.json``): every
  checkpoint token is eventually consumed by exactly one of resume,
  supersede/complete-discard, or corrupt-quarantine (which counts both
  ``corrupt`` and ``discarded``), or it is still live on disk.

Checkpoint lifecycle (docs/RESILIENCE.md has the operator table)::

    write (cadence boundary) --> [live on disk] --+--> resumed
                                                  +--> discarded
                                                  |    (superseded /
                                                  |     run complete)
                                                  +--> corrupt
                                                       (quarantined,
                                                        typed refusal)

Lost work is bounded by the cadence: a ``preempt`` fault (the seeded
chaos point in :mod:`.faults`) fires at segment boundaries AFTER the
previous boundary's checkpoint is durable, so at most ``cadence``
supersteps are ever recomputed.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import tempfile
import threading
from dataclasses import asdict, dataclass

import numpy as np

from ..obs import metrics as _obs_metrics
from ..obs import recorder as _recorder
from . import faults as _faults

_MAGIC = b"TJCKPT1\n"
FORMAT_VERSION = 1

#: Engine flavors the checkpoint runners accept, per topology.  The
#: rest are typed refusals with the reason in the message:
#:   - spd/cholesky-style fast paths have no pivot probe, so there is
#:     no pivot record to snapshot and no singularity evidence to
#:     carry across a resume;
#:   - swapfree/lookahead carry engine-internal pipeline state (alive
#:     masks, probe-ahead panels) that is not part of the closed
#:     (state, swaps, t) tuple;
#:   - pallas grouped flavors fuse across steps.
SINGLE_ENGINES = ("unrolled", "fori", "grouped")
DIST_ENGINES = ("unrolled", "fori")

_M_WRITTEN = _obs_metrics.counter(
    "tpu_jordan_ckpt_written_total",
    "superstep checkpoints durably written (atomic rename complete)")
_M_RESUMED = _obs_metrics.counter(
    "tpu_jordan_ckpt_resumed_total",
    "checkpoints consumed by a resume (key-matched, checksum-verified)")
_M_CORRUPT = _obs_metrics.counter(
    "tpu_jordan_ckpt_corrupt_total",
    "checkpoint loads refused: bad magic/header/truncation/checksum")
_M_DISCARDED = _obs_metrics.counter(
    "tpu_jordan_ckpt_discarded_total",
    "checkpoint tokens discarded (superseded, run complete, or "
    "corrupt-quarantined)")


# ---------------------------------------------------------------------
# Typed failures
# ---------------------------------------------------------------------


class CheckpointError(RuntimeError):
    """Base of the checkpoint/resume failure taxonomy."""


class CheckpointNotFoundError(CheckpointError):
    """``resume_from=`` named a run with no durable checkpoint — e.g.
    cadence > Nr wrote none.  A resume NEVER silently degrades to a
    from-scratch run; the caller must ask for one explicitly."""


class CheckpointCorruptError(CheckpointError):
    """The on-disk entry failed the magic/header/checksum gates.  The
    file is quarantined (renamed ``*.corrupt``) and its token counted
    discarded — resuming from it is refused, never attempted."""


class CheckpointMismatchError(CheckpointError):
    """The stored key does not describe this call: mismatched
    (workload, engine, topology, n, m, Nr, dtype, nrhs) — resuming a
    float64 2D solve from a float32 1D invert's bytes would be silent
    corruption, so it is a typed refusal instead."""


class CheckpointUnsupportedError(CheckpointError):
    """This engine/dtype flavor has no checkpointable closed state
    (SPD fast path, swapfree/lookahead pipelines, sub-fp32 storage,
    complex distributed flavors that do not exist yet)."""


class PreemptedError(CheckpointError):
    """The chip went away mid-sweep (the seeded ``preempt`` fault, or
    a real revocation surfaced by the abort hook).  Raised AFTER the
    last cadence-boundary checkpoint is durable; ``step`` is that
    boundary (None when nothing was written) — the caller resumes from
    it instead of recomputing."""

    def __init__(self, msg, *, run_id: str, step: int | None):
        super().__init__(msg)
        self.run_id = run_id
        self.step = step


# ---------------------------------------------------------------------
# Key + store
# ---------------------------------------------------------------------


@dataclass(frozen=True)
class CheckpointKey:
    """What a checkpoint IS a checkpoint of.  Every field except
    ``cadence`` must match at resume time (``cadence`` may legitimately
    change between legs — it only schedules future writes)."""

    run_id: str
    workload: str          # "invert" | "solve"
    engine: str            # "unrolled" | "fori" | "grouped"
    topology: str          # "single" | "1d:<p>" | "2d:<pr>x<pc>"
    n: int
    m: int
    Nr: int                # padded block-row count (layout-dependent)
    dtype: str
    nrhs: int              # 0 for inverts
    cadence: int

    MATCH_FIELDS = ("workload", "engine", "topology", "n", "m", "Nr",
                    "dtype", "nrhs")

    def to_json(self) -> dict:
        return asdict(self)

    @classmethod
    def from_json(cls, doc: dict) -> "CheckpointKey":
        return cls(**{f: doc[f] for f in cls.__dataclass_fields__})

    def require_match(self, stored: "CheckpointKey") -> None:
        bad = [f for f in self.MATCH_FIELDS
               if getattr(self, f) != getattr(stored, f)]
        if bad:
            detail = ", ".join(
                f"{f}: stored {getattr(stored, f)!r} != requested "
                f"{getattr(self, f)!r}" for f in bad)
            raise CheckpointMismatchError(
                f"checkpoint for run {self.run_id!r} does not describe "
                f"this call ({detail}); resuming would be silent "
                f"corruption — refused")


class CheckpointStore:
    """Host-side checkpoint files + the token ledger.

    One file per ``run_id`` (a new write atomically supersedes the
    previous one — only the LATEST boundary matters for resume), plus
    ``ledger.json`` with the persistent counts.  Thread-safe: the fleet
    writes from replica worker threads."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self._counts = {"written": 0, "resumed": 0, "discarded": 0,
                        "corrupt": 0}
        self._live: dict[str, bool] = {}
        self._load_ledger()

    # ---- paths / ledger persistence ---------------------------------

    def _path(self, run_id: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in run_id)
        return os.path.join(self.root, f"{safe}.ckpt")

    @property
    def _ledger_path(self) -> str:
        return os.path.join(self.root, "ledger.json")

    def _load_ledger(self) -> None:
        try:
            with open(self._ledger_path) as f:
                doc = json.load(f)
            self._counts.update({k: int(doc.get(k, 0))
                                 for k in self._counts})
            self._live = {r: True for r in doc.get("live_runs", [])}
        except (OSError, ValueError):
            pass

    def _persist_ledger_locked(self) -> None:
        doc = dict(self._counts)
        doc["live_runs"] = sorted(self._live)
        text = json.dumps(doc, indent=1, sort_keys=True) + "\n"
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".ledger.tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(text)
            os.replace(tmp, self._ledger_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # ---- write ------------------------------------------------------

    def write(self, key: CheckpointKey, step: int,
              arrays: dict[str, np.ndarray]) -> int:
        """Durably persist ``arrays`` as run ``key.run_id``'s state at
        superstep ``step``.  Returns the payload byte count.  Atomic:
        readers see the old checkpoint or the new one, never a tear."""
        buf = io.BytesIO()
        np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
        payload = buf.getvalue()
        digest = hashlib.sha256(payload).hexdigest()
        header = json.dumps({
            "version": FORMAT_VERSION, "key": key.to_json(),
            "step": int(step), "sha256": digest,
            "payload_bytes": len(payload),
        }, sort_keys=True).encode()
        blob = (_MAGIC + len(header).to_bytes(4, "big") + header
                + payload)
        path = self._path(key.run_id)
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".ckpt.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with self._lock:
            if self._live.get(key.run_id):
                # Supersede: the previous boundary's token is consumed
                # by this newer one.
                self._counts["discarded"] += 1
                _M_DISCARDED.inc()
            self._counts["written"] += 1
            self._live[key.run_id] = True
            self._persist_ledger_locked()
        _M_WRITTEN.inc()
        _recorder.record("ckpt_written", run_id=key.run_id,
                         step=int(step), bytes=len(payload),
                         sha=digest[:12], workload=key.workload,
                         topology=key.topology)
        return len(payload)

    # ---- load / resume ----------------------------------------------

    def _quarantine(self, run_id: str, reason: str) -> None:
        path = self._path(run_id)
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            pass
        with self._lock:
            self._counts["corrupt"] += 1
            if self._live.pop(run_id, None):
                self._counts["discarded"] += 1
                _M_DISCARDED.inc()
            self._persist_ledger_locked()
        _M_CORRUPT.inc()
        _recorder.record("ckpt_corrupt", run_id=run_id, reason=reason)

    def _read(self, run_id: str):
        path = self._path(run_id)
        if not os.path.exists(path):
            raise CheckpointNotFoundError(
                f"no durable checkpoint for run {run_id!r} in "
                f"{self.root} (a cadence larger than the superstep "
                f"count writes none); a resume never silently degrades "
                f"to a from-scratch run")
        with open(path, "rb") as f:
            blob = f.read()
        if blob[:len(_MAGIC)] != _MAGIC:
            self._quarantine(run_id, "bad magic")
            raise CheckpointCorruptError(
                f"checkpoint for run {run_id!r}: bad magic — not a "
                f"checkpoint file (quarantined)")
        try:
            hlen = int.from_bytes(blob[len(_MAGIC):len(_MAGIC) + 4],
                                  "big")
            header = json.loads(
                blob[len(_MAGIC) + 4:len(_MAGIC) + 4 + hlen])
            payload = blob[len(_MAGIC) + 4 + hlen:]
        except (ValueError, IndexError) as e:
            self._quarantine(run_id, "unparseable header")
            raise CheckpointCorruptError(
                f"checkpoint for run {run_id!r}: unparseable header "
                f"(quarantined)") from e
        if len(payload) != header.get("payload_bytes"):
            self._quarantine(run_id, "truncated payload")
            raise CheckpointCorruptError(
                f"checkpoint for run {run_id!r}: payload truncated "
                f"({len(payload)} of {header.get('payload_bytes')} "
                f"bytes; quarantined)")
        if hashlib.sha256(payload).hexdigest() != header.get("sha256"):
            self._quarantine(run_id, "checksum mismatch")
            raise CheckpointCorruptError(
                f"checkpoint for run {run_id!r}: payload checksum "
                f"mismatch (quarantined) — a resume from corrupt bits "
                f"is refused, never attempted")
        key = CheckpointKey.from_json(header["key"])
        with np.load(io.BytesIO(payload)) as z:
            arrays = {k: z[k] for k in z.files}
        return key, int(header["step"]), arrays

    def peek(self, run_id: str):
        """Read + verify WITHOUT consuming the token (inspection)."""
        return self._read(run_id)

    def has_live(self, run_id: str) -> bool:
        """True while run ``run_id`` holds a live (unconsumed)
        checkpoint token — the fleet router's resume probe on a
        re-queue hop (no file I/O, nothing consumed)."""
        with self._lock:
            return bool(self._live.get(run_id))

    def resume(self, key: CheckpointKey):
        """Consume run ``key.run_id``'s live checkpoint for a resume:
        verify integrity, require the stored key to describe this call,
        and account the token.  Returns ``(step, arrays)``.

        The token gates the file: a checkpoint already consumed by a
        resume/discard is a typed miss even while its bytes linger on
        disk — a second consumer double-counting ``resumed`` is exactly
        the ledger drift the invariant exists to catch."""
        with self._lock:
            if not self._live.get(key.run_id):
                raise CheckpointNotFoundError(
                    f"no live checkpoint token for run "
                    f"{key.run_id!r}: nothing durable was written, or "
                    f"the checkpoint was already consumed by a "
                    f"resume/discard; a resume never silently degrades "
                    f"to a from-scratch run")
        stored, step, arrays = self._read(key.run_id)
        key.require_match(stored)
        with self._lock:
            if not self._live.pop(key.run_id, None):
                raise CheckpointNotFoundError(
                    f"checkpoint for run {key.run_id!r} was consumed "
                    f"concurrently; a resume never silently degrades "
                    f"to a from-scratch run")
            self._counts["resumed"] += 1
            self._persist_ledger_locked()
        _M_RESUMED.inc()
        _recorder.record("ckpt_resumed", run_id=key.run_id,
                         step=int(step), workload=key.workload,
                         topology=key.topology)
        return step, arrays

    def discard(self, run_id: str, reason: str = "complete") -> bool:
        """Consume the live token (run finished, or the caller gave
        up).  Idempotent — False when there was nothing live."""
        with self._lock:
            live = self._live.pop(run_id, None)
            if live:
                self._counts["discarded"] += 1
                self._persist_ledger_locked()
        if not live:
            return False
        _M_DISCARDED.inc()
        try:
            os.unlink(self._path(run_id))
        except OSError:
            pass
        _recorder.record("ckpt_discarded", run_id=run_id, reason=reason)
        return True

    # ---- accounting -------------------------------------------------

    def ledger(self) -> dict:
        with self._lock:
            c = dict(self._counts)
            live = len(self._live)
        c["live"] = live
        c["invariant_holds"] = (
            c["written"] == c["resumed"] + c["discarded"] + live)
        return c


# ---------------------------------------------------------------------
# Segment-compile bookkeeping
# ---------------------------------------------------------------------

#: Process-wide signatures of segment executables already built.  This
#: mirrors jax's jit cache over our static arguments (same repo idiom
#: as the serve executors' compiles/cache_hits counters): a warm
#: resume whose segment grid aligns with the original run's re-uses
#: every executable, so ``info["segment_compiles"] == 0`` — the
#: acceptance pin.
_SEG_SIGNATURES: set = set()
_SEG_LOCK = threading.Lock()


def _note_segment(sig: tuple) -> bool:
    """True when this signature is NEW (a compile happens)."""
    with _SEG_LOCK:
        if sig in _SEG_SIGNATURES:
            return False
        _SEG_SIGNATURES.add(sig)
        return True


def _segments(start: int, Nr: int, cadence: int):
    t = start
    while t < Nr:
        t1 = min(t + cadence, Nr)
        yield t, t1
        t = t1


def fingerprint(arr) -> str:
    """sha256 of an array's bytes — the bit-identity witness the demo
    report and check_ckpt compare."""
    a = np.ascontiguousarray(np.asarray(arr))
    return hashlib.sha256(a.tobytes()).hexdigest()


# ---------------------------------------------------------------------
# Runners
# ---------------------------------------------------------------------


def _derive_topology(mesh) -> str:
    if mesh is None:
        return "single"
    shape = tuple(mesh.devices.shape)
    if len(shape) == 1:
        return f"1d:{shape[0]}"
    if len(shape) == 2:
        return f"2d:{shape[0]}x{shape[1]}"
    raise CheckpointUnsupportedError(
        f"no checkpointable engine for a {len(shape)}-axis mesh")


def _check_flavor(workload: str, engine: str, mesh, dtype, spd: bool):
    import jax.numpy as jnp

    engines = SINGLE_ENGINES if mesh is None else DIST_ENGINES
    if engine not in engines:
        raise CheckpointUnsupportedError(
            f"engine {engine!r} is not checkpointable on "
            f"{'single-device' if mesh is None else 'distributed'} "
            f"topologies (supported: {'/'.join(engines)}): swapfree/"
            f"lookahead flavors carry pipeline state outside the "
            f"closed (state, swaps, t) tuple, and pallas grouped "
            f"flavors fuse across steps")
    if spd:
        raise CheckpointUnsupportedError(
            "the SPD fast path has no pivot probe — no pivot record "
            "to snapshot and no singularity evidence to carry across "
            "a resume; checkpointing it is refused")
    jdt = jnp.dtype(dtype)
    if jdt.kind == "c" and mesh is not None:
        raise CheckpointUnsupportedError(
            "complex distributed flavors do not exist yet "
            "(ROADMAP); checkpointing one cannot be meaningful — "
            "refused rather than invented")
    if jdt.kind == "f" and jdt.itemsize < 4:
        raise CheckpointUnsupportedError(
            f"sub-fp32 storage dtype {jdt.name}: the engines compute "
            f"in fp32 with one final rounding, so there is no "
            f"byte-exact {jdt.name} elimination state to snapshot")


def _fire_preempt(run_id: str, durable_step: int | None):
    """The preempt injection point: one segment boundary.  A scheduled
    hit converts to the typed PreemptedError AFTER the last boundary's
    checkpoint is durable (it is — writes happen before this fires)."""
    try:
        _faults.fire("preempt")
    except (_faults.InjectedFaultError,
            _faults.InjectedTransientError) as e:
        _recorder.record("ckpt_preempted", run_id=run_id,
                         step=-1 if durable_step is None
                         else int(durable_step))
        raise PreemptedError(
            f"preempted mid-sweep (run {run_id!r}); last durable "
            f"checkpoint at superstep {durable_step} — resume from it "
            f"instead of recomputing", run_id=run_id,
            step=durable_step) from e


def _check_abort(abort, run_id: str, durable_step: int | None):
    """The real-revocation twin of the preempt fault: the fleet's
    replica kill path hands the runner an ``abort`` callable returning
    an exception factory when the hosting replica died.  Checked at
    segment boundaries only — mid-segment device work is never torn."""
    if abort is None:
        return
    exc = abort()
    if exc is not None:
        _recorder.record("ckpt_preempted", run_id=run_id,
                         step=-1 if durable_step is None
                         else int(durable_step), cause="abort")
        raise exc


def checkpointed_invert(a, block_size=None, *, store: CheckpointStore,
                        run_id: str, cadence: int,
                        engine: str = "unrolled", group: int = 4,
                        mesh=None, eps=None, precision=None,
                        use_pallas: bool = False, resume_from=None,
                        abort=None):
    """Invert ``a`` with superstep checkpointing.  Returns
    ``(inv, singular, info)`` where the inverse **bit-matches** the
    monolithic engine of the same flavor.  ``resume_from=run_id``
    re-enters at the last durable boundary (typed refusals for
    missing/corrupt/mismatched checkpoints — never a silent
    from-scratch run)."""
    return _run_checkpointed(
        "invert", a, None, block_size, store=store, run_id=run_id,
        cadence=cadence, engine=engine, group=group, mesh=mesh,
        eps=eps, precision=precision, use_pallas=use_pallas,
        resume_from=resume_from, abort=abort, spd=False)


def checkpointed_solve(a, b, block_size=None, *,
                       store: CheckpointStore, run_id: str,
                       cadence: int, engine: str = "unrolled",
                       mesh=None, eps=None, precision=None,
                       use_pallas: bool = False, resume_from=None,
                       abort=None, spd: bool = False):
    """Solve ``a @ x = b`` with superstep checkpointing; the
    ``checkpointed_invert`` contract, for the solve working set
    (A, X, singular)."""
    return _run_checkpointed(
        "solve", a, b, block_size, store=store, run_id=run_id,
        cadence=cadence, engine=engine, group=0, mesh=mesh, eps=eps,
        precision=precision, use_pallas=use_pallas,
        resume_from=resume_from, abort=abort, spd=spd)


def _run_checkpointed(workload, a, b, block_size, *, store, run_id,
                      cadence, engine, group, mesh, eps, precision,
                      use_pallas, resume_from, abort, spd):
    import jax.numpy as jnp
    from jax import lax

    from ..config import default_block_size, eps_for

    if cadence < 1:
        raise ValueError(f"cadence must be >= 1, got {cadence}")
    if resume_from is not None and resume_from != run_id:
        raise CheckpointMismatchError(
            f"resume_from={resume_from!r} does not name this run "
            f"({run_id!r}); a resume consumes exactly its own run's "
            f"checkpoint")

    a = jnp.asarray(a)
    dtype = a.dtype
    _check_flavor(workload, engine, mesh, dtype, spd)
    n = a.shape[-1]
    m = min(block_size or default_block_size(n), n)
    if precision is None:
        precision = lax.Precision.HIGHEST
    if eps is None:
        eps = eps_for(dtype)
    nrhs = 0
    b2 = None
    if workload == "solve":
        b = jnp.asarray(b)
        b2 = b if b.ndim == 2 else b[:, None]
        nrhs = b2.shape[1]

    topology = _derive_topology(mesh)

    # --- layout + cadence grid (grouped cadence rounds UP to group
    # multiples: U/P panels are intra-group temporaries, so group
    # boundaries are the only points where (V, swaps, t) is closed).
    if mesh is None:
        Nr = -(-n // m)
        grid = max(1, min(group, Nr)) if engine == "grouped" else 1
    else:
        if topology.startswith("1d"):
            from ..parallel.layout import CyclicLayout
            lay = CyclicLayout.create(n, m, mesh.devices.shape[0])
        else:
            from ..parallel.layout import CyclicLayout2D
            pr, pc = mesh.devices.shape
            lay = CyclicLayout2D.create(n, m, pr, pc)
        Nr = lay.Nr
        grid = 1
    cad = -(-cadence // grid) * grid

    key = CheckpointKey(run_id=run_id, workload=workload, engine=engine,
                        topology=topology, n=int(n), m=int(m),
                        Nr=int(Nr), dtype=jnp.dtype(dtype).name,
                        nrhs=int(nrhs), cadence=int(cad))

    # --- initial state (host-side numpy: byte-exact round-trips)
    state, start = _init_state(workload, a, b2 if workload == "solve"
                               else None, key, mesh)
    durable: int | None = None
    resumed = False
    if resume_from is not None:
        step, arrays = store.resume(key)
        if step % grid:
            raise CheckpointMismatchError(
                f"resume superstep {step} is off the grouped engine's "
                f"group-{grid} boundary grid — the stored entry cannot "
                f"have come from this engine flavor; refused")
        if not (0 <= step < Nr):
            raise CheckpointMismatchError(
                f"resume superstep {step} outside [0, {Nr}) for this "
                f"layout; refused")
        missing = set(state) - set(arrays)
        if missing:
            raise CheckpointMismatchError(
                f"checkpoint for run {run_id!r} lacks state arrays "
                f"{sorted(missing)}; refused")
        for name in state:
            if (arrays[name].shape != state[name].shape
                    or arrays[name].dtype != state[name].dtype):
                raise CheckpointMismatchError(
                    f"checkpoint array {name!r} is "
                    f"{arrays[name].dtype}{arrays[name].shape}, this "
                    f"call needs "
                    f"{state[name].dtype}{state[name].shape}; refused")
        state = {name: arrays[name] for name in state}
        start = step
        durable = step
        resumed = True

    info = {"run_id": run_id, "workload": workload, "engine": engine,
            "topology": topology, "n": int(n), "m": int(m),
            "Nr": int(Nr), "cadence": int(cad), "start_step": start,
            "resumed": resumed, "segments_run": [],
            "segment_compiles": 0, "ckpt_written": 0,
            "ckpt_bytes_last": 0}

    # --- the segmented sweep
    for t0, t1 in _segments(start, Nr, cad):
        _check_abort(abort, run_id, durable)
        _fire_preempt(run_id, durable)
        sig = ("seg", workload, engine, topology, int(n), int(m),
               int(Nr), key.dtype, int(nrhs), t0, t1, bool(use_pallas))
        if _note_segment(sig):
            info["segment_compiles"] += 1
        state = _run_segment(workload, engine, state, t0, t1, key,
                             mesh, eps, precision, use_pallas, group)
        info["segments_run"].append((t0, t1))
        if t1 < Nr:
            info["ckpt_bytes_last"] = store.write(key, t1, state)
            info["ckpt_written"] += 1
            durable = t1

    _check_abort(abort, run_id, durable)
    fsig = ("fin", workload, engine, topology, int(n), int(m), int(Nr),
            key.dtype, int(nrhs))
    if _note_segment(fsig):
        info["segment_compiles"] += 1
    out, singular = _finalize(workload, state, key, mesh)
    store.discard(run_id, reason="complete")
    return out, singular, info


# ---- state init / segment dispatch / finalize, per topology ---------


def _spec1d():
    from jax.sharding import PartitionSpec

    from ..parallel.mesh import AXIS
    return (PartitionSpec(AXIS, None, None), PartitionSpec(AXIS),
            PartitionSpec(AXIS, None))


def _spec2d():
    from jax.sharding import PartitionSpec

    from ..parallel.mesh import AXIS_C, AXIS_R
    return (PartitionSpec(AXIS_R, None, AXIS_C),
            PartitionSpec(AXIS_R, None, None),
            PartitionSpec(AXIS_R, AXIS_C),
            PartitionSpec(AXIS_R, AXIS_C, None))


def _init_state(workload, a, b2, key: CheckpointKey, mesh):
    import jax.numpy as jnp

    from ..ops.padding import pad_with_identity

    n, m, Nr = key.n, key.m, key.Nr
    N = Nr * m
    if mesh is None:
        if workload == "invert":
            state = {"V": np.asarray(pad_with_identity(a, N)),
                     "singular": np.asarray(False),
                     "swaps": np.zeros((Nr,), np.int32)}
        else:
            X = jnp.zeros((N, key.nrhs), a.dtype).at[:n].set(b2)
            state = {"A": np.asarray(pad_with_identity(a, N)),
                     "X": np.asarray(X),
                     "singular": np.asarray(False)}
        return state, 0
    if key.topology.startswith("1d"):
        from ..parallel.layout import CyclicLayout
        from ..parallel.ring_gemm import _to_identity_padded_blocks
        from ..parallel.sharded_inplace import scatter_rhs_1d

        p = mesh.devices.shape[0]
        lay = CyclicLayout.create(n, m, p)
        W = np.asarray(_to_identity_padded_blocks(a, lay, mesh))
        if workload == "invert":
            state = {"W": W, "singular": np.zeros((p,), bool),
                     "swaps": np.zeros((p, lay.Nr), np.int32)}
        else:
            state = {"W": W,
                     "X": np.asarray(scatter_rhs_1d(b2, lay, mesh)),
                     "singular": np.zeros((p,), bool)}
        return state, 0
    from ..parallel.jordan2d import scatter_matrix_2d
    from ..parallel.jordan2d_inplace import scatter_rhs_2d
    from ..parallel.layout import CyclicLayout2D

    pr, pc = mesh.devices.shape
    lay = CyclicLayout2D.create(n, m, pr, pc)
    W = np.asarray(scatter_matrix_2d(a, lay, mesh))
    if workload == "invert":
        state = {"W": W, "singular": np.zeros((pr, pc), bool),
                 "swaps": np.zeros((pr, pc, lay.Nr), np.int32)}
    else:
        state = {"W": W, "X": np.asarray(scatter_rhs_2d(b2, lay, mesh)),
                 "singular": np.zeros((pr, pc), bool)}
    return state, 0


def _run_segment(workload, engine, state, t0, t1, key: CheckpointKey,
                 mesh, eps, precision, use_pallas, group):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    n, m, Nr, nrhs = key.n, key.m, key.Nr, key.nrhs
    if mesh is None:
        if workload == "solve":
            from ..linalg.engine import solve_segment, solve_segment_fori

            fn = solve_segment if engine == "unrolled" \
                else solve_segment_fori
            A, X, s = fn(jnp.asarray(state["A"]),
                         jnp.asarray(state["X"]),
                         jnp.asarray(state["singular"]), t0=t0, t1=t1,
                         Nr=Nr, m=m, k=nrhs, eps=eps,
                         precision=precision)
            return {"A": np.asarray(A), "X": np.asarray(X),
                    "singular": np.asarray(s)}
        from ..ops.jordan_inplace import (invert_segment,
                                          invert_segment_fori,
                                          invert_segment_grouped)

        if engine == "grouped":
            V, s, sw = invert_segment_grouped(
                jnp.asarray(state["V"]), jnp.asarray(state["singular"]),
                jnp.asarray(state["swaps"]), t0=t0, t1=t1, Nr=Nr, m=m,
                group=group, eps=eps, precision=precision,
                use_pallas=use_pallas)
        else:
            fn = invert_segment if engine == "unrolled" \
                else invert_segment_fori
            V, s, sw = fn(jnp.asarray(state["V"]),
                          jnp.asarray(state["singular"]),
                          jnp.asarray(state["swaps"]), t0=t0, t1=t1,
                          Nr=Nr, m=m, eps=eps, precision=precision,
                          use_pallas=use_pallas)
        return {"V": np.asarray(V), "singular": np.asarray(s),
                "swaps": np.asarray(sw)}

    unroll = engine == "unrolled"
    if key.topology.startswith("1d"):
        from ..parallel.layout import CyclicLayout
        from ..parallel.sharded_inplace import (
            _sharded_jordan_inplace_segment,
            _sharded_jordan_solve_segment)

        lay = CyclicLayout.create(n, m, mesh.devices.shape[0])
        sW, sS, sSw = _spec1d()

        def put(arr, spec):
            return jax.device_put(np.asarray(arr),
                                  NamedSharding(mesh, spec))

        if workload == "solve":
            W, X, s = _sharded_jordan_solve_segment(
                put(state["W"], sW), put(state["X"], sW),
                put(state["singular"], sS), mesh, lay, nrhs, t0, t1,
                eps, precision, use_pallas, unroll)
            return {"W": np.asarray(W), "X": np.asarray(X),
                    "singular": np.asarray(s)}
        W, s, sw = _sharded_jordan_inplace_segment(
            put(state["W"], sW), put(state["singular"], sS),
            put(state["swaps"], sSw), mesh, lay, t0, t1, eps,
            precision, use_pallas, unroll)
        return {"W": np.asarray(W), "singular": np.asarray(s),
                "swaps": np.asarray(sw)}

    from ..parallel.jordan2d_inplace import (
        _sharded_jordan2d_inplace_segment,
        _sharded_jordan_solve_2d_segment)
    from ..parallel.layout import CyclicLayout2D

    pr, pc = mesh.devices.shape
    lay = CyclicLayout2D.create(n, m, pr, pc)
    sW, sX, sS, sSw = _spec2d()

    def put2(arr, spec):
        return jax.device_put(np.asarray(arr),
                              NamedSharding(mesh, spec))

    if workload == "solve":
        W, X, s = _sharded_jordan_solve_2d_segment(
            put2(state["W"], sW), put2(state["X"], sX),
            put2(state["singular"], sS), mesh, lay, nrhs, t0, t1, eps,
            precision, use_pallas, unroll)
        return {"W": np.asarray(W), "X": np.asarray(X),
                "singular": np.asarray(s)}
    W, s, sw = _sharded_jordan2d_inplace_segment(
        put2(state["W"], sW), put2(state["singular"], sS),
        put2(state["swaps"], sSw), mesh, lay, t0, t1, eps, precision,
        use_pallas, unroll)
    return {"W": np.asarray(W), "singular": np.asarray(s),
            "swaps": np.asarray(sw)}


def _finalize(workload, state, key: CheckpointKey, mesh):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    n, m, Nr = key.n, key.m, key.Nr
    if mesh is None:
        singular = bool(np.asarray(state["singular"]))
        if workload == "solve":
            return np.asarray(state["X"])[:n], singular
        from ..ops.jordan_inplace import invert_finalize

        inv = invert_finalize(jnp.asarray(state["V"]),
                              jnp.asarray(state["swaps"]), n=n, Nr=Nr,
                              m=m)
        return np.asarray(inv), singular
    singular = bool(np.asarray(state["singular"]).any())
    if key.topology.startswith("1d"):
        from ..parallel.layout import CyclicLayout
        from ..parallel.sharded_inplace import (
            _sharded_inplace_finalize, gather_inverse_inplace,
            gather_solution_1d)

        lay = CyclicLayout.create(n, m, mesh.devices.shape[0])
        sW, sS, sSw = _spec1d()
        if workload == "solve":
            return np.asarray(gather_solution_1d(
                jnp.asarray(state["X"]), lay, n)), singular
        W = _sharded_inplace_finalize(
            jax.device_put(state["W"], NamedSharding(mesh, sW)),
            jax.device_put(state["swaps"], NamedSharding(mesh, sSw)),
            mesh, lay)
        return np.asarray(gather_inverse_inplace(W, lay, n)), singular
    from ..parallel.jordan2d_inplace import (
        _sharded_jordan2d_inplace_finalize, gather_inverse_inplace_2d,
        gather_solution_2d)
    from ..parallel.layout import CyclicLayout2D

    pr, pc = mesh.devices.shape
    lay = CyclicLayout2D.create(n, m, pr, pc)
    sW, sX, sS, sSw = _spec2d()
    if workload == "solve":
        return np.asarray(gather_solution_2d(
            jnp.asarray(state["X"]), lay, n)), singular
    W = _sharded_jordan2d_inplace_finalize(
        jax.device_put(state["W"], NamedSharding(mesh, sW)),
        jax.device_put(state["swaps"], NamedSharding(mesh, sSw)),
        mesh, lay)
    return np.asarray(gather_inverse_inplace_2d(W, lay, n)), singular
