"""Policy-driven retry / deadline / circuit breaking (ISSUE 5 tentpole
part 2) — ONE home for the failure-handling logic that previously lived
as three private islands: the typed transient classifier in
``tuning/measure.py`` (promoted here verbatim; measure.py and bench.py
now import it), the plan-cache corruption fallback, and the serve
overload backpressure.

Pieces:

  * ``is_transient`` / ``retry_transient`` — the typed transient
    classifier (a runtime/transport exception TYPE carrying a
    documented-transient message marker; both conditions required —
    substring matching alone once let an accuracy AssertionError that
    merely quoted "INTERNAL" trigger a full n=16384 re-run).
  * :class:`RetryPolicy` — bounded retries with exponential backoff and
    DETERMINISTIC jitter (a pure function of the attempt index — same
    discipline as the obs fake clocks: no hidden randomness anywhere in
    the failure path), an injectable ``sleep``/classifier, and every
    retry counted in ``tpu_jordan_retries_total`` (zero on the
    fault-free warm path — acceptance-pinned).
  * :class:`DeadlineExceededError` — the typed per-request deadline
    failure (queue wait + execute, enforced by the serve dispatcher).
  * :class:`CircuitBreaker` — closed -> (K consecutive failures) open ->
    typed fast-fail (:class:`CircuitOpenError`) instead of queueing
    doomed work -> half-open probe after a cooldown -> closed on probe
    success, reopened on probe failure.  State exported as the
    ``tpu_jordan_breaker_state`` gauge.
  * :class:`ResiliencePolicy` — the umbrella the product surface takes
    (``solve(policy=)``, ``JordanSolver(policy=)``,
    ``JordanService(policy=)``): retry knobs, the residual-gate /
    degradation-ladder knobs (``resilience/degrade.py``), and the
    breaker knobs.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any

from ..obs import metrics as _obs_metrics
from ..obs import recorder as _recorder

_M_RETRIES = _obs_metrics.counter(
    "tpu_jordan_retries_total",
    "retries performed by RetryPolicy (transient failures and detected "
    "result corruption), labeled by component")
_M_BREAKER_STATE = _obs_metrics.gauge(
    "tpu_jordan_breaker_state",
    "circuit breaker state: 0 closed, 1 open, 2 half-open")
_M_BREAKER_OPEN = _obs_metrics.counter(
    "tpu_jordan_breaker_open_total",
    "closed/half-open -> open breaker transitions")
_M_DEADLINE = _obs_metrics.counter(
    "tpu_jordan_deadline_exceeded_total",
    "requests failed by their deadline, labeled by phase (queue|execute)")

#: Documented-transient message markers (tunnel/remote-compile failure
#: class, benchmarks/PHASES.md).  Marker AND type are both required.
_RETRYABLE = ("INTERNAL", "remote_compile", "read body", "DEADLINE")


class DeadlineExceededError(TimeoutError):
    """A request's ``deadline_ms`` elapsed (queue wait + execute) before
    its result could be delivered — the serve dispatcher's typed
    per-request deadline failure (never a hang, never a silent drop)."""


class CircuitOpenError(RuntimeError):
    """Fast-fail from an OPEN circuit breaker: the bucket's executor has
    failed K consecutive times and queueing more work at it would be
    queueing doomed work.  Retry after the cooldown (the breaker then
    admits a half-open probe)."""


class ResultCorruptionError(ArithmeticError):
    """A computed result failed the integrity gate (non-finite values
    where the residual machinery promises finite ones) — the typed form
    of silent corruption, raised so the retry/degradation policy can act
    instead of a wrong answer reaching a caller."""


class CapacityExceededError(MemoryError):
    """A resident-bytes budget refused an admission (ISSUE 13): the
    requested residency does not fit under the
    :class:`~..obs.capacity.CapacityBudget` ceiling and the evictor
    could not make room (everything evictable is pinned).  Raised at
    SUBMIT time — before any device launch — so an over-budget
    ``invert(resident=True)`` is a typed answer, never an OOM
    mid-launch.  Evict or unpin a handle (``HandleStore.evict`` /
    ``unpin``), or raise the budget, and retry."""


def is_transient(e: Exception) -> bool:
    """Transient = a runtime/transport exception TYPE carrying one of
    the documented-transient message markers.  Both conditions required
    (module docstring; promoted from ``tuning/measure.py``, ISSUE 5)."""
    if not any(s in str(e) for s in _RETRYABLE):
        return False
    types = [OSError, ConnectionError, TimeoutError]    # tunnel/transport
    try:
        from jax.errors import JaxRuntimeError
        types.append(JaxRuntimeError)
    except ImportError:
        pass
    try:
        from jaxlib.xla_extension import XlaRuntimeError
        types.append(XlaRuntimeError)
    except ImportError:
        pass
    return isinstance(e, tuple(types))


def retryable(e: Exception) -> bool:
    """The default RetryPolicy classifier: the transient transport class
    plus detected result corruption (a re-run clears transient
    corruption; persistent corruption exhausts the budget and surfaces
    typed)."""
    return isinstance(e, ResultCorruptionError) or is_transient(e)


def _jitter_fraction(attempt: int) -> float:
    """Deterministic jitter in [0, 1): a Weyl sequence over the attempt
    index (golden-ratio multiplier) — well spread, zero state, and
    byte-reproducible run to run (the fake-clock discipline)."""
    return (attempt * 0.6180339887498949) % 1.0


@dataclass
class RetryPolicy:
    """Bounded retry with exponential backoff and deterministic jitter.

    ``call(fn)`` runs ``fn`` up to ``1 + max_retries`` times; an
    exception the ``classify`` predicate rejects propagates immediately
    (an accuracy assertion must never be retried into a pass).  The
    delay before retry k (0-based) is
    ``min(max_backoff_s, backoff_s * multiplier**k)`` stretched by up to
    ``jitter_pct`` percent of itself via the deterministic jitter —
    injectable ``sleep`` (and the zero default base) keep tests and the
    serve dispatcher's drain path instantaneous.
    """

    max_retries: int = 1
    backoff_s: float = 0.0
    multiplier: float = 2.0
    max_backoff_s: float = 2.0
    jitter_pct: float = 10.0
    classify: Any = None          # predicate(exc) -> bool; None = retryable
    sleep: Any = None             # injectable; None = time.sleep

    def delay_s(self, attempt: int) -> float:
        """The deterministic pre-retry delay for 0-based ``attempt``."""
        base = min(self.max_backoff_s,
                   self.backoff_s * (self.multiplier ** attempt))
        return base * (1.0 + self.jitter_pct / 100.0
                       * _jitter_fraction(attempt))

    def call(self, fn, component: str = "default", on_retry=None,
             exemplar: str | None = None):
        """Run ``fn()`` under the policy.  ``on_retry(exc, attempt)``
        (optional) runs before each re-attempt — the hook call sites use
        to rebuild donated input buffers.  ``exemplar`` (ISSUE 8) is an
        affected request id attached to the retry counter and the
        flight-recorder retry events (the serve dispatcher passes one
        of the batch's riders)."""
        classify = self.classify if self.classify is not None else retryable
        sleep = self.sleep if self.sleep is not None else time.sleep
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:              # noqa: BLE001
                if attempt >= self.max_retries or not classify(e):
                    raise
                _M_RETRIES.inc(component=component, exemplar=exemplar)
                _recorder.record("retry", component=component,
                                 attempt=attempt, error=type(e).__name__,
                                 **({"request_id": exemplar}
                                    if exemplar else {}))
                delay = self.delay_s(attempt)
                if delay > 0:
                    sleep(delay)
                if on_retry is not None:
                    on_retry(e, attempt)
                attempt += 1


#: The historical one-shot contract (formerly ``tuning/measure.py``):
#: one retry, no backoff, strict transient classification only.
_ONE_SHOT = RetryPolicy(max_retries=1, backoff_s=0.0, classify=is_transient)


def retry_transient(fn):
    """One retry on the documented-transient remote-compile failure
    class (benchmarks/PHASES.md: the same program passes minutes later;
    the round-4 headline capture was lost to exactly one such failure).
    Anything else — including accuracy/singularity assertions — is a
    real result and propagates immediately.  Now a thin veneer over
    :class:`RetryPolicy` (ISSUE 5 satellite: one classifier, one
    backoff implementation, retries counted in
    ``tpu_jordan_retries_total``)."""
    return _ONE_SHOT.call(fn, component="measure")


class CircuitBreaker:
    """Per-resource circuit breaker (serve buckets hold one each).

    closed --K consecutive failures--> open --cooldown--> half-open
    --probe success--> closed; --probe failure--> open again.

    ``allow()`` is the admission check (False = fast-fail with
    :class:`CircuitOpenError` at the call site); ``record_success`` /
    ``record_failure`` are the outcome feedback.  ``clock`` is any
    zero-arg monotonic callable (tests inject a fake — the obs
    discipline); state transitions export the
    ``tpu_jordan_breaker_state`` gauge and count opens in
    ``tpu_jordan_breaker_open_total``.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
    _GAUGE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}

    def __init__(self, failures: int = 3, cooldown_s: float = 5.0,
                 clock=None, name: str = ""):
        if failures < 1:
            raise ValueError("failures must be >= 1")
        self.failures = int(failures)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock if clock is not None else time.monotonic
        self.name = str(name)
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._export()

    def _export(self):
        _M_BREAKER_STATE.set(self._GAUGE[self._state], breaker=self.name)

    @property
    def state(self) -> str:
        with self._lock:
            # Surface the half-open transition even if nobody called
            # allow() yet — the gauge should reflect admissibility.
            if (self._state == self.OPEN
                    and self.clock() - self._opened_at >= self.cooldown_s):
                return self.HALF_OPEN
            return self._state

    def allow(self) -> bool:
        """Admission check; flips open -> half-open once the cooldown
        has elapsed (the next admitted request IS the probe)."""
        with self._lock:
            if self._state == self.OPEN:
                if self.clock() - self._opened_at < self.cooldown_s:
                    return False
                self._state = self.HALF_OPEN
                self._export()
                _recorder.record("breaker_transition", breaker=self.name,
                                 state=self.HALF_OPEN)
            return True

    def _open(self):
        self._state = self.OPEN
        self._opened_at = self.clock()
        self._consecutive = 0
        self._export()
        _M_BREAKER_OPEN.inc(breaker=self.name)
        _recorder.record("breaker_transition", breaker=self.name,
                         state=self.OPEN)

    def record_success(self) -> None:
        with self._lock:
            transitioned = self._state != self.CLOSED
            self._state = self.CLOSED
            self._consecutive = 0
            self._export()
        if transitioned:
            # Only TRANSITIONS are black-box events: record_success
            # fires on every healthy batch, and a flight recorder full
            # of "still closed" would evict the events that matter.
            _recorder.record("breaker_transition", breaker=self.name,
                             state=self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._open()                 # failed probe: straight back
                return
            self._consecutive += 1
            if self._state == self.CLOSED \
                    and self._consecutive >= self.failures:
                self._open()


@dataclass
class ResiliencePolicy:
    """The umbrella policy the product surface takes.

    Retry: ``retry`` (a :class:`RetryPolicy`) wraps compile, execute,
    and measurement calls wherever the policy is threaded.

    Residual gate / degradation ladder (``resilience/degrade.py``,
    driver solves): a result whose ``rel_residual`` exceeds
    ``gate_tol * eps * n * kappa`` (eps of ``gate_dtype`` when set, else
    of the solve's own result dtype; NaN always fails) escalates —
    ``refine_steps`` of Newton-Schulz iterative refinement first, then
    (``escalate=True``) a higher-precision re-solve up the PRECISIONS /
    dtype ladder — with every rung recorded on ``SolveResult.recovery``
    and as ``recover`` span children.  A ladder that exhausts without
    passing raises :class:`ResidualGateError`: a wrong inverse is never
    returned silently.

    Breaker (serve): ``breaker_failures`` consecutive terminal executor
    failures open a per-bucket breaker for ``breaker_cooldown_s``.
    """

    retry: RetryPolicy = field(default_factory=RetryPolicy)
    gate_tol: float = 16.0
    gate_dtype: Any = None
    refine_steps: int = 2
    escalate: bool = True
    breaker_failures: int = 3
    breaker_cooldown_s: float = 5.0


class ResidualGateError(ArithmeticError):
    """The degradation ladder exhausted every rung (refine, then the
    escalated re-solve) without the residual gate passing — surfaced
    typed instead of returning a known-bad inverse."""

    def __init__(self, msg: str, recovery: tuple = ()):
        super().__init__(msg)
        self.recovery = recovery


#: The defaults the serving layer uses when no policy is passed: two
#: retries with a short capped backoff, the standard gate, K=3 breaker.
DEFAULT_POLICY = ResiliencePolicy(
    retry=RetryPolicy(max_retries=2, backoff_s=0.01, max_backoff_s=0.25))
