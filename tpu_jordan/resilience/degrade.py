"""The numerical degradation ladder (ISSUE 5 tentpole part 3).

Every solve already computes the independent residual
``‖A·A⁻¹ − I‖∞`` and κ∞ — but until this layer nothing *acted* on a
failed verification: the number was reported and a silently wrong
inverse could still reach a caller.  Here the driver (when a
:class:`~.policy.ResiliencePolicy` is attached) runs the residual gate

    rel_residual <= gate_tol * eps * n * kappa_inf

(eps of ``policy.gate_dtype`` when set — the accuracy SLO — else of the
solve's own result dtype; a NaN rel_residual always fails, which is how
injected/real result corruption is caught) and, on failure, escalates
through the recovery rungs the large-scale TPU linear-algebra literature
leans on (Lewis et al. arXiv:2112.09017, JAXMg arXiv:2601.14466):

  1. **refine** — Newton-Schulz iterative refinement (``ops/refine.py``)
     at the solve's working precision (>= fp32, never below the
     request) with HIGHEST-precision products, on the inverse in hand:
     two GEMMs per step, no recompile, fixes small gate misses
     (final-rounding damage, mild corruption).  Requires the initial residual < 1 to converge —
     a bf16-grade miss on an ill-conditioned matrix diverges here and
     falls through.
  2. **resolve** — a full re-solve at escalated precision: storage dtype
     promoted up the ladder (bf16/f16 -> fp32) and matmul precision
     pinned to HIGHEST.  Also clears transient result corruption even
     when no precision headroom remains (the re-solve is a fresh
     execution of a fresh load).

Each rung is recorded on ``SolveResult.recovery`` (rung name, rel
residual before/after, pass verdict) and as a child of the ``recover``
span, and counted in ``tpu_jordan_recovery_rungs_total``.  A ladder
that exhausts without passing raises
:class:`~.policy.ResidualGateError` — never a silent wrong answer.
"""

from __future__ import annotations

import math

from ..obs import metrics as _obs_metrics
from ..obs import recorder as _recorder
from .policy import ResidualGateError, ResiliencePolicy

_M_RUNGS = _obs_metrics.counter(
    "tpu_jordan_recovery_rungs_total",
    "degradation-ladder rungs executed (refine / resolve), labeled by "
    "rung and outcome")
_M_GATE_FAIL = _obs_metrics.counter(
    "tpu_jordan_residual_gate_failures_total",
    "solves whose residual gate failed and entered the recovery ladder")


def gate_eps(dtype) -> float:
    """Machine epsilon of the gate's reference dtype."""
    import jax.numpy as jnp

    return float(jnp.finfo(jnp.dtype(dtype)).eps)


def gate_threshold(policy: ResiliencePolicy, n: int, kappa: float,
                   dtype) -> float:
    """``gate_tol * eps * n * kappa`` — the expected-error model the
    driver documents (rel residual ≈ eps·n·κ∞ for a healthy solve),
    widened by the policy's tolerance.  κ is floored at 1 (a gate must
    never tighten below eps·n) and a non-finite κ (corrupt inverse)
    yields a NaN threshold, which fails the gate as intended.

    The threshold is CAPPED at 0.5 (bench.py's dynamic-gate ceiling,
    same rationale): a rel residual ≥ 0.5 means ‖I−AX‖ ≈ ‖I‖ — no
    inverse at all, whatever κ claims.  The cap is what keeps the gate
    non-vacuous at bf16 eps (ISSUE 6): with eps_bf16 ≈ 7.8e-3 the
    eps·n·κ model exceeds 1 for any κ ≳ 1/(tol·eps·n), and without the
    ceiling a bf16-computed non-inverse would "pass" — exactly the
    silent degradation the ladder exists to prevent."""
    eps = gate_eps(policy.gate_dtype if policy.gate_dtype is not None
                   else dtype)
    if not math.isfinite(kappa):
        # A corrupt inverse poisons κ; the threshold must fail the gate
        # (note max(1.0, nan) would silently return 1.0 — NaN compares
        # false both ways — so the guard is explicit).
        return float("nan")
    return min(policy.gate_tol * eps * max(1, n) * max(1.0, kappa), 0.5)


def gate_passes(rel_residual: float, threshold: float) -> bool:
    """NaN-hostile comparison: any NaN (corrupt residual or corrupt
    threshold via κ) fails."""
    return bool(rel_residual <= threshold) and math.isfinite(rel_residual)


def solve_gate_threshold(policy: ResiliencePolicy, n: int, dtype) -> float:
    """The residual gate for the SOLVE workloads (ISSUE 11): judged on
    the normwise backward error

        ‖A·X − B‖∞ / (‖A‖∞·‖X‖∞ + ‖B‖∞)  <=  gate_tol · eps · n

    which is κ-FREE — a backward-stable solve has a small backward
    error whatever the conditioning, so the gate is both cheaper (no
    A⁻¹ to norm) and tighter than the invert gate's eps·n·κ∞ model:
    exactly why serving X = A⁻¹B beats inverting first even on the
    verification bill.  Same 0.5 non-vacuousness ceiling as
    :func:`gate_threshold` (a rel residual ≥ 0.5 is no solution at
    all), same ``gate_dtype`` SLO override."""
    eps = gate_eps(policy.gate_dtype if policy.gate_dtype is not None
                   else dtype)
    return min(policy.gate_tol * eps * max(1, n), 0.5)


def maybe_recover(policy: ResiliencePolicy, tel, *, a_fresh, inv,
                  residual: float, norm_a: float, kappa: float, n: int,
                  dtype, resolve):
    """The driver's post-residual hook (single-device solves): run the
    gate and, on failure, the ladder.

    ``a_fresh`` is the freshly re-loaded A the residual was verified
    against (reference reload semantics — recovery never trusts
    algorithm state); ``resolve`` is a zero-arg callable performing the
    escalated re-solve and returning a ``SolveResult``-like object
    (inverse / residual / kappa / _norm_a).

    Returns ``(inv, residual, norm_a, kappa, recovery)`` where
    ``recovery`` is a tuple of per-rung records (empty when the gate
    passed outright — the fault-free path pays one comparison).  The
    refined/re-solved inverse is returned at the working precision that
    produced it (fp32 after a refine of a bf16 solve): the recovered
    number IS the product, re-rounding it down would undo the rung.
    """
    rel = residual / norm_a if norm_a else residual
    threshold = gate_threshold(policy, n, kappa, dtype)
    if gate_passes(rel, threshold):
        return inv, residual, norm_a, kappa, ()

    _M_GATE_FAIL.inc()
    _recorder.record("residual_gate_failure", n=n,
                     rel_residual=float(rel), threshold=float(threshold))
    recovery = []
    with tel.span("recover", n=n, rel_residual=float(rel),
                  threshold=float(threshold)) as rsp:
        # ---- rung 1: iterative refinement ---------------------------
        if policy.refine_steps > 0:
            with tel.span("refine", steps=policy.refine_steps) as sp:
                inv2, res2, norm2, kap2 = _refine(
                    a_fresh, inv, policy.refine_steps)
                rel2 = res2 / norm2 if norm2 else res2
                # Judged at the refine work dtype (>= fp32, never BELOW
                # the request: a float64 solve's gate stays eps64 —
                # unless the policy pins an explicit gate_dtype SLO).
                thr2 = gate_threshold(policy, n, kap2, inv2.dtype)
                passed = gate_passes(rel2, thr2)
                sp.attrs.update(rel_residual=float(rel2), passed=passed)
            recovery.append({
                "rung": "refine", "steps": policy.refine_steps,
                "rel_residual_before": float(rel),
                "rel_residual_after": float(rel2), "passed": passed,
            })
            _M_RUNGS.inc(rung="refine",
                         outcome="passed" if passed else "failed")
            _recorder.record("recovery_rung", rung="refine",
                             outcome="passed" if passed else "failed",
                             rel_residual=float(rel2))
            if passed:
                rsp.attrs["recovered_by"] = "refine"
                return inv2, res2, norm2, kap2, tuple(recovery)

        # ---- rung 2: escalated re-solve -----------------------------
        if policy.escalate:
            with tel.span("resolve") as sp:
                res = resolve()
                rel3 = res.rel_residual
                thr3 = gate_threshold(policy, n, res.kappa,
                                      res.inverse.dtype)
                passed = gate_passes(rel3, thr3)
                sp.attrs.update(rel_residual=float(rel3), passed=passed,
                                dtype=str(res.inverse.dtype))
            recovery.append({
                "rung": "resolve", "dtype": str(res.inverse.dtype),
                "rel_residual_before": float(rel),
                "rel_residual_after": float(rel3), "passed": passed,
            })
            _M_RUNGS.inc(rung="resolve",
                         outcome="passed" if passed else "failed")
            _recorder.record("recovery_rung", rung="resolve",
                             outcome="passed" if passed else "failed",
                             rel_residual=float(rel3))
            if passed:
                rsp.attrs["recovered_by"] = "resolve"
                return (res.inverse, res.residual, res._norm_a,
                        res.kappa, tuple(recovery))

    raise ResidualGateError(
        f"residual gate failed (rel {rel:.3e} > {threshold:.3e}) and "
        f"the recovery ladder exhausted "
        f"({' -> '.join(r['rung'] for r in recovery) or 'no rungs'})",
        recovery=tuple(recovery))


def _refine(a_fresh, inv, steps: int):
    """Newton-Schulz at HIGHEST precision in the solve's working dtype
    — at least fp32 (bf16/f16 storage refines at fp32) and never BELOW
    the request (a float64 solve refines at float64); returns the
    refreshed (inv, residual, norm_a, kappa) at that dtype."""
    import jax.numpy as jnp
    from jax import lax

    from ..ops import inf_norm, newton_schulz, residual_inf_norm

    work = jnp.promote_types(jnp.asarray(a_fresh).dtype, jnp.float32)
    aw = jnp.asarray(a_fresh, work)
    xw = newton_schulz(aw, jnp.asarray(inv, work), steps,
                       precision=lax.Precision.HIGHEST)
    residual = float(residual_inf_norm(aw, xw))
    norm_a = float(inf_norm(aw))
    kappa = norm_a * float(inf_norm(xw))
    return xw, residual, norm_a, kappa
