"""Deterministic fault injection (ISSUE 5 tentpole part 1).

Chaos testing a serving system is worthless if the chaos is not
reproducible: a probabilistic fault that fires on Tuesdays can neither
pin a regression nor be replayed in a failing CI log.  Here every fault
is an **nth-call schedule**: an injection point fires on exactly the
k-th time it is reached (1-based, counted per point under a lock), so a
seeded :class:`FaultPlan` produces byte-identical chaos on every run —
the same discipline as the tuner's injected timings and the obs layer's
fake clocks.

Injection points (``POINTS``), threaded through the layers built in
PRs 1-4:

  ==================  ====================================================
  point               fires inside
  ==================  ====================================================
  compile             driver solve/solve_batch compile spans,
                      ``JordanSolver._compile``, serve
                      ``BucketExecutor._build``
  execute             driver timed executions, the serve dispatcher's
                      per-batch executable run
  plan_cache_write    ``tuning/plan_cache.PlanCache.save`` (simulates
                      disk full / read-only dir)
  measure             ``tuning/measure.measure_direct`` timed calls
  result_corrupt_nan  the serve dispatcher's result fan-out and the
                      driver's post-execute result (silent-corruption
                      simulation: poisons the result so the integrity /
                      residual gates must catch it)
  dispatch            the serve dispatcher, before executor lookup
  replica_kill        the fleet replica's dispatch path
                      (``fleet/replica.py``): a scheduled hit crashes
                      the replica mid-stream — state DEAD, queued work
                      failed with the typed ``ReplicaKilledError`` (the
                      router re-queues it), the supervisor warm-replaces
                      the worker (ISSUE 7)
  preempt             the checkpointed runners' segment boundaries
                      (``resilience/checkpoint.py``) and the LP/QP
                      drivers' iteration tops: a scheduled hit is the
                      chip going away mid-sweep — the runner converts
                      it to the typed ``PreemptedError`` AFTER the last
                      cadence-boundary checkpoint is durable, so lost
                      work is bounded by the cadence and the retry
                      resumes instead of recomputing (ISSUE 20)
  ==================  ====================================================

A point with no active plan costs one module-global ``is None`` check —
the fault-free warm path pays nothing measurable (acceptance-pinned).
Every fired injection increments ``tpu_jordan_faults_injected_total``
(labeled by point) and is recorded on the plan itself, so a chaos
report can account for every fault as retried, degraded, or typed-error
(``tools/check_chaos.py``) — none silent.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field

import numpy as np

from ..obs import metrics as _obs_metrics
from ..obs import recorder as _recorder

#: The named injection points.  ``fire()`` on an unknown point raises —
#: a typo'd point would otherwise be chaos that never happens.
POINTS = ("compile", "execute", "plan_cache_write", "measure",
          "result_corrupt_nan", "dispatch", "replica_kill", "preempt")

#: Injection modes: how a scheduled hit manifests at the call site.
#:   transient — raises :class:`InjectedTransientError` (classified
#:     retryable by ``resilience.policy.is_transient``: a transport-type
#:     exception carrying a documented-transient marker);
#:   permanent — raises :class:`InjectedFaultError` (never retried —
#:     the "doomed executor" fixture for breaker tests);
#:   oserror — raises ``OSError`` (the plan-cache write failure class);
#:   corrupt — does not raise; ``corrupt(point)`` returns True and the
#:     call site poisons its own result (NaN injection).
MODES = ("transient", "permanent", "oserror", "corrupt")

_M_INJECTED = _obs_metrics.counter(
    "tpu_jordan_faults_injected_total",
    "faults fired by an active FaultPlan, labeled by injection point")


class InjectedFaultError(RuntimeError):
    """A permanent injected fault: NOT transient-classified, so retry
    policies propagate it immediately — the deterministic stand-in for
    a doomed executor / poisoned program."""


class InjectedTransientError(ConnectionError):
    """A transient injected fault.  ``ConnectionError`` + the
    "INTERNAL" marker is exactly what ``resilience.policy.is_transient``
    classifies as the documented-transient remote-compile/transport
    failure class, so the production retry path handles it with zero
    test-only special cases."""


@dataclass(frozen=True)
class FaultSpec:
    """One point's schedule: fire on the given 1-based call indices."""

    point: str
    calls: tuple[int, ...]
    mode: str = "transient"

    def __post_init__(self):
        if self.point not in POINTS:
            raise ValueError(f"unknown fault point {self.point!r}; "
                             f"choose from {'/'.join(POINTS)}")
        if self.mode not in MODES:
            raise ValueError(f"unknown fault mode {self.mode!r}; "
                             f"choose from {'/'.join(MODES)}")
        if any(c < 1 for c in self.calls):
            raise ValueError("call indices are 1-based")


class FaultPlan:
    """A set of :class:`FaultSpec` schedules plus the per-point call
    counters.  Thread-safe (the serve dispatcher and caller threads both
    cross injection points).  ``injections`` records every fired fault
    ``(point, call_index, mode)`` in firing order — the chaos report's
    ground truth."""

    def __init__(self, specs):
        self._lock = threading.Lock()
        self._calls: dict[str, int] = {}
        self._sched: dict[str, dict[int, str]] = {}
        self.specs = tuple(specs)
        for spec in self.specs:
            sched = self._sched.setdefault(spec.point, {})
            for c in spec.calls:
                if c in sched:
                    raise ValueError(
                        f"duplicate schedule for {spec.point!r} call {c}")
                sched[c] = spec.mode
        self.injections: list[tuple[str, int, str]] = []

    @classmethod
    def seeded(cls, seed: int, horizon: int = 20,
               points: dict | None = None) -> "FaultPlan":
        """Derive nth-call schedules from a seed: for each point, pick
        ``count`` distinct call indices uniformly in [1, horizon] with a
        ``np.random.default_rng(seed)`` stream.  Same seed, same points
        dict -> byte-identical plan, run after run.  This is THE seeded
        schedule builder — the chaos demo parameterizes it rather than
        forking its own derivation.

        ``points`` maps point name -> injection count, or
        -> ``(count, horizon)`` to bound a point's schedule by how often
        that point is actually reached (e.g. ``compile`` fires ~once
        per bucket, ``execute`` once per dispatched batch); the default
        is the chaos-demo mix (compile failures, transient execute
        errors, NaN result corruption, plan-cache write failures — the
        ISSUE 5 acceptance set).  Seeded modes: ``plan_cache_write`` ->
        oserror, ``result_corrupt_nan`` -> corrupt, ``replica_kill`` ->
        permanent (a process crash is not transient — the replica dies
        and the supervisor replaces it, ISSUE 7), ``preempt`` ->
        permanent (a preempted chip does not come back for a retry in
        place — the checkpointed runner types it and the caller
        resumes, ISSUE 20), everything else transient (other permanent
        faults are a deliberate hand-built choice, never a seeded
        surprise).
        """
        if points is None:
            points = {"compile": 1, "execute": 3,
                      "result_corrupt_nan": 2, "plan_cache_write": 1}
        rng = np.random.default_rng(seed)
        specs = []
        # Deterministic iteration order: sorted point names, so the rng
        # stream consumption (and therefore the plan) is seed-only.
        for point in sorted(points):
            spec = points[point]
            count, h = spec if isinstance(spec, tuple) else (spec, horizon)
            if count < 1:
                continue
            count = min(count, h)
            calls = tuple(sorted(
                int(c) + 1
                for c in rng.choice(h, size=count, replace=False)))
            mode = ("oserror" if point == "plan_cache_write"
                    else "corrupt" if point == "result_corrupt_nan"
                    else "permanent" if point in ("replica_kill",
                                                  "preempt")
                    else "transient")
            specs.append(FaultSpec(point, calls, mode))
        return cls(specs)

    # ---- firing ------------------------------------------------------

    def _hit(self, point: str) -> str | None:
        """Count one call at ``point``; return the scheduled mode if
        this call index fires, else None."""
        if point not in POINTS:
            raise ValueError(f"unknown fault point {point!r}")
        with self._lock:
            idx = self._calls.get(point, 0) + 1
            self._calls[point] = idx
            mode = self._sched.get(point, {}).get(idx)
            if mode is not None:
                self.injections.append((point, idx, mode))
        if mode is not None:
            _M_INJECTED.inc(point=point)
            # Black box (ISSUE 8): every fired injection is a recorded
            # event, so the chaos checkers can validate the CAUSAL
            # chain (fault -> retry/reroute/rung -> clean response)
            # instead of only reconciling end-state counters.
            _recorder.record("fault_injected", point=point, call=idx,
                             mode=mode)
        return mode

    def fire(self, point: str) -> None:
        """Count a call at a raise-style point; raise per the schedule."""
        mode = self._hit(point)
        if mode is None or mode == "corrupt":
            # A corrupt schedule on a raise point is a no-op rather than
            # an error: the raise points cannot poison a result.
            return
        msg = f"injected {mode} fault at point {point!r}"
        if mode == "transient":
            raise InjectedTransientError(f"INTERNAL: {msg}")
        if mode == "oserror":
            raise OSError(28, f"{msg} (simulated disk full)")
        raise InjectedFaultError(msg)

    def corrupt(self, point: str) -> bool:
        """Count a call at a corrupt-style point; True when this call's
        result should be poisoned by the call site."""
        return self._hit(point) == "corrupt"

    # ---- reporting ---------------------------------------------------

    @property
    def injected_total(self) -> int:
        with self._lock:
            return len(self.injections)

    def calls(self) -> dict[str, int]:
        with self._lock:
            return dict(self._calls)

    def report(self) -> dict:
        """Plain-JSON view for the chaos report: per-point injected
        counts plus the full firing log."""
        with self._lock:
            by_point: dict[str, int] = {}
            for point, _, _ in self.injections:
                by_point[point] = by_point.get(point, 0) + 1
            return {
                "injected_total": len(self.injections),
                "injected_by_point": by_point,
                "calls_by_point": dict(self._calls),
                "log": [{"point": p, "call": c, "mode": m}
                        for p, c, m in self.injections],
            }


#: THE active plan (module global, visible across threads — the serve
#: dispatcher must see the plan the test thread activated).  None means
#: every injection point is a single attribute-load no-op.
_ACTIVE: FaultPlan | None = None
_ACTIVE_LOCK = threading.Lock()


@contextlib.contextmanager
def activate(plan: FaultPlan):
    """Install ``plan`` as the process-wide active fault plan for the
    duration of the block.  Nesting is rejected: two overlapping chaos
    scopes would make nth-call counting ambiguous."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError("a FaultPlan is already active; chaos "
                               "scopes do not nest")
        _ACTIVE = plan
    try:
        yield plan
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = None


def active() -> FaultPlan | None:
    return _ACTIVE


def fire(point: str) -> None:
    """The raise-style injection point hook.  No active plan: one
    global load, zero work (the warm-path contract)."""
    plan = _ACTIVE
    if plan is not None:
        plan.fire(point)


def corrupt(point: str) -> bool:
    """The corrupt-style injection point hook; False when quiescent."""
    plan = _ACTIVE
    return False if plan is None else plan.corrupt(point)
