"""``ckpt_demo`` — the ``--ckpt-demo`` CLI mode's engine (ISSUE 20
acceptance).

One self-contained run proves the preemption-safety contract end to
end, four legs sharing ONE :class:`~.checkpoint.CheckpointStore` (so
the ledger invariant ``written == resumed + discarded + live`` spans
the whole demo):

  1. **single_invert** — a single-device blocked invert is preempted
     mid-sweep by the seeded ``preempt`` fault (a DERIVED schedule —
     ``FaultPlan.seeded`` — never a probability), typed
     :class:`~.checkpoint.PreemptedError` AFTER the boundary's
     checkpoint is durable; the resume re-enters at that superstep and
     must produce the BIT-IDENTICAL inverse of the uninterrupted
     baseline with ZERO segment compiles (the warm-resume pin).
  2. **dist_solve** — the same discipline on a 1D ``p``-worker sharded
     solve (the mid-sweep state is the full distributed working set:
     [A|X] shards, per-worker singular flags, the pivot/permutation
     record).
  3. **lp_stream** — a resumable LP optimization stream: the driver
     persists the resident-handle bytes + iterate audit every
     ``ckpt_every`` iterations; the preempted stream resumes to the
     IDENTICAL ``kkt_hex`` fingerprint trail and final certificate
     fingerprint.
  4. **fleet_kill** — the fleet journey: a checkpointed distributed
     solve is routed to a replica, the replica is KILLED mid-sweep
     (the runner's abort hook surfaces it at the next segment
     boundary, after that boundary's checkpoint is durable), and the
     router re-queues with a RESUME (``ckpt_resume`` journey hop) —
     the surviving replica finishes from the last durable superstep,
     bit-matching the uninterrupted run.  Lost work is bounded by the
     cadence in every leg.

Returns the one-line-JSON report ``tools/check_ckpt.py`` validates
(exit 2 = a silent from-scratch recompute, a divergent resume, an
unpaired preemption, or a ledger that does not add up).  Needs an
8-device host and x64: re-execs itself on a forced virtual CPU
platform when the current process cannot host that (the dryrun
recipe, shared with the comm demo)."""

from __future__ import annotations

import tempfile
import time

import numpy as np


def _preempt_plan(seed: int, horizon: int):
    """The seeded preempt schedule for one leg: ONE hit, call index
    derived from the seed over ``horizon`` boundary calls — same seed,
    same schedule, byte-identical run after run."""
    from . import FaultPlan

    return FaultPlan.seeded(seed, points={"preempt": (1, horizon)})


def _run_preempted(fn, plan):
    """Run ``fn`` under ``plan``; return the typed PreemptedError (or
    None when the schedule never fired — a reportable condition, not a
    crash)."""
    from . import activate
    from .checkpoint import PreemptedError

    try:
        with activate(plan):
            fn()
    except PreemptedError as e:
        return e
    return None


def ckpt_demo(n: int = 96, block_size: int = 16, cadence: int = 2,
              seed: int = 0, workers: int = 4, lp_m: int = 8,
              ckpt_dir: str | None = None, dtype=None) -> dict:
    """Run the four-leg preemption-safety acceptance demo; returns the
    report ``tools/check_ckpt.py`` validates.  ``ckpt_dir`` None = a
    temp store deleted after; pass a path to inspect the checkpoint
    files and ledger afterwards."""
    import json
    import subprocess
    import sys

    import jax

    from ..obs.comm import _cpu_env, _repo_root

    try:
        can_inline = (len(jax.devices()) >= max(8, workers)
                      and jax.config.jax_enable_x64)
    except RuntimeError:
        can_inline = False
    if not can_inline:
        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_enable_x64', True)\n"
            "import json\n"
            "from tpu_jordan.resilience.ckpt_demo import ckpt_demo\n"
            f"print(json.dumps(ckpt_demo(n={int(n)}, "
            f"block_size={int(block_size)}, cadence={int(cadence)}, "
            f"seed={int(seed)}, workers={int(workers)}, "
            f"lp_m={int(lp_m)}, ckpt_dir={ckpt_dir!r})))\n")
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=_cpu_env(max(8, workers)), cwd=_repo_root(),
            capture_output=True, text=True, timeout=900)
        if proc.returncode != 0:
            raise RuntimeError(
                f"ckpt_demo subprocess failed (rc={proc.returncode}): "
                f"{proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    return _ckpt_demo_inline(n, block_size, cadence, seed, workers,
                             lp_m, ckpt_dir, dtype)


def _ckpt_demo_inline(n, block_size, cadence, seed, workers, lp_m,
                      ckpt_dir, dtype) -> dict:
    import shutil
    import threading

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ..fleet import JordanFleet
    from ..lpqp import lp_instance, solve_lp
    from ..obs.recorder import RECORDER
    from ..parallel.layout import CyclicLayout
    from ..parallel.mesh import AXIS
    from ..resilience import ResiliencePolicy
    from ..resilience.policy import RetryPolicy
    from .checkpoint import (CheckpointStore, checkpointed_invert,
                             checkpointed_solve, fingerprint)

    t_all = time.perf_counter()
    dt = jnp.dtype(dtype if dtype is not None else jnp.float64)
    m = int(block_size)
    cadence = int(cadence)
    tmp_dir = None
    if ckpt_dir is None:
        tmp_dir = tempfile.mkdtemp(prefix="tpu_jordan_ckpt_")
        ckpt_dir = tmp_dir
    store = CheckpointStore(ckpt_dir)
    mark = RECORDER.total
    rng = np.random.default_rng(seed)
    legs = {}
    try:
        # ---- leg 1: single-device invert, seeded preempt ------------
        a1 = np.asarray(rng.standard_normal((n, n)) + n * np.eye(n), dt)
        Nr1 = -(-n // m)
        boundaries1 = len(range(0, Nr1, cadence))
        inv_base, _, _ = checkpointed_invert(
            a1, m, store=store, run_id="demo:single:base",
            cadence=cadence, engine="fori")
        fp_base1 = fingerprint(inv_base)
        plan1 = _preempt_plan(seed, max(1, boundaries1 - 1))
        pe1 = _run_preempted(
            lambda: checkpointed_invert(
                a1, m, store=store, run_id="demo:single",
                cadence=cadence, engine="fori"), plan1)
        inv_res, _, info1 = checkpointed_invert(
            a1, m, store=store, run_id="demo:single", cadence=cadence,
            engine="fori",
            resume_from=("demo:single" if pe1 is not None
                         and pe1.step is not None else None))
        fp1 = fingerprint(inv_res)
        legs["single_invert"] = {
            "run_id": "demo:single", "workload": "invert",
            "topology": "single", "engine": "fori", "n": n,
            "block_size": m, "Nr": Nr1, "cadence": cadence,
            "planned_calls": plan1.report(),
            "preempt_step": (-1 if pe1 is None or pe1.step is None
                             else int(pe1.step)),
            "baseline_fp": fp_base1, "resume_fp": fp1,
            "bit_match": fp1 == fp_base1,
            "resume_start_step": info1["start_step"],
            "resumed": info1["resumed"],
            "resume_segments": info1["segments_run"],
            "resume_compiles": info1["segment_compiles"],
        }

        # ---- leg 2: 1D distributed solve, seeded preempt ------------
        mesh = Mesh(np.array(jax.devices()[:workers]), (AXIS,))
        a2 = np.asarray(rng.standard_normal((n, n)) + n * np.eye(n), dt)
        b2 = np.asarray(rng.standard_normal((n, 4)), dt)
        lay = CyclicLayout.create(n, m, workers)
        boundaries2 = len(range(0, lay.Nr, cadence))
        x_base, _, _ = checkpointed_solve(
            a2, b2, m, store=store, run_id="demo:dist:base",
            cadence=cadence, engine="fori", mesh=mesh)
        fp_base2 = fingerprint(x_base)
        plan2 = _preempt_plan(seed, max(1, boundaries2 - 1))
        pe2 = _run_preempted(
            lambda: checkpointed_solve(
                a2, b2, m, store=store, run_id="demo:dist",
                cadence=cadence, engine="fori", mesh=mesh), plan2)
        x_res, _, info2 = checkpointed_solve(
            a2, b2, m, store=store, run_id="demo:dist",
            cadence=cadence, engine="fori", mesh=mesh,
            resume_from=("demo:dist" if pe2 is not None
                         and pe2.step is not None else None))
        fp2 = fingerprint(x_res)
        legs["dist_solve"] = {
            "run_id": "demo:dist", "workload": "solve",
            "topology": f"1d:{workers}", "engine": "fori", "n": n,
            "block_size": m, "Nr": lay.Nr, "cadence": cadence,
            "planned_calls": plan2.report(),
            "preempt_step": (-1 if pe2 is None or pe2.step is None
                             else int(pe2.step)),
            "baseline_fp": fp_base2, "resume_fp": fp2,
            "bit_match": fp2 == fp_base2,
            "resume_start_step": info2["start_step"],
            "resumed": info2["resumed"],
            "resume_segments": info2["segments_run"],
            "resume_compiles": info2["segment_compiles"],
        }

        # ---- leg 3 + 4 share a fleet policy -------------------------
        policy = ResiliencePolicy(
            retry=RetryPolicy(max_retries=4, backoff_s=0.0))
        fleet_kw = dict(replicas=2, engine="auto", dtype=dt,
                        batch_cap=1, max_wait_ms=0.5,
                        stable_after_s=0.2, liveness_deadline_s=30.0,
                        policy=policy)

        # ---- leg 3: resumable LP stream, seeded preempt -------------
        prob = lp_instance(m=lp_m, seed=seed + 3, cond="well")
        with JordanFleet(**fleet_kw) as flt:
            ref = solve_lp(prob, flt)
        lp_iters = ref.iterations
        ckpt_every = 3
        plan3 = _preempt_plan(seed, max(2, lp_iters - 2))
        with JordanFleet(**fleet_kw) as flt:
            pe3 = _run_preempted(
                lambda: solve_lp(prob, flt, ckpt_store=store,
                                 ckpt_every=ckpt_every,
                                 run_id="demo:lp"), plan3)
            # Nothing durable (preempt before the first cadence write,
            # or the stream finished first): a from-scratch run is the
            # CORRECT recovery — lost work is still < one cadence
            # window — and the report says so; with a durable token the
            # resume is mandatory (a silent from-scratch there is the
            # checker's exit-2).
            rep = solve_lp(prob, flt, ckpt_store=store,
                           ckpt_every=ckpt_every, run_id="demo:lp",
                           resume=(pe3 is not None
                                   and pe3.step is not None))
        legs["lp_stream"] = {
            "run_id": "demo:lp", "workload": "lp",
            "topology": "fleet", "engine": "simplex",
            "n": prob.n, "Nr": lp_iters, "cadence": ckpt_every,
            "planned_calls": plan3.report(),
            "preempt_step": (-1 if pe3 is None or pe3.step is None
                             else int(pe3.step)),
            "baseline_fp": ref.fingerprint,
            "resume_fp": rep.fingerprint,
            "bit_match": rep.fingerprint == ref.fingerprint,
            "resume_start_step": (int(pe3.step)
                                  if pe3 is not None
                                  and pe3.step is not None else 0),
            "resumed": pe3 is not None and pe3.step is not None,
            "kkt_trail_match": ([r["kkt_hex"] for r in ref.iterates]
                                == [r["kkt_hex"] for r in rep.iterates]),
            "resume_compiles": 0,
        }

        # ---- leg 4: fleet kill-path resume --------------------------
        a4 = np.asarray(rng.standard_normal((n, n)) + n * np.eye(n), dt)
        b4 = np.asarray(rng.standard_normal((n, 4)), dt)
        spec = {"store": store, "cadence": cadence, "engine": "fori",
                "mesh": mesh, "block_size": m}
        with JordanFleet(**fleet_kw) as flt:
            res_b = flt.solve_system(
                a4, b4, timeout=600.0,
                ckpt=dict(spec, run_id="demo:fleet:base"))
            fp_base4 = fingerprint(res_b.solution)
            # The kill is wall-clock racy (the sweep may finish before
            # the killer lands): bounded retries with fresh run ids
            # until a kill provably interrupted the sweep and the
            # re-queued hop RESUMED it — the report records how many
            # attempts the race cost (never a silent pass).
            attempts = 0
            while True:
                attempts += 1
                run_id = f"demo:fleet:{attempts}"
                fut = flt.submit_solve(a4, b4,
                                       ckpt=dict(spec, run_id=run_id))
                t0 = time.monotonic()
                while not store.has_live(run_id):
                    if time.monotonic() - t0 > 300:
                        raise RuntimeError(
                            "fleet leg: no checkpoint became durable")
                    time.sleep(0.001)
                serving = {t.name.split("tpu-jordan-ckpt-")[1]
                           for t in threading.enumerate()
                           if t.name.startswith("tpu-jordan-ckpt-")}
                killed = [r.name for r in flt.live_replicas()
                          if r.name in serving
                          and r.kill(reason="chaos")]
                res4 = fut.result(timeout=600.0)
                if res4.ckpt_info["resumed"] or attempts >= 3:
                    break
        fp4 = fingerprint(res4.solution)
        info4 = res4.ckpt_info
        legs["fleet_kill"] = {
            "run_id": run_id, "workload": "solve",
            "topology": f"1d:{workers}", "engine": "fori", "n": n,
            "block_size": m, "Nr": lay.Nr, "cadence": cadence,
            "killed_replicas": killed, "kill_attempts": attempts,
            "preempt_step": info4["start_step"],
            "baseline_fp": fp_base4, "resume_fp": fp4,
            "bit_match": fp4 == fp_base4,
            "resume_start_step": info4["start_step"],
            "resumed": info4["resumed"],
            "resume_segments": info4["segments_run"],
            "resume_compiles": info4["segment_compiles"],
        }
    finally:
        if tmp_dir is not None:
            shutil.rmtree(tmp_dir, ignore_errors=True)

    from ..obs.metrics import REGISTRY

    c = REGISTRY.counter
    counters = {
        "written": c("tpu_jordan_ckpt_written_total").total(),
        "resumed": c("tpu_jordan_ckpt_resumed_total").total(),
        "corrupt": c("tpu_jordan_ckpt_corrupt_total").total(),
        "discarded": c("tpu_jordan_ckpt_discarded_total").total(),
    }
    ledger = store.ledger()
    # The demo's own verdict (the checker re-derives it independently):
    # a divergent resume, a durable checkpoint silently ignored, a
    # recompiling warm resume, or a ledger that does not add up.
    silent_loss = (
        not ledger["invariant_holds"]
        or any(not leg["bit_match"]
               or leg.get("resume_compiles", 1) != 0
               or (leg.get("preempt_step", -1) >= 0
                   and not leg.get("resumed"))
               for leg in legs.values()))
    return {
        "metric": "ckpt_demo",
        "n": n, "block_size": m, "cadence": cadence, "seed": seed,
        "workers": workers, "dtype": str(dt),
        "legs": legs,
        "ledger": ledger,
        "counters": counters,
        "silent_loss": silent_loss,
        "blackbox": RECORDER.dump(events=RECORDER.since(mark)),
        "elapsed_s": round(time.perf_counter() - t_all, 3),
    }
