"""tpu_jordan.resilience — deterministic fault injection, policy-driven
retry/deadline/circuit-breaking, and the numerical degradation ladder
(ISSUE 5 tentpole; docs/RESILIENCE.md is the operator guide).

Three modules:

  * ``faults`` — named injection points (compile / execute /
    plan_cache_write / measure / result_corrupt_nan / dispatch)
    activated by a seeded :class:`FaultPlan` of nth-call schedules —
    never probabilities — so every chaos test replays exactly.
  * ``policy`` — the shared transient classifier + :class:`RetryPolicy`
    (deterministic-jitter backoff, injectable sleep), the typed
    :class:`DeadlineExceededError` / :class:`CircuitOpenError` /
    :class:`ResultCorruptionError` failures, the per-bucket
    :class:`CircuitBreaker`, and the :class:`ResiliencePolicy` umbrella
    the product surface takes.
  * ``degrade`` — the residual-gate degradation ladder: refine
    (Newton-Schulz) then a higher-precision re-solve, each rung recorded
    on ``SolveResult.recovery`` and in the span tree; a wrong inverse is
    never returned silently (:class:`ResidualGateError`).
"""

from . import faults
from .faults import (FaultPlan, FaultSpec, InjectedFaultError,
                     InjectedTransientError, activate)
from .policy import (DEFAULT_POLICY, CapacityExceededError,
                     CircuitBreaker, CircuitOpenError,
                     DeadlineExceededError, ResidualGateError,
                     ResiliencePolicy, ResultCorruptionError, RetryPolicy,
                     is_transient, retry_transient, retryable)

__all__ = [
    "faults", "FaultPlan", "FaultSpec", "InjectedFaultError",
    "InjectedTransientError", "activate",
    "DEFAULT_POLICY", "CapacityExceededError", "CircuitBreaker",
    "CircuitOpenError", "DeadlineExceededError", "ResidualGateError",
    "ResiliencePolicy", "ResultCorruptionError", "RetryPolicy",
    "is_transient", "retry_transient", "retryable",
]
