"""tpu_jordan.resilience — deterministic fault injection, policy-driven
retry/deadline/circuit-breaking, and the numerical degradation ladder
(ISSUE 5 tentpole; docs/RESILIENCE.md is the operator guide).

Three modules:

  * ``faults`` — named injection points (compile / execute /
    plan_cache_write / measure / result_corrupt_nan / dispatch)
    activated by a seeded :class:`FaultPlan` of nth-call schedules —
    never probabilities — so every chaos test replays exactly.
  * ``policy`` — the shared transient classifier + :class:`RetryPolicy`
    (deterministic-jitter backoff, injectable sleep), the typed
    :class:`DeadlineExceededError` / :class:`CircuitOpenError` /
    :class:`ResultCorruptionError` failures, the per-bucket
    :class:`CircuitBreaker`, and the :class:`ResiliencePolicy` umbrella
    the product surface takes.
  * ``degrade`` — the residual-gate degradation ladder: refine
    (Newton-Schulz) then a higher-precision re-solve, each rung recorded
    on ``SolveResult.recovery`` and in the span tree; a wrong inverse is
    never returned silently (:class:`ResidualGateError`).
  * ``checkpoint`` — preemption-safe execution (ISSUE 20): superstep
    elimination snapshots to a host-side :class:`CheckpointStore` at a
    configurable cadence, ``resume_from=`` re-entry that bit-matches
    the uninterrupted run, and the typed refusal taxonomy
    (missing/corrupt/mismatched/unsupported — never a silent
    from-scratch recompute).
"""

from . import faults
from .checkpoint import (CheckpointCorruptError, CheckpointError,
                         CheckpointKey, CheckpointMismatchError,
                         CheckpointNotFoundError, CheckpointStore,
                         CheckpointUnsupportedError, PreemptedError,
                         checkpointed_invert, checkpointed_solve,
                         fingerprint)
from .faults import (FaultPlan, FaultSpec, InjectedFaultError,
                     InjectedTransientError, activate)
from .policy import (DEFAULT_POLICY, CapacityExceededError,
                     CircuitBreaker, CircuitOpenError,
                     DeadlineExceededError, ResidualGateError,
                     ResiliencePolicy, ResultCorruptionError, RetryPolicy,
                     is_transient, retry_transient, retryable)

__all__ = [
    "faults", "FaultPlan", "FaultSpec", "InjectedFaultError",
    "InjectedTransientError", "activate",
    "DEFAULT_POLICY", "CapacityExceededError", "CircuitBreaker",
    "CircuitOpenError", "DeadlineExceededError", "ResidualGateError",
    "ResiliencePolicy", "ResultCorruptionError", "RetryPolicy",
    "is_transient", "retry_transient", "retryable",
    "CheckpointError", "CheckpointNotFoundError",
    "CheckpointCorruptError", "CheckpointMismatchError",
    "CheckpointUnsupportedError", "PreemptedError", "CheckpointKey",
    "CheckpointStore", "checkpointed_invert", "checkpointed_solve",
    "fingerprint",
]
