"""Framework-wide numeric configuration.

The reference hard-codes ``EPS = 1e-15`` (main.cpp:7) as the *relative*
singularity threshold for fp64: a pivot is singular when
``|pivot| < EPS * norm(A)`` (main.cpp:782).  TPUs are fp32/bf16-native, so the
threshold must scale with the working precision; fp64 keeps the reference
value exactly.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Relative singularity thresholds per dtype.  fp64 matches the reference
# (main.cpp:7); the others keep the same ~4.5x-machine-eps margin.
_EPS_BY_DTYPE = {
    np.dtype(np.float64): 1e-15,
    np.dtype(np.float32): 5e-7,
    np.dtype(jnp.bfloat16): 4e-2,
    np.dtype(np.float16): 4e-3,
    # Complex dtypes (ISSUE 11): pivot magnitudes are |z| (real), so the
    # threshold is the component dtype's — complex64 arithmetic carries
    # float32 rounding, complex128 float64.
    np.dtype(np.complex64): 5e-7,
    np.dtype(np.complex128): 1e-15,
}

# Matches MAX_P in the reference (main.cpp:6): pretty-printers show at most
# this many rows/cols of a matrix corner.
MAX_PRINT = 10


def eps_for(dtype) -> float:
    """Relative singularity threshold for ``dtype``.

    Mirrors the role of ``EPS`` in the reference (main.cpp:7, used at
    main.cpp:782), generalized across precisions.
    """
    dt = np.dtype(dtype)
    try:
        return _EPS_BY_DTYPE[dt]
    except KeyError:
        raise ValueError(f"no singularity threshold known for dtype {dt}")


def default_block_size(n: int) -> int:
    """A reasonable MXU-friendly block size for an n x n problem.

    The reference exposes block size as the runtime knob ``m`` (argv) and its
    fast path needs m % 3 == 0 (main.cpp:158).  On TPU the analogous
    constraint is alignment to the 128-lane MXU tile, so we pick multiples
    of 128 (or small powers of two below that for tiny problems).

    Measured on v5e (benchmarks/PHASES.md): m=128 is the throughput sweet
    spot up to n=4096 (probe cost scales with n²·m, so smaller blocks win);
    n ≥ 8192 needs m=384 at fp32 — smaller pivot blocks (m <= 256) push
    the late Schur-complement pivots under the fp32 noise floor on
    ill-conditioned fixtures and the probe (correctly) flags them
    singular, while m=384 still divides by 128 so the fused-panel probe
    kernel applies (126 ms vs 177 ms at m=512 for the 8192 inversion).
    """
    if n >= 8192:
        return 384
    if n >= 512:
        return 128
    if n >= 128:
        return 64
    return max(8, 1 << max(0, (n // 4).bit_length() - 1))
