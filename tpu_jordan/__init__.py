"""tpu_jordan — a TPU-native distributed dense linear algebra framework.

Brand-new JAX/XLA/pallas/pjit implementation of everything the MPI reference
``yusupov1alik/MPI-Jordan-crazy-acceleration`` can do: block Gauss–Jordan
matrix inversion with condition-based block pivoting, 1D row-block-cyclic
sharding, ring GEMM, residual verification, matrix generators/file I/O, and
a CLI — designed for the MXU/ICI, not translated from MPI.
"""

from . import (config, io, linalg, models, obs, ops, parallel,
               resilience, serve, tuning, utils)
from .driver import SingularMatrixError, SolveResult, solve
from .linalg import LstsqResult, SolveSystemResult, lstsq, solve_system

__version__ = "0.1.0"
