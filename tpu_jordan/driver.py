"""End-to-end solve driver.

TPU-native rebuild of ``solve`` (main.cpp:343-519): load or generate A,
print its corner, time the inversion, print the inverse's corner, then
independently verify with the residual ‖A·A⁻¹ − I‖∞ on a *freshly
regenerated/re-read* A (the reference destroys A in place and reloads it,
main.cpp:463-488 — we keep the reload semantics so verification never trusts
state left over from the algorithm).

Differences by design (documented, not accidental):
  * the residual is always computed — the reference skips it at p == 1
    without -DHILBERT (main.cpp:498-513), which is a gap in its own
    verification, not a feature worth parity;
  * timing excludes compilation (first call compiles, the timed call is the
    cached executable) and uses ``block_until_ready`` — the honest analog of
    the max-allreduced MPI_Wtime bracket (main.cpp:427-458).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .config import default_block_size
from .io import read_matrix_file
from .obs import hwcost as _hwcost
from .obs import metrics as _obs_metrics
from .obs.spans import NULL as _NULL_TEL
from .obs.spans import attribute_phases, timed_blocking
from .ops import generate, inf_norm, residual_inf_norm
from .resilience import faults as _faults


from jax import lax as _lax

from .ops.refine import PRECISIONS as _PRECISIONS


class SingularMatrixError(ArithmeticError):
    """No block column had an invertible pivot candidate — the reference's
    collective "singular matrix" exit (main.cpp:1075-1083, 435-437)."""


class UsageError(ValueError):
    """Invalid flag combination (e.g. gather=False on the single-device
    path, or refine without gather) — maps to the reference's usage exit
    code 1 (main.cpp:77-85), distinct from internal ValueErrors."""


@dataclass
class SolveResult:
    inverse: jax.Array | None
    elapsed: float          # seconds, the reference's glob_time (main.cpp:455-458)
    residual: float         # ‖A·A⁻¹ − I‖∞ (main.cpp:490-513)
    n: int
    block_size: int
    gflops: float           # 2n³ / t, the convention used in BASELINE.md
    inverse_blocks: jax.Array | None = None  # sharded cyclic blocks (gather=False)
    layout: object | None = None             # CyclicLayout of inverse_blocks
    kappa: float | None = None  # κ∞(A) = ‖A‖∞‖A⁻¹‖∞ (ops/norms.condition_inf):
    #   no reference analog — the accuracy context the residual needs
    #   (expected rel residual ≈ eps·n·κ∞/‖A‖∞, benchmarks/PHASES.md)
    engine: str | None = None   # the RESOLVED engine that ran ("auto" never
    #   appears here: the tuner's pick is recorded so callers can see —
    #   and re-request — exactly what ran)
    group: int = 0              # resolved delayed-group size (0 = ungrouped)
    plan: object | None = None  # tuning.Plan when engine="auto" selected it
    #   (source: "cache" via plan.source preserved / cost_model / measured)
    trace: object | None = None  # obs.spans.Span root ("solve") when the
    #   caller passed telemetry= — select/load/compile/execute/gather/
    #   residual children plus model-attributed hot-loop phases; the
    #   execute span's duration IS `elapsed` (one shared bracket,
    #   obs/spans.timed_blocking — they cannot disagree)
    recovery: tuple = ()  # degradation-ladder rungs this solve climbed
    #   (resilience/degrade.py, policy= solves only): one dict per rung
    #   ("refine" / "resolve") with rel_residual before/after and the
    #   pass verdict — empty on the fault-free gate-passing path.  When
    #   non-empty, `inverse` (and residual/kappa) are the RECOVERED
    #   numbers, possibly at a higher precision than requested.
    numerics: object | None = None  # obs.numerics.NumericsReport when
    #   the caller passed numerics="summary"/"trace" (ISSUE 10): the
    #   per-superstep health record — pivot criterion values, growth
    #   watermark, verified residual — mirrored into the metrics
    #   registry and spiking into the flight recorder BEFORE any
    #   recovery rung.  None at the "off" default (zero cost).
    comm: object | None = None  # obs.comm.CommReport on every
    #   DISTRIBUTED solve (ISSUE 14): the layout-derived per-phase
    #   collective byte/message accounting, the observed-vs-analytical
    #   reconciliation when obs.comm.recording() was active around the
    #   solve, and the measured-vs-projected drift record.  None on
    #   single-device solves (no collectives to account).
    work: object | None = None  # obs.work.WorkReport on every
    #   DISTRIBUTED solve (ISSUE 19): per-worker useful-FLOP shares
    #   summing EXACTLY to the 2n³ convention, the max/mean skew and
    #   ragged-tail penalty, and the cost_analysis reconciliation
    #   (devices × per-device vs the padded executed model).  None on
    #   single-device solves (one worker has no skew to account).

    @property
    def rel_residual(self) -> float | None:
        """‖A·X−I‖∞ / ‖A‖∞ — reported on every solve path (round 5:
        the distributed non-refine branches take exact row abs-sums off
        the block-sharded state, so the accuracy context scales with
        the memory-contract modes)."""
        return None if self._norm_a is None else self.residual / self._norm_a

    _norm_a: float | None = None             # ‖A‖∞, backing rel_residual


# The engine vocabulary is DERIVED from the declarative registry
# (tuning/registry.py — name, legality, cost hook per configuration);
# tests/test_tuning.py lints that the two can never drift.
from .tuning.registry import ENGINES, PALLAS_ENGINES


def _record_compile(compile_span, component: str) -> None:
    """ONE compile-accounting path across the process (solve driver,
    solve_batch, the distributed core, solver models): increments
    ``tpu_jordan_compiles_total`` — the name the warm-path acceptance
    pin scrapes — and observes the span's duration into
    ``tpu_jordan_compile_seconds``, so the counter and the histogram's
    ``_count`` can never disagree."""
    _obs_metrics.counter(
        "tpu_jordan_compiles_total",
        "executable compiles (solve driver, solver models, serve "
        "executor cache)").inc(component=component)
    _obs_metrics.histogram(
        "tpu_jordan_compile_seconds",
        "wall seconds spent in AOT lowering+compilation",
    ).observe(compile_span.duration, component=component)


def _attribute_solve_phases(tel, esp, engine: str, n: int,
                            block_size: int, group: int = 0) -> None:
    """Phase attribution under the ``execute`` span (single-device
    solves): the fused-kernel engines get MEASURED children — the
    probe, swap, and update kernels are separately launchable, so the
    host brackets each once per configuration and scales the measured
    fractions onto the execute span (``measured=True``,
    ``source="kernel_bracket"``) — while the pure-XLA engines keep the
    flops-model split (``modeled=True``; the host cannot bracket inside
    one fused XLA executable).  tools/check_telemetry.py fails any
    Pallas-path trace that still carries modeled phase children.

    The kernel brackets cost three timed launches per (n, m, group,
    mode) configuration — size-capped at a 4096-edge bracket twin so
    they can never OOM a large solve (pallas_update._BRACKET_MAX_N) —
    and only run when the telemetry actually retains spans
    (``NullTelemetry`` keeps the warm path free)."""
    if engine in PALLAS_ENGINES and getattr(tel, "retain", False):
        from .obs.spans import attribute_phases_measured
        from .ops.pallas_update import measured_phase_fractions

        mode = "bf16" if engine.endswith("bf16") else "fp32"
        fractions = measured_phase_fractions(n, block_size,
                                             group or 2, mode=mode)
        attribute_phases_measured(esp, fractions,
                                  source="kernel_bracket")
    else:
        attribute_phases(esp, n, block_size,
                         lookahead=engine == "lookahead")


def _dist_workers(be):
    """The driver workers spec a distributed backend was built from
    (p or (pr, pc)) — recovered from the layout, for TunePoint keys."""
    lay = be.lay
    return (lay.pr, lay.pc) if hasattr(lay, "pc") else lay.p


def _attach_overlap_evidence(esp, n: int, block_size: int,
                             workers) -> None:
    """Scheduling evidence on a lookahead execute span (ISSUE 16): the
    comm model's projected probe-overlap headroom —
    min(probe, elim)/total, the SAME number the registry's lookahead
    cost hooks discount by — attached next to the hwcost attrs so a
    trace reader can compare the projected hideable fraction against
    the measured wall.  Best-effort: a point the comm model cannot
    price leaves the attr absent, never fabricated."""
    try:
        from .tuning.registry import TunePoint, probe_overlap_headroom

        pt = TunePoint.create(n, block_size, workers=workers)
        esp.attrs["probe_overlap_headroom"] = float(
            f"{probe_overlap_headroom(pt):.4g}")
    except Exception:                            # noqa: BLE001
        pass


def _solve_metrics(n: int, elapsed: float, exec_span,
                   singular: bool = False, batch: int = 1) -> None:
    """Registry bookkeeping shared by every solve path; GFLOP/s rides
    the execute span as an attribute (the Scoreboard convention)."""
    _obs_metrics.counter("tpu_jordan_solves_total",
                         "driver solves executed").inc()
    _obs_metrics.histogram(
        "tpu_jordan_solve_seconds",
        "timed elimination wall seconds (the glob_time analog)",
    ).observe(elapsed)
    if elapsed > 0:
        exec_span.attrs["gflops"] = round(
            2.0 * n**3 * batch / elapsed / 1e9, 3)
    if singular:
        _obs_metrics.counter("tpu_jordan_singular_total",
                             "solves/requests flagged singular"
                             ).inc(component="solve")


def _trace_engine_for(engine: str) -> str:
    """Which instrumented twin a ``numerics="trace"`` solve runs: the
    fp32 fused-kernel engine traces through its BIT-MATCHING XLA
    grouped twin (the ISSUE 6 pin — identical pivot choices, identical
    result bits, so the trace is the truth about the Pallas solve
    too); every other engine traces itself."""
    return "grouped" if engine == "grouped_pallas" else engine


def _numerics_report(numerics: str, *, n, block_size, engine, residual,
                     norm_a, kappa, dtype, policy, nstats=None):
    """Build + observe + spike one solve's numerics record (ISSUE 10).

    MUST run before the recovery ladder: the spike events this records
    are the causal explanation a later ``recovery_rung`` flight-
    recorder event points back to (tools/check_numerics.py validates
    the seq ordering).  When a policy is attached, the residual spike
    threshold IS the policy's own gate threshold — a gate failure can
    never outrun its spike."""
    from .obs import numerics as _numerics

    rel = residual / norm_a if norm_a else residual
    kw = dict(n=n, block_size=block_size, engine=engine,
              rel_residual=rel, kappa=kappa, norm_a=norm_a, dtype=dtype)
    if numerics == "trace":
        report = _numerics.trace_report(
            nstats, trace_engine=_trace_engine_for(engine), **kw)
    else:
        report = _numerics.summary_report(**kw)
    _numerics.observe(report)
    thresholds = None
    if policy is not None:
        from .resilience.degrade import gate_threshold

        gd = policy.gate_dtype if policy.gate_dtype is not None else dtype
        thresholds = _numerics.SpikeThresholds(
            residual=gate_threshold(policy, n, kappa, gd))
    _numerics.record_spikes(report, thresholds)
    return report


def resolve_engine(engine: str, group: int):
    """Shared engine/group flag contract (solve, JordanSolver, CLI).

    Returns the resolved ``(engine, group)`` pair: "auto" stays "auto"
    (the caller then routes it through the autotuner ladder —
    ``tuning.auto_select``: plan cache, cost-model ranking over the
    declarative registry, optionally measured tuning) unless
    ``group > 1`` explicitly opts into the delayed-group-update engine;
    "grouped" defaults ``group`` to the measured-best k=2.

    Measured dispatch guidance (benchmarks/PHASES.md round 4, v5e fp32):
    for WELL-CONDITIONED matrices at n >= 8192, ``engine="grouped"`` with
    block_size=128, group=2 is the fastest configuration (22.2 TF/s at
    16384² — 72% of the chip's matmul envelope — vs 20.3 for the plain
    engine at its best m); at n <= 4096, or on ill-conditioned inputs
    where small pivot blocks sit under the fp32 noise floor (the |i−j|
    fixture at n >= 8192 with m <= 256), the plain engine at the
    default block size remains the right choice.  This policy is
    encoded as a cost-hook prior in the registry (grouped is never
    cost-preferred on a single chip below 8192), so cost-only "auto"
    reproduces it; a measured tuning run (``tune=True``) can still
    overrule the model with evidence.

    "swapfree" is the distributed pod-scale comm design (lowest
    projected comm bill at the v5p north-star meshes) and is legal
    under either gather mode: its deferred permutations run as bucketed
    ppermute rounds with residency capped at one shard
    (parallel/permute.py), so it composes with gather=False — the only
    memory mode that reaches 32768²+.
    """
    if engine not in ENGINES:
        raise UsageError(f"unknown engine {engine!r}; choose from "
                         f"{'/'.join(ENGINES)}")
    if group < 0:
        raise UsageError("group must be >= 0")
    if group == 1:
        # group=1 IS the plain in-place engine (one panel per "group");
        # honoring it silently as k=2 — or running the plain engine
        # under the grouped label — would misreport the configuration.
        raise UsageError("group=1 is the plain in-place engine; use "
                         "engine='inplace' (or group >= 2)")
    if group > 1 and engine == "inplace":
        raise UsageError("group > 1 requires engine='grouped' (or 'auto')")
    if group > 1 and engine == "augmented":
        raise UsageError("the augmented reference-parity engine has no "
                         "grouped variant")
    if group > 1 and engine == "swapfree":
        raise UsageError("the swap-free engine has no grouped variant")
    if engine == "lookahead":
        # group >= 2 selects the grouped lookahead twin (single-device
        # only — the distributed compile fns refuse the combination with
        # a typed UsageError of their own).
        return "lookahead", (group if group > 1 else 0)
    if engine == "grouped":
        return "grouped", (group if group > 1 else 2)
    if engine in PALLAS_ENGINES:
        # The fused-kernel engines are grouped engines (the kernel IS
        # the group-closing superstep); same default k=2 as "grouped".
        return engine, (group if group > 1 else 2)
    if engine == "auto" and group > 1:
        return "grouped", group
    return engine, 0


def solve(
    n: int,
    block_size: int | None = None,
    file: str | None = None,
    generator: str = "absdiff",
    dtype=jnp.float32,
    refine: int = 0,
    workers: int = 1,
    device=None,
    verbose: bool = False,
    gather: bool = True,
    precision: str = "highest",
    engine: str = "auto",
    group: int = 0,
    tune: bool = False,
    plan_cache: str | None = None,
    telemetry=None,
    policy=None,
    numerics: str = "off",
) -> SolveResult:
    """Invert an n x n matrix from a file or a generator and verify it.

    ``telemetry`` (an ``obs.spans.Telemetry``) records the solve as a
    span tree — ``solve`` root with select/load/compile/execute/gather/
    residual children, model-attributed hot-loop phases (pivot /
    permute / eliminate) under ``execute`` — returned on
    ``SolveResult.trace`` and exportable as Chrome trace-event JSON
    (``obs/export.py``, docs/OBSERVABILITY.md).  The driver's metrics
    (solves, compiles, singular flags, timings) land in the
    process-wide ``obs.metrics.REGISTRY`` either way.

    ``workers > 1`` runs the distributed path: 1D mesh over that many
    devices, sharded elimination, ring-GEMM residual — the analog of
    ``mpirun -np workers`` on the reference.  A *tuple* ``workers=(pr, pc)``
    runs the 2D block-cyclic path instead: both matrix axes sharded over a
    (pr, pc) mesh, SUMMA residual — per-worker memory O(n²/(pr·pc)), the
    scaling mode the reference's rows-only layout can't reach
    (main.cpp:366-370).  When the matrix comes from a generator, every
    worker builds its own shard on device (init_matrix parity,
    main.cpp:128-149); file input streams one block-row strip at a time
    straight onto the owner devices (read_matrix parity,
    main.cpp:242-276) — either way no n×n host array exists on
    distributed meshes.  With ``gather=False`` the inverse too stays as
    sharded cyclic blocks (``result.inverse_blocks`` +
    ``result.layout``), the memory-scaling mode for north-star sizes.

    ``precision``: "highest" (default, fp32-faithful products), "high"
    (bf16x3 products), or "mixed" (HIGH sweeps + ≥2 HIGHEST Newton–Schulz
    steps — ~2.7x cheaper sweeps for well-scaled matrices; see
    benchmarks/PHASES.md for the measured accuracy ladder).

    ``engine``/``group`` select the elimination engine (resolve_engine:
    "auto" | "inplace" | "grouped" | "augmented" | "swapfree" |
    "grouped_pallas" | "grouped_pallas_bf16" | "lookahead"; the measured
    dispatch policy lives in its docstring).  ``engine="lookahead"``
    (ISSUE 16) reorders each superstep — critical panel, then the NEXT
    step's pivot probe, then the trailing eliminate — so the probe
    overlaps the bulk GEMM; bit-identical results to the plain/grouped
    engines on every flavor.  Engines differ in speed and
    summation order only — same pivot rule, same results to rounding.
    The fused-kernel engines are single-device; ``grouped_pallas_bf16``
    (bf16-compute/fp32-accumulate dots, arXiv:2112.09017) auto-attaches
    ``DEFAULT_POLICY`` when no ``policy`` is given and judges the
    residual gate at bf16 eps (capped at 0.5), so a bf16-grade miss
    walks the recovery ladder — refine, then an fp32 re-solve on the
    fp32 fused sibling — and is never returned silently degraded.

    ``engine="auto"`` resolves through the autotuner ladder
    (tuning/tuner.py): a ``plan_cache`` JSON hit costs zero
    measurements; otherwise the declarative registry's legality + cost
    ranking picks the projected-best engine, and ``tune=True``
    additionally MEASURES the cost-pruned survivors (robust median-of-k,
    IQR outlier rejection) and persists the winner to ``plan_cache``.
    The resolved choice is reported on ``SolveResult.engine``/``group``/
    ``plan``.  ``tune``/``plan_cache`` with an explicit engine is a
    UsageError — a requested engine leaves nothing to tune.

    ``policy`` (a ``resilience.ResiliencePolicy``) attaches the
    resilience layer (ISSUE 5, docs/RESILIENCE.md): transient
    compile/execute failures are retried per ``policy.retry`` (counted
    in ``tpu_jordan_retries_total``), and on single-device solves the
    residual gate (``rel_residual <= gate_tol·eps·n·κ∞``) guards the
    result — a failing gate escalates through the degradation ladder
    (Newton-Schulz refine, then a higher-precision re-solve), each rung
    recorded on ``SolveResult.recovery`` and as ``recover`` span
    children; an exhausted ladder raises ``ResidualGateError`` instead
    of returning a known-bad inverse.  Without a policy, behavior (and
    the warm-path cost) is unchanged.

    ``numerics`` (ISSUE 10, docs/OBSERVABILITY.md): ``"off"`` (the
    default — zero cost), ``"summary"`` (a ``NumericsReport`` on
    ``SolveResult.numerics`` built only from numbers the solve already
    returns), or ``"trace"`` (the full per-superstep health trace —
    chosen pivot block, its inverse ∞-norm [the paper's selection
    criterion], candidate-norm spread, element-growth watermark — from
    the instrumented unrolled engines; single-device, host-visible
    engines only).  Both non-off modes mirror into the
    ``tpu_jordan_pivot_condition``/``_growth_factor``/``_residual``
    histograms and record ``numerics_spike`` flight-recorder events on
    threshold exceedances BEFORE the recovery ladder runs, so a rung
    is always causally explained.

    Raises SingularMatrixError like the reference's -2 path
    (main.cpp:435-437); file errors propagate from read_matrix_file.
    """
    tel = telemetry if telemetry is not None else _NULL_TEL
    with tel.span("solve", n=n, workers=str(workers),
                  generator=(None if file else generator)) as root:
        res = _solve_impl(n, block_size, file, generator, dtype, refine,
                          workers, device, verbose, gather, precision,
                          engine, group, tune, plan_cache, tel, policy,
                          numerics)
    if telemetry is not None:
        res.trace = root
    return res


def _solve_impl(n, block_size, file, generator, dtype, refine, workers,
                device, verbose, gather, precision, engine, group, tune,
                plan_cache, tel, policy=None,
                numerics: str = "off") -> SolveResult:
    if block_size is None:
        block_size = default_block_size(n)
    prec = _PRECISIONS[precision]
    engine, group = resolve_engine(engine, group)
    if numerics != "off":
        from .obs.numerics import resolve_mode

        numerics = resolve_mode(numerics)
    distributed = isinstance(workers, tuple) or workers > 1
    if jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating):
        # Complex dtypes (ISSUE 11): single-device, augmented-family
        # engines only — the [A | I] elimination and the |z|-based
        # residual machinery are dtype-generic, while the in-place/
        # grouped/fused engines' layout tricks and the distributed
        # scatter/collective paths are validated for real dtypes.
        # engine="auto" lands here too: registry legality routes
        # complex points to the augmented config.
        if distributed:
            raise UsageError(
                "complex dtypes run single-device (the distributed "
                "scatter/collective paths are real-dtype); "
                "workers must be 1")
        if engine not in ("auto", "augmented"):
            raise UsageError(
                f"complex dtype requires engine='augmented' (or "
                f"'auto'); engine={engine!r} is a real-dtype engine — "
                f"for X = A⁻¹B use linalg.solve_system, which is "
                f"complex-native")
    if (tune or plan_cache is not None) and engine != "auto":
        raise UsageError("tune/plan_cache apply to engine='auto' only "
                         "(an explicit engine leaves nothing to tune)")
    if not distributed and not gather:
        raise UsageError(
            "gather=False is only supported on distributed paths "
            "(workers > 1 or a (pr, pc) tuple)"
        )
    if distributed:
        # Flag validity is engine-independent — check it BEFORE the
        # autotuner so an invalid combination never pays for selection
        # (let alone a measured tuning run).
        check_gather_flags(gather, refine, precision, engine)
    if numerics == "trace" and distributed:
        raise UsageError(
            "numerics='trace' instruments the single-device unrolled "
            "engines (the per-superstep stats are host-visible there); "
            "distributed solves support numerics='summary'")
    plan = None
    if engine == "auto":
        from .tuning.tuner import auto_select

        engine, group, plan = auto_select(n, block_size, dtype, workers,
                                          gather, tune=tune,
                                          plan_cache=plan_cache,
                                          telemetry=tel)

    if distributed and engine in PALLAS_ENGINES:
        raise UsageError(
            f"engine={engine!r} is a single-device fused-kernel engine "
            "(the Pallas update kernel has no sharded variant yet); "
            "use engine='grouped' on distributed meshes")
    if engine == "grouped_pallas_bf16" and policy is None:
        # The bf16 path NEVER runs unguarded: without an explicit
        # policy the default residual-gate ladder is attached, so a
        # bf16-grade miss walks refine -> fp32 re-solve (recorded on
        # SolveResult.recovery) instead of reaching the caller as a
        # silently degraded inverse (ISSUE 6 acceptance).
        from .resilience.policy import DEFAULT_POLICY

        policy = DEFAULT_POLICY

    def load():
        if file is not None:
            host = read_matrix_file(file, n, dtype)
            return jax.device_put(jnp.asarray(host, dtype), device)
        return jax.device_put(generate(generator, (n, n), dtype), device)

    if distributed:
        from .ops.refine import resolve_precision

        sweep_prec, refine = resolve_precision(prec, refine)
        be = make_distributed_backend(workers, n, block_size, engine, group)
        res = _solve_distributed_core(
            be, n, block_size, file, generator, dtype, refine, verbose,
            gather, load, sweep_prec, tel=tel, engine=engine,
            policy=policy,
        )
        res.engine, res.group, res.plan = engine, group, plan
        if numerics != "off":
            # Distributed solves get the summary record (built only
            # from what the core already verified — the honest mode for
            # engines the host can't see inside).
            res.numerics = _numerics_report(
                "summary", n=n, block_size=res.block_size, engine=engine,
                residual=res.residual, norm_a=res._norm_a,
                kappa=res.kappa, dtype=dtype, policy=policy)
        return res

    if engine == "swapfree":
        raise UsageError("engine='swapfree' is a distributed engine "
                         "(its win is collective bytes); use workers=p")

    with tel.span("load"):
        a = load()
    if verbose:
        from .utils.printing import print_corner

        print("A")
        print_corner(a)

    # AOT-compile so the timed call measures the executable alone
    # without running the O(n^3) inversion twice.  The input buffer is
    # DONATED: A is re-loaded fresh for the residual anyway (reference
    # reload semantics), and donation lets XLA alias A's HBM into the
    # working matrix — the difference between fitting and OOM at
    # n >= 16384 (4 GB per n=32768 fp32 buffer on a 16 GB chip).
    collect = numerics == "trace"
    with tel.span("compile", engine=engine, n=n) as csp:
        def _compile():
            _faults.fire("compile")
            return jax.jit(
                single_device_invert(n, block_size, engine, group,
                                     collect_stats=collect),
                static_argnames=("block_size", "refine", "precision"),
                donate_argnums=(0,),
            ).lower(
                a, block_size=block_size, refine=refine, precision=prec
            ).compile()
        compiled = (policy.retry.call(_compile, component="solve.compile")
                    if policy is not None else _compile())
    _record_compile(csp, "solve")
    # XLA's own accounting, read ONCE per compile (ISSUE 10 hwcost):
    # flops/bytes/HBM footprint off the executable — zero per-execute
    # cost, attached to the execute span below.
    exe_cost = _hwcost.executable_cost(compiled)

    def _execute():
        _faults.fire("execute")
        return timed_blocking(compiled, a, telemetry=tel,
                              name="execute", engine=engine)

    def _reload_donated(_e, _attempt):
        # The timed call DONATES a; a retry after a mid-execution
        # failure must rebuild the input buffer first.
        nonlocal a
        a = load()

    out, esp = (
        policy.retry.call(_execute, component="solve.execute",
                          on_retry=_reload_donated)
        if policy is not None else _execute())
    if collect:
        inv, singular, nstats = out
    else:
        (inv, singular), nstats = out, None
    elapsed = esp.duration
    _attribute_solve_phases(tel, esp, engine, n, block_size, group)
    _solve_metrics(n, elapsed, esp, singular=bool(singular))
    _hwcost.attach_execute_cost(esp, exe_cost,
                                analytical_flops=2.0 * float(n) ** 3)
    if _faults.corrupt("result_corrupt_nan"):
        # Silent-corruption simulation: poison the computed inverse so
        # the residual (verified against a FRESH A below) goes NaN and
        # the policy's gate — not a lucky caller — must catch it.
        inv = inv.at[0, 0].set(float("nan"))

    if bool(singular):
        raise SingularMatrixError("singular matrix")

    if verbose:
        print(f"glob_time: {elapsed:.2f}")
        print("inverse matrix:\n")
        print_corner(inv)

    # Re-load A (the reference re-reads/regenerates, main.cpp:463-488) and
    # verify independently (all distributed cases returned above via
    # _solve_distributed_core, so this is always the single-device residual).
    with tel.span("residual"):
        a_fresh = load()
        residual = float(residual_inf_norm(a_fresh, inv))
        norm_a = float(inf_norm(a_fresh))
        kappa = norm_a * float(inf_norm(inv))  # condition_inf, one pass each

    # The numerics record is built, observed, and SPIKED before the
    # recovery ladder below runs: a recovery_rung flight-recorder event
    # must be causally preceded by the numerics evidence explaining it
    # (ISSUE 10 acceptance; tools/check_numerics.py).
    nreport = None
    if numerics != "off":
        nreport = _numerics_report(
            numerics, n=n, block_size=block_size, engine=engine,
            residual=residual, norm_a=norm_a, kappa=kappa, dtype=dtype,
            policy=policy, nstats=nstats)

    recovery = ()
    if policy is not None:
        # The residual gate + degradation ladder (ISSUE 5): refine on
        # the inverse in hand, then an escalated re-solve — storage
        # dtype promoted to fp32 where sub-fp32, matmul precision to
        # HIGHEST — which also clears transient result corruption (the
        # re-solve is a fresh execution of a fresh load).
        from .resilience.degrade import maybe_recover

        def _escalated_resolve():
            esc_dtype = (jnp.float32
                         if jnp.dtype(dtype).itemsize < 4 else dtype)
            # The bf16 fused-kernel engine escalates to its fp32
            # sibling: same pivot rule and kernel, full-precision dots
            # — the "fp32 re-solve" rung of the bf16 recipe
            # (arXiv:2112.09017).
            esc_engine = ("grouped_pallas"
                          if engine == "grouped_pallas_bf16" else engine)
            return _solve_impl(n, block_size, file, generator, esc_dtype,
                               refine, workers, device, False, gather,
                               "highest", esc_engine, group, False, None,
                               tel)

        # The gate judges a bf16-computed inverse at bf16 eps (a
        # bf16-grade residual on a well-conditioned matrix is a PASS,
        # not a ladder walk) unless the policy pins an explicit
        # gate_dtype SLO — gate_threshold prefers policy.gate_dtype.
        gate_dtype = (jnp.bfloat16
                      if engine == "grouped_pallas_bf16" else dtype)
        inv, residual, norm_a, kappa, recovery = maybe_recover(
            policy, tel, a_fresh=a_fresh, inv=inv, residual=residual,
            norm_a=norm_a, kappa=kappa, n=n, dtype=gate_dtype,
            resolve=_escalated_resolve)

    if verbose:
        print(f"residual: {residual:e}")
        print(f"kappa_inf: {kappa:e}")

    return SolveResult(
        inverse=inv,
        elapsed=elapsed,
        residual=residual,
        n=n,
        block_size=block_size,
        gflops=(2.0 * n**3 / elapsed / 1e9) if elapsed > 0 else 0.0,
        kappa=kappa,
        _norm_a=norm_a,
        engine=engine,
        group=group,
        plan=plan,
        recovery=recovery,
        numerics=nreport,
    )


def batch_metrics(a, x, n_real=None, precision=_lax.Precision.HIGHEST):
    """Per-element accuracy assembly for the batched path — ONE shared
    implementation (ISSUE 3: factored out of ``solve_batch`` so the
    serving executors and the bench batched rows reuse it instead of
    forking their own residual conventions).

    ``a``/``x`` are (B, N, N) stacks; returns a dict of (B,) arrays:
    ``residual`` ‖A·X−I‖∞, ``norm_a`` ‖A‖∞, ``norm_x`` ‖X‖∞,
    ``kappa`` = ‖A‖∞‖X‖∞, and ``rel_residual`` = residual/‖A‖∞ — the
    same conventions as ``SolveResult`` (ops/residual.py, ops/norms.py).

    ``n_real`` (optional (B,) int vector) masks the norms to each
    element's REAL rows when the stack is identity-padded to a shape
    bucket (serve/executors.py): pad rows abs-sum to exactly 1 and would
    cap a small true norm; real rows are exact because pad columns
    contribute 0 to them (ops/padding.py — the pad block of a real row
    is exactly zero, and stays zero through elimination).  The residual
    needs no mask: a pad row of A·X−I is identically zero.
    """
    N = a.shape[-1]
    r = jnp.matmul(a, x, precision=precision) - jnp.eye(N, dtype=x.dtype)
    r_sums = jnp.sum(jnp.abs(r), axis=-1)
    a_sums = jnp.sum(jnp.abs(a), axis=-1)
    x_sums = jnp.sum(jnp.abs(x), axis=-1)
    if n_real is not None:
        mask = (jnp.arange(N)[None, :]
                < jnp.asarray(n_real, jnp.int32)[:, None])
        zero = jnp.asarray(0, r_sums.dtype)
        r_sums = jnp.where(mask, r_sums, zero)
        a_sums = jnp.where(mask, a_sums, zero)
        x_sums = jnp.where(mask, x_sums, zero)
    residual = jnp.max(r_sums, axis=-1)
    norm_a = jnp.max(a_sums, axis=-1)
    norm_x = jnp.max(x_sums, axis=-1)
    return {
        "residual": residual,
        "norm_a": norm_a,
        "norm_x": norm_x,
        "kappa": norm_a * norm_x,
        # Guarded division: an all-masked filler element (n_real=0) has
        # norm_a == 0 and must report 0, not NaN.
        "rel_residual": jnp.where(norm_a > 0, residual
                                  / jnp.where(norm_a > 0, norm_a, 1),
                                  residual),
    }


def solve_batch(
    n: int,
    block_size: int | None = None,
    batch: int = 1,
    generator: str = "absdiff",
    dtype=jnp.float32,
    refine: int = 0,
    precision: str = "highest",
    verbose: bool = False,
    telemetry=None,
) -> SolveResult:
    """Invert ``batch`` generated n×n matrices in ONE vmapped computation
    (the north-star batch capability, ops/batched.py; single device).

    Elements are generated with per-element index offsets (b·n on both
    axes), which yields distinct matrices for the ``rand`` generator and
    identical copies for translation-invariant ones like ``absdiff`` —
    either way an honest throughput measurement.  ``gflops`` uses the
    2n³·batch convention; ``residual`` is element 0's, and a
    SingularMatrixError reports how many elements were flagged.
    """
    from .ops import batched_jordan_invert

    if block_size is None:
        block_size = default_block_size(n)
    prec = _PRECISIONS[precision]
    # ONE vmapped generate (offsets are traced-friendly) instead of a
    # B-term stack, and the input buffer is DONATED: at the 512x2048^2
    # north-star scale the batch is 8.6 GB, so aliasing it into the
    # working matrix is the difference between fitting and OOM — the
    # same policy as the single-solve driver; A[0] is regenerated fresh
    # for the residual (reference reload semantics).
    tel = telemetry if telemetry is not None else _NULL_TEL
    with tel.span("solve_batch", n=n, batch=batch) as root:
        with tel.span("load"):
            offs = jnp.arange(batch, dtype=jnp.int32) * n
            a = jax.jit(jax.vmap(
                lambda o: generate(generator, (n, n), dtype, row_offset=o,
                                   col_offset=o)
            ))(offs)  # jit fuses the index grids — eager is 2x the batch
        with tel.span("compile", n=n, batch=batch) as csp:
            compiled = jax.jit(
                lambda x: batched_jordan_invert(
                    x, block_size=block_size, refine=refine,
                    precision=prec),
                donate_argnums=(0,),
            ).lower(a).compile()
        _record_compile(csp, "solve")
        exe_cost = _hwcost.executable_cost(compiled)
        (inv, singular), esp = timed_blocking(compiled, a, telemetry=tel,
                                              name="execute", batch=batch)
        elapsed = esp.duration
        nsing = int(jnp.sum(singular))
        _solve_metrics(n, elapsed, esp, singular=bool(nsing), batch=batch)
        _hwcost.attach_execute_cost(
            esp, exe_cost, analytical_flops=2.0 * float(n) ** 3 * batch)
        if nsing:
            raise SingularMatrixError(
                f"singular matrix ({nsing}/{batch} elements flagged)")
        with tel.span("residual"):
            a0 = generate(generator, (n, n), dtype)
            met = batch_metrics(a0[None], inv[:1])
            residual = float(met["residual"][0])
    if verbose:
        print(f"glob_time: {elapsed:.2f} ({batch} matrices)")
        print(f"residual[0]: {residual:e}")
    return SolveResult(
        inverse=inv,
        elapsed=elapsed,
        residual=residual,
        n=n,
        block_size=block_size,
        gflops=((2.0 * n**3 * batch / elapsed / 1e9)
                if elapsed > 0 else 0.0),
        kappa=float(met["kappa"][0]),
        _norm_a=float(met["norm_a"][0]),
        trace=root if telemetry is not None else None,
    )


def _blocks_inf_norm(blocks, row_order, m: int, n: int):
    """‖·‖∞ straight from an (Nr, m, N) block tensor: row abs-sums
    (summing the STORED columns of a row IS the full row abs-sum —
    column storage order is a permutation, and identity-pad columns
    contribute 0 to real rows), masked to real rows via the layout's
    row storage order (identity-pad rows sum to exactly 1 and must not
    cap a small true norm).  Runs on the sharded array — one O(n²/P)
    pass per worker plus a scalar max collective; nothing n×n
    materializes (the κ∞ path for gather=False solves)."""
    order = jnp.asarray(row_order, jnp.int32)
    gi = order[:, None] * m + jnp.arange(m)[None, :]
    sums = jnp.sum(jnp.abs(blocks), axis=-1)
    return jnp.max(jnp.where(gi < n, sums, 0.0))


def make_distributed_backend(workers, n: int, block_size: int,
                             engine: str = "auto", group: int = 0):
    """The distributed backend for a workers spec: int p -> 1D row-cyclic,
    tuple (pr, pc) -> 2D block-cyclic.  Shared by ``solve`` and
    ``JordanSolver`` so layout policy can't drift between them.
    ``engine``/``group`` must already be resolved (resolve_engine)."""
    m = min(block_size, n)
    be = (_Dist2D(workers, n, m) if isinstance(workers, tuple)
          else _Dist1D(workers, n, m))
    be.inplace = engine != "augmented"
    be.group = group
    be.swapfree = engine == "swapfree"
    be.lookahead = engine == "lookahead"
    return be


def check_gather_flags(gather: bool, refine: int, precision: str = "highest",
                       engine: str = "auto"):
    """Flag-compatibility contract for distributed solves, shared by
    ``solve`` and ``JordanSolver``: refinement (and the 'mixed' policy
    that implies it) runs on the gathered inverse.  The swap-free
    engine is legal under EITHER gather mode: its deferred row
    permutation runs as bucketed ``ppermute`` rounds inside the engine
    (parallel/permute.py — per-worker residency capped at one shard),
    so ``swapfree=True, gather=False`` is the pod-scale configuration:
    the lowest projected comm bill in the only memory mode that reaches
    32768²+ (benchmarks/comm_model.py).

    ``engine`` currently gates nothing (the swap-free restriction it
    existed for is gone) but stays in the signature: it is the shared
    flag contract both entry points already thread, and the natural
    seam for any future engine-specific gather rule."""
    if precision == "mixed" and not gather:
        raise UsageError(
            "precision='mixed' requires gather=True: it implies >=2 "
            "Newton-Schulz steps, which run on the gathered inverse"
        )
    if refine and not gather:
        raise UsageError("refine requires gather=True (it runs on the "
                         "gathered inverse)")


def single_device_invert(n: int, block_size: int, engine: str = "auto",
                         group: int = 0, collect_stats: bool = False):
    """The single-device inversion entry point for a given problem size
    and (resolved) engine choice.

    "auto"/"inplace": the in-place 2N³ engine — the unrolled trace
    (static shrinking probe window) when its compile cost is reasonable,
    the fori_loop variant beyond (identical results, compile cost
    independent of Nr).  "grouped": the delayed-group-update engine
    (same dispatch by Nr; the measured large-n winner — see
    resolve_engine's docstring for the dispatch policy).  "augmented":
    the ~4N³ reference-parity implementation (global_scale mode).

    ``collect_stats=True`` (``numerics="trace"``, ISSUE 10) compiles
    the INSTRUMENTED unrolled twin returning ``(x, singular, stats)``
    with the per-superstep health arrays.  Host-visible engines only:
    the augmented path, the fori engines (Nr > MAX_UNROLL_NR), and the
    bf16 fused kernel (whose rounded dots the XLA twin cannot
    reproduce) are typed ``UsageError``s — a trace must describe the
    solve that actually ran, never a silently different one."""

    from .ops import block_jordan_invert_inplace
    from .ops.jordan_inplace import (
        block_jordan_invert_inplace_fori,
        block_jordan_invert_inplace_grouped,
        block_jordan_invert_inplace_grouped_fori,
        block_jordan_invert_inplace_grouped_lookahead,
        block_jordan_invert_inplace_grouped_pallas,
        block_jordan_invert_inplace_lookahead,
    )
    from .parallel.sharded_inplace import MAX_UNROLL_NR

    Nr = -(-n // min(block_size, n))
    unroll = Nr <= MAX_UNROLL_NR
    if engine == "lookahead" and not unroll:
        raise UsageError(
            f"engine='lookahead' is unrolled-only (the critical-panel "
            f"split needs static column offsets) and Nr={Nr} exceeds "
            f"MAX_UNROLL_NR={MAX_UNROLL_NR}; use engine='inplace' (its "
            f"fori twin) or a larger block_size")
    if engine == "lookahead":
        # The probe-ahead twins bit-match the plain/grouped engines, so
        # the numerics trace instruments the lookahead engine ITSELF
        # (collect_stats=True on the same executable) — the trace
        # describes the solve that actually ran, and its pivot column
        # pins the sequence equal to the non-lookahead twin's.
        eng_la = (block_jordan_invert_inplace_grouped_lookahead
                  if group > 1 else block_jordan_invert_inplace_lookahead)
        kw_la = {"group": group} if group > 1 else {}

        def fn_la(a, block_size=None, refine=0,
                  precision=_lax.Precision.HIGHEST):
            return eng_la(a, block_size=block_size, refine=refine,
                          precision=precision,
                          collect_stats=collect_stats, **kw_la)

        return jax.jit(fn_la, static_argnames=("block_size", "refine",
                                               "precision"))
    if collect_stats:
        if engine == "augmented":
            raise UsageError(
                "numerics='trace' has no instrumented twin for the "
                "augmented reference-parity engine; use "
                "engine='inplace'/'grouped' or numerics='summary'")
        if engine == "grouped_pallas_bf16":
            raise UsageError(
                "numerics='trace' cannot instrument the bf16 fused "
                "kernel (its rounded dots have no bit-matching "
                "host-visible twin); use numerics='summary', or trace "
                "the fp32 sibling engine='grouped_pallas'")
        if not unroll:
            raise UsageError(
                f"numerics='trace' instruments the unrolled engines "
                f"only and Nr={Nr} exceeds MAX_UNROLL_NR="
                f"{MAX_UNROLL_NR}; use a larger block_size or "
                f"numerics='summary'")
        if engine in PALLAS_ENGINES or group > 1 or engine == "grouped":
            kg = group if group > 1 else 2

            def fn_tr_g(a, block_size=None, refine=0,
                        precision=_lax.Precision.HIGHEST):
                # The fp32 fused-kernel engine traces through its
                # bit-matching XLA grouped twin (ISSUE 6 pin): same
                # pivot choices, same result bits.
                return block_jordan_invert_inplace_grouped(
                    a, block_size=block_size, refine=refine,
                    precision=precision, group=kg, collect_stats=True)

            return jax.jit(fn_tr_g, static_argnames=(
                "block_size", "refine", "precision"))

        def fn_tr(a, block_size=None, refine=0,
                  precision=_lax.Precision.HIGHEST):
            return block_jordan_invert_inplace(
                a, block_size=block_size, refine=refine,
                precision=precision, collect_stats=True)

        return jax.jit(fn_tr, static_argnames=(
            "block_size", "refine", "precision"))
    if engine in PALLAS_ENGINES:
        if not unroll:
            raise UsageError(
                f"engine={engine!r} is unrolled-only (the fused kernel's "
                f"mask geometry is compile-time) and Nr={Nr} exceeds "
                f"MAX_UNROLL_NR={MAX_UNROLL_NR}; use engine='grouped' "
                "(its fori twin) or a larger block_size")
        mode = "bf16" if engine.endswith("bf16") else "fp32"
        kg = group if group > 1 else 2

        def fn_pl(a, block_size=None, refine=0,
                  precision=_lax.Precision.HIGHEST):
            return block_jordan_invert_inplace_grouped_pallas(
                a, block_size=block_size, refine=refine,
                precision=precision, group=kg, mode=mode)

        return jax.jit(fn_pl, static_argnames=("block_size", "refine",
                                               "precision"))
    if engine == "augmented":
        from .ops import block_jordan_invert

        def fn_aug(a, block_size=None, refine=0,
                   precision=_lax.Precision.HIGHEST):
            # global_scale=True: inner pivots thresholded against
            # eps * ‖A‖ of the whole local strip — the reference's exact
            # rule (main.cpp:972/1046), what the "augmented
            # reference-parity" label promises.  (The per-block relative
            # scale used everywhere else is the documented deliberate
            # deviation.)
            return block_jordan_invert(a, block_size=block_size,
                                       refine=refine, precision=precision,
                                       global_scale=True)

        return jax.jit(fn_aug, static_argnames=("block_size", "refine",
                                                "precision"))
    if group > 1:
        eng = (block_jordan_invert_inplace_grouped if unroll
               else block_jordan_invert_inplace_grouped_fori)

        def fn(a, block_size=None, refine=0,
               precision=_lax.Precision.HIGHEST):
            return eng(a, block_size=block_size, refine=refine,
                       precision=precision, group=group)

        # Callers .lower() the result (solve, JordanSolver) — hand them
        # a jitted callable like the plain branches do.
        return jax.jit(fn, static_argnames=("block_size", "refine",
                                            "precision"))
    return (block_jordan_invert_inplace if unroll
            else block_jordan_invert_inplace_fori)


class _Dist1D:
    """1D row-block-cyclic backend (the reference's own layout,
    main.cpp:118-123).

    Engine selection mirrors ``single_device_invert``: always the
    in-place 2N³ elimination (parallel/sharded_inplace.py — half the
    flops, memory, and collective bytes of the augmented path); its
    compile fn picks the unrolled trace vs the fori_loop engine by Nr.
    The augmented path stays addressable by setting ``inplace = False``
    (reference-parity escape hatch)."""

    def __init__(self, workers: int, n: int, m: int):
        from .parallel import make_mesh
        from .parallel.layout import CyclicLayout

        self.mesh = make_mesh(workers)
        self.lay = CyclicLayout.create(n, m, workers)
        self.inplace = True
        self.group = 0
        self.swapfree = False
        self.lookahead = False

    def generate_W(self, generator, dtype):
        from .parallel import sharded_generate

        return sharded_generate(generator, self.lay, self.mesh, dtype,
                                augmented=not self.inplace)

    def scatter_W(self, a):
        if self.inplace:
            from .parallel.ring_gemm import _to_identity_padded_blocks

            return _to_identity_padded_blocks(a, self.lay, self.mesh)
        from .parallel.sharded_jordan import scatter_augmented

        return scatter_augmented(a, self.lay, self.mesh)

    def compile(self, W, precision=_lax.Precision.HIGHEST):
        if self.inplace:
            from .parallel.sharded_inplace import (
                compile_sharded_jordan_inplace,
            )

            return compile_sharded_jordan_inplace(W, self.mesh, self.lay,
                                                  precision=precision,
                                                  group=self.group,
                                                  swapfree=self.swapfree,
                                                  lookahead=self.lookahead)
        from .parallel.sharded_jordan import compile_sharded_jordan

        return compile_sharded_jordan(W, self.mesh, self.lay,
                                      precision=precision)

    def gather(self, out, n):
        if self.inplace:
            from .parallel.sharded_inplace import gather_inverse_inplace

            return gather_inverse_inplace(out, self.lay, n)
        from .parallel.sharded_jordan import gather_inverse

        return gather_inverse(out, self.lay, n)

    def inv_blocks(self, out):
        # In-place output IS the inverse in cyclic row order; the augmented
        # output carries it as the B half.
        return out if self.inplace else out[:, :, self.lay.N:]

    def generate_a_blocks(self, generator, dtype):
        from .parallel import sharded_generate

        return sharded_generate(generator, self.lay, self.mesh, dtype,
                                augmented=False)

    def scatter_a_blocks(self, a):
        from .parallel.ring_gemm import _to_identity_padded_blocks

        return _to_identity_padded_blocks(a, self.lay, self.mesh)

    def stream_W(self, path, dtype, storage_dtype=None):
        from .parallel.scatter_stream import stream_scatter_1d

        return stream_scatter_1d(path, self.lay, self.mesh, dtype,
                                 augmented=not self.inplace,
                                 storage_dtype=storage_dtype)

    def stream_a_blocks(self, path, dtype, storage_dtype=None):
        from .parallel.scatter_stream import stream_scatter_1d

        return stream_scatter_1d(path, self.lay, self.mesh, dtype,
                                 augmented=False,
                                 storage_dtype=storage_dtype)

    def residual(self, a_blocks, inv_blocks):
        from .parallel.ring_gemm import distributed_residual_blocks

        return distributed_residual_blocks(a_blocks, inv_blocks,
                                           self.mesh, self.lay)

    def inf_norm_blocks(self, blocks):
        return _blocks_inf_norm(blocks, self.lay.cyclic_block_order(),
                                self.lay.m, self.lay.n)

    def corner(self, inv_blocks, n):
        from .parallel.sharded_inplace import inverse_corner_1d

        return inverse_corner_1d(inv_blocks, self.lay, n)


class _Dist2D:
    """2D block-cyclic backend over a (pr, pc) mesh (SUMMA residual) —
    per-worker memory O(n²/(pr·pc)).

    Engine selection mirrors ``_Dist1D``: always the in-place 2N³
    elimination (parallel/jordan2d_inplace.py); its compile fn picks the
    unrolled trace vs the fori_loop engine by Nr.  The augmented path
    stays addressable by setting ``inplace = False``."""

    def __init__(self, shape: tuple, n: int, m: int):
        from .parallel import make_mesh_2d
        from .parallel.layout import CyclicLayout2D

        pr, pc = shape
        self.mesh = make_mesh_2d(pr, pc)
        self.lay = CyclicLayout2D.create(n, m, pr, pc)
        self.inplace = True
        self.group = 0
        self.swapfree = False
        self.lookahead = False

    def generate_W(self, generator, dtype):
        from .parallel.jordan2d import sharded_generate_2d

        return sharded_generate_2d(generator, self.lay, self.mesh, dtype,
                                   augmented=not self.inplace)

    def scatter_W(self, a):
        if self.inplace:
            from .parallel.jordan2d import scatter_matrix_2d

            return scatter_matrix_2d(a, self.lay, self.mesh)
        from .parallel.jordan2d import scatter_augmented_2d

        return scatter_augmented_2d(a, self.lay, self.mesh)

    def compile(self, W, precision=_lax.Precision.HIGHEST):
        if self.inplace:
            from .parallel.jordan2d_inplace import (
                compile_sharded_jordan_inplace_2d,
            )

            return compile_sharded_jordan_inplace_2d(
                W, self.mesh, self.lay, precision=precision,
                group=self.group, swapfree=self.swapfree,
                lookahead=self.lookahead)
        from .parallel.jordan2d import compile_sharded_jordan_2d

        return compile_sharded_jordan_2d(W, self.mesh, self.lay,
                                         precision=precision)

    def gather(self, out, n):
        if self.inplace:
            from .parallel.jordan2d_inplace import gather_inverse_inplace_2d

            return gather_inverse_inplace_2d(out, self.lay, n)
        from .parallel.jordan2d import gather_inverse_2d

        return gather_inverse_2d(out, self.lay, n)

    def inv_blocks(self, out):
        if self.inplace:
            return out
        from .parallel.jordan2d import split_inverse_blocks_2d

        return split_inverse_blocks_2d(out, self.lay, self.mesh)

    def generate_a_blocks(self, generator, dtype):
        from .parallel.jordan2d import sharded_generate_2d

        return sharded_generate_2d(generator, self.lay, self.mesh, dtype,
                                   augmented=False)

    def scatter_a_blocks(self, a):
        from .parallel.jordan2d import scatter_matrix_2d

        return scatter_matrix_2d(a, self.lay, self.mesh)

    def stream_W(self, path, dtype, storage_dtype=None):
        from .parallel.scatter_stream import stream_scatter_2d

        return stream_scatter_2d(path, self.lay, self.mesh, dtype,
                                 augmented=not self.inplace,
                                 storage_dtype=storage_dtype)

    def stream_a_blocks(self, path, dtype, storage_dtype=None):
        from .parallel.scatter_stream import stream_scatter_2d

        return stream_scatter_2d(path, self.lay, self.mesh, dtype,
                                 augmented=False,
                                 storage_dtype=storage_dtype)

    def residual(self, a_blocks, inv_blocks):
        from .parallel.jordan2d import distributed_residual_2d

        return distributed_residual_2d(a_blocks, inv_blocks, self.mesh,
                                       self.lay)

    def inf_norm_blocks(self, blocks):
        return _blocks_inf_norm(blocks, self.lay.row_perm(), self.lay.m,
                                self.lay.n)

    def corner(self, inv_blocks, n):
        from .parallel.jordan2d_inplace import inverse_corner_2d

        return inverse_corner_2d(inv_blocks, self.lay, n)


def _solve_distributed_core(
    be, n: int, block_size: int, file, generator: str, dtype,
    refine: int, verbose: bool, gather: bool, load,
    precision=_lax.Precision.HIGHEST, tel=_NULL_TEL, engine=None,
    policy=None,
):
    """The one distributed solve skeleton, shared by the 1D and 2D
    layouts via the backend adapter ``be``.

    Reference analog end to end: init_matrix fills each rank's strip
    locally (main.cpp:128-149; our generator path — fully device-resident,
    zero host n×n arrays), or read_matrix STREAMS a file one block-row
    strip at a time straight onto the owner devices (main.cpp:242-276
    semantics: host memory O(n·m), never O(n²) —
    parallel/scatter_stream.py); Jordan runs (timed like glob_time,
    main.cpp:427-450: elimination only, compile/gather excluded); A is
    re-read/regenerated and the residual MAX-allreduced with only a scalar
    leaving the mesh (main.cpp:463-513).  Refinement (no reference analog)
    runs on the gathered inverse and therefore requires ``gather=True``
    (and, for file input, one full host read).
    """
    from .obs import comm as _comm
    from .obs import work as _work
    from .ops import newton_schulz

    if refine and not gather:
        raise UsageError("refine requires gather=True (it runs on the "
                         "gathered inverse)")

    # Sub-fp32 storage dtypes compute in fp32 and round once at the end —
    # the same policy as the single-device kernels (ops/jordan.py): bf16
    # elimination state is measured divergent.
    in_dtype = jnp.dtype(dtype)
    if in_dtype.itemsize < 4:
        dtype = jnp.float32
    # Sub-fp32 storage quantizes A itself before the fp32 upcast (the
    # single-device semantics: the matrix being inverted IS the rounded
    # one) — the streamed strips round per-strip, same result.
    storage = in_dtype if in_dtype != jnp.dtype(dtype) else None

    with tel.span("load", streamed=file is not None):
        if file is None:
            W = be.generate_W(generator, dtype)
        else:
            W = be.stream_W(file, dtype, storage)
    if verbose:
        from .io import read_matrix_corner
        from .utils.printing import print_corner

        print("A")
        print_corner(read_matrix_corner(file, n, dtype)
                     if file is not None
                     else generate(generator, (min(n, 10), min(n, 10)),
                                   dtype))

    # The communication observatory (ISSUE 14): the layout-derived
    # analytical collective accounting is built for EVERY distributed
    # solve (host-side index math, no device cost); the observed
    # trace-time counts are captured only under obs.comm.recording().
    eng_name = engine or ("swapfree" if be.swapfree
                          else "lookahead" if getattr(be, "lookahead", False)
                          else "grouped" if be.group > 1
                          else "inplace" if be.inplace else "augmented")
    comm_rep = _comm.engine_report(
        engine=eng_name, lay=be.lay, dtype=dtype, gather=gather,
        refine=refine, group=be.group)
    # The work observatory (ISSUE 19): the same layout math, pointed at
    # compute — per-worker useful-FLOP shares (integer-exact against
    # the 2n³ convention), skew gauges, and the ragged-tail penalty.
    work_rep = _work.engine_report(engine=eng_name, lay=be.lay,
                                   dtype=dtype, group=be.group)

    with tel.span("compile", engine=engine, n=n) as csp:
        def _compile():
            _faults.fire("compile")
            if _comm.recording_active():
                with _comm.record_collectives() as rec:
                    run = be.compile(W, precision)
                # .lower() re-traces per call, so a compile always
                # yields a fresh observed multiset to reconcile.
                comm_rep.attach_observed("engine", rec.records)
                return run
            return be.compile(W, precision)
        run = (policy.retry.call(_compile, component="solve.compile")
               if policy is not None else _compile())
    _record_compile(csp, "solve")
    # XLA accounting where the backend exposes it (ISSUE 10 hwcost);
    # a backend compile wrapper without cost_analysis reports
    # unavailable — never a modeled substitute.
    exe_cost = _hwcost.executable_cost(run)
    # The execute fault point fires here too, but distributed execute is
    # NOT retried (the sharded working state may be donated into the
    # engine): a mid-flight failure propagates typed, never silently.
    _faults.fire("execute")
    (out, singular), esp = timed_blocking(run, W, telemetry=tel,
                                          name="execute", engine=engine)
    elapsed = esp.duration
    la = bool(getattr(be, "lookahead", False))
    attribute_phases(esp, n, be.lay.m, distributed=True, lookahead=la)
    _hwcost.attach_execute_cost(esp, exe_cost,
                                analytical_flops=2.0 * float(n) ** 3)
    if la:
        _attach_overlap_evidence(esp, n, be.lay.m, _dist_workers(be))
    # Per-solve comm accounting on the execute span + the registry
    # counters, and the measured-vs-projected drift verdict (judged
    # only where the projection claims to describe the hardware —
    # obs/comm.DriftPolicy).
    comm_rep.observe_metrics()
    comm_rep.attach_span(esp)
    _comm.observe_drift(comm_rep, elapsed, esp)
    _comm.set_last_report(comm_rep)
    # Work accounting on the same span: the share/skew gauges, and the
    # hwcost pin (devices × per-device cost_analysis judged against the
    # padded executed-work model — SPMD cost is uniform per device).
    work_rep.attach_xla(exe_cost, span=esp)
    work_rep.observe_metrics()
    work_rep.attach_span(esp)
    _work.set_last_report(work_rep)
    singular_flag = bool(singular.any())
    _solve_metrics(n, elapsed, esp, singular=singular_flag)
    if singular_flag:
        raise SingularMatrixError("singular matrix")

    with tel.span("gather", gathered=gather):
        inv = be.gather(out, n) if gather else None
        inv_b = None if (gather and refine) else be.inv_blocks(out)
    # Round to the storage dtype BEFORE verification, so the reported
    # residual reflects what the caller actually receives.
    if in_dtype != dtype:
        inv = None if inv is None else inv.astype(in_dtype)
        inv_b = None if inv_b is None else inv_b.astype(in_dtype)
    # Verification source is always *fresh* (re-read / regenerated), never
    # algorithm state — the reference's reload semantics (main.cpp:463-488).
    # κ∞ = ‖A‖∞‖X‖∞ is reported on EVERY branch (round 5): the refine
    # branch computes it from the full matrices it already holds; the
    # non-refine branches take exact row abs-sums straight off the
    # block-sharded state (be.inf_norm_blocks — column storage order is
    # irrelevant to a row sum), so nothing n×n ever materializes.
    kappa = norm_a = None
    with tel.span("residual", refined=bool(refine)):
        if refine:
            a_full = load() if file is not None else generate(
                generator, (n, n), dtype
            )
            a_full = jnp.asarray(a_full, dtype)
            inv = newton_schulz(a_full, jnp.asarray(inv, dtype), refine)
            # Round to the storage dtype BEFORE the residual (same policy
            # as the non-refine branch): the reported number must include
            # the final rounding error of what the caller receives.
            inv = inv.astype(in_dtype)
            inv_f = inv.astype(dtype)
            residual = float(residual_inf_norm(a_full, inv_f))
            norm_a = float(inf_norm(a_full))
            kappa = norm_a * float(inf_norm(inv_f))  # = condition_inf
            del inv_f
        else:
            a_b = (be.stream_a_blocks(file, dtype, storage)
                   if file is not None
                   else be.generate_a_blocks(generator, dtype))
            inv_bf = jnp.asarray(inv_b, dtype)
            if _comm.recording_active():
                # The ring-GEMM / SUMMA verification's collectives are
                # their own reconciliation section; an empty capture
                # (the residual executable was jit-cache-hit, nothing
                # re-traced) leaves the section un-judged.
                with _comm.record_collectives() as rrec:
                    residual = float(be.residual(a_b, inv_bf))
                comm_rep.attach_observed("residual", rrec.records)
            else:
                residual = float(be.residual(a_b, inv_bf))
            norm_a = float(be.inf_norm_blocks(a_b))
            kappa = norm_a * float(be.inf_norm_blocks(inv_bf))

    if verbose:
        from .utils.printing import print_corner

        print(f"glob_time: {elapsed:.2f}")
        print("inverse matrix:\n")
        # gather=False still shows the corner (the reference always
        # prints it, main.cpp:459-461) — assembled from the owning
        # blocks alone, never a global gather.
        print_corner(inv if inv is not None else be.corner(inv_b, n))
        print(f"residual: {residual:e}")
        if kappa is not None:
            print(f"kappa_inf: {kappa:e}")
    return SolveResult(
        inverse=inv,
        elapsed=elapsed,
        residual=residual,
        n=n,
        block_size=be.lay.m,
        gflops=(2.0 * n**3 / elapsed / 1e9) if elapsed > 0 else 0.0,
        inverse_blocks=None if gather else inv_b,
        layout=None if gather else be.lay,
        kappa=kappa,
        _norm_a=norm_a,
        comm=comm_rep,
        work=work_rep,
    )
