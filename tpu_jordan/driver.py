"""End-to-end solve driver.

TPU-native rebuild of ``solve`` (main.cpp:343-519): load or generate A,
print its corner, time the inversion, print the inverse's corner, then
independently verify with the residual ‖A·A⁻¹ − I‖∞ on a *freshly
regenerated/re-read* A (the reference destroys A in place and reloads it,
main.cpp:463-488 — we keep the reload semantics so verification never trusts
state left over from the algorithm).

Differences by design (documented, not accidental):
  * the residual is always computed — the reference skips it at p == 1
    without -DHILBERT (main.cpp:498-513), which is a gap in its own
    verification, not a feature worth parity;
  * timing excludes compilation (first call compiles, the timed call is the
    cached executable) and uses ``block_until_ready`` — the honest analog of
    the max-allreduced MPI_Wtime bracket (main.cpp:427-458).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .config import default_block_size
from .io import read_matrix_file
from .ops import block_jordan_invert, generate, residual_inf_norm


class SingularMatrixError(ArithmeticError):
    """No block column had an invertible pivot candidate — the reference's
    collective "singular matrix" exit (main.cpp:1075-1083, 435-437)."""


@dataclass
class SolveResult:
    inverse: jax.Array | None
    elapsed: float          # seconds, the reference's glob_time (main.cpp:455-458)
    residual: float         # ‖A·A⁻¹ − I‖∞ (main.cpp:490-513)
    n: int
    block_size: int
    gflops: float           # 2n³ / t, the convention used in BASELINE.md
    inverse_blocks: jax.Array | None = None  # sharded cyclic blocks (gather=False)
    layout: object | None = None             # CyclicLayout of inverse_blocks


def solve(
    n: int,
    block_size: int | None = None,
    file: str | None = None,
    generator: str = "absdiff",
    dtype=jnp.float32,
    refine: int = 0,
    workers: int = 1,
    device=None,
    verbose: bool = False,
    gather: bool = True,
) -> SolveResult:
    """Invert an n x n matrix from a file or a generator and verify it.

    ``workers > 1`` runs the distributed path: 1D mesh over that many
    devices, sharded elimination, ring-GEMM residual — the analog of
    ``mpirun -np workers`` on the reference.  When the matrix comes from a
    generator, every worker builds its own shard on device (init_matrix
    parity, main.cpp:128-149) and the residual is computed without ever
    materializing an n×n array on the host; with ``gather=False`` the
    inverse too stays as sharded cyclic blocks (``result.inverse_blocks``
    + ``result.layout``), the memory-scaling mode for north-star sizes.

    Raises SingularMatrixError like the reference's -2 path
    (main.cpp:435-437); file errors propagate from read_matrix_file.
    """
    if block_size is None:
        block_size = default_block_size(n)

    def load():
        if file is not None:
            host = read_matrix_file(file, n, dtype)
            return jax.device_put(jnp.asarray(host, dtype), device)
        return jax.device_put(generate(generator, (n, n), dtype), device)

    if workers > 1 and file is None:
        # Fully device-resident: shard-local generation, sharded solve,
        # distributed residual; zero host-side n×n arrays.
        return _solve_distributed_generated(
            n, block_size, workers, generator, dtype, refine, verbose,
            gather,
        )

    if not gather:
        raise ValueError(
            "gather=False is only supported on the generator-driven "
            "distributed path (workers > 1 and no file)"
        )

    a = load()
    if verbose:
        from .utils.printing import print_corner

        print("A")
        print_corner(a)

    if workers > 1:
        inv, singular, elapsed = _solve_distributed(
            a, n, block_size, workers, refine
        )
    else:
        # AOT-compile so the timed call measures the executable alone
        # without running the O(n^3) inversion twice.
        compiled = block_jordan_invert.lower(
            a, block_size=block_size, refine=refine
        ).compile()
        t0 = time.perf_counter()
        inv, singular = compiled(a)
        jax.block_until_ready(inv)
        elapsed = time.perf_counter() - t0

    if bool(singular):
        raise SingularMatrixError("singular matrix")

    if verbose:
        print(f"glob_time: {elapsed:.2f}")
        print("inverse matrix:\n")
        print_corner(inv)

    # Re-load A (the reference re-reads/regenerates, main.cpp:463-488) and
    # verify independently — with the distributed ring GEMM when sharded,
    # like the reference (main.cpp:490-513).
    a_fresh = load()
    if workers > 1:
        from .parallel import distributed_residual, make_mesh

        residual = float(distributed_residual(
            a_fresh, inv, make_mesh(workers), min(block_size, n)
        ))
    else:
        residual = float(residual_inf_norm(a_fresh, inv))
    if verbose:
        print(f"residual: {residual:e}")

    return SolveResult(
        inverse=inv,
        elapsed=elapsed,
        residual=residual,
        n=n,
        block_size=block_size,
        gflops=2.0 * n**3 / elapsed / 1e9,
    )


def _solve_distributed_generated(
    n: int, block_size: int, workers: int, generator: str, dtype,
    refine: int, verbose: bool, gather: bool,
):
    """Generator-driven distributed solve with no host-side n×n arrays.

    The reference analog end to end: init_matrix fills each rank's strip
    locally (main.cpp:128-149), Jordan runs, A is *regenerated* and the
    residual MAX-allreduced (main.cpp:463-513) — all of it device-resident
    here.  Refinement (no reference analog) runs on the gathered inverse
    and therefore requires ``gather=True``.
    """
    from .ops import newton_schulz
    from .parallel import make_mesh, sharded_generate
    from .parallel.layout import CyclicLayout
    from .parallel.ring_gemm import distributed_residual_blocks
    from .parallel.sharded_jordan import (
        compile_sharded_jordan,
        gather_inverse,
    )

    if refine and not gather:
        raise ValueError("refine requires gather=True (it runs on the "
                         "gathered inverse)")
    mesh = make_mesh(workers)
    lay = CyclicLayout.create(n, min(block_size, n), workers)
    W = sharded_generate(generator, lay, mesh, dtype, augmented=True)
    if verbose:
        from .utils.printing import print_corner

        print("A")
        print_corner(generate(generator, (min(n, 10), min(n, 10)), dtype))
    run = compile_sharded_jordan(W, mesh, lay)
    t0 = time.perf_counter()
    out, singular = run(W)
    jax.block_until_ready(out)
    elapsed = time.perf_counter() - t0
    if bool(singular.any()):
        raise SingularMatrixError("singular matrix")

    inv_blocks = out[:, :, lay.N:]
    inv = None
    if gather:
        inv = gather_inverse(out, lay, n)
    if refine:
        a_full = generate(generator, (n, n), dtype)
        inv = newton_schulz(a_full, inv, refine)
        from .ops import residual_inf_norm

        residual = float(residual_inf_norm(a_full, inv))
    else:
        # Residual against a *freshly regenerated* A (main.cpp:463-488),
        # fully distributed: only this scalar leaves the mesh.
        a_blocks = sharded_generate(generator, lay, mesh, dtype,
                                    augmented=False)
        residual = float(distributed_residual_blocks(a_blocks, inv_blocks,
                                                     mesh, lay))
    if verbose:
        print(f"glob_time: {elapsed:.2f}")
        if inv is not None:
            from .utils.printing import print_corner

            print("inverse matrix:\n")
            print_corner(inv)
        print(f"residual: {residual:e}")
    return SolveResult(
        inverse=inv,
        elapsed=elapsed,
        residual=residual,
        n=n,
        block_size=min(block_size, n),
        gflops=2.0 * n**3 / elapsed / 1e9,
        inverse_blocks=None if gather else inv_blocks,
        layout=None if gather else lay,
    )


def _solve_distributed(a, n: int, block_size: int, workers: int,
                       refine: int):
    """Run the shared sharded front end with a timer around the sharded
    elimination alone (compile, gather and refinement excluded) — the same
    bracket as the reference's glob_time around Jordan (main.cpp:427-450)
    and as the generator-driven path, so the two modes report comparable
    numbers."""
    from .ops import newton_schulz
    from .parallel import make_mesh
    from .parallel.sharded_jordan import (
        gather_inverse,
        prepare_sharded_invert,
    )

    mesh = make_mesh(workers)
    blocks, lay, run = prepare_sharded_invert(a, mesh, block_size)
    t0 = time.perf_counter()
    out, singular = run(blocks)
    jax.block_until_ready(out)
    elapsed = time.perf_counter() - t0
    inv = newton_schulz(a, gather_inverse(out, lay, n), refine)
    jax.block_until_ready(inv)
    return inv, singular.any(), elapsed
