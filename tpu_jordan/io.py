"""Matrix file I/O.

TPU-native replacement for ``read_matrix`` (main.cpp:209-282): the reference's
file format is n*n whitespace-separated decimal numbers read row-major with
``fscanf("%lf")``.  The reference scatters block rows over ranks with
MPI_Send as it reads (main.cpp:244-274); here the host parses the file and
``jax.device_put`` with a NamedSharding places the shards — the scatter is
the sharding, not hand-written sends.

Error contract mirrors the reference's collective error codes
(main.cpp:231-237, 277): -1 "cannot open" → FileNotFoundError, -2 "cannot
read" → MatrixReadError.

A fast C++ parser for large files lives in ``native/`` (used when built,
transparent fallback to numpy otherwise).
"""

from __future__ import annotations

import numpy as np


class MatrixReadError(ValueError):
    """File exists but does not contain n*n parseable numbers (the
    reference's -2 "cannot read" path, main.cpp:255, 277)."""


def read_matrix_file(path: str, n: int, dtype=np.float64) -> np.ndarray:
    """Read an (n, n) matrix of whitespace-separated numbers from ``path``.

    Raises FileNotFoundError (reference -1) or MatrixReadError (-2).
    """
    try:
        from .native import parse_matrix_text

        vals = parse_matrix_text(path, n * n)
    except ImportError:
        try:
            with open(path) as fh:
                tokens = fh.read().split()
        except OSError as e:
            raise FileNotFoundError(f"cannot open {path}") from e
        try:
            vals = np.array(tokens[: n * n], dtype=np.float64)
        except ValueError as e:
            raise MatrixReadError(f"cannot read {path}") from e
    if vals is None or vals.size < n * n:
        raise MatrixReadError(f"cannot read {path}")
    return vals[: n * n].reshape(n, n).astype(dtype)


def write_matrix_file(path: str, a: np.ndarray) -> None:
    """Write a matrix in the reference's format (whitespace-separated,
    row-major) so our files round-trip through the reference binary."""
    np.savetxt(path, np.asarray(a), fmt="%.17g")


def read_matrix_corner(path: str, n: int, dtype=np.float64,
                       k: int = 10) -> np.ndarray:
    """Top-left min(n, k)-corner of the matrix in ``path`` — the
    print_matrix gather (main.cpp:297-341) without reading past the first
    k rows (O(n·k) host work, never the whole file)."""
    k = min(n, k)
    with MatrixStripReader(path, n, dtype) as reader:
        return np.ascontiguousarray(reader.read_rows(k)[:, :k])


class MatrixStripReader:
    """Incremental row-strip reader: the streaming analog of the
    reference's root-rank scatter loop (main.cpp:242-276), which reads ONE
    block-row buffer at a time so host memory stays O(n·m) — never O(n²).

    Uses the native chunked strtod stream when built (``make native``),
    else a pure-Python chunked tokenizer with the same contract.  Context
    manager; raises FileNotFoundError / MatrixReadError like
    ``read_matrix_file``.
    """

    _CHUNK = 1 << 20

    def __init__(self, path: str, n: int, dtype=np.float64):
        self.path = path
        self.n = n
        self.dtype = dtype
        self._native = None
        self._fh = None
        self._tail = ""
        self._pending: list[str] = []
        try:
            from .native import MatrixStream

            self._native = MatrixStream(path)
        except ImportError:
            try:
                self._fh = open(path)
            except OSError as e:
                raise FileNotFoundError(f"cannot open {path}") from e

    def read_rows(self, nrows: int) -> np.ndarray:
        """Next ``nrows`` full rows as an (nrows, n) array."""
        count = nrows * self.n
        if self._native is not None:
            vals = self._native.read(count)
        else:
            vals = self._read_tokens_py(count)
        if vals.size < count:
            raise MatrixReadError(f"cannot read {self.path}")
        return vals.reshape(nrows, self.n).astype(self.dtype)

    def _read_tokens_py(self, count: int) -> np.ndarray:
        out = np.empty(count, dtype=np.float64)
        got = 0
        while got < count:
            while self._pending and got < count:
                take = min(count - got, len(self._pending))
                try:
                    out[got:got + take] = self._pending[:take]
                except ValueError as e:
                    raise MatrixReadError(
                        f"cannot read {self.path}") from e
                del self._pending[:take]
                got += take
            if got == count:
                break
            chunk = self._fh.read(self._CHUNK)
            if not chunk:
                # Flush the carried partial token, then EOF.
                if self._tail:
                    self._pending = [self._tail]
                    self._tail = ""
                    continue
                break
            data = self._tail + chunk
            # A token may straddle the chunk boundary: carry the tail
            # unless the chunk ends in whitespace.
            if data[-1].isspace():
                self._tail = ""
                self._pending = data.split()
            else:
                toks = data.split()
                self._tail = toks.pop() if toks else ""
                self._pending = toks
        return out[:got]

    def close(self):
        if self._native is not None:
            self._native.close()
        if self._fh is not None:
            self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
