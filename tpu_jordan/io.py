"""Matrix file I/O.

TPU-native replacement for ``read_matrix`` (main.cpp:209-282): the reference's
file format is n*n whitespace-separated decimal numbers read row-major with
``fscanf("%lf")``.  The reference scatters block rows over ranks with
MPI_Send as it reads (main.cpp:244-274); here the host parses the file and
``jax.device_put`` with a NamedSharding places the shards — the scatter is
the sharding, not hand-written sends.

Error contract mirrors the reference's collective error codes
(main.cpp:231-237, 277): -1 "cannot open" → FileNotFoundError, -2 "cannot
read" → MatrixReadError.

A fast C++ parser for large files lives in ``native/`` (used when built,
transparent fallback to numpy otherwise).
"""

from __future__ import annotations

import numpy as np


class MatrixReadError(ValueError):
    """File exists but does not contain n*n parseable numbers (the
    reference's -2 "cannot read" path, main.cpp:255, 277)."""


def read_matrix_file(path: str, n: int, dtype=np.float64) -> np.ndarray:
    """Read an (n, n) matrix of whitespace-separated numbers from ``path``.

    Raises FileNotFoundError (reference -1) or MatrixReadError (-2).
    """
    try:
        from .native import parse_matrix_text

        vals = parse_matrix_text(path, n * n)
    except ImportError:
        try:
            with open(path) as fh:
                tokens = fh.read().split()
        except OSError as e:
            raise FileNotFoundError(f"cannot open {path}") from e
        try:
            vals = np.array(tokens[: n * n], dtype=np.float64)
        except ValueError as e:
            raise MatrixReadError(f"cannot read {path}") from e
    if vals is None or vals.size < n * n:
        raise MatrixReadError(f"cannot read {path}")
    return vals[: n * n].reshape(n, n).astype(dtype)


def write_matrix_file(path: str, a: np.ndarray) -> None:
    """Write a matrix in the reference's format (whitespace-separated,
    row-major) so our files round-trip through the reference binary."""
    np.savetxt(path, np.asarray(a), fmt="%.17g")
