"""The dynamic micro-batcher (ISSUE 3 tentpole part 2).

A thread-safe request queue grouped by shape bucket plus ONE dispatcher
thread.  A bucket dispatches when it can fill a whole batch
(``batch_cap`` requests), when its oldest request has waited
``max_wait_ms`` (the latency bound — a lone request never starves
waiting for company), or when the service is draining for shutdown.
Partial batches are padded with identity filler elements (inert and
never singular — the executable's shape is static), so occupancy is the
explicit throughput-vs-latency dial (docs/SERVING.md).

Each dispatched batch runs through the bucket's AOT executable
(``executors.py``) and the per-element results — inverse (unpadded back
to the request's n), κ∞, rel_residual, singular flag, queue/execute
timings — fan back to per-request ``concurrent.futures.Future``s.  A
singular element resolves ITS future's result with ``singular=True``;
healthy elements of the same batch are untouched (``solve_batch``'s
per-element flag machinery — no batch-wide poisoning).

Admission control is the caller's thread: a full bounded queue raises
:class:`ServiceOverloadedError` at ``submit`` time — typed backpressure,
never a silent drop.  An execution failure resolves every future of its
batch with the exception, same contract.

Resilience (ISSUE 5, docs/RESILIENCE.md) — when a
``resilience.ResiliencePolicy`` is attached:

  * **Retry + integrity gate** — the per-batch executable run is
    wrapped in ``policy.retry``: transient failures (and the
    ``execute`` fault point) re-run the batch; a non-finite
    rel_residual on any real, non-singular element (real corruption, or
    the ``result_corrupt_nan`` fault point) raises the typed
    :class:`~..resilience.policy.ResultCorruptionError`, which the
    retry absorbs — a re-run clears transient corruption, so riders
    still receive the bit-exact fault-free result.
  * **Deadlines** — ``submit(..., deadline_s=)`` covers queue wait AND
    execute: a request whose deadline passed is failed with the typed
    :class:`~..resilience.policy.DeadlineExceededError` at dispatch
    (before riding a doomed batch) or at fan-out (its batch finished
    too late) — never a hang, never a silent drop.
  * **Circuit breaker** — per-bucket (held by the
    :class:`~.executors.ExecutorCache`): K consecutive terminal batch
    failures open the bucket; ``submit`` then fast-fails with
    :class:`~..resilience.policy.CircuitOpenError` instead of queueing
    doomed work, until a half-open probe succeeds after the cooldown.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass

import numpy as np

from ..obs import metrics as _obs_metrics
from ..resilience import faults as _faults
from ..resilience.policy import (CircuitOpenError, DeadlineExceededError,
                                 ResultCorruptionError)


class ServiceOverloadedError(RuntimeError):
    """The bounded request queue is full — backpressure, not a drop.
    Callers retry with their own policy; the service never discards an
    accepted request (ISSUE 3 acceptance contract)."""


class ServiceClosedError(RuntimeError):
    """submit() after close() — the service no longer accepts work."""


class MixedUpdateBatchError(TypeError):
    """A rider in one update-lane batch does not match the lane's
    (bucket, k_bucket, dtype) — mixed-bucket or mixed-dtype riders are
    refused TYPED, never silently padded/cast to the lane's compiled
    shape (ISSUE 17).  Only direct batcher misuse can produce one:
    ``JordanService.submit_update`` pads every rider to its own lane
    key, so lanes stay homogeneous by construction."""


@dataclass
class InvertResult:
    """What a request's future resolves to: the unpadded result plus
    the per-element accuracy/diagnostics the compiled batch program
    assembled (``driver.batch_metrics`` /
    ``linalg.solve_batch_metrics``).

    ISSUE 11: solve requests (``submit(a, b)``) resolve to the same
    type with ``workload="solve"``, ``solution`` = the (n, k) X and
    ``inverse=None`` — no inverse is ever formed for them;
    ``rel_residual`` is then the κ-free ‖A·X − B‖ backward error and
    ``kappa`` the ‖A‖‖X‖/‖B‖ conditioning estimate."""

    inverse: object           # (n, n) device array, padding sliced off
    n: int
    bucket_n: int
    singular: bool
    kappa: float
    rel_residual: float
    queue_seconds: float      # submit -> dispatch
    execute_seconds: float    # the batch execution this request rode
    batch_occupancy: int      # real requests in that batch
    workload: str = "invert"  # "invert" | "solve" | "update"
    solution: object = None   # (n, k) X for solve requests
    # ---- resident-update fields (ISSUE 12; None off the update lane)
    update_outcome: str = None    # "refreshed" | "re_inverted" | "gated"
    handle: object = None         # the HandleRef the update mutated
    handle_version: int = None    # committed version after this update
    drift: float = None           # accumulated drift after this update


@dataclass
class _Request:
    padded: np.ndarray        # (bucket_n, bucket_n) identity-padded input
    n: int
    bucket_n: int
    t_enqueue: float
    future: Future
    t_deadline: float | None = None   # absolute perf_counter deadline
    ctx: object = None        # obs.journey.RequestContext (ISSUE 8)
    workload: str = "invert"  # lane workload (ISSUE 11/12)
    padded_b: np.ndarray = None       # (bucket_n, rhs) zero-padded RHS
    rhs: int = 0              # RHS-width bucket of the lane
    k: int = 0                # this request's REAL RHS/rank width
    handle: object = None     # update lane: the HandleRef to mutate
    padded_u: np.ndarray = None       # (bucket_n, k_bucket) zero-padded
    padded_v: np.ndarray = None       # (bucket_n, k_bucket) zero-padded
    mesh: str = "single"      # topology of the lane (ISSUE 18)

    def hop(self, event: str, **attrs) -> None:
        """One journey event for this rider (no-op without a context —
        the batcher never requires journeys to function)."""
        if self.ctx is not None:
            self.ctx.event(event, **attrs)


def _lane(workload: str, bucket_n: int, rhs: int = 0,
          mesh: str = "single"):
    """The queue/breaker key for a request class: invert lanes keep the
    historical bare int (every pre-ISSUE-11 key, stat label, and
    breaker name is byte-identical); solve lanes are
    ("solve", bucket_n, rhs) tuples.  Mesh lanes (ISSUE 18) are
    4-tuples carrying the topology — distinct meshes of one bucket are
    distinct queues, breakers, and stats rows."""
    if mesh != "single":
        return (workload, bucket_n, int(rhs), mesh)
    return bucket_n if workload == "invert" else (workload, bucket_n,
                                                  int(rhs))


def _lane_label(lane):
    """The stats/metrics label of a lane: the bare bucket int for
    invert, ``"solve:<bucket>:k<rhs>"`` for solve lanes, and the same
    with an ``@mesh`` suffix for mesh lanes (matching
    ``executors.get_info``'s label, so the two can never drift)."""
    if isinstance(lane, int):
        return lane
    if len(lane) == 4:
        wl, b, rhs, mesh = lane
        base = b if wl == "invert" else f"{wl}:{b}:k{rhs}"
        return f"{base}@{mesh}"
    return f"{lane[0]}:{lane[1]}:k{lane[2]}"


def _lane_workload(lane) -> str:
    return "invert" if isinstance(lane, int) else lane[0]


def _lane_mesh(lane) -> str:
    return lane[3] if isinstance(lane, tuple) and len(lane) == 4 \
        else "single"


class MicroBatcher:
    """The queue + dispatcher.  ``autostart=False`` leaves the
    dispatcher thread unstarted (tests fill the bounded queue
    deterministically, then ``start()`` drains it); ``close()`` on a
    never-started batcher drains inline on the calling thread."""

    def __init__(self, executors, stats, batch_cap: int = 8,
                 max_wait_ms: float = 2.0, max_queue: int = 256,
                 block_size: int | None = None, autostart: bool = True,
                 telemetry=None, policy=None, numerics: str = "off",
                 handles=None, update_drift_budget_factor=None):
        from ..obs.spans import NULL

        if batch_cap < 1:
            raise ValueError("batch_cap must be >= 1")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.executors = executors
        self.stats = stats
        # Resident-handle store (ISSUE 12): where the update lanes read
        # committed (A, A⁻¹) state and write through — fleet-shared
        # when the service was built with shared_handles.  The drift
        # factor widens/narrows the accumulated-drift budget (None =
        # linalg.update.DRIFT_BUDGET_FACTOR, the documented default).
        self.handles = handles
        self._drift_factor = update_drift_budget_factor
        # Numerics knob (ISSUE 10): "off" (the serve-path default —
        # zero added work on the dispatch path) or "summary" (each real
        # rider's already-computed rel_residual/κ∞ observed into the
        # tpu_jordan_residual histogram, spiking the flight recorder on
        # expected-error exceedances).  "trace" is a solve-path mode:
        # the batched executables are fused and host-opaque, so the
        # service validates it away (JordanService).
        self.numerics = numerics
        # Resilience policy (ISSUE 5): retry/integrity-gate on the batch
        # execution, deadline enforcement, breaker feedback.  None keeps
        # the pre-resilience behavior exactly.
        self.policy = policy
        # Telemetry (ISSUE 4): each dispatched batch is an "execute"
        # span (dispatcher-thread root; bucket/occupancy attrs), so the
        # wall time fanned to futures IS the span duration.
        self._tel = telemetry if telemetry is not None else NULL
        self.batch_cap = int(batch_cap)
        self.max_wait = float(max_wait_ms) / 1e3
        self.max_queue = int(max_queue)
        self.block_size = block_size
        self._cv = threading.Condition()
        # Serializes close() itself: the supervisor and a context
        # manager __exit__ may race to close the same batcher (ISSUE 7
        # satellite); the second caller must block until the first
        # finished, then no-op.
        self._close_lock = threading.Lock()
        self._queues: dict[int, deque] = {}
        self._queued = 0
        self._closing = False
        self._thread: threading.Thread | None = None
        # Dispatcher-progress signal (ISSUE 7 liveness): ``_ticks``
        # advances every time the dispatcher returns to the pick/wait
        # cycle, ``_busy`` is True while it is out executing a batch.
        # A fleet replica's heartbeat stamps off this (``progress()``),
        # so a dispatcher stuck mid-execute — the real production
        # wedge — stops proving liveness and the supervisor's staleness
        # deadline catches it.
        self._ticks = 0
        self._busy = False
        if autostart:
            self.start()

    # ---- caller side -------------------------------------------------

    def submit(self, padded: np.ndarray, n: int, bucket_n: int,
               deadline_s: float | None = None, ctx=None,
               workload: str = "invert", padded_b: np.ndarray = None,
               rhs: int = 0, k: int = 0, handle=None,
               padded_u: np.ndarray = None,
               padded_v: np.ndarray = None,
               mesh: str = "single") -> Future:
        lane = _lane(workload, bucket_n, rhs, mesh)
        label = _lane_label(lane)
        br = self.executors.breaker(label) \
            if self.policy is not None else None
        if br is not None and not br.allow():
            # Typed fast-fail instead of queueing doomed work: the
            # bucket's executor has failed K consecutive times; a
            # half-open probe is admitted once the cooldown elapses.
            self.stats.rejected(label, workload=workload)
            if ctx is not None:
                ctx.event("breaker_fast_fail", bucket=bucket_n)
            raise CircuitOpenError(
                f"bucket {label} circuit open after repeated executor "
                f"failures — retry after the cooldown")
        now = time.perf_counter()
        req = _Request(padded, n, bucket_n, now, Future(),
                       t_deadline=(None if deadline_s is None
                                   else now + float(deadline_s)),
                       ctx=ctx, workload=workload, padded_b=padded_b,
                       rhs=int(rhs), k=int(k), handle=handle,
                       padded_u=padded_u, padded_v=padded_v,
                       mesh=str(mesh))
        with self._cv:
            if self._closing:
                req.hop("reject", reason="closed")
                raise ServiceClosedError("service is closed")
            if self._queued >= self.max_queue:
                self.stats.rejected(label, workload=workload)
                req.hop("reject", reason="overload", queued=self._queued)
                raise ServiceOverloadedError(
                    f"request queue full ({self.max_queue} pending) — "
                    f"retry later (typed backpressure, nothing dropped)")
            # The enqueue hop is recorded BEFORE the queue append (and
            # under _cv): the dispatcher's "dispatch" hop can otherwise
            # race ahead of "enqueue" in the journey.  Lock order is
            # _cv -> ctx -> recorder, never reversed.
            req.hop("enqueue", bucket=bucket_n, queued=self._queued + 1)
            self._queues.setdefault(lane, deque()).append(req)
            self._queued += 1
            self.stats.request(label, workload=workload)
            self._cv.notify()
        return req.future

    def start(self) -> None:
        with self._cv:
            if self._closing or self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._loop, name="tpu-jordan-serve", daemon=True)
            self._thread.start()

    def close(self, drain: bool = True, error=None,
              join_timeout_s: float | None = None) -> None:
        """Stop accepting work.  ``drain=True`` (the default) completes
        every queued request before returning; ``drain=False`` fails
        queued futures with :class:`ServiceClosedError` (explicitly —
        never silently), or with whatever the zero-arg ``error`` factory
        builds (the fleet's replica kill passes its typed
        ``ReplicaKilledError`` so the router re-queues, ISSUE 7).

        Idempotent and thread-safe: concurrent closers serialize on one
        lock, and the second (and every later) call finds nothing left
        to do.  Queued futures are failed OUTSIDE the queue lock —
        their done-callbacks (the fleet router re-dispatches from one)
        may submit to other services and must never run under this
        batcher's lock.

        ``join_timeout_s`` bounds the dispatcher-thread join (ISSUE 7
        kill path): killing a replica whose dispatcher is genuinely
        wedged must not block the supervising thread forever.  On
        timeout the daemon thread is abandoned (counted) — it observes
        ``_closing`` and exits if it ever comes back.  ``None`` (the
        default, every clean-shutdown path) joins until the drain
        completes."""
        with self._close_lock:
            doomed = []
            with self._cv:
                self._closing = True
                if not drain:
                    for q in self._queues.values():
                        while q:
                            doomed.append(q.popleft())
                    self._queued = 0
                self._cv.notify_all()
            make_error = error if error is not None else (
                lambda: ServiceClosedError(
                    "service closed before this request ran"))
            for req in doomed:
                # Claim-then-fail: a future the caller already
                # cancelled is left alone (claim fails).
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(make_error())
            if self._thread is not None:
                self._thread.join(join_timeout_s)
                if self._thread.is_alive():
                    # Wedged dispatcher abandoned on the kill path: the
                    # reference stays so a later (clean) close can join
                    # again; start() is already fenced by _closing.
                    _obs_metrics.counter(
                        "tpu_jordan_serve_dispatcher_abandoned_total",
                        "dispatcher threads still alive past a bounded "
                        "close join (wedged mid-execute) — abandoned as "
                        "daemons by the replica kill path",
                    ).inc()
                else:
                    self._thread = None
            elif self._queued:
                # Never started: drain inline on the caller's thread
                # (the loop exits once closing and empty).
                self._loop()

    def reap(self, join_timeout_s: float | None = 0.0) -> bool:
        """Retry the join of a dispatcher thread a bounded kill-path
        ``close`` abandoned (ISSUE 20 satellite).  A dispatcher wedged
        mid-execute that eventually unsticks observes ``_closing`` and
        exits — but the abandonment left its Thread reference parked in
        ``_thread`` forever.  ``reap`` joins it again (bounded by
        ``join_timeout_s``, default an instant poll) and drops the
        reference once the thread is really gone, counting the recovery
        in ``tpu_jordan_serve_dispatcher_reaped_total``.  Returns True
        when no abandoned thread remains (reaped now, or none was ever
        abandoned); False while it is still alive (try again later).
        Never blocks a live service: before ``close`` there is nothing
        abandoned to reap."""
        with self._close_lock:
            t = self._thread
            if t is None:
                return True
            if not self._closing:
                # Still serving — the dispatcher is working, not
                # abandoned.
                return False
            t.join(join_timeout_s)
            if t.is_alive():
                return False
            self._thread = None
        _obs_metrics.counter(
            "tpu_jordan_serve_dispatcher_reaped_total",
            "abandoned dispatcher threads successfully joined by a "
            "later reap() retry — the bounded kill-path abandonment, "
            "undone once the wedge cleared",
        ).inc()
        return True

    @property
    def queued(self) -> int:
        with self._cv:
            return self._queued

    def progress(self) -> tuple[int, bool]:
        """``(ticks, busy)`` — the dispatcher liveness signal.  An idle
        dispatcher (parked in the condition wait, ``busy=False``) is
        responsive; a busy one proves liveness by advancing ``ticks``
        (it returned from a batch).  ``busy=True`` with a frozen tick
        count is a dispatcher stuck mid-execute: the caller (the fleet
        replica's heartbeat) stops stamping and lets the supervisor's
        staleness deadline declare the wedge.  Safe to call against a
        wedged dispatcher — it never holds the queue lock while
        executing."""
        with self._cv:
            return self._ticks, self._busy

    # ---- dispatcher side ---------------------------------------------

    def _pick(self, now: float) -> tuple[int, str] | None:
        """The ``(bucket, cause)`` to dispatch: any full batch
        (``cause="full"``), else the bucket whose head request has aged
        past the deadline (``"deadline"``, oldest head first); when
        draining, any nonempty bucket (``"drain"``).  The cause lands
        on every rider's journey — WHY a batch went when it did is the
        occupancy-vs-latency dial made per-request-visible."""
        best = None
        for b, q in self._queues.items():
            if not q:
                continue
            age = now - q[0].t_enqueue
            if len(q) >= self._lane_cap(b):
                cause = "full"
            elif age >= self.max_wait:
                cause = "deadline"
            elif self._closing:
                cause = "drain"
            else:
                continue
            if best is None or age > best[1]:
                best = (b, age, cause)
        return None if best is None else (best[0], best[2])

    def _lane_cap(self, lane) -> int:
        """A lane's dispatch capacity: ``batch_cap`` everywhere except
        mesh lanes, which go at occupancy 1 — one sharded program owns
        the whole mesh per launch (ISSUE 18), so a "full batch" there
        is one request."""
        return 1 if _lane_mesh(lane) != "single" else self.batch_cap

    def _next_deadline(self, now: float) -> float | None:
        waits = [self.max_wait - (now - q[0].t_enqueue)
                 for q in self._queues.values() if q]
        return max(0.0, min(waits)) if waits else None

    def _loop(self) -> None:
        while True:
            with self._cv:
                self._busy = False
                self._ticks += 1
                while True:
                    now = time.perf_counter()
                    picked = self._pick(now)
                    if picked is not None:
                        bucket, cause = picked
                        q = self._queues[bucket]
                        take = min(len(q), self._lane_cap(bucket))
                        batch = [q.popleft() for _ in range(take)]
                        self._queued -= take
                        # Claim each future (the stdlib executor
                        # protocol): a caller-cancelled one drops out
                        # here, and no future can transition under the
                        # execution — set_result below can never race
                        # a cancel into InvalidStateError.
                        batch = [r for r in batch
                                 if r.future.set_running_or_notify_cancel()]
                        # Deadline, phase 1 (queue wait): a request
                        # already past its deadline must not ride a
                        # batch — fail it typed, here, before dispatch.
                        batch = self._fail_expired(batch, "queue")
                        if not batch:
                            continue
                        self._busy = True
                        break
                    if self._closing and self._queued == 0:
                        return
                    self._cv.wait(self._next_deadline(now))
            for req in batch:
                req.hop("dispatch", cause=cause, occupancy=len(batch))
            self._execute(bucket, batch, now)

    # ---- the resident-update lane (ISSUE 12) -------------------------

    def _execute_updates(self, lane, batch: list,
                         t_dispatch: float) -> None:
        """Dispatch one picked update-lane batch (ISSUE 12, batched in
        ISSUE 17).  Riders targeting DISTINCT handles share ONE vmapped
        SMW launch through the lane's batch-cap executable (each
        element re-verified in-launch, per-element singular/gate
        flags); same-handle followers — whose input state depends on
        the batch-mate ahead of them — and every rider of a cap-1 lane
        run sequentially through the one-per-launch executable, so
        per-handle update ordering is preserved exactly.  A rider's
        terminal failure is ITS typed error and ITS batch-failure
        count; batch-mates are untouched.  Mixed-bucket/dtype riders
        are refused with the typed :class:`MixedUpdateBatchError` —
        never silently padded to the lane's compiled shape."""
        label = _lane_label(lane)
        bucket, kb = lane[1], lane[2]
        br = self.executors.breaker(label) \
            if self.policy is not None else None

        def fail_batch(riders, e):
            _obs_metrics.counter(
                "tpu_jordan_serve_batch_failures_total",
                "dispatched batches that terminally failed (after any "
                "retries) and fanned a typed error to their riders",
            ).inc(bucket=label)
            if br is not None:
                br.record_failure()
            for req in riders:
                req.hop("batch_failure", error=type(e).__name__)
                if not req.future.done():
                    req.future.set_exception(e)

        try:
            _faults.fire("dispatch")
            ex, source = self.executors.get_info(
                bucket, 1, self.block_size, workload="update", rhs=kb)
        except BaseException as e:                  # noqa: BLE001
            fail_batch(batch, e)
            return
        queue_waits = [t_dispatch - req.t_enqueue for req in batch]
        from ..resilience.policy import ResidualGateError
        from .handles import UnknownHandleError

        # Typed-refusal contract (ISSUE 17): every rider must already
        # match the lane's compiled (bucket, k_bucket, dtype) — the
        # service pads to the lane key, so only direct batcher misuse
        # can violate this, and it is refused typed, never padded.
        dtype = np.dtype(ex.key.dtype)
        shape = (bucket, kb)
        conforming = []
        for i, req in enumerate(batch):
            pu, pv = req.padded_u, req.padded_v
            if (req.bucket_n != bucket or int(req.rhs) != kb
                    or pu is None or pv is None
                    or tuple(pu.shape) != shape
                    or tuple(pv.shape) != shape
                    or np.dtype(pu.dtype) != dtype
                    or np.dtype(pv.dtype) != dtype):
                e = MixedUpdateBatchError(
                    f"update rider (bucket {req.bucket_n}, k_bucket "
                    f"{req.rhs}, factors "
                    f"{None if pu is None else (tuple(pu.shape), str(pu.dtype))}) "
                    f"does not match lane {label} "
                    f"(bucket {bucket}, k_bucket {kb}, {dtype}) — "
                    f"mixed riders are refused, never silently padded")
                req.hop("typed_failure", error=type(e).__name__)
                if not req.future.done():
                    req.future.set_exception(e)
            else:
                conforming.append((i, req))

        # Split the batch: the FIRST rider per distinct handle can
        # share one vmapped launch (their input states are independent
        # committed snapshots); later same-handle riders must observe
        # the batch-mate's committed result first.
        group, followers, seen = [], [], set()
        for i, req in conforming:
            hid = req.handle.handle_id
            if hid in seen:
                followers.append((i, req))
            else:
                seen.add(hid)
                group.append((i, req))
        use_group = self.batch_cap > 1 and len(group) > 1
        if not use_group:
            followers = conforming
            group = []

        singular_served = 0
        exec_total = 0.0
        ok = True

        def settle(req, res):
            """Interpret one rider's outcome (InvertResult | None |
            exception) with the lane's shared failure taxonomy."""
            nonlocal ok, singular_served
            if res is None:
                # Deadline expired during execute: the rider was
                # failed typed BEFORE the commit (the handle is
                # untouched — a typed update failure never leaves a
                # half-trusted mutation behind).
                return
            if isinstance(res, (UnknownHandleError, ResidualGateError)):
                # Typed CALLER/NUMERICS outcomes — an evicted handle,
                # or one handle's gate/drift failure the rung couldn't
                # recover — are THIS rider's answer, not lane-health
                # evidence: no breaker feedback, no batch-failure
                # count (the invert lane never counts caller bugs or
                # per-element numerics against its breaker either).
                req.hop("typed_failure", error=type(res).__name__)
                if not req.future.done():
                    req.future.set_exception(res)
                return
            if isinstance(res, BaseException):
                ok = False
                _obs_metrics.counter(
                    "tpu_jordan_serve_batch_failures_total",
                    "dispatched batches that terminally failed (after "
                    "any retries) and fanned a typed error to their "
                    "riders",
                ).inc(bucket=label)
                if br is not None:
                    br.record_failure()
                req.hop("batch_failure", error=type(res).__name__)
                if not req.future.done():
                    req.future.set_exception(res)
                return
            singular_served += int(res.singular)
            req.hop("served", singular=bool(res.singular),
                    outcome=res.update_outcome,
                    version=res.handle_version,
                    seconds=round(res.execute_seconds, 6))
            req.future.set_result(res)

        if group:
            try:
                ex_b, source_b = self.executors.get_info(
                    bucket, self.batch_cap, self.block_size,
                    workload="update", rhs=kb)
            except BaseException as e:              # noqa: BLE001
                fail_batch([r for _, r in group], e)
                group, ex_b = [], None
            if group:
                for _, req in group:
                    req.hop("executor", bucket=bucket, source=source_b,
                            engine=ex_b.key.engine,
                            batched=len(group))
                try:
                    results, exec_s = self._run_update_group(
                        [r for _, r in group], ex_b,
                        [queue_waits[i] for i, _ in group], len(batch))
                except BaseException as e:          # noqa: BLE001
                    fail_batch([r for _, r in group], e)
                    ok = False
                else:
                    exec_total += exec_s
                    for (_, req), res in zip(group, results):
                        settle(req, res)

        for i, req in followers:
            req.hop("executor", bucket=bucket, source=source,
                    engine=ex.key.engine)
            try:
                res = self._run_one_update(req, ex, queue_waits[i],
                                           len(batch))
            except BaseException as e:              # noqa: BLE001
                res = e
            else:
                if res is not None and not isinstance(res, BaseException):
                    exec_total += res.execute_seconds
            settle(req, res)
        if ok and br is not None:
            br.record_success()
        self.stats.batch(label, occupancy=len(batch),
                         exec_seconds=exec_total,
                         queue_seconds=queue_waits,
                         singular=singular_served, workload="update")

    def _run_update_group(self, group: list, ex, queue_waits: list,
                          occupancy: int):
        """One vmapped SMW launch for riders targeting DISTINCT handles
        (ISSUE 17): read each handle's committed state under its store
        lock (locks taken in sorted handle-id order — one global
        acquisition order, so concurrent group launches can never
        deadlock), stack the (A, A⁻¹, U, V, n_real) quadruples with
        inert identity/zero fillers for empty slots, run the batch-cap
        executable ONCE (retried + integrity-gated over the REAL
        elements), then judge every rider's gate/drift/rung and commit
        PER HANDLE exactly as the one-per-launch path does.

        Returns ``(results, exec_seconds)`` where each result is the
        rider's ``InvertResult``, ``None`` (deadline — failed typed,
        handle untouched), or the rider's own typed exception; a raise
        out of this method is a whole-launch terminal failure."""
        import contextlib
        import math

        import jax.numpy as jnp

        from ..obs import hwcost as _hwcost
        from ..obs.spans import timed_blocking
        from .handles import UnknownHandleError

        store = self.handles
        bucket = ex.key.bucket_n
        cap, N, K = ex.key.batch_cap, ex.key.bucket_n, ex.key.rhs
        dtype = np.dtype(ex.key.dtype)
        results = [None] * len(group)
        with contextlib.ExitStack() as stack:
            sts = {}
            live = []
            for i, req in sorted(enumerate(group),
                                 key=lambda t: t[1].handle.handle_id):
                hid = req.handle.handle_id
                try:
                    sts[hid] = stack.enter_context(store.txn(hid))
                except UnknownHandleError as e:
                    results[i] = e
                else:
                    live.append(i)
            live.sort()
            if not live:
                return results, 0.0
            a = np.tile(np.eye(N, dtype=dtype), (cap, 1, 1))
            inv = a.copy()
            u = np.zeros((cap, N, K), dtype)
            v = np.zeros((cap, N, K), dtype)
            nr = np.zeros((cap, 1), np.int32)
            for slot, i in enumerate(live):
                req = group[i]
                st = sts[req.handle.handle_id]
                a[slot] = st.a
                inv[slot] = st.inverse
                u[slot] = req.padded_u
                v[slot] = req.padded_v
                nr[slot] = req.n
            args = tuple(jnp.asarray(x) for x in (a, inv, u, v, nr))

            def run_once():
                _faults.fire("execute")
                out, esp = timed_blocking(
                    ex.run, *args, telemetry=self._tel, name="execute",
                    bucket=bucket, occupancy=len(live),
                    workload="update")
                _hwcost.attach_execute_cost(
                    esp, ex.cost,
                    analytical_flops=len(live)
                    * _hwcost.baseline_workload_flops(
                        bucket, "update", k=ex.key.rhs))
                a_new, inv_new, sing, kappa, rel = out
                sing = np.asarray(sing)
                kappa = np.asarray(kappa, float)
                rel = np.array(np.asarray(rel), float)
                for slot in range(len(live)):
                    if (not bool(sing[slot])
                            and _faults.corrupt("result_corrupt_nan")):
                        rel[slot] = float("nan")
                # Integrity gate per REAL element (the invert-lane
                # discipline): a non-singular update must report a
                # finite in-launch rel_residual — corruption is typed
                # and retryable, and no commit has happened yet, so
                # the whole-launch retry is mutation-safe.
                for slot in range(len(live)):
                    if (not bool(sing[slot])
                            and not math.isfinite(float(rel[slot]))):
                        raise ResultCorruptionError(
                            f"non-finite rel_residual in batched "
                            f"update launch (bucket {bucket}, slot "
                            f"{slot}) — corrupted result detected by "
                            f"the integrity gate")
                return a_new, inv_new, sing, kappa, rel, esp.duration

            def on_retry(exc, attempt):
                for i in live:
                    group[i].hop("retry", attempt=attempt,
                                 error=type(exc).__name__)

            a_new, inv_new, sing, kappa, rel, exec_s = (
                self.policy.retry.call(
                    run_once, component="serve.update",
                    on_retry=on_retry,
                    exemplar=(group[live[0]].ctx.request_id
                              if group[live[0]].ctx is not None
                              else None))
                if self.policy is not None else run_once())

            for slot, i in enumerate(live):
                req = group[i]
                st = sts[req.handle.handle_id]
                try:
                    results[i] = self._finish_update(
                        req, st, ex, np.asarray(a_new[slot]),
                        np.asarray(inv_new[slot]), bool(sing[slot]),
                        float(kappa[slot]), float(rel[slot]), exec_s,
                        queue_waits[i], occupancy)
                except BaseException as e:          # noqa: BLE001
                    # One rider's typed gate exhaustion must not abort
                    # a batch-mate's commit — per-rider fates, exactly
                    # like the sequential path.
                    results[i] = e
        return results, exec_s

    def _run_one_update(self, req, ex, queue_s: float,
                        occupancy: int):
        """One rider's SMW application end to end: read the committed
        handle state under its lock, run the lane executable (retried
        + integrity-gated per the policy), judge the residual gate and
        the accumulated-drift budget against the MUTATED matrix, walk
        the re_invert rung when they fire, and WRITE THROUGH the new
        committed state.  Returns the rider's ``InvertResult``; raises
        typed on terminal failure (handle state untouched — committed,
        never half-updated)."""
        import jax.numpy as jnp
        import math

        from ..obs import hwcost as _hwcost
        from ..obs.spans import timed_blocking

        bucket = req.bucket_n
        handle = req.handle
        store = self.handles
        with store.txn(handle.handle_id) as st:
            args = (jnp.asarray(st.a), jnp.asarray(st.inverse),
                    jnp.asarray(req.padded_u),
                    jnp.asarray(req.padded_v),
                    jnp.asarray([req.n], jnp.int32))

            def run_once():
                _faults.fire("execute")
                out, esp = timed_blocking(
                    ex.run, *args, telemetry=self._tel, name="execute",
                    bucket=bucket, occupancy=1, workload="update")
                _hwcost.attach_execute_cost(
                    esp, ex.cost,
                    analytical_flops=_hwcost.baseline_workload_flops(
                        bucket, "update", k=ex.key.rhs))
                a_new, inv_new, sing, kappa, rel = out
                sing = bool(sing)
                kappa = float(kappa)
                rel = float(rel)
                if not sing and _faults.corrupt("result_corrupt_nan"):
                    rel = float("nan")
                # Integrity gate (the invert-lane discipline): a
                # non-singular update must report a finite in-launch
                # rel_residual — corruption is typed and retryable.
                if not sing and not math.isfinite(rel):
                    raise ResultCorruptionError(
                        f"non-finite rel_residual for update "
                        f"(handle {handle.handle_id}, bucket {bucket}) "
                        f"— corrupted result detected by the "
                        f"integrity gate")
                return a_new, inv_new, sing, kappa, rel, esp.duration

            def on_retry(exc, attempt):
                req.hop("retry", attempt=attempt,
                        error=type(exc).__name__)

            a_new, inv_new, sing, kappa, rel, exec_s = (
                self.policy.retry.call(
                    run_once, component="serve.update",
                    on_retry=on_retry,
                    exemplar=(req.ctx.request_id
                              if req.ctx is not None else None))
                if self.policy is not None else run_once())

            return self._finish_update(req, st, ex, a_new, inv_new,
                                       sing, kappa, rel, exec_s,
                                       queue_s, occupancy)

    def _finish_update(self, req, st, ex, a_new, inv_new, sing: bool,
                       kappa: float, rel: float, exec_s: float,
                       queue_s: float, occupancy: int):
        """Judge and commit ONE update rider's launch result — shared
        by the one-per-launch path and the batched group launch (the
        judgment/commit discipline is identical; only the launch shape
        differs).  Must be called with ``st``'s handle transaction
        held.  Returns the rider's ``InvertResult``, or ``None`` when
        the deadline expired (failed typed, handle untouched); raises
        the typed ``ResidualGateError`` on gate exhaustion."""
        import jax.numpy as jnp

        from ..linalg.update import drift_budget, drift_exceeded
        from ..resilience.degrade import gate_passes, gate_threshold

        bucket = req.bucket_n
        handle = req.handle
        store = self.handles
        # Deadline, judged BEFORE the commit: an update past its
        # deadline fails typed with the handle untouched — "typed
        # failure = no mutation" holds unconditionally (the invert
        # lanes check after fan-out; an update has state to
        # protect).
        if not self._fail_expired([req], "execute"):
            return None

        if self.numerics == "summary" and not sing:
            # Observed (and spiked) BEFORE the gate/rung run — the
            # ISSUE 10 causality discipline: a recovery_rung event
            # must be preceded by the numerics evidence (the
            # PRE-recovery residual, judged by the policy's own
            # gate threshold) that explains it.
            self._observe_update_numerics(req, ex, kappa, rel)

        outcome, recovery_rel = "refreshed", rel
        if sing:
            # Typed singularity, handle untouched: the mutation
            # would have destroyed the matrix's rank — the rider
            # learns it, the resident state stays consistent.
            outcome = "gated"
        elif self.policy is not None:
            thr = gate_threshold(self.policy, req.n, kappa,
                                 jnp.dtype(ex.key.dtype))
            budget = drift_budget(thr, self._drift_factor)
            new_drift = st.drift + max(rel, 0.0)
            if (not gate_passes(rel, thr)
                    or drift_exceeded(new_drift, budget)):
                if (self.numerics == "summary"
                        and gate_passes(rel, thr)):
                    # Drift-caused: the residual spike above
                    # cannot explain this rung (rel passed), so
                    # the budget exceedance records its own spike.
                    from ..obs.numerics import record_drift_spike

                    record_drift_spike(n=req.n,
                                       engine=ex.key.engine,
                                       value=new_drift,
                                       threshold=budget)
                outcome, kappa, recovery_rel, inv_new = (
                    self._reinvert_rung(req, a_new, rel,
                                        new_drift, thr, budget))
                new_drift = 0.0
                if outcome == "gated":
                    # The rung's FRESH elimination flagged the
                    # mutated matrix singular — the capacitance
                    # solve's rounded determinant slipped past the
                    # eps threshold, but the from-scratch pivot
                    # probe cannot be fooled: typed singularity,
                    # handle untouched.
                    sing = True
            if not sing:
                store.commit(st, a=np.asarray(a_new),
                             inverse=np.asarray(inv_new),
                             kappa=kappa,
                             rel_residual=recovery_rel,
                             drift=new_drift,
                             reinverted=outcome == "re_inverted")
        else:
            # No policy = no gate (the PR 5 contract): drift still
            # accumulates so an attached policy later sees history.
            store.commit(st, a=np.asarray(a_new),
                         inverse=np.asarray(inv_new), kappa=kappa,
                         rel_residual=rel,
                         drift=st.drift + max(rel, 0.0))
        version, drift_after = st.version, st.drift
        req.hop("update", outcome=outcome, version=version,
                drift=round(drift_after, 9))
        return InvertResult(
            inverse=(None if sing
                     else np.asarray(inv_new)[:req.n, :req.n]),
            n=req.n, bucket_n=bucket, singular=sing, kappa=kappa,
            rel_residual=recovery_rel, queue_seconds=queue_s,
            execute_seconds=exec_s, batch_occupancy=occupancy,
            workload="update", update_outcome=outcome, handle=handle,
            handle_version=version, drift=drift_after)

    def _reinvert_rung(self, req, a_new, rel, new_drift, thr,
                       budget):
        """The "re_invert" degradation rung (ISSUE 12): the residual
        gate or the accumulated-drift budget fired, so the mutated
        matrix is re-eliminated FROM SCRATCH through a warm CAP-1
        invert executable (zero new compiles — warmed next to the
        update lane: one matrix, one elimination, never batch_cap
        identity fillers paying batch_cap eliminations) and judged
        again.  Passing resets the drift ledger; failing raises the
        typed ``ResidualGateError`` (the rider's answer — never a
        silently stale inverse)."""
        import jax.numpy as jnp

        from ..obs import recorder as _recorder
        from ..resilience.degrade import (_M_GATE_FAIL, _M_RUNGS,
                                          gate_passes, gate_threshold)
        from ..resilience.policy import ResidualGateError

        bucket = req.bucket_n
        cause = ("drift_budget" if gate_passes(rel, thr)
                 else "residual_gate")
        _M_GATE_FAIL.inc()
        _recorder.record("residual_gate_failure", n=req.n,
                         workload="update", rel_residual=float(rel),
                         threshold=float(thr), drift=float(new_drift),
                         budget=float(budget), cause=cause)
        inv_ex = self.executors.get(bucket, 1, self.block_size)
        dtype = jnp.dtype(inv_ex.key.dtype)
        stacked = np.asarray(a_new)[None]
        n_real = np.asarray([req.n], np.int32)
        inv2, sing2, kap2, rel2 = inv_ex.run(jnp.asarray(stacked),
                                             jnp.asarray(n_real))
        sing2 = bool(sing2[0])
        kap2, rel2 = float(kap2[0]), float(rel2[0])
        passed = (not sing2
                  and gate_passes(rel2, gate_threshold(
                      self.policy, req.n, kap2, dtype)))
        _M_RUNGS.inc(rung="re_invert",
                     outcome="passed" if passed else "failed")
        _recorder.record("recovery_rung", rung="re_invert",
                         workload="update",
                         outcome="passed" if passed else "failed",
                         singular=sing2, rel_residual=float(rel2))
        req.hop("recovery_rung", rung="re_invert", cause=cause,
                passed=passed)
        if sing2:
            # The from-scratch pivot probe flagged the MUTATED matrix
            # singular: the mutation destroyed rank but the k×k
            # capacitance determinant rounded just past the eps
            # threshold.  This is the typed singularity answer, not a
            # gate exhaustion — the rider gets the per-element
            # singular flag (the invert lanes' contract) and the
            # committed resident state stays untouched.
            return "gated", kap2, rel2, np.asarray(inv2[0])
        if not passed:
            raise ResidualGateError(
                f"update residual gate failed ({cause}: rel {rel:.3e},"
                f" drift {new_drift:.3e} vs threshold {thr:.3e} / "
                f"budget {budget:.3e}) and the re_invert rung did not "
                f"recover (handle {req.handle.handle_id})",
                recovery=({"rung": "re_invert", "cause": cause,
                           "rel_residual_after": rel2,
                           "passed": False},))
        return "re_inverted", kap2, rel2, np.asarray(inv2[0])

    def _observe_update_numerics(self, req, ex, kappa, rel) -> None:
        """Serve-path ``numerics="summary"`` for ONE update rider: the
        in-launch verified rel_residual/κ∞ against the MUTATED matrix
        — the PRE-recovery numbers, observed workload-tagged and
        spiked against the attached policy's OWN gate threshold (an
        update's residual IS an inverse residual), so a gate failure
        can never outrun its spike."""
        import jax.numpy as jnp

        from ..obs import numerics as _numerics

        thresholds = None
        if self.policy is not None:
            from ..resilience.degrade import gate_threshold

            thresholds = _numerics.SpikeThresholds(
                residual=gate_threshold(self.policy, req.n,
                                        float(kappa),
                                        jnp.dtype(ex.key.dtype)))
        rep = _numerics.summary_report(
            n=req.n, block_size=ex.block_size, engine=ex.key.engine,
            rel_residual=float(rel), kappa=float(kappa), norm_a=0.0,
            dtype=ex.key.dtype, workload="update")
        _numerics.observe(rep)
        _numerics.record_spikes(rep, thresholds)

    def _fail_expired(self, batch: list, phase: str) -> list:
        """Split out requests past their deadline; fail them with the
        typed error (counted, labeled by phase) and return the rest."""
        now = time.perf_counter()
        live = []
        for req in batch:
            if req.t_deadline is not None and now > req.t_deadline:
                _obs_metrics.counter(
                    "tpu_jordan_deadline_exceeded_total").inc(
                        phase=phase,
                        exemplar=(req.ctx.request_id
                                  if req.ctx is not None else None))
                req.hop("deadline", phase=phase)
                if not req.future.done():
                    req.future.set_exception(DeadlineExceededError(
                        f"deadline exceeded in {phase} "
                        f"(n={req.n}, bucket={req.bucket_n})"))
            else:
                live.append(req)
        return live

    def _observe_numerics(self, batch, ex, sing, kappa, rel) -> None:
        """Serve-path ``numerics="summary"`` (ISSUE 10): observe each
        real, non-singular rider's in-launch rel_residual/κ∞ — numbers
        the compiled batch program already returned, the honest summary
        discipline for fused executables — into the numerics
        histograms, spiking the flight recorder on expected-error
        (eps·n·κ) exceedances.  Never runs at the "off" default.
        Solve-lane riders (ISSUE 11) report workload-tagged: their rel
        is the κ-free ‖A·X − B‖ backward error, so the spike threshold
        is the solve gate's eps·n form, not eps·n·κ."""
        from ..obs import numerics as _numerics

        wl = ex.key.workload
        for i, req in enumerate(batch):
            if bool(sing[i]):
                continue
            thresholds = None
            if wl != "invert":
                # Solve riders spike on the SAME κ-free backward-error
                # gate the policy would judge them by (the service's
                # attached policy — DEFAULT_POLICY's shape when
                # resilience is off), at the rider's REAL n: a gate
                # failure can never outrun its spike, and the serve
                # path agrees with the direct API on identical inputs.
                from ..resilience.degrade import solve_gate_threshold
                from ..resilience.policy import DEFAULT_POLICY

                pol = self.policy if self.policy is not None \
                    else DEFAULT_POLICY
                thresholds = _numerics.SpikeThresholds(
                    residual=solve_gate_threshold(pol, req.n,
                                                  ex.key.dtype))
            rep = _numerics.summary_report(
                n=req.n, block_size=ex.block_size,
                engine=ex.key.engine, rel_residual=float(rel[i]),
                kappa=float(kappa[i]), norm_a=0.0, dtype=ex.key.dtype,
                workload=wl)
            _numerics.observe(rep)
            _numerics.record_spikes(rep, thresholds)

    def _execute_mesh(self, lane, batch: list, t_dispatch: float) -> None:
        """Dispatch one mesh-lane request (ISSUE 18): the distributed
        AOT executable (``serve/meshlanes.MeshLaneExecutor``) at
        occupancy 1 — scatter, the sharded elimination, gather — with
        the full serve discipline inherited: journeys, breaker
        feedback, deadlines, retry + integrity gate, numerics summary,
        and the comm observatory's per-execute analytical inventory
        (observed records attached at compile time, drift judged per
        execute) exactly like ``solve_system(workers=...)``."""
        import math

        import jax.numpy as jnp

        workload, bucket, rhs, mesh = lane
        label = _lane_label(lane)
        br = self.executors.breaker(label) \
            if self.policy is not None else None
        req = batch[0]
        try:
            _faults.fire("dispatch")
            ex, source = self.executors.get_info(
                bucket, 1, self.block_size, workload=workload, rhs=rhs,
                mesh=mesh)
            req.hop("executor", bucket=bucket, source=source,
                    engine=ex.key.engine, mesh=mesh)
            from ..obs import comm as _comm
            from ..obs import hwcost as _hwcost
            from ..obs.spans import timed_blocking

            a = jnp.asarray(req.padded)
            run_args = (a,) if workload == "invert" \
                else (a, jnp.asarray(req.padded_b))

            def run_once():
                _faults.fire("execute")
                comm_rep = ex.comm_report()
                out, esp = timed_blocking(
                    ex.run, *run_args, telemetry=self._tel,
                    name="execute", bucket=bucket, occupancy=1,
                    workload=workload, mesh=mesh)
                res, sing_flags = out
                _hwcost.attach_execute_cost(
                    esp, ex.cost,
                    analytical_flops=_hwcost.baseline_workload_flops(
                        bucket, workload, k=rhs))
                comm_rep.observe_metrics()
                comm_rep.attach_span(esp)
                _comm.observe_drift(comm_rep, esp.duration, esp)
                _comm.set_last_report(comm_rep)
                sing = bool(np.asarray(sing_flags).any())
                kappa = rel = 0.0
                if not sing:
                    kappa, rel = ex.metrics(
                        req.padded, res,
                        req.padded_b if workload != "invert" else None)
                    if _faults.corrupt("result_corrupt_nan"):
                        rel = float("nan")
                    # Integrity gate (the single-device lanes'
                    # discipline, host-verified here): corruption is
                    # typed and retryable, never a wrong answer served.
                    if not math.isfinite(rel):
                        raise ResultCorruptionError(
                            f"non-finite rel_residual on mesh lane "
                            f"{label} — corrupted result detected by "
                            f"the integrity gate")
                return res, sing, kappa, rel, esp.duration

            def on_retry(exc, attempt):
                req.hop("retry", attempt=attempt,
                        error=type(exc).__name__)

            res, sing, kappa, rel, exec_s = (
                self.policy.retry.call(
                    run_once, component="serve.execute",
                    on_retry=on_retry,
                    exemplar=(req.ctx.request_id
                              if req.ctx is not None else None))
                if self.policy is not None else run_once())
        except BaseException as e:                  # noqa: BLE001
            _obs_metrics.counter(
                "tpu_jordan_serve_batch_failures_total",
                "dispatched batches that terminally failed (after any "
                "retries) and fanned a typed error to their riders",
            ).inc(bucket=label)
            if br is not None:
                br.record_failure()
            for r in batch:
                r.hop("batch_failure", error=type(e).__name__)
                if not r.future.done():
                    r.future.set_exception(e)
            return
        if br is not None:
            br.record_success()

        queue_waits = [t_dispatch - req.t_enqueue]
        self.stats.batch(label, occupancy=1, exec_seconds=exec_s,
                         queue_seconds=queue_waits, singular=int(sing),
                         workload=workload)
        if self.numerics == "summary":
            self._observe_numerics(batch, ex, np.asarray([sing]),
                                   np.asarray([kappa]),
                                   np.asarray([rel]))
        if not self._fail_expired(batch, "execute"):
            return
        req.hop("served", singular=sing, seconds=round(exec_s, 6),
                mesh=mesh)
        out = np.asarray(res)
        req.future.set_result(InvertResult(
            inverse=(out[:req.n, :req.n]
                     if workload == "invert" else None),
            n=req.n, bucket_n=bucket, singular=sing,
            kappa=float(kappa), rel_residual=float(rel),
            queue_seconds=queue_waits[0], execute_seconds=exec_s,
            batch_occupancy=1, workload=workload,
            solution=(out[:req.n, :req.k]
                      if workload != "invert" else None)))

    def _execute(self, lane, batch: list, t_dispatch: float) -> None:
        import jax.numpy as jnp

        bucket = lane if isinstance(lane, int) else lane[1]
        workload = _lane_workload(lane)
        if workload == "update":
            return self._execute_updates(lane, batch, t_dispatch)
        if _lane_mesh(lane) != "single":
            return self._execute_mesh(lane, batch, t_dispatch)
        label = _lane_label(lane)
        br = self.executors.breaker(label) \
            if self.policy is not None else None
        try:
            _faults.fire("dispatch")
            rhs = 0 if isinstance(lane, int) else lane[2]
            ex, source = self.executors.get_info(bucket, self.batch_cap,
                                                 self.block_size,
                                                 workload=workload,
                                                 rhs=rhs)
            for req in batch:
                # Compile-vs-cache-hit is a per-request journey fact
                # (ISSUE 8): "my request paid a compile" is exactly the
                # warm-path violation the zero-compile pin guards.
                req.hop("executor", bucket=bucket, source=source,
                        engine=ex.key.engine)
            dtype = jnp.dtype(ex.key.dtype)
            cap = self.batch_cap
            stacked = np.broadcast_to(
                np.eye(bucket, dtype=dtype), (cap, bucket, bucket)).copy()
            n_real = np.zeros((cap,), np.int32)
            for i, req in enumerate(batch):
                stacked[i] = req.padded
                n_real[i] = req.n
            if workload == "invert":
                args = (jnp.asarray(stacked), jnp.asarray(n_real))
            else:
                # Solve lane (ISSUE 11): the zero-padded RHS stack rides
                # next to the identity-padded A stack; filler slots keep
                # an all-zero B, whose solution against the identity
                # filler A is exactly zero — inert like the invert
                # lanes' identity filler.
                stacked_b = np.zeros((cap, bucket, rhs), dtype)
                for i, req in enumerate(batch):
                    stacked_b[i] = req.padded_b
                args = (jnp.asarray(stacked), jnp.asarray(stacked_b),
                        jnp.asarray(n_real))
            from ..obs.spans import timed_blocking

            def run_once():
                _faults.fire("execute")
                out, esp = timed_blocking(
                    ex.run, *args,
                    telemetry=self._tel, name="execute", bucket=bucket,
                    occupancy=len(batch), workload=workload)
                # Achieved-vs-analytical attrs off the executable's own
                # accounting (ISSUE 10 hwcost; read once at compile,
                # attached per span — dict writes, no device work).
                from ..obs import hwcost as _hwcost

                _hwcost.attach_execute_cost(
                    esp, ex.cost,
                    analytical_flops=_hwcost.baseline_workload_flops(
                        bucket, workload, k=rhs) * cap)
                inv, sing, kappa, rel = out
                sing = np.asarray(sing)
                kappa = np.asarray(kappa)
                # Writable host copy: the corruption fault point (and
                # nothing else) mutates it; np.asarray of a jax array
                # is read-only.
                rel = np.array(rel)
                # Silent-corruption simulation: a corrupted inverse
                # would carry a corrupted in-launch rel_residual
                # (batch_metrics runs in the same executable), so
                # poisoning a rider's rel IS the faithful signature the
                # gate must catch.  Target the first NON-singular real
                # element (the gate deliberately ignores singular ones,
                # whose rel is already meaningless) and only consume the
                # scheduled injection when such a target exists — an
                # all-singular batch can't carry detectable corruption.
                tgt = next((i for i in range(len(batch))
                            if not sing[i]), None)
                if tgt is not None \
                        and _faults.corrupt("result_corrupt_nan"):
                    rel[tgt] = np.nan
                # Integrity gate: every real, non-singular element must
                # report a finite rel_residual — the per-element number
                # the same launch computed from its own inverse.  A
                # non-finite one is corruption, typed and retryable
                # (cheap: len(batch) scalar checks, no extra transfer).
                bad = [i for i in range(len(batch))
                       if not sing[i] and not np.isfinite(rel[i])]
                if bad:
                    raise ResultCorruptionError(
                        f"non-finite rel_residual for batch elements "
                        f"{bad} (bucket {bucket}) — corrupted result "
                        f"detected by the integrity gate")
                return inv, sing, kappa, rel, esp.duration

            def on_retry(exc, attempt):
                # Every rider of the retried batch journeys the retry
                # (the chaos acceptance: an injected execute fault must
                # appear as a retry hop on the requests it touched).
                for req in batch:
                    req.hop("retry", attempt=attempt,
                            error=type(exc).__name__)

            inv, sing, kappa, rel, exec_s = (
                self.policy.retry.call(
                    run_once, component="serve.execute",
                    on_retry=on_retry,
                    exemplar=(batch[0].ctx.request_id
                              if batch[0].ctx is not None else None))
                if self.policy is not None else run_once())
        except BaseException as e:                  # noqa: BLE001
            # Fan the failure to every rider — a batch error must be N
            # explicit per-request failures, never a hang or a drop.
            # ONE terminal-failure count per batch (not per rider): the
            # unit the chaos accounting reconciles against injected
            # faults (every raise-style injection either triggered a
            # counted retry or terminated exactly one attempt chain).
            _obs_metrics.counter(
                "tpu_jordan_serve_batch_failures_total",
                "dispatched batches that terminally failed (after any "
                "retries) and fanned a typed error to their riders",
            ).inc(bucket=label)
            if br is not None:
                br.record_failure()
            for req in batch:
                req.hop("batch_failure", error=type(e).__name__)
                if not req.future.done():
                    req.future.set_exception(e)
            return
        if br is not None:
            br.record_success()

        queue_waits = [t_dispatch - req.t_enqueue for req in batch]
        self.stats.batch(label, occupancy=len(batch),
                         exec_seconds=exec_s, queue_seconds=queue_waits,
                         singular=int(sing[:len(batch)].sum()),
                         workload=workload)
        if self.numerics == "summary":
            self._observe_numerics(batch, ex, sing, kappa, rel)
        # Deadline, phase 2 (execute): a batch that finished past a
        # rider's deadline fails THAT rider typed; batch-mates are
        # unaffected.
        live = {id(r) for r in self._fail_expired(batch, "execute")}
        for i, req in enumerate(batch):
            if id(req) not in live:
                continue
            req.hop("served", singular=bool(sing[i]),
                    seconds=round(exec_s, 6))
            req.future.set_result(InvertResult(
                inverse=(inv[i, :req.n, :req.n]
                         if workload == "invert" else None),
                n=req.n,
                bucket_n=bucket,
                singular=bool(sing[i]),
                kappa=float(kappa[i]),
                rel_residual=float(rel[i]),
                queue_seconds=queue_waits[i],
                execute_seconds=exec_s,
                batch_occupancy=len(batch),
                workload=workload,
                solution=(inv[i, :req.n, :req.k]
                          if workload != "invert" else None),
            ))
