"""``update_demo`` — the ``--update-demo`` CLI mode's engine (ISSUE 12
acceptance).

One self-contained run proves the resident-inverse contract end to end,
in three legs sharing ONE fleet-shared executor store:

  1. **serve ledger** — a warmed :class:`~.service.JordanService`
     creates a resident handle (``invert(a, resident=True)``) and
     streams ``updates`` rank-``rank`` mutations through the O(n²k)
     update lane, with one deliberately rank-destroying mutation
     sprinkled mid-stream (its typed "gated" outcome must ride the
     ledger) and a zero-drift-budget burst at the end (every update
     trips the "re_invert" degradation rung deterministically — the
     ladder demonstration).  Pins: ZERO compiles and ZERO plan-cache
     measurements on the warm update path, and every update accounted
     ``refreshed | re_inverted | gated`` (``tools/check_update.py``
     validates; exit 2 = a silently stale inverse).
  2. **warm latency + FLOPs** — median warm update latency vs median
     warm re-invert latency at the same bucket (the acceptance bound:
     the update must win), next to both executables' own XLA
     ``cost_analysis`` FLOPs (the update executable's must be strictly
     below the fresh-invert executable's — k ≤ n/8 is the documented
     regime) and the achieved-vs-analytical 4n²k+O(nk²) rate (hwcost).
  3. **fleet chaos** — the same deterministic update stream twice
     through an N-replica :class:`~..fleet.JordanFleet` sharing the
     executor store: fault-free (the replay baseline), then under a
     seeded ``replica_kill`` schedule crashing replicas mid-stream.
     Handles live in the fleet-shared :class:`~.handles.HandleStore`,
     so a kill loses nothing: the router re-queues, the retry re-reads
     committed state, and every per-update outcome — AND the final
     resident inverse — must bit-match the fault-free replay; the
     final resident inverse is additionally verified against a
     from-scratch solve of the mutated matrix (the fresh invert lane)
     with the residual gate.  Zero compiles after warmup across kills
     and warm replacements (the PR 7 pin, extended to update lanes).
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from ..obs.metrics import REGISTRY
from ..resilience import FaultPlan, ResiliencePolicy
from ..resilience import activate as _activate
from ..resilience.policy import RetryPolicy
from .executors import ExecutorStore, bucket_for, k_bucket_for
from .service import JordanService


def _fixture(n: int, rank: int, updates: int, seed: int, dtype):
    """The deterministic demo fixture: one well-conditioned seeded A
    plus an update stream scaled so each mutation perturbs without
    destroying conditioning.  Update ``updates // 2`` is replaced at
    stream time by the rank-destroying mutation (computed against the
    then-committed A — see ``_run_update_stream``)."""
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)).astype(dtype)
    scale = 1.0 / np.sqrt(float(n) * rank)
    stream = [(rng.standard_normal((n, rank)).astype(dtype) * scale,
               rng.standard_normal((n, rank)).astype(dtype) * scale)
              for _ in range(updates)]
    return a, stream


def _singular_factors(a_committed: np.ndarray, n: int, rank: int, dtype):
    """Rank-destroying factors against the COMMITTED matrix: zero out
    column 0 (u = −A·e₀ padded to rank-k with zero columns), so the
    capacitance determinant — det(A+UVᵀ)/det(A) — is exactly the
    singularity signal the typed "gated" outcome must carry."""
    u = np.zeros((n, rank), dtype)
    v = np.zeros((n, rank), dtype)
    u[:, 0] = -np.asarray(a_committed[:n, 0])
    v[0, 0] = 1.0
    return u, v


def _classify_update(target, ref, u, v, timeout: float = 600.0):
    """One update outcome tuple for the replay comparison:
    ("ok", outcome, version, inverse-bytes) or ("error", type-name).
    ``target`` is a JordanService or JordanFleet (same surface)."""
    try:
        fut = target.submit_update(ref, u, v)
        res = fut.result(timeout)
        if res.singular:
            return ("ok", "gated", res.handle_version, b"")
        return ("ok", res.update_outcome, res.handle_version,
                np.asarray(res.inverse).tobytes())
    except Exception as e:                           # noqa: BLE001
        return ("error", type(e).__name__)


def _run_update_stream(target, ref, a0, stream, n, rank, dtype,
                       singular_at: int | None):
    """Apply the stream SEQUENTIALLY (per-handle ordering is the
    determinism contract) and track the true mutated matrix host-side
    — the from-scratch verification target.  Returns (outcomes,
    a_track)."""
    a_track = np.asarray(a0, dtype).copy()
    outcomes = []
    for i, (u, v) in enumerate(stream):
        if singular_at is not None and i == singular_at:
            u, v = _singular_factors(a_track, n, rank, dtype)
        out = _classify_update(target, ref, u, v)
        outcomes.append(out)
        if out[0] == "ok" and out[1] in ("refreshed", "re_inverted"):
            a_track = a_track + u @ v.T
    return outcomes, a_track


def _median_latency(samples):
    s = sorted(samples)
    return s[len(s) // 2] if s else None


def update_demo(n: int = 2048, block_size: int | None = None,
                rank: int = 32, updates: int = 8, replicas: int = 3,
                kills: int = 1, seed: int = 0, dtype=jnp.float32,
                telemetry=None) -> dict:
    """Run the three-leg resident-update acceptance demo; returns the
    one-line JSON report ``tools/check_update.py`` validates (exit 2 =
    silent stale inverse)."""
    t0 = time.perf_counter()
    if updates < 3:
        raise ValueError("update_demo needs updates >= 3 (the ledger "
                         "must show refreshed + gated outcomes)")
    dtype = jnp.dtype(dtype)
    a0, stream = _fixture(n, rank, updates, seed, dtype)
    singular_at = updates // 2
    store = ExecutorStore()
    policy = ResiliencePolicy(
        retry=RetryPolicy(max_retries=max(4, kills + 2), backoff_s=0.0))
    bucket = bucket_for(n)
    kb = k_bucket_for(rank)

    def counters():
        c = REGISTRY.counter
        return {
            "compiles": c("tpu_jordan_compiles_total").total(),
            "measurements":
                c("tpu_jordan_tuner_measurements_total").total(),
            "rungs": c("tpu_jordan_recovery_rungs_total").total(),
            "deaths":
                c("tpu_jordan_fleet_replica_deaths_total").total(),
            "restarts": c("tpu_jordan_fleet_restarts_total").total(),
            "reroutes": c("tpu_jordan_fleet_reroutes_total").total(),
            "faults": c("tpu_jordan_faults_injected_total").total(),
        }

    # ---- leg 1: serve ledger + drift-rung demonstration -------------
    with JordanService(engine="auto", dtype=dtype, batch_cap=1,
                       max_wait_ms=0.5, block_size=block_size,
                       policy=policy, shared_executors=store,
                       telemetry=telemetry) as svc:
        svc.warmup(update_shapes=[(n, rank)])
        after_warm = counters()
        ref = svc.invert(a0, resident=True, handle_id="svc",
                         timeout=600)
        ledger_outcomes, a_track = _run_update_stream(
            svc, ref, a0, stream, n, rank, dtype, singular_at)

        # ---- leg 2: warm latency + FLOPs, same warm service ---------
        upd_lat, inv_lat = [], []
        for i in range(3):
            u, v = stream[i % len(stream)]
            res = svc.update(ref, u, v, timeout=600)
            upd_lat.append(res.execute_seconds)
            a_track = a_track + u @ v.T
            inv_res = svc.submit(a_track).result(600)
            inv_lat.append(inv_res.execute_seconds)
        ex_upd = svc.executors.get(bucket, 1, svc._batcher.block_size,
                                   workload="update", rhs=kb)
        ex_inv = svc.executors.get(bucket, 1, svc._batcher.block_size)
        svc_stats = svc.stats()
    serve_counters = counters()

    # The deterministic re_invert demonstration: a zero drift budget
    # trips the rung on EVERY update — the ladder is exercised without
    # depending on fixture conditioning (linalg.update.drift_budget's
    # factor override; the documented default governs everywhere else).
    with JordanService(engine="auto", dtype=dtype, batch_cap=1,
                       max_wait_ms=0.5, block_size=block_size,
                       policy=policy, shared_executors=store,
                       update_drift_budget_factor=0.0) as svc2:
        svc2.warmup(update_shapes=[(n, rank)])
        ref2 = svc2.invert(a0, resident=True, handle_id="svc-drift",
                           timeout=600)
        u, v = stream[0]
        drift_res = svc2.update(ref2, u, v, timeout=600)
    drift_counters = counters()

    upd_ms = _median_latency(upd_lat) * 1e3
    inv_ms = _median_latency(inv_lat) * 1e3
    upd_flops = ex_upd.cost.flops if ex_upd.cost.available else None
    inv_flops = ex_inv.cost.flops if ex_inv.cost.available else None
    from ..obs import hwcost as _hwcost

    analytical = _hwcost.baseline_workload_flops(bucket, "update", k=kb)

    # ---- leg 3: fleet chaos vs fault-free replay --------------------
    from ..fleet import JordanFleet

    fleet_kw = dict(engine="auto", dtype=dtype, batch_cap=1,
                    max_wait_ms=0.5, block_size=block_size,
                    policy=policy, executor_store=store,
                    stable_after_s=0.2, liveness_deadline_s=5.0,
                    max_queue=max(4 * updates, 64))
    before = counters()
    with JordanFleet(replicas=replicas, **fleet_kw) as flt:
        flt.warmup([n], update_shapes=[(n, rank)])
        fref = flt.invert(a0, resident=True, handle_id="flt",
                          timeout=600)
        baseline, a_base = _run_update_stream(
            flt, fref, a0, stream, n, rank, dtype, singular_at)
        base_state = flt.handles.get("flt")
        base_inv_bytes = np.asarray(base_state.inverse).tobytes()
    after_free = counters()

    horizon = max(3, updates)
    plan = FaultPlan.seeded(seed,
                            points={"replica_kill": (kills, horizon)})
    with JordanFleet(replicas=replicas, **fleet_kw) as cflt:
        cflt.warmup([n], update_shapes=[(n, rank)])
        chaos_warm = counters()
        with _activate(plan):
            cref = cflt.invert(a0, resident=True, handle_id="flt",
                               timeout=600)
            chaos, a_chaos = _run_update_stream(
                cflt, cref, a0, stream, n, rank, dtype, singular_at)
        chaos_state = cflt.handles.get("flt")
        chaos_inv = np.asarray(chaos_state.inverse).copy()
        chaos_a = np.asarray(chaos_state.a).copy()
        chaos_snapshot = chaos_state.snapshot()
        # From-scratch solve of the MUTATED matrix through the warm
        # fresh-invert lane: the independent verification target.
        fresh = cflt.invert(chaos_a[:n, :n], timeout=600)
        fleet_stats = cflt.stats()
    after = counters()
    delta = {k: after[k] - before[k] for k in before}

    # ---- compare chaos vs the fault-free replay ---------------------
    mismatches = []
    matched = 0
    typed_errors: dict[str, int] = {}
    for i, (base, ch) in enumerate(zip(baseline, chaos)):
        if ch[0] == "error":
            typed_errors[ch[1]] = typed_errors.get(ch[1], 0) + 1
            continue
        if ch == base:
            matched += 1
        else:
            mismatches.append({"update": i, "why": (
                f"outcome diverged from the fault-free replay: "
                f"{base[:3]} vs {ch[:3]}")})
    final_bitmatch = (chaos_inv.tobytes() == base_inv_bytes)
    if not final_bitmatch:
        mismatches.append({"update": "final",
                           "why": "post-kill resident inverse bits "
                                  "diverged from the fault-free replay"})

    # ---- from-scratch verification of the post-kill inverse ---------
    from ..resilience.degrade import gate_threshold

    fresh_inv = np.asarray(fresh.inverse)
    denom = float(np.abs(fresh_inv).sum(axis=-1).max())
    vs_fresh = (float(np.abs(chaos_inv[:n, :n] - fresh_inv)
                      .sum(axis=-1).max()) / denom if denom else 0.0)
    gate_thr = gate_threshold(policy, n, fresh.kappa, dtype)
    resident_rel = float(chaos_snapshot["rel_residual"])
    fresh_ok = bool(resident_rel <= gate_thr) and resident_rel == resident_rel

    # ---- the per-update accounting ledger ---------------------------
    def tally(outs):
        t = {"refreshed": 0, "re_inverted": 0, "gated": 0, "error": 0}
        for o in outs:
            if o[0] == "error":
                t["error"] += 1
            else:
                t[o[1]] += 1
        return t

    serve_tally = tally(ledger_outcomes)
    chaos_tally = tally(chaos)
    ledger_ok = (sum(serve_tally.values()) == updates
                 and sum(chaos_tally.values()) == updates)

    silent_stale = (bool(mismatches) or not fresh_ok or not ledger_ok
                    or delta["compiles"] - (chaos_warm["compiles"]
                                            - before["compiles"]) != 0)

    report = {
        "metric": "update_demo",
        "n": n, "rank": rank, "k_bucket": kb, "bucket_n": bucket,
        "updates": updates, "replicas": replicas, "seed": seed,
        "dtype": dtype.name,
        "serve": {
            "ledger": serve_tally,
            "outcomes": [list(o[:3]) for o in ledger_outcomes],
            "compiles_on_update_path": (
                serve_counters["compiles"] - after_warm["compiles"]),
            "measurements": serve_counters["measurements"]
                - after_warm["measurements"],
            "drift_rung": {
                "forced_budget_factor": 0.0,
                "outcome": drift_res.update_outcome,
                "drift_after": drift_res.drift,
                "rungs_fired": (drift_counters["rungs"]
                                - serve_counters["rungs"]),
            },
            "handles": svc_stats["handles"],
        },
        "latency": {
            "warm_update_ms": round(upd_ms, 3),
            "warm_reinvert_ms": round(inv_ms, 3),
            "update_beats_reinvert": bool(upd_ms < inv_ms),
            "speedup_x": round(inv_ms / upd_ms, 2) if upd_ms else None,
        },
        "hwcost": {
            "update_executable_flops": upd_flops,
            "invert_executable_flops": inv_flops,
            "update_vs_invert_flops": (
                round(upd_flops / inv_flops, 4)
                if upd_flops and inv_flops else None),
            "flops_below_invert": (
                bool(upd_flops < inv_flops)
                if upd_flops and inv_flops else None),
            "analytical_update_flops": analytical,
            "flops_convention": "4n^2k + 2nk^2",
            "k_over_n": round(kb / bucket, 4),
            "env": _hwcost.runtime_env(),
        },
        "chaos": {
            "faults": plan.report(),
            "kills_injected": int(delta["faults"]
                                  - (after_free["faults"]
                                     - before["faults"])),
            "deaths": delta["deaths"],
            "restarts": delta["restarts"],
            "reroutes": delta["reroutes"],
            "compiles_delta_after_warmup": (after["compiles"]
                                            - chaos_warm["compiles"]),
            "ledger": chaos_tally,
            "outcomes": [list(o[:3]) for o in chaos],
            "final_inverse_bitmatch_replay": final_bitmatch,
            "handle": chaos_snapshot,
        },
        "verification": {
            "resident_rel_residual": resident_rel,
            "gate_threshold": float(gate_thr),
            "gate_passes": fresh_ok,
            "vs_fresh_solve_rel_diff": vs_fresh,
            "fresh_solve_rel_residual": float(fresh.rel_residual),
        },
        "matched_bitwise": matched,
        "typed_errors": typed_errors,
        "mismatches": mismatches,
        "fleet_ledger": fleet_stats["ledger"],
        "silent_stale": bool(silent_stale),
        "elapsed_s": round(time.perf_counter() - t0, 3),
    }
    return report
