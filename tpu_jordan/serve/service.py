"""``JordanService`` — the serving product surface (ISSUE 3 tentpole
part 3).

The library so far is one-shot: every ``solve()`` pays selection and
(for a new shape) blocking compilation, and the dedicated small-n
batched engine is only reachable by hand-assembling a uniform batch.
The service turns that into a request stream: callers ``submit()``
arbitrary (n, n) matrices and get futures; requests are rounded up to
power-of-two shape buckets (exact — identity padding), micro-batched
per bucket up to ``batch_cap`` or a ``max_wait_ms`` deadline, and run
through per-bucket AOT executables that are compiled at most once
(``serve/executors.py``).  Engine choice per bucket rides PR 2's plan
cache, so a warm server performs zero measurements and zero recompiles.

Contract highlights (docs/SERVING.md is the operator guide):

  * **Admission control** — the queue is bounded (``max_queue``); a full
    queue raises :class:`ServiceOverloadedError` at submit time.  Typed
    backpressure, never a silent drop.
  * **Warmup** — ``warmup(shapes=...)`` pre-compiles the buckets those
    shapes land in, so the first real request never pays a compile.
  * **Per-element verification** — every result carries κ∞ and
    rel_residual from the same compiled launch (``driver.batch_metrics``)
    plus its element's singular flag; one singular request never poisons
    its batch-mates.
  * **Clean shutdown** — ``close()`` (or the context manager) drains
    in-flight and queued work before returning.
  * **Observability** — ``stats()`` reports per-bucket counters and
    latency percentiles (``serve/stats.py``).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future

import jax.numpy as jnp
import numpy as np

from ..obs.journey import JourneyLog
from ..resilience.policy import DEFAULT_POLICY
from .batcher import (InvertResult, MicroBatcher, ServiceClosedError,
                      ServiceOverloadedError)
from .executors import (ExecutorCache, bucket_for, k_bucket_for,
                        rhs_bucket_for)
from .handles import HandleRef
from .stats import ServeStats


class JordanService:
    """A dynamic-batching inversion service on one device.

    Args:
      engine: "auto" (default — resolved per bucket through the PR 2
        tuner ladder: plan cache, then registry cost ranking) or an
        explicit single-device engine ("inplace" | "grouped" |
        "augmented").
      plan_cache: optional path to the PR 2 JSON plan cache; batched
        keys carry a ``bN`` segment (``tuning/plan_cache.plan_key``).
      dtype: storage dtype of requests/results.
      batch_cap: max requests fused into one executable launch (the
        executable's static batch dimension).
      max_wait_ms: how long the oldest queued request may wait for
        batch-mates before a partial batch dispatches (the
        occupancy-vs-latency dial, docs/SERVING.md).
      max_queue: bounded-queue admission limit across all buckets.
      block_size: pivot block size override for every bucket (default:
        ``config.default_block_size`` per bucket).
      autostart: start the dispatcher thread immediately (tests pass
        False to stage the queue deterministically, then ``start()``).
      telemetry: optional ``obs.spans.Telemetry`` — executor compiles
        and per-batch executions are recorded as distinct compile /
        execute spans (a warm server's trace shows ZERO compile spans),
        and every counter mirrors into the process-wide
        ``obs.metrics.REGISTRY`` regardless (docs/OBSERVABILITY.md).
      policy: the ``resilience.ResiliencePolicy`` (ISSUE 5,
        docs/RESILIENCE.md).  The default ("default") is
        ``resilience.DEFAULT_POLICY``: transient batch failures and
        detected result corruption retried (2 retries, capped backoff),
        per-bucket circuit breakers (K=3, typed ``CircuitOpenError``
        fast-fail while open, half-open probe after the cooldown).
        Pass ``None`` to turn the resilience layer off entirely.
      default_deadline_ms: deadline applied to every ``submit``/
        ``invert`` that doesn't pass its own ``deadline_ms`` — covers
        queue wait + execute; an exceeded deadline resolves the future
        with the typed ``DeadlineExceededError``.  None (default) means
        no deadline.
      shared_executors: optional fleet-shared
        :class:`~.executors.ExecutorStore` (ISSUE 7) — compiled bucket
        executables are fetched from / installed into it, so N fleet
        replicas compile each key at most once between them and a
        replacement replica warms up with zero compiles.  None (the
        default): a private store, single-service behavior unchanged.
      plan_cache_read_only: open ``plan_cache`` frozen (the fleet's
        shared pre-tuned plans): this replica can never write it, and
        a write attempt is a typed ``UsageError``
        (``tuning/plan_cache.py``).  ``plan_cache`` may also be a
        pre-loaded :class:`~..tuning.plan_cache.PlanCache` instance,
        used as-is — the fleet passes one frozen instance to every
        replica instead of re-parsing the file per spawn.
      metric_labels: extra labels stamped on every process-wide metric
        series this service mirrors (``serve/stats.py``) — the fleet
        passes ``{"replica": <slot>}`` so one Prometheus scrape
        aggregates the pool with per-replica breakdown
        (docs/FLEET.md).
      numerics: ``"off"`` (the default — the warm-path pins run with
        it; zero added dispatch work) or ``"summary"`` — each real
        rider's in-launch rel_residual/κ∞ observed into the
        ``tpu_jordan_residual`` histogram with expected-error spikes
        into the flight recorder (ISSUE 10, docs/OBSERVABILITY.md).
        ``"trace"`` is a solve-path mode and a typed refusal here.
      handle_budget_bytes: optional resident-bytes ceiling for the
        private handle store (ISSUE 13, docs/SERVING.md): an
        over-budget ``invert(resident=True)`` evicts least-recently-
        served unpinned handles to make room — each eviction a journey
        hop + flight-recorder event — or raises the typed
        ``CapacityExceededError`` at submit.  None = unmetered
        admission (the ledger still accounts every byte).  Mutually
        exclusive with ``shared_handles`` (a shared store carries its
        own budget).
      mesh_shapes: topologies this service may open mesh-backed lanes
        on (ISSUE 18, ``serve/meshlanes.py``): an iterable of workers
        specs — ints ('p8'), (pr, pc) tuples ('2x4'), or topology
        labels — validated against ``jax.device_count()`` at
        construction (an unformable mesh is a typed ``UsageError``
        here, never a crash mid-launch).  Requires
        ``lane_budget_bytes``: the projected per-device arg+out bytes
        (``executors.projected_lane_bytes``) are the admission signal
        — a request that fits the single-device budget stays on the
        historical lanes; one that doesn't routes to the SMALLEST
        configured mesh whose per-device share fits (a
        ``mesh_admitted`` journey hop carries the projection); one no
        mesh can hold is a typed ``CapacityExceededError`` at submit.
      lane_budget_bytes: the per-device byte budget the admission walk
        compares projections against (docs/SERVING.md).  None (the
        default) disables mesh routing entirely — every request serves
        on the single-device lanes, exactly the pre-mesh behavior.
    """

    def __init__(self, engine: str = "auto", plan_cache: str | None = None,
                 dtype=jnp.float32, batch_cap: int = 8,
                 max_wait_ms: float = 2.0, max_queue: int = 256,
                 block_size: int | None = None, autostart: bool = True,
                 telemetry=None, policy="default",
                 default_deadline_ms: float | None = None,
                 shared_executors=None,
                 plan_cache_read_only: bool = False,
                 metric_labels: dict | None = None,
                 numerics: str = "off",
                 shared_handles=None,
                 update_drift_budget_factor: float | None = None,
                 handle_budget_bytes: int | None = None,
                 mesh_shapes=(), lane_budget_bytes: int | None = None):
        self.dtype = jnp.dtype(dtype)
        self.batch_cap = int(batch_cap)
        self.telemetry = telemetry
        self.policy = DEFAULT_POLICY if policy == "default" else policy
        self.default_deadline_ms = default_deadline_ms
        # Numerics knob (ISSUE 10, docs/OBSERVABILITY.md): "off" is THE
        # serve-path default — the warm-path pins run with it and the
        # observatory costs the hot path nothing.  "summary" observes
        # each rider's in-launch rel_residual/κ∞ (numbers the batch
        # executable already returns) into the numerics histograms.
        # "trace" needs the instrumented unrolled solve path — the
        # batched serve executables are fused and host-opaque, so it
        # is a typed refusal here, never a silently different record.
        from ..obs.numerics import resolve_mode

        self.numerics = resolve_mode(numerics)
        if self.numerics == "trace":
            from ..driver import UsageError

            raise UsageError(
                "numerics='trace' is a solve-path mode (the serve "
                "executables are fused; the host cannot see their "
                "supersteps) — use numerics='summary' on the service, "
                "or driver.solve(numerics='trace') for the full trace")
        # Resident-inverse handles (ISSUE 12): the database of live
        # (A, A⁻¹) pairs the update lanes mutate.  A fleet passes ONE
        # shared store to every replica (the ExecutorStore discipline),
        # so a replica kill never loses a handle and a warm replacement
        # has nothing to rebuild; None keeps a private store — the
        # single-service behavior.  ``handle_budget_bytes`` (ISSUE 13)
        # caps the private store's resident bytes (LRU eviction over
        # last-served, pinned exempt, typed CapacityExceededError at
        # submit when nothing is evictable); a SHARED store's budget
        # belongs to whoever built the store — the one wiring rule
        # lives in ``handles.build_handle_store``.
        from .handles import build_handle_store

        self.handles = build_handle_store(shared_handles,
                                          handle_budget_bytes,
                                          "the service")
        self._handle_seq = 0
        self._stats = ServeStats(labels=metric_labels)
        self.executors = ExecutorCache(
            engine=engine, plan_cache=plan_cache,
            dtype=self.dtype, stats=self._stats,
            telemetry=telemetry, policy=self.policy,
            store=shared_executors,
            plan_cache_read_only=plan_cache_read_only)
        self._batcher = MicroBatcher(
            self.executors, self._stats, batch_cap=batch_cap,
            max_wait_ms=max_wait_ms, max_queue=max_queue,
            block_size=block_size, autostart=autostart,
            telemetry=telemetry, policy=self.policy,
            numerics=self.numerics, handles=self.handles,
            update_drift_budget_factor=update_drift_budget_factor)
        # Request-journey log (ISSUE 8, always on): deterministic
        # ``request_id``s in submit order; every hop mirrors into the
        # process-wide flight recorder.  A fleet replica does NOT mint
        # ids — the router passes the fleet-level context through.
        self.journey = JourneyLog(prefix="req")
        # Mesh-backed lanes (ISSUE 18): each configured topology is
        # validated NOW (typed UsageError on an unformable mesh) and
        # held sorted by device count — the admission walk always
        # routes to the SMALLEST mesh that fits, so capacity scales
        # with n instead of every big request grabbing the whole host.
        from .meshlanes import mesh_devices, mesh_label, normalize_mesh

        lanes = {}
        for spec in mesh_shapes:
            workers = normalize_mesh(spec)
            lanes[mesh_label(workers)] = mesh_devices(workers)
        self._mesh_lanes = sorted(lanes.items(), key=lambda t: (t[1], t[0]))
        self.lane_budget_bytes = (None if lane_budget_bytes is None
                                  else int(lane_budget_bytes))
        if self._mesh_lanes and self.lane_budget_bytes is None:
            from ..driver import UsageError

            raise UsageError(
                "mesh_shapes without lane_budget_bytes: the per-device "
                "byte budget IS the admission signal deciding which "
                "requests leave the single-device lane — pass "
                "lane_budget_bytes (docs/SERVING.md)")
        self._closed = False
        self._close_lock = threading.Lock()

    # ---- mesh admission (ISSUE 18) -----------------------------------

    def _admit_mesh(self, n: int, bucket: int, workload: str, rhs: int,
                    ctx) -> str:
        """The submit-time admission walk: single-device lane if the
        projection fits the budget, else the smallest configured mesh
        whose PER-DEVICE share fits, else a typed
        ``CapacityExceededError`` — refused here, at submit, with a
        ``reject`` journey hop and a ``capacity_refused`` recorder
        event; the launch that would have OOMed never happens."""
        from .executors import projected_lane_bytes
        from .meshlanes import MESH_SINGLE

        budget = self.lane_budget_bytes
        if budget is None:
            return MESH_SINGLE
        single = projected_lane_bytes(bucket, self.batch_cap, self.dtype,
                                      workload, rhs)
        if single <= budget:
            return MESH_SINGLE
        best = single
        for label, devices in self._mesh_lanes:
            proj = projected_lane_bytes(bucket, 1, self.dtype, workload,
                                        rhs, devices=devices)
            best = min(best, proj)
            if proj <= budget:
                ctx.event("mesh_admitted", mesh=label,
                          projected_bytes=proj, budget_bytes=budget,
                          single_device_bytes=single)
                return label
        from ..obs import capacity as _capacity
        from ..resilience.policy import CapacityExceededError

        _capacity.record_refusal(
            requested=best,
            live_bytes=_capacity.live_bytes("executor_lanes"),
            budget_bytes=budget, pinned=0)
        ctx.event("reject", reason="capacity", projected_bytes=best,
                  budget_bytes=budget)
        largest = (f"the largest configured mesh "
                   f"({self._mesh_lanes[-1][0]!r})"
                   if self._mesh_lanes else
                   "the single-device lane (no mesh_shapes configured)")
        raise CapacityExceededError(
            f"n={n} (bucket {bucket}, workload {workload!r}) projects "
            f"{best} bytes/device on {largest}; lane_budget_bytes is "
            f"{budget} — configure a larger mesh_shapes entry or raise "
            f"the budget (the request is refused at submit, never an "
            f"OOM mid-launch)")

    # ---- request path ------------------------------------------------

    def submit(self, a, b=None, deadline_ms: float | None = None,
               _ctx=None) -> Future:
        """Queue one request; returns a future resolving to
        :class:`InvertResult`.

        ``submit(a)`` is the historical invert request.  ``submit(a, b)``
        (ISSUE 11) is a SOLVE request: X = A⁻¹B with no inverse ever
        formed — ``b`` is (n,) or (n, k), the request lands on its own
        (workload, bucket_n, rhs-bucket) lane with its own AOT
        executable (``linalg.block_jordan_solve`` vmapped, resolved
        through the workload-scoped tuner ladder), and the result
        carries ``solution``/``workload="solve"`` with the κ-free
        ‖A·X − B‖ backward error as ``rel_residual``.

        Raises :class:`ServiceOverloadedError` when the bounded queue
        is full (backpressure — retry later),
        :class:`~..resilience.policy.CircuitOpenError` while the
        bucket's breaker is open (fast-fail — doomed work is not
        queued), and :class:`ServiceClosedError` after ``close()``.

        ``deadline_ms`` (default: the service's ``default_deadline_ms``)
        bounds queue wait + execute; exceeding it resolves the future
        with the typed
        :class:`~..resilience.policy.DeadlineExceededError`.

        ``_ctx`` (internal, ISSUE 8): an existing journey
        :class:`~..obs.journey.RequestContext` to thread through — the
        fleet router passes the fleet-level context so one request has
        ONE journey across reroutes; when None (every direct caller)
        the service mints its own and closes it with the future."""
        a = np.asarray(a, self.dtype)
        if a.ndim != 2 or a.shape[0] != a.shape[1]:
            raise ValueError(f"expected a square (n, n) matrix, "
                             f"got shape {a.shape}")
        n = a.shape[0]
        bucket = bucket_for(n)
        padded = np.asarray(np.eye(bucket, dtype=self.dtype))
        padded[:n, :n] = a
        workload, padded_b, rhs, k = "invert", None, 0, 0
        if b is not None:
            workload = "solve"
            b = np.asarray(b, self.dtype)
            if b.ndim == 1:
                b = b[:, None]
            if b.ndim != 2 or b.shape[0] != n or b.shape[1] < 1:
                raise ValueError(f"b must be (n,) or (n, k>=1) with "
                                 f"n={n} rows, got shape {b.shape}")
            k = b.shape[1]
            rhs = rhs_bucket_for(k)
            padded_b = np.zeros((bucket, rhs), self.dtype)
            padded_b[:n, :k] = b
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        own_ctx = _ctx is None
        ctx = (self.journey.new(n, bucket, workload=workload)
               if own_ctx else _ctx)
        try:
            mesh = self._admit_mesh(n, bucket, workload, rhs, ctx)
            fut = self._batcher.submit(
                padded, n, bucket,
                deadline_s=(None if deadline_ms is None
                            else float(deadline_ms) / 1e3),
                ctx=ctx, workload=workload, padded_b=padded_b,
                rhs=rhs, k=k, mesh=mesh)
        except Exception as e:
            if own_ctx:
                ctx.close("error", error=type(e).__name__)
            raise
        if own_ctx:
            # The terminal outcome rides the future: the done callback
            # writes the journey's "result" event and feeds the SLO
            # outcome/latency series.  Fleet contexts are closed by the
            # router (the OUTER future is the request's terminal).
            fut.add_done_callback(ctx.close_from_future)
        return fut

    @staticmethod
    def result(future: Future, timeout: float | None = None) -> InvertResult:
        """Block on a submitted future (sugar over ``future.result``)."""
        return future.result(timeout)

    def invert(self, a, timeout: float | None = None,
               deadline_ms: float | None = None, resident: bool = False,
               handle_id: str | None = None):
        """Synchronous submit + wait.  Raises
        :class:`~..driver.SingularMatrixError` when THIS request's
        element was flagged (batch-mates are unaffected either way —
        the async ``submit`` path reports the flag on the result
        instead, for callers that want to inspect rather than raise).

        ``resident=True`` (ISSUE 12) additionally installs the
        (A, A⁻¹) pair as a RESIDENT handle in the handle store and
        returns a :class:`~.handles.HandleRef` (``ref.result`` carries
        the ``InvertResult``): subsequent ``update(ref, u, v)`` calls
        apply rank-k Sherman–Morrison–Woodbury mutations in O(n²k)
        instead of paying a fresh O(n³) elimination
        (docs/SERVING.md).  ``handle_id`` names the handle (demos pass
        deterministic ids so chaos replays compare); default: a
        service-minted ``h<N>``.

        Capacity admission (ISSUE 13): with a budget on the handle
        store, the 2·bucket²·dtype the new handle would pin is admitted
        BEFORE the invert is submitted — LRU unpinned handles are
        evicted to make room (each eviction a ``capacity_evict``
        journey hop on THIS request plus a flight-recorder event), and
        an admission nothing evictable can satisfy raises the typed
        ``CapacityExceededError`` here, at submit: the elimination
        never launches, so over-budget residency can never OOM
        mid-launch."""
        if not resident:
            res = self.submit(a, deadline_ms=deadline_ms).result(timeout)
            if res.singular:
                from ..driver import SingularMatrixError

                raise SingularMatrixError("singular matrix")
            return res
        from .handles import resident_handle_bytes

        arr = np.asarray(a, self.dtype)
        if arr.ndim != 2 or arr.shape[0] != arr.shape[1]:
            raise ValueError(f"expected a square (n, n) matrix, "
                             f"got shape {arr.shape}")
        n = arr.shape[0]
        bucket = bucket_for(n)
        if self.lane_budget_bytes is not None:
            from .executors import projected_lane_bytes

            if (projected_lane_bytes(bucket, self.batch_cap, self.dtype)
                    > self.lane_budget_bytes):
                from ..driver import UsageError

                raise UsageError(
                    f"resident=True pins the (A, A⁻¹) pair on ONE "
                    f"device (the SMW update lanes are single-chip); "
                    f"bucket {bucket} exceeds lane_budget_bytes="
                    f"{self.lane_budget_bytes} on the single-device "
                    f"lane, so this invert would route to a mesh lane "
                    f"— invert without resident=True (the mesh lanes "
                    f"serve it), or raise lane_budget_bytes")
        ctx = self.journey.new(n, bucket, workload="invert")
        try:
            self.handles.ensure_capacity(
                resident_handle_bytes(bucket, self.dtype),
                hop=ctx.event, replacing=handle_id)
            fut = self.submit(arr, deadline_ms=deadline_ms, _ctx=ctx)
        except Exception as e:
            ctx.close("error", error=type(e).__name__)
            raise
        fut.add_done_callback(ctx.close_from_future)
        res = fut.result(timeout)
        if res.singular:
            from ..driver import SingularMatrixError

            raise SingularMatrixError("singular matrix")
        return self._create_handle(arr, res, handle_id)

    def _create_handle(self, a, res: InvertResult,
                       handle_id: str | None) -> HandleRef:
        """Install one resident handle from a completed invert (the
        shared ``handles.create_resident_handle`` recipe)."""
        from .handles import create_resident_handle

        if handle_id is None:
            with self._close_lock:
                self._handle_seq += 1
                handle_id = f"h{self._handle_seq}"
        return create_resident_handle(self.handles, self.dtype, a, res,
                                      handle_id)

    def submit_update(self, handle: HandleRef, u, v,
                      deadline_ms: float | None = None,
                      _ctx=None) -> Future:
        """Queue one rank-k resident-inverse update (ISSUE 12): apply
        A ← A + U·Vᵀ to the handle's committed state and refresh its
        inverse by the Sherman–Morrison–Woodbury identity in O(n²k) —
        re-verified in the same launch against the MUTATED matrix,
        with the accumulated-drift budget deciding when the
        "re_invert" rung pays a fresh elimination instead
        (docs/WORKLOADS.md).  The future resolves to an
        :class:`~.batcher.InvertResult` with ``workload="update"``,
        the committed ``handle_version``/``drift``, and
        ``update_outcome`` ∈ {refreshed, re_inverted, gated}.  Typed
        rejections/failures exactly like ``submit``."""
        from ..linalg.update import as_update_factors
        from .handles import HandleRef as _Ref

        if not isinstance(handle, _Ref):
            raise ValueError(f"update() takes the HandleRef returned "
                             f"by invert(resident=True), got "
                             f"{type(handle).__name__}")
        n = handle.n
        u, v, k = as_update_factors(u, v, n, self.dtype)
        kb = k_bucket_for(k)
        bucket = handle.bucket_n
        padded_u = np.zeros((bucket, kb), self.dtype)
        padded_u[:n, :k] = u
        padded_v = np.zeros((bucket, kb), self.dtype)
        padded_v[:n, :k] = v
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        own_ctx = _ctx is None
        ctx = (self.journey.new(n, bucket, workload="update")
               if own_ctx else _ctx)
        try:
            fut = self._batcher.submit(
                None, n, bucket,
                deadline_s=(None if deadline_ms is None
                            else float(deadline_ms) / 1e3),
                ctx=ctx, workload="update", rhs=kb, k=k,
                handle=handle, padded_u=padded_u, padded_v=padded_v)
        except Exception as e:
            if own_ctx:
                ctx.close("error", error=type(e).__name__)
            raise
        if own_ctx:
            fut.add_done_callback(ctx.close_from_future)
        return fut

    def update(self, handle: HandleRef, u, v,
               timeout: float | None = None,
               deadline_ms: float | None = None) -> InvertResult:
        """Synchronous ``submit_update`` + wait; raises
        ``SingularMatrixError`` when the mutation made the matrix
        singular (typed — the handle's committed state is untouched)."""
        res = self.submit_update(handle, u, v,
                                 deadline_ms=deadline_ms).result(timeout)
        if res.singular:
            from ..driver import SingularMatrixError

            raise SingularMatrixError(
                "singular matrix (rank-k update destroyed rank; "
                "resident state unchanged)")
        return res

    def solve_system(self, a, b, timeout: float | None = None,
                     deadline_ms: float | None = None) -> InvertResult:
        """Synchronous ``submit(a, b)`` + wait (ISSUE 11): X = A⁻¹B
        through the solve lane; raises ``SingularMatrixError`` when
        THIS request's element was flagged (batch-mates unaffected)."""
        res = self.submit(a, b, deadline_ms=deadline_ms).result(timeout)
        if res.singular:
            from ..driver import SingularMatrixError

            raise SingularMatrixError("singular matrix")
        return res

    # ---- lifecycle ---------------------------------------------------

    def project_capacity(self, shapes=(), solve_shapes=(),
                         update_shapes=(), mesh_shapes=()) -> dict:
        """Projected arg+out bytes per lane the given request mix would
        open — WITHOUT compiling anything (ISSUE 13: what a bucket
        costs to open, visible before paying for it).  Same lane
        vocabulary as :meth:`warmup` (update shapes include each n's
        invert lane and its cap-1 re_invert twin); every projection is
        recorded on the ``tpu_jordan_capacity_projected_lane_bytes``
        gauge.  Temps are compiler-known only: the post-compile
        ``memory_analysis`` number lands in the ``executor_lanes``
        capacity ledger."""
        from ..obs import capacity as _capacity
        from .executors import lane_label, projected_lane_bytes

        cap = self.batch_cap
        out = {}

        def project(workload, bucket, batch_cap, rhs=0, mesh="single",
                    devices=1):
            label = lane_label(workload, bucket, batch_cap, rhs, mesh)
            out[label] = projected_lane_bytes(bucket, batch_cap,
                                              self.dtype, workload, rhs,
                                              devices=devices)
            _capacity.record_projection(label, out[label])

        for n in shapes:
            project("invert", bucket_for(int(n)), cap)
        for n, k in solve_shapes:
            project("solve", bucket_for(int(n)), cap,
                    rhs_bucket_for(int(k)))
        for n, k in update_shapes:
            b = bucket_for(int(n))
            project("invert", b, cap)
            if cap != 1:
                project("invert", b, 1)      # the re_invert cap-1 twin
            project("update", b, 1, k_bucket_for(int(k)))
            if cap != 1:
                # The batched update lane (ISSUE 17): distinct-handle
                # riders share one vmapped launch at the service's cap.
                project("update", b, cap, k_bucket_for(int(k)))
        for entry in mesh_shapes:
            workload, b, rhs, label, devices = self._mesh_entry(entry)
            # Per-DEVICE share (ISSUE 18): the mesh lane's projection
            # divides the O(n²) terms over the mesh — the number the
            # admission walk compares against lane_budget_bytes.
            project(workload, b, 1, rhs, mesh=label, devices=devices)
        return out

    def _mesh_entry(self, entry):
        """Decode one warmup/projection mesh-lane entry — ``(n, mesh)``
        (an invert lane) or ``(n, k, mesh)`` (a solve lane) — into
        ``(workload, bucket, rhs, mesh_label, devices)``.  The mesh
        spec takes anything :func:`~.meshlanes.normalize_mesh` does."""
        from .meshlanes import mesh_devices, mesh_label, normalize_mesh

        if len(entry) == 2:
            n, spec = entry
            workload, rhs = "invert", 0
        else:
            n, k, spec = entry
            workload, rhs = "solve", rhs_bucket_for(int(k))
        workers = normalize_mesh(spec)
        return (workload, bucket_for(int(n)), rhs, mesh_label(workers),
                mesh_devices(workers))

    def warmup(self, shapes=(), solve_shapes=(), update_shapes=(),
               mesh_shapes=()) -> dict:
        """Pre-compile the executables for every bucket the given
        request sizes land in; returns {lane: resolved engine}.
        After a warmup covering the live shape mix, the serve path
        performs zero compiles and zero plan-cache measurements (both
        counter-pinned by the acceptance test).

        ``solve_shapes`` (ISSUE 11): an iterable of (n, k) pairs to
        pre-compile the solve lanes those requests land in — the
        zero-compile warm-path contract covers both workloads.

        ``update_shapes`` (ISSUE 12): an iterable of (n, k) pairs to
        pre-compile the resident-update lanes for, PLUS each n's invert
        lane (handle creation rides the normal batched lane) AND its
        CAP-1 invert twin (the "re_invert" degradation rung eliminates
        ONE mutated matrix — it must not pay batch_cap eliminations of
        identity fillers), so a warm update path performs zero compiles
        even when a rung fires.

        Every lane's projected arg+out bytes are recorded BEFORE its
        compile (ISSUE 13, :meth:`project_capacity`) — the
        ``tpu_jordan_capacity_projected_lane_bytes`` gauge tells an
        operator what the warmup is about to pin before it pins it.

        ``mesh_shapes`` (ISSUE 18): ``(n, mesh)`` / ``(n, k, mesh)``
        entries pre-compile the distributed mesh-backed lanes those
        requests route to — the zero-compile warm-path contract covers
        the topologies too."""
        self.project_capacity(shapes=shapes, solve_shapes=solve_shapes,
                              update_shapes=update_shapes,
                              mesh_shapes=mesh_shapes)
        out = {}
        for n in shapes:
            b = bucket_for(int(n))
            ex = self.executors.get(b, self.batch_cap,
                                    self._batcher.block_size)
            out[b] = ex.key.engine
        for n, k in solve_shapes:
            b = bucket_for(int(n))
            rhs = rhs_bucket_for(int(k))
            ex = self.executors.get(b, self.batch_cap,
                                    self._batcher.block_size,
                                    workload="solve", rhs=rhs)
            out[f"solve:{b}:k{rhs}"] = ex.key.engine
        for n, k in update_shapes:
            b = bucket_for(int(n))
            ex = self.executors.get(b, self.batch_cap,
                                    self._batcher.block_size)
            out[b] = ex.key.engine
            if self.batch_cap != 1:
                # The re_invert rung's cap-1 twin (one matrix per
                # elimination); same executable when batch_cap == 1.
                self.executors.get(b, 1, self._batcher.block_size)
            kb = k_bucket_for(int(k))
            ex = self.executors.get(b, 1, self._batcher.block_size,
                                    workload="update", rhs=kb)
            out[f"update:{b}:k{kb}"] = ex.key.engine
            if self.batch_cap != 1:
                # The batched update lane (ISSUE 17): riders targeting
                # DISTINCT handles share one vmapped SMW launch at the
                # service's batch cap; the cap-1 lane above stays warm
                # for occupancy-1 batches and same-handle followers.
                self.executors.get(b, self.batch_cap,
                                   self._batcher.block_size,
                                   workload="update", rhs=kb)
        for entry in mesh_shapes:
            workload, b, rhs, label, _ = self._mesh_entry(entry)
            ex, _src = self.executors.get_info(
                b, 1, self._batcher.block_size, workload=workload,
                rhs=rhs, mesh=label)
            lane = (f"{b}" if workload == "invert"
                    else f"{workload}:{b}:k{rhs}")
            out[f"{lane}@{label}"] = ex.key.engine
        return out

    def start(self) -> None:
        """Start the dispatcher (no-op when ``autostart=True``)."""
        self._batcher.start()

    def close(self, drain: bool = True, error=None,
              join_timeout_s: float | None = None) -> None:
        """Stop accepting requests; ``drain=True`` completes all queued
        and in-flight work before returning.

        Idempotent and thread-safe (ISSUE 7 satellite): the fleet
        supervisor and a ``with``-block ``__exit__`` may race to close
        the same replica — the first caller does the work, every later
        (or concurrent) call blocks until it finished and then no-ops.
        ``error`` (a zero-arg exception factory, ``drain=False`` only)
        types the failure queued requests receive — the replica kill
        path passes ``ReplicaKilledError`` so the fleet router
        re-queues them instead of reporting a plain closed service.
        ``join_timeout_s`` bounds the dispatcher join (the kill path:
        abandoning a wedged dispatcher beats freezing the supervisor —
        ``serve/batcher.py``); None joins until drained.

        Closing an ALREADY-closed service retries the reap of any
        dispatcher thread a previous bounded close abandoned (ISSUE 20
        satellite): a wedge that cleared after the abandonment is
        joined now and counted in
        ``tpu_jordan_serve_dispatcher_reaped_total`` — the second
        close is how the caller (a fleet teardown sweeping dead
        replicas) reclaims the thread without ever blocking on a still-
        wedged one."""
        with self._close_lock:
            if not self._closed:
                self._batcher.close(drain=drain, error=error,
                                    join_timeout_s=join_timeout_s)
                self._closed = True
            else:
                self._batcher.reap(join_timeout_s=(
                    0.0 if join_timeout_s is None else join_timeout_s))

    def __enter__(self) -> "JordanService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- observability ----------------------------------------------

    def stats(self) -> dict:
        """Per-bucket counters + latency percentiles (serve/stats.py),
        the resolved engine per compiled bucket, and the plan-cache
        measurement counter (0 on the cost-only ladder — the
        warm-server pin)."""
        snap = self._stats.snapshot()
        snap["engines"] = {
            ((f"{k.bucket_n}" if k.workload == "invert"
              else f"{k.workload}:{k.bucket_n}:k{k.rhs}")
             + (f"@{k.mesh}" if k.mesh != "single" else "")):
            {"engine": k.engine,
             "batch_cap": k.batch_cap,
             "workload": k.workload,
             "mesh": k.mesh,
             "plan_source": (ex.plan.source
                             if ex.plan else None)}
            for k, ex in self.executors.entries()
        }
        snap["mesh_lanes"] = {label: devices
                              for label, devices in self._mesh_lanes}
        snap["lane_budget_bytes"] = self.lane_budget_bytes
        snap["measurements"] = self.executors.measurements
        snap["batch_cap"] = self.batch_cap
        snap["queued"] = self._batcher.queued
        snap["handles"] = self.handles.snapshot()
        snap["handle_budget"] = self.handles.budget_snapshot()
        snap["breakers"] = {str(b): s for b, s
                            in self.executors.breaker_states().items()}
        return snap


def serve_demo(n: int, block_size: int | None = None, requests: int = 64,
               batch_cap: int = 8, max_wait_ms: float = 2.0,
               engine: str = "auto", plan_cache: str | None = None,
               dtype=jnp.float32, generator: str = "rand",
               telemetry=None, numerics: str = "off",
               workers=1) -> dict:
    """The ``--serve-demo`` CLI mode's engine: a self-contained
    sustained-throughput demonstration on whatever backend is live.

    Submits ``requests`` mixed-size concurrent requests — sizes cycle
    through {n, n/2, n/4} (floored at the service's minimum bucket), so
    ≥ 3 shape buckets are exercised whenever n ≥ 4·MIN_BUCKET_N —
    through a warmed :class:`JordanService`, waits for every future, and
    returns the one-line JSON report: request/batch counts, per-bucket
    stats with mean occupancy and latency percentiles, the compile and
    plan-cache measurement counters (a warm server pins both at zero on
    the request path), worst rel_residual, and wall time.

    ``--workers W`` (ISSUE 18): configure ONE mesh lane on a W-device
    mesh (``'8'`` → 1D, ``'2x4'`` → 2D) with ``lane_budget_bytes`` set
    just under the LARGEST bucket's single-device projection — so the
    big size provably routes through the distributed lane (the
    ``mesh_admitted`` journey hop) while the smaller sizes stay
    single-device, all in one warm run.
    """
    import time

    from ..ops import generate
    from .executors import bucket_for, projected_lane_bytes
    from .meshlanes import mesh_label, normalize_mesh

    sizes = sorted({max(1, n), max(1, n // 2), max(1, n // 4)},
                   reverse=True)
    mesh_kw, label = {}, None
    if workers not in (1, None):
        # The admission signal is the demo's plot device: a budget one
        # byte under the big bucket's single-device projection forces
        # exactly that bucket onto the mesh lane.
        label = mesh_label(normalize_mesh(workers))
        budget = projected_lane_bytes(bucket_for(sizes[0]), batch_cap,
                                      dtype) - 1
        mesh_kw = {"mesh_shapes": (workers,),
                   "lane_budget_bytes": budget}
    elapsed0 = time.perf_counter()
    with JordanService(engine=engine, plan_cache=plan_cache, dtype=dtype,
                       batch_cap=batch_cap, max_wait_ms=max_wait_ms,
                       max_queue=max(requests, 1),
                       block_size=block_size, telemetry=telemetry,
                       numerics=numerics, **mesh_kw) as svc:
        if label is None:
            svc.warmup(shapes=sizes)
        else:
            svc.warmup(shapes=sizes[1:],
                       mesh_shapes=[(sizes[0], label)])
        compiles_after_warmup = svc.stats()["totals"]["compiles"]
        futures = []
        for i in range(requests):
            sz = sizes[i % len(sizes)]
            # Distinct well-conditioned matrices per request via index
            # offsets (the solve_batch convention).
            a = generate(generator, (sz, sz), dtype,
                         row_offset=i * sz, col_offset=i * sz)
            futures.append(svc.submit(a))
        results = [f.result(timeout=600) for f in futures]
        stats = svc.stats()
    elapsed = time.perf_counter() - elapsed0
    singular = sum(r.singular for r in results)
    worst_rel = max((r.rel_residual for r in results
                     if not r.singular and r.rel_residual is not None),
                    default=None)
    mesh_doc = {}
    if label is not None:
        mesh_requests = sum(
            s["requests"] for b, s in stats["buckets"].items()
            if s.get("mesh", "single") != "single")
        mesh_doc = {"mesh": label,
                    "lane_budget_bytes": mesh_kw["lane_budget_bytes"],
                    "mesh_requests": mesh_requests}
    return {
        "metric": "serve_demo",
        "requests": requests,
        "request_sizes": sizes,
        "buckets": len(stats["buckets"]),
        "batch_cap": batch_cap,
        **mesh_doc,
        "singular": singular,
        "worst_rel_residual": (None if worst_rel is None
                               else f"{worst_rel:.1e}"),
        "compiles": stats["totals"]["compiles"],
        "compiles_on_request_path": (stats["totals"]["compiles"]
                                     - compiles_after_warmup),
        "plan_cache_measurements": stats["measurements"],
        "mean_occupancy": {
            b: s["mean_occupancy"] for b, s in stats["buckets"].items()},
        "elapsed_s": round(elapsed, 3),
        "stats": stats,
    }


def _chaos_requests(n: int, requests: int, seed: int, dtype):
    """The deterministic mixed request stream both chaos-demo passes
    share: sizes cycle {n, n/2} (>= 2 shape buckets at n >= 2·MIN),
    well-conditioned standard-normal fixtures from one seeded stream,
    plus deliberately singular (rank-1) matrices sprinkled at fixed
    indices — their typed per-element flags must survive the chaos."""
    rng = np.random.default_rng(seed)
    sizes = [max(1, n), max(1, n // 2)]
    mats = []
    for i in range(requests):
        s = sizes[i % len(sizes)]
        if i % 17 == 5:
            mats.append(np.ones((s, s), dtype))      # rank 1: singular
        else:
            mats.append(rng.standard_normal((s, s)).astype(dtype))
    return mats


def _classify_response(f, timeout: float = 600.0):
    """One response outcome tuple: ("ok", inverse-bytes, singular) or
    ("error", type-name, None).  ``f`` is a future, or the typed
    exception a submit-time rejection raised.  The chaos demo and the
    fleet demo (``fleet/demo.py``) both bit-compare a chaos stream
    against a fault-free replay of THESE tuples — one shared encoding,
    or the comparison silently diverges."""
    if isinstance(f, Exception):
        return ("error", type(f).__name__, None)
    try:
        r = f.result(timeout)
        return ("ok", np.asarray(r.inverse).tobytes(), bool(r.singular))
    except Exception as e:                           # noqa: BLE001
        return ("error", type(e).__name__, None)


def compare_outcomes(baseline, under):
    """Bit-compare a chaos stream's outcome tuples against the
    fault-free replay's (both from :func:`_classify_response`) —
    returns ``(matched, singular, typed_errors, mismatches)``.

    ONE implementation for the chaos demo and the fleet demo (ISSUE 8
    satellite): the two previously hand-rolled twin loops, which could
    drift apart and silently change what "matched" means between the
    two checkers."""
    matched = singular = 0
    typed_errors: dict[str, int] = {}
    mismatches: list[dict] = []
    for i, (base, chaos) in enumerate(zip(baseline, under)):
        if chaos[0] == "error":
            typed_errors[chaos[1]] = typed_errors.get(chaos[1], 0) + 1
            continue
        if base[0] != "ok":
            mismatches.append({"request": i, "why": (
                f"fault-free run failed ({base[1]}) but chaos "
                f"succeeded")})
        elif chaos[2] != base[2]:
            mismatches.append({"request": i,
                               "why": "singular flag diverged"})
        elif chaos[1] != base[1]:
            mismatches.append({"request": i,
                               "why": "inverse bits diverged"})
        else:
            matched += 1
            singular += int(chaos[2])
    return matched, singular, typed_errors, mismatches


def _run_stream(svc, mats, timeout: float = 600.0):
    """Submit a staged request stream (deterministic batching: queue
    everything, then start the dispatcher) and classify every response:
    ("ok", inverse-bytes, singular) or ("error", type-name).  A typed
    submit-time rejection (breaker fast-fail, backpressure) is an
    "error" outcome like any other — never an unhandled crash."""
    futs = []
    for a in mats:
        try:
            futs.append(svc.submit(a))
        except Exception as e:                       # noqa: BLE001
            futs.append(e)
    svc.start()
    return [_classify_response(f, timeout) for f in futs]


def chaos_demo(n: int = 96, block_size: int | None = None,
               requests: int = 50, batch_cap: int = 4,
               max_wait_ms: float = 2.0, seed: int = 0,
               dtype=jnp.float32, plan_cache: str | None = None,
               telemetry=None) -> dict:
    """The ``--chaos-demo`` CLI mode's engine (ISSUE 5 acceptance): the
    same deterministic mixed request stream is served twice — once
    fault-free (the replay baseline), once under a seeded
    :class:`~..resilience.faults.FaultPlan` injecting compile failures,
    transient execute errors, NaN result corruption, and plan-cache
    write failures — and every chaos response must either bit-match the
    fault-free run of the same request or carry a typed error.  The
    report accounts for every injected fault as retried, degraded, or
    typed-error (``tools/check_chaos.py`` validates; none silent).
    """
    import tempfile
    import time

    from ..obs.journey import outcome_ledger
    from ..obs.metrics import REGISTRY
    from ..obs.recorder import RECORDER
    from ..resilience import FaultPlan, ResiliencePolicy
    from ..resilience import activate as _activate
    from ..resilience.policy import RetryPolicy

    t0 = time.perf_counter()
    mats = _chaos_requests(n, requests, seed, jnp.dtype(dtype))
    shapes = sorted({a.shape[0] for a in mats})
    # Retry budget sized so every seeded injection is absorbable even if
    # the schedule lands several faults on ONE dispatch (each retry
    # advances the nth-call counter): execute(3) + corrupt(2) worst-case
    # stack on a single batch, plus headroom.
    policy = ResiliencePolicy(
        retry=RetryPolicy(max_retries=6, backoff_s=0.0))

    def make_service(cache_path):
        svc = JordanService(engine="auto", plan_cache=cache_path,
                            dtype=dtype, batch_cap=batch_cap,
                            max_wait_ms=max_wait_ms,
                            max_queue=max(requests, 1),
                            block_size=block_size, autostart=False,
                            telemetry=telemetry, policy=policy)
        svc.warmup(shapes=shapes)
        return svc

    # ---- pass 1: the fault-free replay baseline ---------------------
    with make_service(None) as svc:
        baseline = _run_stream(svc, mats)

    # ---- the seeded fault plan (FaultPlan.seeded — the ONE schedule
    # builder).  Per-point horizons sized to how often each point is
    # actually reached: compile/plan_cache_write fire during the
    # 2-bucket warmup (~2 calls each), execute/corrupt once per
    # dispatched batch (>= requests / batch_cap).
    exec_horizon = max(4, requests // max(1, batch_cap) // 2)
    plan = FaultPlan.seeded(seed, points={
        "compile": (1, 2),
        "execute": (3, exec_horizon),
        "result_corrupt_nan": (2, exec_horizon),
        "plan_cache_write": (1, 2),
    })

    # ---- pass 2: the same stream under injected chaos ---------------
    def counters():
        return {
            "retries": REGISTRY.counter(
                "tpu_jordan_retries_total").total(),
            "plan_cache_write_failures": REGISTRY.counter(
                "tpu_jordan_plan_cache_write_failures_total").total(),
            "recovery_rungs": REGISTRY.counter(
                "tpu_jordan_recovery_rungs_total").total(),
            "breaker_opens": REGISTRY.counter(
                "tpu_jordan_breaker_open_total").total(),
            "deadline_exceeded": REGISTRY.counter(
                "tpu_jordan_deadline_exceeded_total").total(),
            "batch_failures": REGISTRY.counter(
                "tpu_jordan_serve_batch_failures_total").total(),
        }

    before = counters()
    cache_dir = None
    if plan_cache is None:
        cache_dir = tempfile.mkdtemp(prefix="tpu_jordan_chaos_")
        plan_cache = f"{cache_dir}/plans.json"
    # Black-box window (ISSUE 8): bracket the CHAOS pass in the
    # process-wide flight recorder, so the report carries the causal
    # evidence (fault -> retry/degradation -> clean response) the
    # checker validates event-by-event.
    bb_mark = RECORDER.total
    try:
        with _activate(plan):
            with make_service(plan_cache) as svc:
                chaos = _run_stream(svc, mats)
    finally:
        if cache_dir is not None:
            import shutil

            shutil.rmtree(cache_dir, ignore_errors=True)
    delta = {k: counters()[k] - before[k] for k in before}
    blackbox = RECORDER.dump(events=RECORDER.since(bb_mark))
    journey_ledger = outcome_ledger(blackbox["events"])

    # ---- compare against the fault-free replay ----------------------
    matched, singular, typed_errors, mismatches = compare_outcomes(
        baseline, chaos)

    # ---- fault accounting: none silent ------------------------------
    # Units are FAULT EVENTS, not rider responses: every raise-style or
    # corrupt injection either triggered one counted retry or
    # terminated exactly one attempt chain (one terminal batch failure,
    # however many riders it fanned to), and plan-cache write faults
    # degraded.  So injected == retried + degraded + terminal holds
    # exactly for an honest run — a positive remainder is a silently
    # absorbed fault, and per-rider fan-out can no longer mask one by
    # driving the ledger negative.
    injected = plan.injected_total
    typed_total = sum(typed_errors.values())
    degraded = delta["plan_cache_write_failures"] + delta["recovery_rungs"]
    terminal = delta["batch_failures"]
    unaccounted = int(injected - delta["retries"] - degraded - terminal)
    report = {
        "metric": "chaos_demo",
        "requests": requests,
        "request_sizes": sorted({a.shape[0] for a in mats}, reverse=True),
        "seed": seed,
        "batch_cap": batch_cap,
        "faults": plan.report(),
        "accounting": {
            "injected": injected,
            "retried": delta["retries"],
            "degraded": degraded,
            "terminal_failures": terminal,
            "typed_error_responses": typed_total,
            "unaccounted": unaccounted,
        },
        "counters_delta": delta,
        "matched_bitwise": matched,
        "singular_flagged": singular,
        "typed_errors": typed_errors,
        "mismatches": mismatches,
        # The journey-derived view of the SAME chaos pass (ISSUE 8:
        # one shared ledger helper) — the checker reconciles it against
        # the response-side ledger above, and validates the embedded
        # black box's causal chains request by request.
        "journey_ledger": journey_ledger,
        "blackbox": blackbox,
        # Negative unaccounted (more retries/failures than injections —
        # a REAL transient happened during the run) is not corruption.
        # A journey GAP (a request the black box saw submitted but
        # never resolved) is silent corruption by definition.
        "silent_corruption": (bool(mismatches) or unaccounted > 0
                              or bool(journey_ledger["gaps"])),
        "elapsed_s": round(time.perf_counter() - t0, 3),
    }
    return report
