"""``JordanService`` — the serving product surface (ISSUE 3 tentpole
part 3).

The library so far is one-shot: every ``solve()`` pays selection and
(for a new shape) blocking compilation, and the dedicated small-n
batched engine is only reachable by hand-assembling a uniform batch.
The service turns that into a request stream: callers ``submit()``
arbitrary (n, n) matrices and get futures; requests are rounded up to
power-of-two shape buckets (exact — identity padding), micro-batched
per bucket up to ``batch_cap`` or a ``max_wait_ms`` deadline, and run
through per-bucket AOT executables that are compiled at most once
(``serve/executors.py``).  Engine choice per bucket rides PR 2's plan
cache, so a warm server performs zero measurements and zero recompiles.

Contract highlights (docs/SERVING.md is the operator guide):

  * **Admission control** — the queue is bounded (``max_queue``); a full
    queue raises :class:`ServiceOverloadedError` at submit time.  Typed
    backpressure, never a silent drop.
  * **Warmup** — ``warmup(shapes=...)`` pre-compiles the buckets those
    shapes land in, so the first real request never pays a compile.
  * **Per-element verification** — every result carries κ∞ and
    rel_residual from the same compiled launch (``driver.batch_metrics``)
    plus its element's singular flag; one singular request never poisons
    its batch-mates.
  * **Clean shutdown** — ``close()`` (or the context manager) drains
    in-flight and queued work before returning.
  * **Observability** — ``stats()`` reports per-bucket counters and
    latency percentiles (``serve/stats.py``).
"""

from __future__ import annotations

from concurrent.futures import Future

import jax.numpy as jnp
import numpy as np

from .batcher import (InvertResult, MicroBatcher, ServiceClosedError,
                      ServiceOverloadedError)
from .executors import ExecutorCache, bucket_for
from .stats import ServeStats


class JordanService:
    """A dynamic-batching inversion service on one device.

    Args:
      engine: "auto" (default — resolved per bucket through the PR 2
        tuner ladder: plan cache, then registry cost ranking) or an
        explicit single-device engine ("inplace" | "grouped" |
        "augmented").
      plan_cache: optional path to the PR 2 JSON plan cache; batched
        keys carry a ``bN`` segment (``tuning/plan_cache.plan_key``).
      dtype: storage dtype of requests/results.
      batch_cap: max requests fused into one executable launch (the
        executable's static batch dimension).
      max_wait_ms: how long the oldest queued request may wait for
        batch-mates before a partial batch dispatches (the
        occupancy-vs-latency dial, docs/SERVING.md).
      max_queue: bounded-queue admission limit across all buckets.
      block_size: pivot block size override for every bucket (default:
        ``config.default_block_size`` per bucket).
      autostart: start the dispatcher thread immediately (tests pass
        False to stage the queue deterministically, then ``start()``).
      telemetry: optional ``obs.spans.Telemetry`` — executor compiles
        and per-batch executions are recorded as distinct compile /
        execute spans (a warm server's trace shows ZERO compile spans),
        and every counter mirrors into the process-wide
        ``obs.metrics.REGISTRY`` regardless (docs/OBSERVABILITY.md).
    """

    def __init__(self, engine: str = "auto", plan_cache: str | None = None,
                 dtype=jnp.float32, batch_cap: int = 8,
                 max_wait_ms: float = 2.0, max_queue: int = 256,
                 block_size: int | None = None, autostart: bool = True,
                 telemetry=None):
        self.dtype = jnp.dtype(dtype)
        self.batch_cap = int(batch_cap)
        self.telemetry = telemetry
        self._stats = ServeStats()
        self.executors = ExecutorCache(engine=engine, plan_cache=plan_cache,
                                       dtype=self.dtype, stats=self._stats,
                                       telemetry=telemetry)
        self._batcher = MicroBatcher(
            self.executors, self._stats, batch_cap=batch_cap,
            max_wait_ms=max_wait_ms, max_queue=max_queue,
            block_size=block_size, autostart=autostart,
            telemetry=telemetry)
        self._closed = False

    # ---- request path ------------------------------------------------

    def submit(self, a) -> Future:
        """Queue one (n, n) matrix; returns a future resolving to
        :class:`InvertResult`.  Raises :class:`ServiceOverloadedError`
        when the bounded queue is full (backpressure — retry later) and
        :class:`ServiceClosedError` after ``close()``."""
        a = np.asarray(a, self.dtype)
        if a.ndim != 2 or a.shape[0] != a.shape[1]:
            raise ValueError(f"expected a square (n, n) matrix, "
                             f"got shape {a.shape}")
        n = a.shape[0]
        bucket = bucket_for(n)
        padded = np.asarray(np.eye(bucket, dtype=self.dtype))
        padded[:n, :n] = a
        return self._batcher.submit(padded, n, bucket)

    @staticmethod
    def result(future: Future, timeout: float | None = None) -> InvertResult:
        """Block on a submitted future (sugar over ``future.result``)."""
        return future.result(timeout)

    def invert(self, a, timeout: float | None = None) -> InvertResult:
        """Synchronous submit + wait.  Raises
        :class:`~..driver.SingularMatrixError` when THIS request's
        element was flagged (batch-mates are unaffected either way —
        the async ``submit`` path reports the flag on the result
        instead, for callers that want to inspect rather than raise)."""
        res = self.submit(a).result(timeout)
        if res.singular:
            from ..driver import SingularMatrixError

            raise SingularMatrixError("singular matrix")
        return res

    # ---- lifecycle ---------------------------------------------------

    def warmup(self, shapes) -> dict:
        """Pre-compile the executables for every bucket the given
        request sizes land in; returns {bucket_n: resolved engine}.
        After a warmup covering the live shape mix, the serve path
        performs zero compiles and zero plan-cache measurements (both
        counter-pinned by the acceptance test)."""
        out = {}
        for n in shapes:
            b = bucket_for(int(n))
            ex = self.executors.get(b, self.batch_cap,
                                    self._batcher.block_size)
            out[b] = ex.key.engine
        return out

    def start(self) -> None:
        """Start the dispatcher (no-op when ``autostart=True``)."""
        self._batcher.start()

    def close(self, drain: bool = True) -> None:
        """Stop accepting requests; ``drain=True`` completes all queued
        and in-flight work before returning."""
        if not self._closed:
            self._batcher.close(drain=drain)
            self._closed = True

    def __enter__(self) -> "JordanService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- observability ----------------------------------------------

    def stats(self) -> dict:
        """Per-bucket counters + latency percentiles (serve/stats.py),
        the resolved engine per compiled bucket, and the plan-cache
        measurement counter (0 on the cost-only ladder — the
        warm-server pin)."""
        snap = self._stats.snapshot()
        snap["engines"] = {
            f"{k.bucket_n}": {"engine": k.engine,
                              "batch_cap": k.batch_cap,
                              "plan_source": (ex.plan.source
                                              if ex.plan else None)}
            for k, ex in self.executors.entries()
        }
        snap["measurements"] = self.executors.measurements
        snap["batch_cap"] = self.batch_cap
        snap["queued"] = self._batcher.queued
        return snap


def serve_demo(n: int, block_size: int | None = None, requests: int = 64,
               batch_cap: int = 8, max_wait_ms: float = 2.0,
               engine: str = "auto", plan_cache: str | None = None,
               dtype=jnp.float32, generator: str = "rand",
               telemetry=None) -> dict:
    """The ``--serve-demo`` CLI mode's engine: a self-contained
    sustained-throughput demonstration on whatever backend is live.

    Submits ``requests`` mixed-size concurrent requests — sizes cycle
    through {n, n/2, n/4} (floored at the service's minimum bucket), so
    ≥ 3 shape buckets are exercised whenever n ≥ 4·MIN_BUCKET_N —
    through a warmed :class:`JordanService`, waits for every future, and
    returns the one-line JSON report: request/batch counts, per-bucket
    stats with mean occupancy and latency percentiles, the compile and
    plan-cache measurement counters (a warm server pins both at zero on
    the request path), worst rel_residual, and wall time.
    """
    import time

    from ..ops import generate

    sizes = sorted({max(1, n), max(1, n // 2), max(1, n // 4)},
                   reverse=True)
    elapsed0 = time.perf_counter()
    with JordanService(engine=engine, plan_cache=plan_cache, dtype=dtype,
                       batch_cap=batch_cap, max_wait_ms=max_wait_ms,
                       max_queue=max(requests, 1),
                       block_size=block_size, telemetry=telemetry) as svc:
        svc.warmup(shapes=sizes)
        compiles_after_warmup = svc.stats()["totals"]["compiles"]
        futures = []
        for i in range(requests):
            sz = sizes[i % len(sizes)]
            # Distinct well-conditioned matrices per request via index
            # offsets (the solve_batch convention).
            a = generate(generator, (sz, sz), dtype,
                         row_offset=i * sz, col_offset=i * sz)
            futures.append(svc.submit(a))
        results = [f.result(timeout=600) for f in futures]
        stats = svc.stats()
    elapsed = time.perf_counter() - elapsed0
    singular = sum(r.singular for r in results)
    worst_rel = max((r.rel_residual for r in results
                     if not r.singular), default=None)
    return {
        "metric": "serve_demo",
        "requests": requests,
        "request_sizes": sizes,
        "buckets": len(stats["buckets"]),
        "batch_cap": batch_cap,
        "singular": singular,
        "worst_rel_residual": (None if worst_rel is None
                               else f"{worst_rel:.1e}"),
        "compiles": stats["totals"]["compiles"],
        "compiles_on_request_path": (stats["totals"]["compiles"]
                                     - compiles_after_warmup),
        "plan_cache_measurements": stats["measurements"],
        "mean_occupancy": {
            b: s["mean_occupancy"] for b, s in stats["buckets"].items()},
        "elapsed_s": round(elapsed, 3),
        "stats": stats,
    }
