"""Resident-inverse handles — the fleet-shared database of live
inverses (ISSUE 12 tentpole).

A :class:`HandleState` is one resident (A, A⁻¹) pair: the identity-
padded MUTATED matrix, its padded resident inverse, the committed
version counter, and the accumulated-drift ledger the update gate
judges (``linalg/update.py``).  States live in a :class:`HandleStore`
— the handle analogue of the PR 7 ``ExecutorStore``: a fleet passes
ONE store to every replica (``JordanService(shared_handles=...)``), so

  * an ``update()`` on any replica reads the committed state and
    WRITES THROUGH under the handle's own lock (per-handle locks, like
    the executor store's per-key build locks: updates to one handle
    serialize across the whole pool, updates to different handles
    proceed concurrently);
  * a ``replica_kill`` never loses resident state — the store is not
    the replica's; queued updates fail typed, the router re-queues
    them, and the retry re-reads the committed state (the in-process
    kill boundary: an in-flight update commits and delivers, a queued
    one never ran — an update is applied exactly once either way);
  * a warm rolling restart "rebuilds" a replacement's handle view for
    free: there is nothing replica-local to rebuild (docs/FLEET.md).

Callers hold a :class:`HandleRef` — coordinates only, no arrays — and
thread it through ``JordanService.update(handle, u, v)`` /
``JordanFleet.update(...)``.  Mutation discipline: state arrays are
replaced wholesale under ``txn()``, never edited in place, so a reader
between transactions always sees one committed version.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field


class UnknownHandleError(KeyError):
    """The handle id names no resident state — never created here, or
    already evicted.  Typed: an update against a missing handle must
    fail loudly, not invert garbage."""


@dataclass(frozen=True)
class HandleRef:
    """What a caller holds for one resident inverse: the id plus the
    coordinates every update request needs to land on the right lane.
    ``result`` (when present) is the creating invert's
    :class:`~.batcher.InvertResult` — sugar so ``invert(a,
    resident=True)`` hands back both the answer and the handle."""

    handle_id: str
    n: int
    bucket_n: int
    dtype: str
    result: object = None

    def __repr__(self) -> str:       # results are big; keep refs terse
        return (f"HandleRef({self.handle_id!r}, n={self.n}, "
                f"bucket={self.bucket_n}, dtype={self.dtype})")


@dataclass
class HandleState:
    """One committed resident state (all arrays PADDED to the bucket).
    ``drift`` is the accumulated per-update rel_residual since the last
    fresh elimination (``linalg.update.drift_budget`` is its ceiling);
    ``version`` counts committed mutations (0 = as created)."""

    handle_id: str
    n: int
    bucket_n: int
    dtype: str
    a: object                     # (bucket, bucket) np — mutated matrix
    inverse: object               # (bucket, bucket) np — resident A⁻¹
    version: int = 0
    drift: float = 0.0
    updates_applied: int = 0
    reinverts: int = 0
    kappa: float = 0.0
    rel_residual: float = 0.0
    lock: threading.Lock = field(default_factory=threading.Lock,
                                 repr=False)

    def snapshot(self) -> dict:
        """The JSON-able per-handle slice of ``service.stats()`` /
        the update-demo report (no arrays).  Taken under the handle's
        own lock so a row can never be torn by a concurrent commit
        (e.g. the new version paired with the previous update's
        drift); never call from inside ``txn()`` of the same handle
        (the lock is not reentrant)."""
        with self.lock:
            return {
                "handle_id": self.handle_id, "n": self.n,
                "bucket_n": self.bucket_n, "dtype": self.dtype,
                "version": self.version, "drift": float(self.drift),
                "updates_applied": self.updates_applied,
                "reinverts": self.reinverts,
                "rel_residual": float(self.rel_residual),
            }


class HandleStore:
    """Thread-safe home for resident handles, shared fleet-wide.

    The outer lock guards the id→state map; each state carries its own
    mutation lock (``txn()``) so concurrent updates of different
    handles never serialize on the store.

    Lock order is STATE → STORE everywhere a state lock is held (txn's
    identity re-check, evict's and create's replacement checks); the
    bare map reads/writes take the store lock alone.  That ordering is
    what lets evict/create wait out an in-flight update without
    deadlock — and guarantees an update can never commit to an
    orphaned state object (the silently-lost-update class)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handles: dict[str, HandleState] = {}

    def create(self, state: HandleState) -> HandleRef:
        """Install a freshly-inverted resident state; re-creating an
        existing id REPLACES it (the caller re-inverted from scratch —
        the new state is the truth, version restarts at 0).  A
        replacement waits out any in-flight ``txn`` on the OLD state
        (its lock) before swapping, so an update never straddles the
        swap: it lands on the old state and is then superseded, or it
        retries onto the new one — never both, never lost."""
        ref = HandleRef(state.handle_id, state.n, state.bucket_n,
                        state.dtype)
        while True:
            with self._lock:
                old = self._handles.get(state.handle_id)
                if old is None:
                    self._handles[state.handle_id] = state
                    return ref
            with old.lock:
                with self._lock:
                    if self._handles.get(state.handle_id) is old:
                        self._handles[state.handle_id] = state
                        return ref
            # old was itself replaced/evicted between the reads: retry.

    def get(self, handle_id: str) -> HandleState:
        with self._lock:
            st = self._handles.get(handle_id)
        if st is None:
            raise UnknownHandleError(
                f"unknown resident handle {handle_id!r} — never "
                f"created, or already evicted")
        return st

    @contextmanager
    def txn(self, handle_id: str):
        """One serialized mutation window for a handle: yields the
        live state under ITS lock, with the store identity RE-CHECKED
        under that lock — a state evicted or replaced between the
        lookup and the lock acquisition is never yielded (an eviction
        raises the typed :class:`UnknownHandleError`; a replacement
        retries onto the new committed state).  Callers compute first
        and assign state fields (via :meth:`commit`) last — an
        exception inside the window leaves the committed state
        untouched."""
        while True:
            st = self.get(handle_id)          # raises if evicted
            with st.lock:
                with self._lock:
                    current = self._handles.get(handle_id)
                if current is st:
                    yield st
                    return
            # Replaced between lookup and lock: loop onto the
            # successor (or raise typed if it was evicted meanwhile).

    @staticmethod
    def commit(state: HandleState, *, a, inverse, kappa: float,
               rel_residual: float, drift: float,
               reinverted: bool = False) -> int:
        """Write-through of one applied update (caller inside
        ``txn()``): arrays replaced wholesale, version bumped, the
        drift ledger advanced (reset by a re_invert rung).  Returns
        the new version."""
        state.a = a
        state.inverse = inverse
        state.kappa = float(kappa)
        state.rel_residual = float(rel_residual)
        state.drift = float(drift)
        state.version += 1
        state.updates_applied += 1
        if reinverted:
            state.reinverts += 1
        return state.version

    def evict(self, handle_id: str) -> bool:
        """Drop a resident handle (False when already gone).  Eviction
        is the caller's lifecycle call — the store never ages state
        out on its own (docs/SERVING.md).  An in-flight ``txn`` is
        waited out (the state's lock) before removal, so a committed
        update is never orphaned by a racing evict."""
        while True:
            with self._lock:
                st = self._handles.get(handle_id)
            if st is None:
                return False
            with st.lock:
                with self._lock:
                    if self._handles.get(handle_id) is st:
                        del self._handles[handle_id]
                        return True
            # st was replaced between the reads: retry on the successor.

    def ids(self) -> list[str]:
        with self._lock:
            return sorted(self._handles)

    def snapshot(self) -> dict:
        """{handle_id: state.snapshot()} — the stats()/report block."""
        with self._lock:
            states = list(self._handles.values())
        return {st.handle_id: st.snapshot() for st in states}

    def __len__(self) -> int:
        with self._lock:
            return len(self._handles)


def create_resident_handle(store: HandleStore, dtype, a, res,
                           handle_id: str) -> HandleRef:
    """Install one resident handle from a completed invert — the ONE
    padding recipe the service and the fleet share: the bucketed
    inverse IS [[A⁻¹, 0], [0, I]] (ops/padding.py), so re-padding the
    returned n×n slice with identity reconstructs the padded resident
    state exactly.  ``res`` is the creating invert's ``InvertResult``;
    the returned ref carries it."""
    import numpy as np

    bucket, n = res.bucket_n, res.n
    a_pad = np.asarray(np.eye(bucket, dtype=dtype))
    a_pad[:n, :n] = np.asarray(a, dtype)
    inv_pad = np.asarray(np.eye(bucket, dtype=dtype))
    inv_pad[:n, :n] = np.asarray(res.inverse)
    ref = store.create(HandleState(
        handle_id=handle_id, n=n, bucket_n=bucket,
        dtype=np.dtype(dtype).name, a=a_pad, inverse=inv_pad,
        kappa=res.kappa, rel_residual=res.rel_residual))
    return HandleRef(ref.handle_id, ref.n, ref.bucket_n, ref.dtype,
                     result=res)
