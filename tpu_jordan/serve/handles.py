"""Resident-inverse handles — the fleet-shared database of live
inverses (ISSUE 12 tentpole).

A :class:`HandleState` is one resident (A, A⁻¹) pair: the identity-
padded MUTATED matrix, its padded resident inverse, the committed
version counter, and the accumulated-drift ledger the update gate
judges (``linalg/update.py``).  States live in a :class:`HandleStore`
— the handle analogue of the PR 7 ``ExecutorStore``: a fleet passes
ONE store to every replica (``JordanService(shared_handles=...)``), so

  * an ``update()`` on any replica reads the committed state and
    WRITES THROUGH under the handle's own lock (per-handle locks, like
    the executor store's per-key build locks: updates to one handle
    serialize across the whole pool, updates to different handles
    proceed concurrently);
  * a ``replica_kill`` never loses resident state — the store is not
    the replica's; queued updates fail typed, the router re-queues
    them, and the retry re-reads the committed state (the in-process
    kill boundary: an in-flight update commits and delivers, a queued
    one never ran — an update is applied exactly once either way);
  * a warm rolling restart "rebuilds" a replacement's handle view for
    free: there is nothing replica-local to rebuild (docs/FLEET.md).

Callers hold a :class:`HandleRef` — coordinates only, no arrays — and
thread it through ``JordanService.update(handle, u, v)`` /
``JordanFleet.update(...)``.  Mutation discipline: state arrays are
replaced wholesale under ``txn()``, never edited in place, so a reader
between transactions always sees one committed version.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

import numpy as np

from ..obs import capacity as _capacity


def resident_handle_bytes(bucket_n: int, dtype) -> int:
    """The bytes ONE resident handle pins: the identity-padded mutated
    matrix plus its padded inverse — 2·bucket²·dtype (ISSUE 13, the
    unit every capacity budget and the ``resident_handle_bytes`` bench
    accounting field are denominated in)."""
    return 2 * int(bucket_n) * int(bucket_n) * np.dtype(dtype).itemsize


class UnknownHandleError(KeyError):
    """The handle id names no resident state — never created here, or
    already evicted.  Typed: an update against a missing handle must
    fail loudly, not invert garbage."""


@dataclass(frozen=True)
class HandleRef:
    """What a caller holds for one resident inverse: the id plus the
    coordinates every update request needs to land on the right lane.
    ``result`` (when present) is the creating invert's
    :class:`~.batcher.InvertResult` — sugar so ``invert(a,
    resident=True)`` hands back both the answer and the handle."""

    handle_id: str
    n: int
    bucket_n: int
    dtype: str
    result: object = None

    def __repr__(self) -> str:       # results are big; keep refs terse
        return (f"HandleRef({self.handle_id!r}, n={self.n}, "
                f"bucket={self.bucket_n}, dtype={self.dtype})")


@dataclass
class HandleState:
    """One committed resident state (all arrays PADDED to the bucket).
    ``drift`` is the accumulated per-update rel_residual since the last
    fresh elimination (``linalg.update.drift_budget`` is its ceiling);
    ``version`` counts committed mutations (0 = as created)."""

    handle_id: str
    n: int
    bucket_n: int
    dtype: str
    a: object                     # (bucket, bucket) np — mutated matrix
    inverse: object               # (bucket, bucket) np — resident A⁻¹
    version: int = 0
    drift: float = 0.0
    updates_applied: int = 0
    reinverts: int = 0
    kappa: float = 0.0
    rel_residual: float = 0.0
    #: capacity accounting (ISSUE 13): resident bytes (stamped by the
    #: store at create), the LRU clock the budget evictor orders by
    #: (stamped at create and on every COMMITTED txn — a failing
    #: update never refreshes its handle's eviction position), and the
    #: pin flag exempting this handle from budget eviction.
    nbytes: int = 0
    last_served: float = 0.0
    pinned: bool = False
    lock: threading.Lock = field(default_factory=threading.Lock,
                                 repr=False)

    def snapshot(self) -> dict:
        """The JSON-able per-handle slice of ``service.stats()`` /
        the update-demo report (no arrays).  Taken under the handle's
        own lock so a row can never be torn by a concurrent commit
        (e.g. the new version paired with the previous update's
        drift); never call from inside ``txn()`` of the same handle
        (the lock is not reentrant)."""
        with self.lock:
            return {
                "handle_id": self.handle_id, "n": self.n,
                "bucket_n": self.bucket_n, "dtype": self.dtype,
                "version": self.version, "drift": float(self.drift),
                "updates_applied": self.updates_applied,
                "reinverts": self.reinverts,
                "rel_residual": float(self.rel_residual),
                "nbytes": int(self.nbytes),
                "pinned": bool(self.pinned),
            }


class HandleStore:
    """Thread-safe home for resident handles, shared fleet-wide.

    The outer lock guards the id→state map; each state carries its own
    mutation lock (``txn()``) so concurrent updates of different
    handles never serialize on the store.

    Lock order is STATE → STORE everywhere a state lock is held (txn's
    identity re-check, evict's and create's replacement checks); the
    bare map reads/writes take the store lock alone.  That ordering is
    what lets evict/create wait out an in-flight update without
    deadlock — and guarantees an update can never commit to an
    orphaned state object (the silently-lost-update class).

    Capacity (ISSUE 13): every create/evict/re-create meters the
    process-wide ``obs.capacity`` ledger (component ``handles``), and
    an attached :class:`~..obs.capacity.CapacityBudget` turns the
    accounting into actuation — admission evicts least-recently-served
    UNPINNED handles until the new state fits, or refuses with the
    typed ``CapacityExceededError`` at submit.  The budget evictor
    goes through :meth:`evict`, so it inherits the STATE → STORE
    discipline: an in-flight ``txn`` is waited out and its committed
    update lands before the removal — never orphaned by the budget
    either."""

    def __init__(self, budget=None, clock=None):
        self._lock = threading.Lock()
        self._handles: dict[str, HandleState] = {}
        #: the resident-bytes ceiling (obs.capacity.CapacityBudget) or
        #: None — the historical unmetered-admission behavior, with the
        #: ledger still metering every byte.
        self.budget = budget
        self._clock = clock if clock is not None else time.monotonic
        self._live_bytes = 0
        self._budget_evictions = 0
        self._refusals = 0

    def create(self, state: HandleState) -> HandleRef:
        """Install a freshly-inverted resident state; re-creating an
        existing id REPLACES it (the caller re-inverted from scratch —
        the new state is the truth, version restarts at 0).  A
        replacement waits out any in-flight ``txn`` on the OLD state
        (its lock) before swapping, so an update never straddles the
        swap: it lands on the old state and is then superseded, or it
        retries onto the new one — never both, never lost.

        Budget admission (ISSUE 13) runs FIRST — the store evicts LRU
        unpinned handles until the new state fits, or raises the typed
        ``CapacityExceededError`` before anything is installed — and
        is RE-CHECKED under the store lock at install: two racing
        creates of distinct ids can both pass the eviction pass, but
        only admissions that still fit install; the loser loops back
        to evict (or refuse typed) rather than silently overshooting
        the ceiling.  A same-id replacement's old bytes are credited
        (a net-zero re-create never evicts an innocent handle).  The
        serving surface additionally pre-admits at submit
        (``ensure_capacity``) so the refusal lands before the invert
        ever launches."""
        state.nbytes = resident_handle_bytes(state.bucket_n, state.dtype)
        state.last_served = self._clock()
        ref = HandleRef(state.handle_id, state.n, state.bucket_n,
                        state.dtype)
        while True:
            if self.budget is not None:
                self.ensure_capacity(state.nbytes,
                                     replacing=state.handle_id)
            with self._lock:
                old = self._handles.get(state.handle_id)
                if old is None:
                    if self._fits_locked(state.nbytes):
                        self._install(state)
                        return ref
                    continue            # admission raced: re-evict
            if old is None:
                continue
            with old.lock:
                with self._lock:
                    if self._handles.get(state.handle_id) is old:
                        if self._fits_locked(state.nbytes
                                             - old.nbytes):
                            self._live_bytes -= old.nbytes
                            self._install(state)
                            return ref
            # old was replaced/evicted between the reads (retry on the
            # successor), or the replacement no longer fits because a
            # racer consumed the credit (loop back to admission).

    def _fits_locked(self, delta: int) -> bool:
        """Does adding ``delta`` net bytes fit the budget?  Caller
        holds the store lock — this is the install-time re-check that
        makes admission atomic with installation."""
        if self.budget is None:
            return True
        return self._live_bytes + delta <= self.budget.max_bytes

    def _install(self, state: HandleState) -> None:
        """Map write + ledger metering (caller holds the store lock).
        A same-id replacement's old bytes are accounted evicted by the
        ledger's replace semantics."""
        self._handles[state.handle_id] = state
        self._live_bytes += state.nbytes
        _capacity.register("handles", (id(self), state.handle_id),
                           state.nbytes, detail=f"n{state.bucket_n}")

    def get(self, handle_id: str) -> HandleState:
        with self._lock:
            st = self._handles.get(handle_id)
        if st is None:
            raise UnknownHandleError(
                f"unknown resident handle {handle_id!r} — never "
                f"created, or already evicted")
        return st

    @contextmanager
    def txn(self, handle_id: str):
        """One serialized mutation window for a handle: yields the
        live state under ITS lock, with the store identity RE-CHECKED
        under that lock — a state evicted or replaced between the
        lookup and the lock acquisition is never yielded (an eviction
        raises the typed :class:`UnknownHandleError`; a replacement
        retries onto the new committed state).  Callers compute first
        and assign state fields (via :meth:`commit`) last — an
        exception inside the window leaves the committed state
        untouched."""
        while True:
            st = self.get(handle_id)          # raises if evicted
            with st.lock:
                with self._lock:
                    current = self._handles.get(handle_id)
                if current is st:
                    v0 = st.version
                    try:
                        yield st
                    finally:
                        # LRU stamp (ISSUE 13), COMMIT-gated: only a
                        # txn that actually committed refreshes the
                        # handle's eviction position — a handle whose
                        # updates keep failing typed must not squat on
                        # residency by bumping its own stamp.
                        if st.version != v0:
                            st.last_served = self._clock()
                    return
            # Replaced between lookup and lock: loop onto the
            # successor (or raise typed if it was evicted meanwhile).

    @staticmethod
    def commit(state: HandleState, *, a, inverse, kappa: float,
               rel_residual: float, drift: float,
               reinverted: bool = False) -> int:
        """Write-through of one applied update (caller inside
        ``txn()``): arrays replaced wholesale, version bumped, the
        drift ledger advanced (reset by a re_invert rung).  Returns
        the new version."""
        state.a = a
        state.inverse = inverse
        state.kappa = float(kappa)
        state.rel_residual = float(rel_residual)
        state.drift = float(drift)
        state.version += 1
        state.updates_applied += 1
        if reinverted:
            state.reinverts += 1
        return state.version

    def evict(self, handle_id: str, cause: str = "caller") -> bool:
        """Drop a resident handle (False when already gone).  Eviction
        is a lifecycle call — the caller's, or the attached budget's
        LRU evictor (``cause="budget"``); the store never ages state
        out on its own otherwise (docs/SERVING.md).  An in-flight
        ``txn`` is waited out (the state's lock) before removal, so a
        committed update is never orphaned by a racing evict — budget
        evictions included.  Every eviction releases the capacity
        ledger and records a ``capacity_eviction`` flight-recorder
        event (a budget eviction without one is the silent-evict class
        ``check_capacity`` exits 2 on)."""
        while True:
            with self._lock:
                st = self._handles.get(handle_id)
            if st is None:
                return False
            with st.lock:
                with self._lock:
                    if self._handles.get(handle_id) is st:
                        del self._handles[handle_id]
                        self._live_bytes -= st.nbytes
                        if cause == "budget":
                            self._budget_evictions += 1
                        live = self._live_bytes
                        _capacity.release("handles",
                                          (id(self), handle_id))
                        _capacity.record_eviction(
                            handle_id, st.nbytes, cause, live,
                            budget_bytes=(self.budget.max_bytes
                                          if self.budget is not None
                                          else None))
                        return True
            # st was replaced between the reads: retry on the successor.

    # ---- capacity admission (ISSUE 13) -------------------------------

    def pin(self, handle_id: str) -> None:
        """Exempt a handle from budget eviction (it still counts
        against the budget — pinned residency is residency)."""
        self.get(handle_id).pinned = True

    def unpin(self, handle_id: str) -> None:
        self.get(handle_id).pinned = False

    def ensure_capacity(self, nbytes: int, exempt=frozenset(),
                        hop=None, replacing: str | None = None
                        ) -> list[str]:
        """Make room for ``nbytes`` of new resident state under the
        attached budget: evict least-recently-served unpinned handles
        (through :meth:`evict` — in-flight txns waited out, events
        recorded) until the admission fits, or raise the typed
        ``CapacityExceededError`` (counted + recorded) when nothing
        evictable remains.  No-op without a budget.

        ``replacing`` names a handle id this admission will REPLACE
        (a same-id re-create): its live bytes are credited against the
        request — a net-zero replacement admits without evicting an
        innocent handle or refusing — and it is exempt from eviction
        (evicting the handle being replaced would emit a spurious
        budget event for bytes the replacement frees anyway).

        ``hop`` (the serving surface passes the creating request's
        journey ``ctx.event``) records one ``capacity_evict`` journey
        hop per victim — the eviction is attributable to the request
        whose admission forced it.  Returns the evicted ids."""
        if self.budget is None:
            return []
        from ..resilience.policy import CapacityExceededError

        nbytes = int(nbytes)
        if replacing is not None:
            exempt = frozenset(exempt) | {replacing}
            with self._lock:
                old = self._handles.get(replacing)
                if old is not None:
                    nbytes = max(0, nbytes - old.nbytes)
        evicted: list[str] = []
        while True:
            with self._lock:
                if self._live_bytes + nbytes <= self.budget.max_bytes:
                    return evicted
                candidates = [st for st in self._handles.values()
                              if not st.pinned
                              and st.handle_id not in exempt]
                pinned = len(self._handles) - len(candidates)
                live = self._live_bytes
            if not candidates:
                with self._lock:
                    self._refusals += 1
                _capacity.record_refusal(nbytes, live,
                                         self.budget.max_bytes, pinned)
                raise CapacityExceededError(
                    f"resident-handle budget exceeded: {nbytes} new "
                    f"bytes would not fit ({live} live of "
                    f"{self.budget.max_bytes} budget, {pinned} "
                    f"pinned/exempt handle(s), nothing evictable) — "
                    f"evict or unpin a handle, or raise the budget")
            victim = self.budget.victims(candidates)[0]
            if self.evict(victim.handle_id, cause="budget"):
                evicted.append(victim.handle_id)
                if hop is not None:
                    hop("capacity_evict", handle=victim.handle_id,
                        bytes=victim.nbytes, cause="budget")
            # A racing evictor may have removed the victim first (evict
            # returned False): loop — the live-bytes re-check decides.

    def budget_snapshot(self) -> dict:
        """The store's capacity block in ``service.stats()`` /
        the demo report."""
        with self._lock:
            pinned = sorted(h for h, st in self._handles.items()
                            if st.pinned)
            return {
                "max_bytes": (self.budget.max_bytes
                              if self.budget is not None else None),
                "live_bytes": self._live_bytes,
                "handles": len(self._handles),
                "pinned": pinned,
                "budget_evictions": self._budget_evictions,
                "refusals": self._refusals,
            }

    def ids(self) -> list[str]:
        with self._lock:
            return sorted(self._handles)

    def snapshot(self) -> dict:
        """{handle_id: state.snapshot()} — the stats()/report block."""
        with self._lock:
            states = list(self._handles.values())
        return {st.handle_id: st.snapshot() for st in states}

    def __len__(self) -> int:
        with self._lock:
            return len(self._handles)


def build_handle_store(shared, budget_bytes: int | None,
                       owner: str) -> HandleStore:
    """The ONE home for the shared-store-vs-budget wiring rule
    (ISSUE 13): ``JordanService`` and ``JordanFleet`` both build their
    handle store through this, so the mutual exclusion — a pre-built
    shared store carries its OWN budget; attaching a second one at the
    consumer would fork the admission policy — can never drift between
    the two surfaces.  ``owner`` names the consumer for the typed
    error."""
    if shared is not None and budget_bytes is not None:
        from ..driver import UsageError

        raise UsageError(
            f"handle_budget_bytes builds {owner}'s own budgeted store; "
            f"a pre-built shared store carries its own budget "
            f"(HandleStore(budget=CapacityBudget(...)) — one admission "
            f"policy for everyone sharing it)")
    if shared is not None:
        return shared
    if budget_bytes is not None:
        from ..obs.capacity import CapacityBudget

        return HandleStore(budget=CapacityBudget(max_bytes=budget_bytes))
    return HandleStore()


def create_resident_handle(store: HandleStore, dtype, a, res,
                           handle_id: str) -> HandleRef:
    """Install one resident handle from a completed invert — the ONE
    padding recipe the service and the fleet share: the bucketed
    inverse IS [[A⁻¹, 0], [0, I]] (ops/padding.py), so re-padding the
    returned n×n slice with identity reconstructs the padded resident
    state exactly.  ``res`` is the creating invert's ``InvertResult``;
    the returned ref carries it."""
    bucket, n = res.bucket_n, res.n
    a_pad = np.asarray(np.eye(bucket, dtype=dtype))
    a_pad[:n, :n] = np.asarray(a, dtype)
    inv_pad = np.asarray(np.eye(bucket, dtype=dtype))
    inv_pad[:n, :n] = np.asarray(res.inverse)
    ref = store.create(HandleState(
        handle_id=handle_id, n=n, bucket_n=bucket,
        dtype=np.dtype(dtype).name, a=a_pad, inverse=inv_pad,
        kappa=res.kappa, rel_residual=res.rel_residual))
    return HandleRef(ref.handle_id, ref.n, ref.bucket_n, ref.dtype,
                     result=res)
