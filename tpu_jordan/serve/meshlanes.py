"""Mesh-backed serve lanes (ISSUE 18 tentpole part 1).

Every serve lane before this PR was a single-device vmapped executable,
so the fleet's n ceiling was one chip's HBM.  A :class:`MeshLaneExecutor`
is the distributed counterpart of ``executors.BucketExecutor``: ONE
AOT-compiled sharded program per ``(workload, bucket, dtype, mesh)``
built from the SAME engine front ends the library path ships —
``linalg.api.solve_mesh_backend`` for the [A | B] solve elimination,
``driver.make_distributed_backend`` for the sharded invert — resolved
through the SAME tuner ladder (the plan-cache key already carries the
topology segment, ``tuning/plan_cache.plan_key``), so a warm mesh lane
performs ZERO compiles and ZERO measurements exactly like the
single-device lanes (counter-pinned in tests/test_meshlanes.py).

Contract differences from the single-device lanes, all deliberate:

  * **batch_cap is 1.**  A mesh program owns the whole mesh for its
    launch — there is no second device set to vmap a batch over.  The
    batcher dispatches mesh lanes at occupancy 1.
  * **Admission is byte-projected.**  ``projected_lane_bytes(...,
    devices=p)`` divides the O(n²) matrix terms by the mesh size — the
    per-device residency — and the service admits a request to the
    smallest mesh whose per-device projection fits the lane budget.  A
    request no mesh can hold is a typed ``CapacityExceededError`` at
    submit, never an OOM mid-launch.
  * **Comm accounting is inherited day one.**  The compile is traced
    under ``obs.comm.record_collectives`` when recording is active, and
    every execute builds the layout-derived analytical
    :class:`~..obs.comm.CommReport` (multiset-reconciled against the
    observed records) exactly like ``solve_system(workers=...)``.
  * **Typed refusals, never silent fallback.**  Complex dtypes, the
    SPD fast path, and ``resident=True`` handles are single-device
    contracts; a mesh lane refuses them with the library's own
    vocabulary (``linalg/api.py``) naming the legal alternatives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..resilience import faults as _faults

#: The non-mesh topology label — the value of ``ExecutorKey.mesh`` for
#: every single-device lane (and the default, so every pre-existing key
#: is byte-identical).
MESH_SINGLE = "single"


def mesh_label(workers) -> str:
    """The topology label of a workers spec — the SAME vocabulary as
    ``TunePoint.topology`` ('p8' for 1D, '2x4' for 2D), so plan-cache
    keys and ``ExecutorKey.mesh`` can never use two spellings."""
    if isinstance(workers, tuple):
        return f"{int(workers[0])}x{int(workers[1])}"
    w = int(workers)
    return MESH_SINGLE if w == 1 else f"p{w}"


def parse_mesh(label: str):
    """The inverse of :func:`mesh_label`: 'p8' -> 8, '2x4' -> (2, 4).
    A malformed label is a typed ``UsageError`` (the serve surface
    never guesses a topology)."""
    from ..driver import UsageError

    s = str(label)
    if s == MESH_SINGLE:
        return 1
    if "x" in s:
        pr, _, pc = s.partition("x")
        if pr.isdigit() and pc.isdigit() and int(pr) > 0 and int(pc) > 0:
            return (int(pr), int(pc))
    elif s.startswith("p") and s[1:].isdigit() and int(s[1:]) > 0:
        return int(s[1:])
    raise UsageError(
        f"mesh spec {label!r} is not a topology label: use 'pN' (1D "
        f"row-cyclic over N devices), 'PRxPC' (2D block-cyclic), an "
        f"int, or a (pr, pc) tuple")


def mesh_devices(workers) -> int:
    """Device count of a workers spec (1D p -> p, (pr, pc) -> pr*pc)."""
    if isinstance(workers, tuple):
        return int(workers[0]) * int(workers[1])
    return int(workers)


def normalize_mesh(spec):
    """Canonicalize a mesh spec (int, (pr, pc) tuple, or topology
    label) to the driver's workers spec, validated against the devices
    this process can actually form a mesh from.  An unformable mesh is
    a typed ``UsageError`` naming the device count — the serve surface
    refuses at configure/submit time, never a mesh-construction crash
    mid-launch."""
    from ..driver import UsageError

    workers = parse_mesh(spec) if isinstance(spec, str) else spec
    if isinstance(workers, tuple):
        workers = (int(workers[0]), int(workers[1]))
        if workers[0] < 1 or workers[1] < 1:
            raise UsageError(
                f"mesh shape {workers} is not a topology: both mesh "
                f"axes must be positive")
    else:
        workers = int(workers)
        if workers < 1:
            raise UsageError(
                f"mesh size {workers} is not a topology: workers must "
                f"be positive")
    need = mesh_devices(workers)
    have = jax.device_count()
    if need < 2:
        raise UsageError(
            "a 1-device mesh is the single-device lane (mesh="
            "'single'); mesh lanes need workers > 1 or a (pr, pc) "
            "tuple")
    if need > have:
        raise UsageError(
            f"mesh {mesh_label(workers)!r} needs {need} devices; this "
            f"process has {have} (jax.device_count()) — serve this "
            f"topology on a host that can form it, or configure a "
            f"smaller mesh_shapes entry")
    return workers


class MeshLaneExecutor:
    """One AOT-compiled distributed executable for one mesh lane.

    ``key`` is an ``executors.ExecutorKey`` with ``mesh != 'single'``
    and ``batch_cap == 1``; ``plan`` the tuner's resolved plan (cost
    ranked through the topology-keyed plan cache — zero measurements).
    The compile runs ONCE here; ``run()`` is scatter -> the sharded
    elimination -> gather, and ``comm_report()`` hands the dispatcher
    the per-execute analytical inventory with the compile-time observed
    records attached (recording permitting)."""

    def __init__(self, key, plan):
        from ..driver import UsageError

        self.key = key
        self.plan = plan
        self.block_size = key.block_size
        if key.batch_cap != 1:
            raise UsageError(
                "mesh lanes dispatch at occupancy 1 (one sharded "
                "program owns the whole mesh per launch); batch_cap "
                "must be 1")
        in_dtype = jnp.dtype(key.dtype)
        if in_dtype.kind == "c":
            raise UsageError(
                "complex dtypes run single-device (the distributed "
                "scatter/collective paths are real-dtype, the invert "
                "engines' contract); serve complex requests on the "
                "single-device lanes (mesh='single')")
        if key.engine == "solve_spd":
            raise UsageError(
                "assume='spd' is the single-device pivot-free fast "
                "path; the distributed [A | B] elimination pivots — "
                "serve SPD requests on the single-device lanes "
                "(mesh='single'), or drop the spd promise")
        if key.workload == "update":
            raise UsageError(
                "the SMW update lanes are single-chip (resident "
                "handles live on one device); mesh lanes serve "
                "workload='invert' and 'solve'")
        self.workers = normalize_mesh(key.mesh)
        self.devices = mesh_devices(self.workers)
        self.in_dtype = in_dtype
        # The distributed core's working-dtype promotion (linalg/api.py
        # / driver.py): sub-fp32 storage computes in fp32.
        self.work_dtype = (jnp.dtype(jnp.float32)
                          if in_dtype.itemsize < 4 else in_dtype)
        #: compile-time traced collective records (obs/comm.py), or
        #: None when recording was off — attached to every execute's
        #: analytical report so the serve path reconciles multiset-
        #: exact like the library path.
        self._observed = None
        self._compiled = (self._build_solve() if key.workload == "solve"
                          else self._build_invert())
        from ..obs import hwcost as _hwcost

        self.cost = _hwcost.executable_cost(self._compiled)

    # ---- builds ------------------------------------------------------

    def _traced_compile(self, compile_once):
        from ..obs import comm as _comm

        _faults.fire("compile")
        if _comm.recording_active():
            with _comm.record_collectives() as rec:
                run = compile_once()
            self._observed = rec.records
            return run
        return compile_once()

    def _build_solve(self):
        from ..driver import UsageError
        from ..linalg.api import solve_mesh_backend
        from ..parallel.sharded_inplace import MAX_UNROLL_NR

        key = self.key
        if key.engine not in ("solve_sharded", "solve_lookahead"):
            raise UsageError(
                f"engine={key.engine!r} is a single-device solve "
                f"engine; mesh solve lanes run engine='solve_sharded' "
                f"or 'solve_lookahead' (or 'auto', which resolves "
                f"there)")
        N, m, K = key.bucket_n, self.block_size, key.rhs
        (mesh, lay, scatter_a, scatter_b, compile_fn,
         gather_x) = solve_mesh_backend(self.workers, N, m)
        self.lay, self.mesh = lay, mesh
        self._scatter_a, self._scatter_b = scatter_a, scatter_b
        self._gather_x = gather_x
        self._unroll = lay.Nr <= MAX_UNROLL_NR
        la = key.engine == "solve_lookahead"
        # Shape/dtype templates only — nothing executes at build.
        W = scatter_a(jnp.eye(N, dtype=self.work_dtype), lay, mesh)
        Xb = scatter_b(jnp.zeros((N, K), self.work_dtype), lay, mesh)
        return self._traced_compile(
            lambda: compile_fn(W, Xb, mesh, lay, lookahead=la))

    def _build_invert(self):
        from ..driver import make_distributed_backend

        key = self.key
        N, m = key.bucket_n, self.block_size
        group = getattr(self.plan, "group", 0) or 0
        engine = "inplace" if key.engine in ("inplace", "auto") else key.engine
        be = make_distributed_backend(self.workers, N, m, engine, group)
        self._be = be
        self.lay, self.mesh = be.lay, be.mesh
        from ..parallel.sharded_inplace import MAX_UNROLL_NR

        self._unroll = be.lay.Nr <= MAX_UNROLL_NR
        # The comm inventory's engine name (driver.py's derivation).
        self._eng_name = ("swapfree" if be.swapfree
                          else "lookahead" if getattr(be, "lookahead", False)
                          else "grouped" if be.group > 1
                          else "inplace" if be.inplace else "augmented")
        W = be.scatter_W(jnp.eye(N, dtype=self.work_dtype))
        return self._traced_compile(lambda: be.compile(W))

    # ---- the per-request path ---------------------------------------

    def run(self, a, b=None):
        """One request through the mesh: scatter the identity-padded A
        (and zero-padded B on solve lanes), execute the compiled
        sharded program, gather the result — returns ``(result,
        singular_flags)`` in the request dtype.  The dispatcher wraps
        this whole call in its ``timed_blocking`` bracket (scatter and
        gather ARE the request's latency on a mesh lane)."""
        a = jnp.asarray(a, self.work_dtype)
        N = self.key.bucket_n
        if self.key.workload == "solve":
            W = self._scatter_a(a, self.lay, self.mesh)
            Xb = self._scatter_b(jnp.asarray(b, self.work_dtype),
                                 self.lay, self.mesh)
            out, sing = self._compiled(W, Xb)
            res = self._gather_x(out, self.lay, N)
        else:
            W = self._be.scatter_W(a)
            out, sing = self._compiled(W)
            res = self._be.gather(out, N)
        if res.dtype != self.in_dtype:
            res = res.astype(self.in_dtype)
        return res, sing

    def metrics(self, a, result, b=None):
        """Host-side dense verification against the CALLER's padded A
        (and B) — ``(kappa_est, rel_residual)``, the same backward-error
        semantics as the batched lanes' in-launch assembly.  Dense is
        deliberate: a mesh request's O(n²) verify is noise next to its
        O(n³/p) elimination, and the gathered result is already in
        hand."""
        from jax import lax as _lax

        a = jnp.asarray(a)
        x = jnp.asarray(result)
        rhs = (jnp.asarray(b) if b is not None
               else jnp.eye(a.shape[0], dtype=a.dtype))
        r = jnp.matmul(a, x, precision=_lax.Precision.HIGHEST) - rhs
        residual = float(jnp.max(jnp.sum(jnp.abs(r), axis=-1)))
        norm = jnp.max(jnp.sum(jnp.abs(a), axis=-1))
        norm_a = float(norm)
        norm_x = float(jnp.max(jnp.sum(jnp.abs(x), axis=-1)))
        norm_b = float(jnp.max(jnp.sum(jnp.abs(rhs), axis=-1)))
        denom = norm_a * norm_x + norm_b
        rel = residual / denom if denom else residual
        kappa = (norm_a * norm_x / norm_b) if norm_b else 0.0
        return kappa, rel

    def comm_report(self):
        """The layout-derived analytical collective inventory for one
        execute (obs/comm.py), with the compile-time observed records
        attached when they were captured.  Invert lanes pass
        ``refine=1``: the serve path verifies densely on the gathered
        result (like the solve flavors), so the ring-GEMM residual
        section is honestly absent from the model."""
        from ..obs import comm as _comm

        key = self.key
        if key.workload == "solve":
            rep = _comm.engine_report(
                engine=key.engine, lay=self.lay, dtype=self.work_dtype,
                gather=True, unroll=self._unroll, rhs=key.rhs)
        else:
            rep = _comm.engine_report(
                engine=self._eng_name, lay=self.lay,
                dtype=self.work_dtype, gather=True, refine=1,
                group=getattr(self._be, "group", 0))
        if self._observed is not None:
            rep.attach_observed("engine", self._observed)
        return rep
