"""Per-bucket serving counters and latency percentiles (ISSUE 3
tentpole part 4).

One :class:`ServeStats` instance rides a :class:`~.service.JordanService`
for its whole life; every mutation happens under one lock because the
writers are two threads (the caller thread on submit/reject, the
dispatcher thread on batch completion and compile).  ``snapshot()``
returns a plain-JSON dict — the payload of ``service.stats()`` and of
the ``--serve-demo`` one-line report.

The per-bucket keys the acceptance contract pins (ISSUE 3): ``requests``,
``batches``, ``mean_occupancy`` (> 1 is the whole point of the
micro-batcher), ``compiles`` (exactly one per (bucket, batch_cap) —
zero after warmup), ``cache_hits``, ``singular``, and p50/p95/p99 for
both queue wait and execute time.
"""

from __future__ import annotations

import threading

# Latency samples kept per (bucket, phase); beyond this the OLDEST are
# dropped (a serving process must not grow without bound).  4096 recent
# samples keep p99 meaningful at any realistic demo scale.
MAX_LATENCY_SAMPLES = 4096

_PCTS = (50.0, 95.0, 99.0)


def _percentiles(samples) -> dict:
    """p50/p95/p99 (milliseconds, 3 decimals) by the nearest-rank method
    on a sorted copy — no numpy interpolation surprises for tiny k."""
    if not samples:
        return {"p50": None, "p95": None, "p99": None}
    s = sorted(samples)
    out = {}
    for p in _PCTS:
        rank = max(0, min(len(s) - 1, int(round(p / 100.0 * len(s))) - 1))
        out[f"p{p:.0f}"] = round(s[rank] * 1e3, 3)
    return out


class _BucketStats:
    """Counters for one shape bucket (all mutation under the owner's
    lock — this class itself is not thread-safe on purpose)."""

    def __init__(self):
        self.requests = 0
        self.rejected = 0
        self.batches = 0
        self.elements = 0          # occupied slots over all batches
        self.compiles = 0
        self.cache_hits = 0
        self.singular = 0
        self.queue_s: list[float] = []
        self.exec_s: list[float] = []

    def to_json(self) -> dict:
        occ = (self.elements / self.batches) if self.batches else 0.0
        return {
            "requests": self.requests,
            "rejected": self.rejected,
            "batches": self.batches,
            "mean_occupancy": round(occ, 3),
            "compiles": self.compiles,
            "cache_hits": self.cache_hits,
            "singular": self.singular,
            "queue_ms": _percentiles(self.queue_s),
            "execute_ms": _percentiles(self.exec_s),
        }


class ServeStats:
    """Thread-safe serving scoreboard, keyed by bucket n."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: dict[int, _BucketStats] = {}

    def _b(self, bucket: int) -> _BucketStats:
        return self._buckets.setdefault(bucket, _BucketStats())

    def request(self, bucket: int) -> None:
        with self._lock:
            self._b(bucket).requests += 1

    def rejected(self, bucket: int) -> None:
        with self._lock:
            self._b(bucket).rejected += 1

    def compile(self, bucket: int) -> None:
        with self._lock:
            self._b(bucket).compiles += 1

    def cache_hit(self, bucket: int) -> None:
        with self._lock:
            self._b(bucket).cache_hits += 1

    def batch(self, bucket: int, occupancy: int, exec_seconds: float,
              queue_seconds, singular: int = 0) -> None:
        """One dispatched batch: ``occupancy`` occupied slots,
        ``queue_seconds`` an iterable of per-request queue waits."""
        with self._lock:
            b = self._b(bucket)
            b.batches += 1
            b.elements += occupancy
            b.singular += singular
            b.exec_s.append(float(exec_seconds))
            b.queue_s.extend(float(q) for q in queue_seconds)
            del b.exec_s[:-MAX_LATENCY_SAMPLES]
            del b.queue_s[:-MAX_LATENCY_SAMPLES]

    def snapshot(self) -> dict:
        with self._lock:
            buckets = {str(k): v.to_json()
                       for k, v in sorted(self._buckets.items())}
        totals = {
            "requests": sum(b["requests"] for b in buckets.values()),
            "rejected": sum(b["rejected"] for b in buckets.values()),
            "batches": sum(b["batches"] for b in buckets.values()),
            "compiles": sum(b["compiles"] for b in buckets.values()),
            "singular": sum(b["singular"] for b in buckets.values()),
        }
        return {"buckets": buckets, "totals": totals}
