"""Per-bucket serving counters and latency percentiles (ISSUE 3
tentpole part 4; re-based on the unified telemetry layer in ISSUE 4).

One :class:`ServeStats` instance rides a :class:`~.service.JordanService`
for its whole life; every mutation happens under one lock because the
writers are two threads (the caller thread on submit/reject, the
dispatcher thread on batch completion and compile).  ``snapshot()``
returns a plain-JSON dict — the payload of ``service.stats()`` and of
the ``--serve-demo`` one-line report.  The per-bucket keys the
acceptance contract pins (ISSUE 3) are unchanged: ``requests``,
``batches``, ``mean_occupancy``, ``compiles``, ``cache_hits``,
``singular``, and p50/p95/p99 for both queue wait and execute time.

ISSUE 4 re-base: the reservoir + nearest-rank percentile machinery this
module prototyped now lives in ``obs/metrics.py`` (``Reservoir``,
``percentiles``) and every mutation is MIRRORED into the process-wide
``tpu_jordan_*`` registry (bucket-labeled series), so a warm server is
scrapeable in Prometheus text format — ``tpu_jordan_compiles_total``
unchanged across requests IS the warm-path acceptance pin — while the
per-instance snapshot API keeps its exact shape.
"""

from __future__ import annotations

import threading

from ..obs import hwcost as _hwcost
from ..obs import metrics as _metrics
from ..obs.metrics import Reservoir

#: Latency samples kept per (bucket, phase); beyond this the OLDEST are
#: dropped (a serving process must not grow without bound).  Now the
#: shared ``obs.metrics`` reservoir bound.
MAX_LATENCY_SAMPLES = _metrics.MAX_RESERVOIR_SAMPLES


def _percentiles(samples) -> dict:
    """p50/p95/p99 in milliseconds (3 decimals) — the serve snapshot's
    historical unit; the nearest-rank core is ``obs.metrics.percentiles``."""
    pct = _metrics.percentiles(samples)
    return {k: (None if v is None else round(v * 1e3, 3))
            for k, v in pct.items()}


# Process-wide registry series (ISSUE 4): every ServeStats mirrors into
# these bucket-labeled metrics.  tpu_jordan_compiles_total is THE shared
# compile counter (driver + solver models + serve executor cache).
_M_REQUESTS = _metrics.counter("tpu_jordan_serve_requests_total",
                               "requests admitted to the serve queue")
_M_REJECTED = _metrics.counter("tpu_jordan_serve_rejected_total",
                               "requests rejected by bounded-queue "
                               "admission (typed backpressure)")
_M_BATCHES = _metrics.counter("tpu_jordan_serve_batches_total",
                              "micro-batches dispatched")
_M_COMPILES = _metrics.counter(
    "tpu_jordan_compiles_total",
    "executable compiles (solve driver, solver models, serve "
    "executor cache)")
_M_CACHE_HITS = _metrics.counter(
    "tpu_jordan_serve_executor_cache_hits_total",
    "serve dispatches satisfied by an already-compiled bucket "
    "executable")
_M_SINGULAR = _metrics.counter("tpu_jordan_singular_total",
                               "solves/requests flagged singular")
_M_OCCUPANCY = _metrics.histogram(
    "tpu_jordan_serve_batch_occupancy",
    "occupied slots per dispatched batch (cap = batch_cap)")
_M_QUEUE_S = _metrics.histogram("tpu_jordan_serve_queue_seconds",
                                "per-request queue wait (submit to "
                                "dispatch)")
_M_EXEC_S = _metrics.histogram("tpu_jordan_serve_execute_seconds",
                               "per-batch executable wall seconds")


class _BucketStats:
    """Counters for one shape bucket (all mutation under the owner's
    lock — this class itself is not thread-safe on purpose)."""

    def __init__(self, workload: str = "invert",
                 mesh: str = "single"):
        self.workload = workload
        self.mesh = mesh
        self.requests = 0
        self.rejected = 0
        self.batches = 0
        self.elements = 0          # occupied slots over all batches
        self.compiles = 0
        self.cache_hits = 0
        self.singular = 0
        self.queue_s = Reservoir(MAX_LATENCY_SAMPLES)
        self.exec_s = Reservoir(MAX_LATENCY_SAMPLES)
        self.executable = None     # hwcost.ExecutableCost json (ISSUE 10)

    def to_json(self) -> dict:
        occ = (self.elements / self.batches) if self.batches else 0.0
        doc = {
            "workload": self.workload,
            "mesh": self.mesh,
            "requests": self.requests,
            "rejected": self.rejected,
            "batches": self.batches,
            "mean_occupancy": round(occ, 3),
            "compiles": self.compiles,
            "cache_hits": self.cache_hits,
            "singular": self.singular,
            "queue_ms": _percentiles(self.queue_s.samples),
            "execute_ms": _percentiles(self.exec_s.samples),
        }
        if self.executable is not None:
            doc["executable"] = self.executable
        return doc


class ServeStats:
    """Thread-safe serving scoreboard, keyed by bucket n.  Mutations
    mirror into the process-wide ``obs.metrics.REGISTRY`` with a
    ``bucket`` label; ``snapshot()`` stays per-instance.

    ``labels`` (ISSUE 7): extra labels stamped on every mirrored series
    — a fleet replica passes ``{"replica": <slot>}`` so the one
    process-wide registry aggregates the whole pool while each series
    stays attributable to its replica (fleet-level Prometheus
    aggregation over the PR 4 exporters, docs/FLEET.md)."""

    #: Label keys that collide with the mirror calls — ones ServeStats
    #: stamps itself ("bucket"/"component") or that bind to the metric
    #: APIs' own parameters (``value`` on ``Counter.inc``/``Gauge.set``/
    #: ``Histogram.observe``; ``exemplar`` on ``Counter.inc``, ISSUE 8).
    #: Any of these as a user label would raise TypeError — or silently
    #: bind to the parameter instead of becoming a label series — deep
    #: in the request path, so refuse up front typed.
    RESERVED_LABELS = frozenset({"bucket", "component", "value",
                                 "exemplar", "mesh"})

    def __init__(self, labels: dict | None = None):
        self._lock = threading.Lock()
        self._labels = {str(k): str(v) for k, v in (labels or {}).items()}
        clash = self.RESERVED_LABELS & set(self._labels)
        if clash:
            from ..driver import UsageError
            raise UsageError(
                f"reserved metric label(s) {sorted(clash)} — these are "
                f"stamped by ServeStats itself; pick different names")
        self._buckets: dict[int, _BucketStats] = {}

    @staticmethod
    def _split_mesh(bucket) -> tuple:
        """Split a lane key's mesh axis (ISSUE 18): ``"4096@2x4"`` →
        ``("4096", "2x4")``; every single-device lane (bare int bucket
        or ``solve:<n>:k<k>`` string) → ``(key, "single")``.  The mesh
        becomes its OWN Prometheus label so distinct topologies of one
        bucket never alias onto one ``bucket=...`` series — and the
        single-device series stay byte-identical (no new label)."""
        s = str(bucket)
        if "@" in s:
            base, _, mesh = s.rpartition("@")
            return base, mesh
        return bucket, "single"

    def _b(self, bucket, workload: str = "invert") -> _BucketStats:
        _, mesh = self._split_mesh(bucket)
        return self._buckets.setdefault(bucket,
                                        _BucketStats(workload, mesh))

    def _wl(self, workload: str) -> dict:
        """Mirror labels for a mutation: invert lanes keep their
        historical label set byte-identical; solve lanes (ISSUE 11)
        add a ``workload`` label so one Prometheus scrape splits
        traffic per workload."""
        if workload == "invert":
            return self._labels
        return dict(self._labels, workload=workload)

    def _mirror(self, bucket, workload: str | None = None) -> dict:
        """The full mirror label set for one mutation: the instance
        labels, the de-aliased ``bucket``, ``workload`` off the invert
        default, and ``mesh`` off the single-device default."""
        base, mesh = self._split_mesh(bucket)
        labels = (self._labels if workload in (None, "invert")
                  else dict(self._labels, workload=workload))
        if mesh != "single":
            labels = dict(labels, mesh=mesh)
        return dict(labels, bucket=base)

    def request(self, bucket, workload: str = "invert") -> None:
        with self._lock:
            self._b(bucket, workload).requests += 1
        _M_REQUESTS.inc(**self._mirror(bucket, workload))

    def rejected(self, bucket, workload: str = "invert") -> None:
        with self._lock:
            self._b(bucket, workload).rejected += 1
        _M_REJECTED.inc(**self._mirror(bucket, workload))

    def compile(self, bucket, workload: str = "invert") -> None:
        with self._lock:
            self._b(bucket, workload).compiles += 1
        _M_COMPILES.inc(component="serve", **self._mirror(bucket))

    def cache_hit(self, bucket, workload: str = "invert") -> None:
        with self._lock:
            self._b(bucket, workload).cache_hits += 1
        _M_CACHE_HITS.inc(**self._mirror(bucket))

    def executable_cost(self, bucket, cost) -> None:
        """Record a bucket executable's XLA accounting (ISSUE 10
        hwcost): the snapshot's per-bucket ``executable`` block and
        the ``tpu_jordan_executable_*`` gauges — read once at compile
        time, zero per-request cost.  Unavailable analysis records
        nothing (absent, never zeroed)."""
        if cost is None or not cost.available:
            return
        with self._lock:
            self._b(bucket).executable = cost.to_json()
        base, mesh = self._split_mesh(bucket)
        labels = (self._labels if mesh == "single"
                  else dict(self._labels, mesh=mesh))
        _hwcost.observe_cost(cost, bucket=base, **labels)

    def batch(self, bucket, occupancy: int, exec_seconds: float,
              queue_seconds, singular: int = 0,
              workload: str = "invert") -> None:
        """One dispatched batch: ``occupancy`` occupied slots,
        ``queue_seconds`` an iterable of per-request queue waits."""
        queue_seconds = [float(q) for q in queue_seconds]
        with self._lock:
            b = self._b(bucket, workload)
            b.batches += 1
            b.elements += occupancy
            b.singular += singular
            b.exec_s.add(float(exec_seconds))
            b.queue_s.extend(queue_seconds)
        lab = self._mirror(bucket)
        _M_BATCHES.inc(**self._mirror(bucket, workload))
        _M_OCCUPANCY.observe(occupancy, **lab)
        _M_EXEC_S.observe(float(exec_seconds), **lab)
        for q in queue_seconds:
            _M_QUEUE_S.observe(q, **lab)
        if singular:
            _M_SINGULAR.inc(singular, component="serve", **lab)
        # Live-bytes device watermark (ISSUE 10, re-based by ISSUE 13):
        # the process-wide sticky probe — a backend whose FIRST probe
        # reported no allocator stats (CPU) stays disabled forever (the
        # warm path pays one lock check), a supporting backend is
        # re-sampled every batch and every capacity/metrics snapshot.
        _hwcost.WATERMARK.sample(**self._labels)

    def snapshot(self) -> dict:
        with self._lock:
            # Lane keys may mix ints (invert buckets, the historical
            # shape) and "solve:<n>:k<k>" strings (ISSUE 11) — sort by
            # the string form so the snapshot stays deterministic.
            buckets = {str(k): v.to_json()
                       for k, v in sorted(self._buckets.items(),
                                          key=lambda kv: str(kv[0]))}
            # Instance-level execute-latency rollup (ISSUE 19): the
            # per-replica half of the cross-replica spread — all lanes'
            # recent execute samples pooled, so a fleet can compare
            # replicas without scraping Prometheus.
            exec_samples: list = []
            for v in self._buckets.values():
                exec_samples.extend(v.exec_s.samples)
        totals = {
            "requests": sum(b["requests"] for b in buckets.values()),
            "rejected": sum(b["rejected"] for b in buckets.values()),
            "batches": sum(b["batches"] for b in buckets.values()),
            "compiles": sum(b["compiles"] for b in buckets.values()),
            "singular": sum(b["singular"] for b in buckets.values()),
        }
        # Per-workload traffic rollup (ISSUE 11): the serve half of the
        # workload accounting story (the direct API's is
        # tpu_jordan_workload_requests_total).
        workloads: dict = {}
        for b in buckets.values():
            w = workloads.setdefault(b["workload"], {
                "requests": 0, "batches": 0, "singular": 0})
            w["requests"] += b["requests"]
            w["batches"] += b["batches"]
            w["singular"] += b["singular"]
        return {"buckets": buckets, "totals": totals,
                "workloads": workloads,
                "labels": dict(self._labels),
                "exec_ms": _percentiles(exec_samples)}


def cross_replica_spread(snapshots) -> dict:
    """Cross-replica execute-latency spread (ISSUE 19): given
    per-replica :meth:`ServeStats.snapshot` dicts, the max-over-min
    ratio of their pooled execute p99s — the fleet's MEASURED skew
    signal, readable straight off ``JordanFleet.stats()`` without
    scraping Prometheus.  Replica identity comes from each snapshot's
    ``labels["replica"]`` (the fleet stamps it at spawn), falling back
    to list position.  Fewer than two replicas with samples is an
    honest ``judged: False`` — never a fabricated spread.  Whether a
    high spread means a SICK replica is the work observatory's call
    (``obs/work.FleetSkewJudge`` normalizes by the analytical layout
    share first — docs/OBSERVABILITY.md)."""
    replicas = {}
    for i, snap in enumerate(snapshots):
        rep = str((snap.get("labels") or {}).get("replica", i))
        replicas[rep] = {
            "exec_ms": snap.get("exec_ms") or _percentiles(()),
            "batches": (snap.get("totals") or {}).get("batches", 0),
        }
    p99 = {r: d["exec_ms"].get("p99") for r, d in replicas.items()}
    live = {r: v for r, v in p99.items() if v}
    out: dict = {"replicas": replicas, "judged": len(live) >= 2,
                 "p99_spread": None, "max_replica": None,
                 "min_replica": None}
    if out["judged"]:
        mx = max(live, key=lambda r: live[r])
        mn = min(live, key=lambda r: live[r])
        out.update({"p99_spread": round(live[mx] / live[mn], 4),
                    "max_replica": mx, "min_replica": mn})
    return out
