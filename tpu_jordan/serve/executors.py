"""Shape-bucketed AOT executable cache (ISSUE 3 tentpole part 1).

Serving many small solves at throughput means never paying trace/compile
on the request path: requests are rounded UP to power-of-two n-buckets
(``bucket_for``) — identity padding makes the rounding *exact*, not
approximate (``ops/padding.py``: the padded inverse is [[A⁻¹, 0], [0, I]]
and the pad blocks stay exactly zero through elimination) — and each
(bucket_n, batch_cap, dtype, engine) gets ONE executable, AOT-lowered
from ``ShapeDtypeStruct``s (no batch materialized to compile) and reused
for every batch ever dispatched to that bucket.

Engine choice is resolved through PR 2's autotuner ladder at a *batched*
tuning point (``TunePoint.create(..., batch=batch_cap)`` — plan-cache
keys grow a ``bN`` segment, ``tuning/plan_cache.plan_key``), so a warm
server performs ZERO plan-cache measurements and ZERO recompiles; both
are counter-pinned by ``tests/test_serve.py`` (``Tuner.measurements``
and the per-bucket ``compiles`` stat).

The compiled program does the whole per-batch job in one launch: invert
the padded stack through the batched engine machinery (``ops/batched``'s
dispatch — the dedicated small-n batch-first engine in its validated
regime), then assemble per-element accuracy (``driver.batch_metrics``,
row-masked to each element's real n) so the batcher can fan κ∞ and
rel_residual back to every request without a second device round trip.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..config import default_block_size
from ..resilience import faults as _faults
from ..resilience.policy import CircuitBreaker
from ..tuning.plan_cache import PlanCache, n_bucket
from ..tuning.registry import TunePoint
from ..tuning.tuner import Tuner

#: The smallest bucket served.  Sub-64 matrices still invert correctly
#: (identity-padded to 64); a finer ladder would multiply executables
#: for no measurable win — a 64² solve is launch-bound, not flop-bound.
MIN_BUCKET_N = 64


def bucket_for(n: int, floor: int = MIN_BUCKET_N) -> int:
    """Round a request size up to its serving bucket: next power of two
    (the same rounding as the plan cache's ``n_bucket``), floored at
    ``MIN_BUCKET_N``.  Exact by identity padding — a bucketed solve
    returns bit-identically the top-left n×n of the padded inverse."""
    if n <= 0:
        raise ValueError(f"matrix dimension must be positive, got {n}")
    return max(floor, n_bucket(n))


@dataclass(frozen=True)
class ExecutorKey:
    """The executable cache key — the coordinates a compiled serving
    program depends on (ISSUE 3 tentpole): shape bucket, batch capacity,
    dtype, the RESOLVED engine (never "auto"), and the pivot block size
    (part of the key so a direct cache user requesting a different m
    can never be handed a stale-m executable from a cache hit)."""

    bucket_n: int
    batch_cap: int
    dtype: str
    engine: str
    block_size: int


class BucketExecutor:
    """One AOT-compiled batched-inversion executable for one bucket.

    ``run(stacked, n_real)`` takes the identity-padded
    (batch_cap, N, N) stack plus the per-element real sizes (0 for
    identity filler slots of a partial batch) and returns numpy-ready
    device arrays: (inverses, singular_flags, kappa, rel_residual).
    """

    def __init__(self, key: ExecutorKey, plan):
        self.key = key
        self.block_size = key.block_size
        self.plan = plan          # tuning.Plan (None for explicit engines)
        self._compiled = self._build()

    def _build(self):
        _faults.fire("compile")
        from ..driver import batch_metrics
        from ..ops import batched_jordan_invert
        from ..ops.jordan import block_jordan_invert
        from ..ops.jordan_inplace import (
            block_jordan_invert_inplace_grouped_fori,
        )

        key = self.key
        m = key.block_size
        if key.engine in ("inplace", "auto"):
            # The batched dispatch (ops/batched.py): the dedicated
            # batch-first small-n engine in its validated regime
            # (Nr <= 4, B >= 32), the vmapped/fori routes otherwise.
            def invert(a):
                return batched_jordan_invert(a, block_size=m)
        elif key.engine == "grouped":
            grouped = block_jordan_invert_inplace_grouped_fori

            def invert(a):
                return jax.vmap(lambda x: grouped(
                    x, block_size=m, group=2))(a)
        elif key.engine == "augmented":
            def invert(a):
                return jax.vmap(lambda x: block_jordan_invert(
                    x, block_size=m))(a)
        else:
            from ..driver import UsageError

            raise UsageError(
                f"engine {key.engine!r} is not servable on a single "
                f"device (the service batches on one chip; distributed "
                f"engines need workers > 1)")

        def fn(a, n_real):
            inv, sing = invert(a)
            met = batch_metrics(a, inv, n_real)
            return inv, sing, met["kappa"], met["rel_residual"]

        dtype = jnp.dtype(key.dtype)
        shape = (key.batch_cap, key.bucket_n, key.bucket_n)
        return jax.jit(fn).lower(
            jax.ShapeDtypeStruct(shape, dtype),
            jax.ShapeDtypeStruct((key.batch_cap,), jnp.int32),
        ).compile()

    def run(self, stacked, n_real):
        return self._compiled(stacked, n_real)


class ExecutorCache:
    """The service's executable store: ``get()`` compiles at most once
    per key (lock-held; ``compiles``/``cache_hits`` counted per bucket
    in ``ServeStats``) and resolves the engine through the PR 2 tuner
    ladder — plan cache first, registry cost ranking otherwise — at a
    batched tuning point.  ``measurements`` (the tuner's counter) stays
    0 for the service's cost-only ladder; the acceptance test pins it.
    """

    def __init__(self, engine: str = "auto", plan_cache: str | None = None,
                 dtype=jnp.float32, stats=None, telemetry=None,
                 policy=None, breaker_clock=None):
        from ..driver import resolve_engine
        from ..obs.spans import NULL

        # Shared flag contract with solve/JordanSolver: "auto" stays
        # auto (resolved per bucket through the tuner), an explicit
        # engine is validated once here.
        self.engine, self.group = resolve_engine(engine, 0)
        self.dtype = jnp.dtype(dtype).name
        self.stats = stats
        # Resilience (ISSUE 5): the policy drives compile-retry here and
        # sizes the per-bucket circuit breakers the batcher consults
        # (K consecutive terminal executor failures open a bucket;
        # half-open probe after the cooldown).  ``breaker_clock`` is the
        # injectable monotonic clock (tests fake the cooldown).
        self.policy = policy
        self._breaker_clock = breaker_clock
        self._breakers: dict[int, CircuitBreaker] = {}
        # Telemetry (ISSUE 4): compiles are recorded as distinct
        # "compile" spans, so a warm server's trace has NONE — the
        # AOT-cache contract made visible.
        self._tel = telemetry if telemetry is not None else NULL
        self._lock = threading.Lock()
        self._executors: dict[ExecutorKey, BucketExecutor] = {}
        #: memoized (engine, plan) per (bucket_n, batch_cap, block_size):
        #: resolution cannot change for the life of the cache, so the
        #: hot dispatch path never re-walks the tuner ladder.
        self._resolved: dict[tuple, tuple] = {}
        cache = PlanCache.load(plan_cache) if plan_cache else None
        self.tuner = Tuner(cache=cache)

    def breaker(self, bucket_n: int) -> CircuitBreaker | None:
        """The bucket's circuit breaker (created on demand; None when no
        policy is attached — resilience off, nothing to trip)."""
        if self.policy is None:
            return None
        with self._lock:
            br = self._breakers.get(bucket_n)
            if br is None:
                br = self._breakers[bucket_n] = CircuitBreaker(
                    failures=self.policy.breaker_failures,
                    cooldown_s=self.policy.breaker_cooldown_s,
                    clock=self._breaker_clock,
                    name=f"serve_bucket_{bucket_n}")
            return br

    def breaker_states(self) -> dict[int, str]:
        with self._lock:
            return {b: br.state for b, br in self._breakers.items()}

    @property
    def measurements(self) -> int:
        """Plan-cache measurement counter (the warm-server pin)."""
        return self.tuner.measurements

    def _resolve(self, bucket_n: int, batch_cap: int, block_size: int):
        """(engine, plan) for one bucket: the tuner ladder for "auto"
        (batched plan-cache key — zero measurements on the cost-only
        ladder, counter-pinned), the explicit engine otherwise."""
        if self.engine != "auto":
            return self.engine, None
        point = TunePoint.create(bucket_n, block_size, self.dtype,
                                 workers=1, gather=True, batch=batch_cap)
        plan = self.tuner.select(point)
        return plan.engine, plan

    def get(self, bucket_n: int, batch_cap: int,
            block_size: int | None = None) -> BucketExecutor:
        """The executor for a bucket — compiled on first use, a cache
        hit forever after (ISSUE 3: a warm server performs zero
        recompiles; the per-bucket ``compiles`` counter is the pin)."""
        m = min(block_size if block_size is not None
                else default_block_size(bucket_n), bucket_n)
        with self._lock:
            rkey = (bucket_n, batch_cap, m)
            if rkey not in self._resolved:
                self._resolved[rkey] = self._resolve(bucket_n, batch_cap, m)
            engine, plan = self._resolved[rkey]
            key = ExecutorKey(bucket_n, batch_cap, self.dtype, engine, m)
            ex = self._executors.get(key)
            if ex is not None:
                if self.stats is not None:
                    self.stats.cache_hit(bucket_n)
                return ex
            with self._tel.span("compile", bucket=bucket_n,
                                engine=engine, batch_cap=batch_cap):
                # Transient compile failures (the remote-compile class,
                # or the `compile` fault point) are retried per the
                # policy; a terminal failure propagates to the caller
                # (the dispatcher fans it to the batch's riders).
                def build():
                    return BucketExecutor(key, plan)
                ex = (self.policy.retry.call(build,
                                             component="serve.compile")
                      if self.policy is not None else build())
            self._executors[key] = ex
            if self.stats is not None:
                self.stats.compile(bucket_n)
            return ex

    def keys(self):
        with self._lock:
            return list(self._executors)

    def entries(self):
        with self._lock:
            return list(self._executors.items())
