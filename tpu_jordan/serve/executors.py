"""Shape-bucketed AOT executable cache (ISSUE 3 tentpole part 1).

Serving many small solves at throughput means never paying trace/compile
on the request path: requests are rounded UP to power-of-two n-buckets
(``bucket_for``) — identity padding makes the rounding *exact*, not
approximate (``ops/padding.py``: the padded inverse is [[A⁻¹, 0], [0, I]]
and the pad blocks stay exactly zero through elimination) — and each
(bucket_n, batch_cap, dtype, engine) gets ONE executable, AOT-lowered
from ``ShapeDtypeStruct``s (no batch materialized to compile) and reused
for every batch ever dispatched to that bucket.

Engine choice is resolved through PR 2's autotuner ladder at a *batched*
tuning point (``TunePoint.create(..., batch=batch_cap)`` — plan-cache
keys grow a ``bN`` segment, ``tuning/plan_cache.plan_key``), so a warm
server performs ZERO plan-cache measurements and ZERO recompiles; both
are counter-pinned by ``tests/test_serve.py`` (``Tuner.measurements``
and the per-bucket ``compiles`` stat).

The compiled program does the whole per-batch job in one launch: invert
the padded stack through the batched engine machinery (``ops/batched``'s
dispatch — the dedicated small-n batch-first engine in its validated
regime), then assemble per-element accuracy (``driver.batch_metrics``,
row-masked to each element's real n) so the batcher can fan κ∞ and
rel_residual back to every request without a second device round trip.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..config import default_block_size
from ..resilience import faults as _faults
from ..resilience.policy import CircuitBreaker
from ..tuning.plan_cache import PlanCache, n_bucket
from ..tuning.registry import TunePoint
from ..tuning.tuner import Tuner

#: The smallest bucket served.  Sub-64 matrices still invert correctly
#: (identity-padded to 64); a finer ladder would multiply executables
#: for no measurable win — a 64² solve is launch-bound, not flop-bound.
MIN_BUCKET_N = 64


def bucket_for(n: int, floor: int = MIN_BUCKET_N) -> int:
    """Round a request size up to its serving bucket: next power of two
    (the same rounding as the plan cache's ``n_bucket``), floored at
    ``MIN_BUCKET_N``.  Exact by identity padding — a bucketed solve
    returns bit-identically the top-left n×n of the padded inverse."""
    if n <= 0:
        raise ValueError(f"matrix dimension must be positive, got {n}")
    return max(floor, n_bucket(n))


def rhs_bucket_for(k: int) -> int:
    """Round a solve request's RHS width up to its lane bucket: next
    power of two, floor 1 (ISSUE 11) — the ONE rounding both
    ``JordanService.submit`` and ``warmup(solve_shapes=)`` use, so a
    warmed lane is always the lane a request lands on.  Exact by zero
    padding: pad columns solve to exactly zero and are sliced off."""
    if k <= 0:
        raise ValueError(f"rhs width must be positive, got {k}")
    return 1 << max(0, int(k - 1).bit_length())


#: The smallest update-lane rank bucket (ISSUE 12): sub-8 mutations
#: still update correctly (zero-padded to 8); a finer ladder would
#: multiply (bucket_n, k_bucket) executables for launch-bound work.
MIN_UPDATE_K = 8


def k_bucket_for(k: int, floor: int = MIN_UPDATE_K) -> int:
    """Round an update's rank up to its lane bucket: the SAME
    power-of-two rounding as the solve lanes (``rhs_bucket_for`` — one
    rounding recipe, never two that can drift), floored at
    ``MIN_UPDATE_K``.  Exact by zero padding (``linalg/update.py``:
    zero U/V columns contribute nothing to U·Vᵀ and make the
    capacitance pad block the identity)."""
    if k <= 0:
        raise ValueError(f"update rank must be positive, got {k}")
    return max(floor, rhs_bucket_for(k))


@dataclass(frozen=True)
class ExecutorKey:
    """The executable cache key — the coordinates a compiled serving
    program depends on (ISSUE 3 tentpole): shape bucket, batch capacity,
    dtype, the RESOLVED engine (never "auto"), and the pivot block size
    (part of the key so a direct cache user requesting a different m
    can never be handed a stale-m executable from a cache hit).

    ``workload``/``rhs`` (ISSUE 11): solve lanes compile their own
    executables per (workload, bucket_n, dtype, rhs-bucket) — an invert
    key keeps the historical defaults, so every pre-existing key (and
    the fleet's shared-store sharing semantics) is unchanged.

    ``mesh`` (ISSUE 18): the lane's topology axis — ``"single"`` for
    every single-device lane (the default, so every pre-existing key is
    byte-identical), or a ``TunePoint.topology`` label (``"p8"``,
    ``"2x4"``) selecting a distributed mesh-backed lane
    (``serve/meshlanes.py``).  Distinct topologies of the same bucket
    are distinct executables, distinct stats rows, and distinct
    capacity entries — never aliased."""

    bucket_n: int
    batch_cap: int
    dtype: str
    engine: str
    block_size: int
    workload: str = "invert"
    rhs: int = 0                  # RHS-width bucket (solve lanes only)
    mesh: str = "single"          # topology label (mesh lanes only)


def lane_label(workload: str, bucket_n: int, batch_cap: int,
               rhs: int = 0, mesh: str = "single") -> str:
    """The capacity-ledger detail label of one lane — workload, bucket,
    batch capacity, (solve/update) the k-bucket, and (mesh lanes) the
    topology.  The ``@mesh`` segment appears only off the single-device
    default, so every pre-existing label is byte-identical while
    distinct topologies of one bucket stop aliasing (ISSUE 18)."""
    base = f"{workload}:{bucket_n}:b{batch_cap}"
    if workload != "invert":
        base = f"{base}:k{rhs}"
    return base if mesh == "single" else f"{base}@{mesh}"


def projected_lane_bytes(bucket_n: int, batch_cap: int, dtype,
                         workload: str = "invert", rhs: int = 0,
                         devices: int = 1) -> int:
    """Projected argument + output bytes of a lane's AOT signature —
    computable BEFORE compiling (ISSUE 13: ``warmup``/
    ``project_capacity`` record this so operators see what a bucket
    costs to open *before* paying the compile).  Temps are
    compiler-known only: the post-compile ``memory_analysis`` footprint
    in the ``executor_lanes`` capacity ledger is the full number; this
    projection is its arg/out floor (exact on backends whose temp
    residency is zero, e.g. the CPU lanes the tests pin).

    ``devices`` (ISSUE 18) is the lane's mesh size: the O(n²) matrix
    terms divide by it (A and the inverse stay sharded — per-DEVICE
    residency is the admission signal), while the O(n·k) RHS/solution
    terms stay whole (X gathers; conservative).  ``devices=1`` is the
    historical projection byte-for-byte."""
    it = jnp.dtype(dtype).itemsize
    n2 = -(-bucket_n * bucket_n // max(1, int(devices)))
    cap, k = int(batch_cap), int(rhs)
    per_elem_out = 1 + 2 * it         # singular flag + kappa + rel
    if workload == "invert":
        args = cap * n2 * it + cap * 4
        outs = cap * n2 * it + cap * per_elem_out
    elif workload == "update":
        # Scales with the batch dimension (ISSUE 17): the batched
        # update lane stacks cap (A, A⁻¹, U, V) quadruples per launch;
        # cap == 1 reproduces the historical unbatched projection
        # byte-for-byte.
        args = cap * (2 * n2 + 2 * bucket_n * k) * it + cap * 4
        outs = cap * 2 * n2 * it + cap * per_elem_out
    else:                             # solve lanes
        args = cap * n2 * it + cap * bucket_n * k * it + cap * 4
        outs = cap * bucket_n * k * it + cap * per_elem_out
    return int(args + outs)


class BucketExecutor:
    """One AOT-compiled batched-inversion executable for one bucket.

    ``run(stacked, n_real)`` takes the identity-padded
    (batch_cap, N, N) stack plus the per-element real sizes (0 for
    identity filler slots of a partial batch) and returns numpy-ready
    device arrays: (inverses, singular_flags, kappa, rel_residual).
    """

    def __init__(self, key: ExecutorKey, plan):
        self.key = key
        self.block_size = key.block_size
        self.plan = plan          # tuning.Plan (None for explicit engines)
        self._compiled = self._build()
        # XLA's own per-executable accounting (ISSUE 10 hwcost), read
        # ONCE at compile time — flops/bytes/HBM footprint for the
        # whole batched launch; zero per-dispatch cost.
        from ..obs import hwcost as _hwcost

        self.cost = _hwcost.executable_cost(self._compiled)

    def _build(self):
        _faults.fire("compile")
        from ..driver import batch_metrics
        from ..ops import batched_jordan_invert
        from ..ops.jordan import block_jordan_invert
        from ..ops.jordan_inplace import (
            block_jordan_invert_inplace_grouped_fori,
        )

        key = self.key
        m = key.block_size
        if key.workload == "update":
            return self._build_update()
        if key.workload != "invert":
            return self._build_solve()
        if jnp.dtype(key.dtype).kind == "c":
            from ..driver import UsageError

            raise UsageError(
                "complex dtypes are served on the solve lanes "
                "(submit(a, b) — linalg.block_jordan_solve is "
                "complex-native); the batched invert engines are "
                "real-dtype")
        if key.engine in ("inplace", "auto"):
            # The batched dispatch (ops/batched.py): the dedicated
            # batch-first small-n engine in its validated regime
            # (Nr <= 4, B >= 32), the vmapped/fori routes otherwise.
            def invert(a):
                return batched_jordan_invert(a, block_size=m)
        elif key.engine == "grouped":
            grouped = block_jordan_invert_inplace_grouped_fori

            def invert(a):
                return jax.vmap(lambda x: grouped(
                    x, block_size=m, group=2))(a)
        elif key.engine == "augmented":
            def invert(a):
                return jax.vmap(lambda x: block_jordan_invert(
                    x, block_size=m))(a)
        else:
            from ..driver import UsageError

            raise UsageError(
                f"engine {key.engine!r} is not servable on a single "
                f"device (the service batches on one chip; distributed "
                f"engines need workers > 1)")

        def fn(a, n_real):
            inv, sing = invert(a)
            met = batch_metrics(a, inv, n_real)
            return inv, sing, met["kappa"], met["rel_residual"]

        dtype = jnp.dtype(key.dtype)
        shape = (key.batch_cap, key.bucket_n, key.bucket_n)
        return jax.jit(fn).lower(
            jax.ShapeDtypeStruct(shape, dtype),
            jax.ShapeDtypeStruct((key.batch_cap,), jnp.int32),
        ).compile()

    def _build_solve(self):
        """The solve-lane executable (ISSUE 11): one vmapped
        ``linalg.block_jordan_solve`` over the identity-padded A stack
        and the zero-padded B stack, with the per-element ‖A·X − B‖
        accuracy assembly (``linalg.solve_batch_metrics``) in the same
        launch — the exact shape of the invert build, solve semantics."""
        from ..linalg.engine import block_jordan_solve, solve_batch_metrics

        key = self.key
        m = key.block_size
        spd = key.engine == "solve_spd"
        if key.engine not in ("solve_aug", "solve_spd"):
            from ..driver import UsageError

            raise UsageError(
                f"engine {key.engine!r} is not a solve-lane engine "
                f"(solve_aug/solve_spd)")

        def fn(a, b, n_real):
            x, sing = jax.vmap(
                lambda aa, bb: block_jordan_solve(aa, bb, block_size=m,
                                                  spd=spd))(a, b)
            met = solve_batch_metrics(a, x, b, n_real)
            return x, sing, met["kappa_est"], met["rel_residual"]

        dtype = jnp.dtype(key.dtype)
        cap, N, K = key.batch_cap, key.bucket_n, key.rhs
        return jax.jit(fn).lower(
            jax.ShapeDtypeStruct((cap, N, N), dtype),
            jax.ShapeDtypeStruct((cap, N, K), dtype),
            jax.ShapeDtypeStruct((cap,), jnp.int32),
        ).compile()

    def _build_update(self):
        """The update-lane executable (ISSUE 12, batched in ISSUE 17):
        Sherman–Morrison–Woodbury rank-k applications — mutate A, update
        the resident inverse, and re-verify against the MUTATED matrix
        in the same compiled program (``linalg.update.
        smw_update_with_metrics``).

        ``batch_cap == 1`` keeps the historical one-application-per-
        launch signature (``(N,N),(N,N),(N,K),(N,K),(1,)``) unchanged —
        same lowered program, same cost_analysis FLOPs pin below the
        fresh-invert executable's.  ``batch_cap > 1`` vmaps the SAME
        kernel over a leading batch axis, like the invert micro-batches:
        each element carries its own (A, A⁻¹, U, V, n_real) and comes
        back with per-element singular/kappa/rel flags — in-launch
        re-verification per element, so a partial batch's inert filler
        slots (identity A/A⁻¹, zero U/V, n_real = 0) never pollute a
        real element's gate judgment."""
        from ..linalg.update import smw_update_with_metrics

        key = self.key
        if key.engine != "smw_update":
            from ..driver import UsageError

            raise UsageError(
                f"engine {key.engine!r} is not an update-lane engine "
                f"(smw_update is the one registered update engine)")

        dtype = jnp.dtype(key.dtype)
        cap, N, K = key.batch_cap, key.bucket_n, key.rhs
        if cap == 1:
            def fn(a, inv, u, v, n_real):
                return smw_update_with_metrics(a, inv, u, v,
                                               n_real=n_real)

            return jax.jit(fn).lower(
                jax.ShapeDtypeStruct((N, N), dtype),
                jax.ShapeDtypeStruct((N, N), dtype),
                jax.ShapeDtypeStruct((N, K), dtype),
                jax.ShapeDtypeStruct((N, K), dtype),
                jax.ShapeDtypeStruct((1,), jnp.int32),
            ).compile()

        def fn(a, inv, u, v, n_real):
            return jax.vmap(
                lambda aa, ii, uu, vv, nr: smw_update_with_metrics(
                    aa, ii, uu, vv, n_real=nr))(a, inv, u, v, n_real)

        return jax.jit(fn).lower(
            jax.ShapeDtypeStruct((cap, N, N), dtype),
            jax.ShapeDtypeStruct((cap, N, N), dtype),
            jax.ShapeDtypeStruct((cap, N, K), dtype),
            jax.ShapeDtypeStruct((cap, N, K), dtype),
            jax.ShapeDtypeStruct((cap, 1), jnp.int32),
        ).compile()

    def run(self, *args):
        """Invert lanes: ``run(stacked, n_real)``; solve lanes:
        ``run(stacked_a, stacked_b, n_real)``; update lanes:
        ``run(a, inv, u, v, n_real)`` — the lane's compiled signature
        either way."""
        return self._compiled(*args)


class ExecutorStore:
    """A thread-safe fleet-wide home for compiled :class:`BucketExecutor`
    objects (ISSUE 7 tentpole).  Each :class:`ExecutorCache` owns a
    private store by default (the single-service behavior, unchanged);
    a fleet passes ONE store to every replica's cache so an executable
    is compiled at most once per key across the whole replica pool —
    this is what makes a warm rolling restart free: the replacement
    replica's ``warmup()`` finds every executable already built and
    performs ZERO compiles (``tpu_jordan_compiles_total`` delta == 0,
    the acceptance pin).  Compiled executables are stateless to call
    (jax AOT programs), so concurrent replicas share them safely.

    ``get_or_build`` serializes builds on a PER-KEY lock — exactly one
    compile per key, never a thundering herd of replicas compiling the
    same bucket — while builds for *different* keys proceed
    concurrently: one replica's slow or retrying compile must not
    stall every other replica's cold bucket (or the supervisor's
    warm-replacement warmup) behind a single store-wide lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._executors: dict[ExecutorKey, BucketExecutor] = {}
        self._building: dict[ExecutorKey, threading.Lock] = {}

    def get_or_build(self, key: ExecutorKey, build):
        """Return ``(executor, built)``: the stored executor for ``key``
        (``built=False``), or the result of ``build()`` installed under
        the key's build lock (``built=True``).  A failed ``build()``
        leaves nothing installed — the next caller for the key retries."""
        with self._lock:
            ex = self._executors.get(key)
            if ex is not None:
                return ex, False
            key_lock = self._building.setdefault(key, threading.Lock())
        with key_lock:
            with self._lock:
                ex = self._executors.get(key)
                if ex is not None:      # a racing builder won
                    return ex, False
            ex = build()
            with self._lock:
                self._executors[key] = ex
            self._meter(key, ex)
            return ex, True

    def _meter(self, key: ExecutorKey, ex) -> None:
        """Capacity metering (ISSUE 13): one ``executor_lanes`` ledger
        entry per compiled executable — its ``memory_analysis``
        arg/out/temp HBM footprint, or the arg+out projection where
        the backend exposes no analysis (labeled ``projected``, never
        silently modeled as the compiler's number).  Executables are
        never dropped, so this class only grows — honest: compiled
        lanes ARE permanent residency."""
        from ..obs import capacity as _capacity

        nbytes = ex.cost.hbm_bytes if ex.cost.available else None
        source = "memory_analysis"
        if nbytes is None:
            devices = 1
            if key.mesh != "single":
                from .meshlanes import mesh_devices, parse_mesh

                devices = mesh_devices(parse_mesh(key.mesh))
            nbytes = projected_lane_bytes(key.bucket_n, key.batch_cap,
                                          key.dtype, key.workload,
                                          key.rhs, devices=devices)
            source = "projected"
        label = lane_label(key.workload, key.bucket_n, key.batch_cap,
                           key.rhs, key.mesh)
        _capacity.register("executor_lanes", (id(self), key), nbytes,
                           detail=f"{label}:{source}")

    def keys(self):
        with self._lock:
            return list(self._executors)

    def entries(self):
        """[(key, executor)] snapshot — the fleet demo's hwcost block
        reads each compiled executable's XLA accounting off this."""
        with self._lock:
            return list(self._executors.items())

    def __len__(self) -> int:
        with self._lock:
            return len(self._executors)


class ExecutorCache:
    """The service's executable store: ``get()`` compiles at most once
    per key (lock-held; ``compiles``/``cache_hits`` counted per bucket
    in ``ServeStats``) and resolves the engine through the PR 2 tuner
    ladder — plan cache first, registry cost ranking otherwise — at a
    batched tuning point.  ``measurements`` (the tuner's counter) stays
    0 for the service's cost-only ladder; the acceptance test pins it.

    ``store`` (ISSUE 7): an optional fleet-shared :class:`ExecutorStore`
    holding the compiled executables; None (the default) keeps a
    private store — byte-identical single-service behavior.  Breakers,
    stats, and plan resolution stay PER CACHE either way (a fleet
    replica's per-bucket breaker is its own health signal; only the
    immutable compiled programs are shared).  ``plan_cache_read_only``
    opens the plan-cache path frozen (the fleet's shared pre-tuned
    plans — ``tuning/plan_cache.py``)."""

    def __init__(self, engine: str = "auto", plan_cache: str | None = None,
                 dtype=jnp.float32, stats=None, telemetry=None,
                 policy=None, breaker_clock=None,
                 store: ExecutorStore | None = None,
                 plan_cache_read_only: bool = False):
        from ..driver import resolve_engine
        from ..obs.spans import NULL

        # Shared flag contract with solve/JordanSolver: "auto" stays
        # auto (resolved per bucket through the tuner), an explicit
        # engine is validated once here.
        self.engine, self.group = resolve_engine(engine, 0)
        self.dtype = jnp.dtype(dtype).name
        self.stats = stats
        # Resilience (ISSUE 5): the policy drives compile-retry here and
        # sizes the per-bucket circuit breakers the batcher consults
        # (K consecutive terminal executor failures open a bucket;
        # half-open probe after the cooldown).  ``breaker_clock`` is the
        # injectable monotonic clock (tests fake the cooldown).
        self.policy = policy
        self._breaker_clock = breaker_clock
        self._breakers: dict[int, CircuitBreaker] = {}
        # Telemetry (ISSUE 4): compiles are recorded as distinct
        # "compile" spans, so a warm server's trace has NONE — the
        # AOT-cache contract made visible.
        self._tel = telemetry if telemetry is not None else NULL
        self._lock = threading.Lock()
        self._store = store if store is not None else ExecutorStore()
        #: this cache's own view of the executables it resolved — what
        #: ``entries()``/``stats()`` report per replica even when the
        #: compiled programs live in a fleet-shared store.
        self._executors: dict[ExecutorKey, BucketExecutor] = {}
        #: memoized (engine, plan) per (bucket_n, batch_cap, block_size):
        #: resolution cannot change for the life of the cache, so the
        #: hot dispatch path never re-walks the tuner ladder.
        self._resolved: dict[tuple, tuple] = {}
        # ``plan_cache`` may be a pre-loaded PlanCache instance (the
        # fleet loads the shared read-only file ONCE and hands every
        # replica — and every warm replacement — the same frozen
        # object, the plan analogue of the shared ExecutorStore) or a
        # path to load here (the single-service behavior).
        if isinstance(plan_cache, PlanCache):
            cache = plan_cache
        else:
            cache = (PlanCache.load(plan_cache,
                                    read_only=plan_cache_read_only)
                     if plan_cache else None)
        self.tuner = Tuner(cache=cache)

    def breaker(self, bucket_n: int) -> CircuitBreaker | None:
        """The bucket's circuit breaker (created on demand; None when no
        policy is attached — resilience off, nothing to trip)."""
        if self.policy is None:
            return None
        with self._lock:
            br = self._breakers.get(bucket_n)
            if br is None:
                br = self._breakers[bucket_n] = CircuitBreaker(
                    failures=self.policy.breaker_failures,
                    cooldown_s=self.policy.breaker_cooldown_s,
                    clock=self._breaker_clock,
                    name=f"serve_bucket_{bucket_n}")
            return br

    def breaker_states(self) -> dict[int, str]:
        with self._lock:
            return {b: br.state for b, br in self._breakers.items()}

    @property
    def measurements(self) -> int:
        """Plan-cache measurement counter (the warm-server pin)."""
        return self.tuner.measurements

    def _resolve(self, bucket_n: int, batch_cap: int, block_size: int,
                 workload: str = "invert", mesh: str = "single"):
        """(engine, plan) for one bucket: the tuner ladder for "auto"
        (batched, workload-scoped plan-cache key — zero measurements on
        the cost-only ladder, counter-pinned), the explicit engine
        otherwise.  A service built with an explicit INVERT engine
        still resolves its solve lanes through the ladder — the invert
        zoo is not a solve vocabulary (tuning/registry.py).

        Mesh lanes (ISSUE 18) ALWAYS resolve through the ladder at a
        distributed point — the plan-cache key's topology segment keys
        them apart from the single-device lanes for free — because an
        explicit single-device engine is not a distributed vocabulary
        either."""
        if mesh != "single":
            from .meshlanes import normalize_mesh

            point = TunePoint.create(bucket_n, block_size, self.dtype,
                                     workers=normalize_mesh(mesh),
                                     gather=True, batch=1,
                                     workload=workload)
            plan = self.tuner.select(point)
            return plan.engine, plan
        if self.engine != "auto" and workload == "invert":
            return self.engine, None
        point = TunePoint.create(bucket_n, block_size, self.dtype,
                                 workers=1, gather=True, batch=batch_cap,
                                 workload=workload)
        plan = self.tuner.select(point)
        return plan.engine, plan

    def get(self, bucket_n: int, batch_cap: int,
            block_size: int | None = None, workload: str = "invert",
            rhs: int = 0) -> BucketExecutor:
        """The executor for a bucket — compiled on first use, a cache
        hit forever after (ISSUE 3: a warm server performs zero
        recompiles; the per-bucket ``compiles`` counter is the pin)."""
        return self.get_info(bucket_n, batch_cap, block_size,
                             workload=workload, rhs=rhs)[0]

    def get_info(self, bucket_n: int, batch_cap: int,
                 block_size: int | None = None,
                 workload: str = "invert", rhs: int = 0,
                 mesh: str = "single"
                 ) -> tuple[BucketExecutor, str]:
        """``get`` plus HOW the executor was obtained — ``"cached"``
        (this cache's own view), ``"shared_store"`` (another replica
        compiled it), or ``"compiled"`` (this call built it).  The
        dispatcher stamps the source on each rider's journey (ISSUE 8:
        compile-vs-cache-hit is a per-request fact, not just a
        counter).  ``workload``/``rhs`` select a solve lane (ISSUE 11);
        ``mesh`` a distributed mesh-backed lane (ISSUE 18 — always
        ``batch_cap=1``, one sharded program per launch)."""
        if mesh != "single":
            batch_cap = 1
        m = min(block_size if block_size is not None
                else default_block_size(bucket_n), bucket_n)
        with self._lock:
            rkey = (bucket_n, batch_cap, m, workload, mesh)
            if rkey not in self._resolved:
                self._resolved[rkey] = self._resolve(bucket_n, batch_cap,
                                                     m, workload, mesh)
            engine, plan = self._resolved[rkey]
            key = ExecutorKey(bucket_n, batch_cap, self.dtype, engine, m,
                              workload, rhs, mesh)
            ex = self._executors.get(key)
        # Stats are keyed by the LANE label (ISSUE 11): invert lanes
        # keep the historical bare bucket int; solve lanes get their
        # own "solve:<bucket>:k<rhs>" row so a solve compile can never
        # masquerade as an invert bucket's.  Mesh lanes append the
        # topology (ISSUE 18) so distinct meshes of one bucket never
        # alias onto one row.
        label = (bucket_n if workload == "invert"
                 else f"{workload}:{bucket_n}:k{rhs}")
        if mesh != "single":
            label = f"{label}@{mesh}"
        if ex is not None:
            if self.stats is not None:
                self.stats.cache_hit(label, workload=workload)
            return ex, "cached"

        def build():
            # The compile span wraps the REAL build only — a
            # shared-store hit must not fake a compile in the trace
            # (the replacement replica's trace has zero compile
            # spans, the ISSUE 7 pin).  Transient compile failures
            # (the remote-compile class, or the `compile` fault
            # point) are retried per the policy; a terminal failure
            # propagates to the caller (the dispatcher fans it to
            # the batch's riders).
            with self._tel.span("compile", bucket=bucket_n,
                                engine=engine, batch_cap=batch_cap,
                                mesh=mesh):
                def one():
                    if mesh != "single":
                        from .meshlanes import MeshLaneExecutor

                        return MeshLaneExecutor(key, plan)
                    return BucketExecutor(key, plan)
                return (self.policy.retry.call(
                            one, component="serve.compile")
                        if self.policy is not None else one())

        # The wait on the store's per-key build happens OUTSIDE this
        # cache's lock: one slow or retrying compile of key X must not
        # stall this replica's dispatch and warmup of every other,
        # already-warm bucket behind the cache-wide lock (the same
        # head-of-line guarantee the store's per-key locks give the
        # fleet).  Two racing same-cache callers both reach the store;
        # exactly one builds, and installing the same executor twice
        # below is idempotent.
        ex, built = self._store.get_or_build(key, build)
        with self._lock:
            self._executors[key] = ex
        if self.stats is not None:
            if built:
                self.stats.compile(label, workload=workload)
            else:
                self.stats.cache_hit(label, workload=workload)
            # Either way this replica now serves the bucket through
            # this executable — its XLA accounting belongs in the
            # replica's stats (and the per-bucket gauges) whether this
            # cache compiled it or adopted it from the shared store.
            self.stats.executable_cost(label, ex.cost)
        return ex, ("compiled" if built else "shared_store")

    def keys(self):
        with self._lock:
            return list(self._executors)

    def entries(self):
        with self._lock:
            return list(self._executors.items())
