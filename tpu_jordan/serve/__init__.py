"""tpu_jordan.serve — a dynamic-batching inversion service with a
shape-bucketed AOT executable cache (ISSUE 3 tentpole).

Four parts (docs/SERVING.md is the operator guide):

  * ``executors`` — requests round up to power-of-two n-buckets
    (identity padding makes the rounding exact); one AOT executable per
    (bucket_n, batch_cap, dtype, engine), compiled at most once; engine
    choice resolved through PR 2's plan cache (batched ``bN`` keys) so
    a warm server performs zero measurements and zero recompiles.
  * ``batcher`` — the thread-safe dynamic micro-batcher: same-bucket
    requests group up to ``batch_cap`` or a ``max_wait_ms`` deadline,
    run through the batched engine machinery, and fan per-element
    results (inverse, κ∞, rel_residual, singular flag) back to
    per-request futures.
  * ``service`` — :class:`JordanService`: ``submit()``/futures plus a
    synchronous ``invert()``, bounded-queue admission control
    (:class:`ServiceOverloadedError` backpressure — never a silent
    drop), ``warmup(shapes=)``, clean draining shutdown, and
    ``serve_demo`` (the ``--serve-demo`` CLI mode's engine).
  * ``stats`` — per-bucket counters (requests, batches, mean occupancy,
    compiles, cache hits, singular count) and p50/p95/p99 queue +
    execute latency percentiles, surfaced via ``service.stats()``.

Resilience (ISSUE 5, docs/RESILIENCE.md): ``JordanService(policy=,
default_deadline_ms=)`` attaches transient retry + a result-integrity
gate on batch execution, typed per-request deadlines
(:class:`DeadlineExceededError` over queue wait + execute), and
per-bucket circuit breakers (:class:`CircuitOpenError` fast-fail while
open, half-open probe after the cooldown) — on by default via
``resilience.DEFAULT_POLICY``.  ``chaos_demo`` (CLI ``--chaos-demo``)
proves the whole stack against a fault-free replay under seeded
deterministic fault injection.

Resident-inverse handles (ISSUE 12, ``handles`` + the ``update``
lanes): ``invert(a, resident=True)`` returns a :class:`HandleRef`;
``update(handle, u, v)`` applies rank-k Sherman–Morrison–Woodbury
mutations in O(n²k) through per-(bucket, k-bucket) AOT executables,
gated by the accumulated-drift budget (``linalg/update.py``) with a
typed "re_invert" degradation rung — never a silently stale inverse.
``update_demo`` (CLI ``--update-demo``) is the acceptance run.
"""

from ..resilience.policy import (CircuitOpenError, DeadlineExceededError,
                                 ResultCorruptionError)
from .batcher import (InvertResult, MicroBatcher, ServiceClosedError,
                      ServiceOverloadedError)
from .executors import (MIN_BUCKET_N, MIN_UPDATE_K, BucketExecutor,
                        ExecutorCache, ExecutorKey, bucket_for,
                        k_bucket_for)
from .handles import (HandleRef, HandleState, HandleStore,
                      UnknownHandleError)
from .service import JordanService, chaos_demo, serve_demo
from .stats import ServeStats
from .update_demo import update_demo

__all__ = [
    "InvertResult", "MicroBatcher", "ServiceClosedError",
    "ServiceOverloadedError",
    "CircuitOpenError", "DeadlineExceededError", "ResultCorruptionError",
    "MIN_BUCKET_N", "MIN_UPDATE_K", "BucketExecutor", "ExecutorCache",
    "ExecutorKey", "bucket_for", "k_bucket_for",
    "HandleRef", "HandleState", "HandleStore", "UnknownHandleError",
    "JordanService", "chaos_demo", "serve_demo", "update_demo",
    "ServeStats",
]
