"""tpu_jordan.serve — a dynamic-batching inversion service with a
shape-bucketed AOT executable cache (ISSUE 3 tentpole).

Four parts (docs/SERVING.md is the operator guide):

  * ``executors`` — requests round up to power-of-two n-buckets
    (identity padding makes the rounding exact); one AOT executable per
    (bucket_n, batch_cap, dtype, engine), compiled at most once; engine
    choice resolved through PR 2's plan cache (batched ``bN`` keys) so
    a warm server performs zero measurements and zero recompiles.
  * ``batcher`` — the thread-safe dynamic micro-batcher: same-bucket
    requests group up to ``batch_cap`` or a ``max_wait_ms`` deadline,
    run through the batched engine machinery, and fan per-element
    results (inverse, κ∞, rel_residual, singular flag) back to
    per-request futures.
  * ``service`` — :class:`JordanService`: ``submit()``/futures plus a
    synchronous ``invert()``, bounded-queue admission control
    (:class:`ServiceOverloadedError` backpressure — never a silent
    drop), ``warmup(shapes=)``, clean draining shutdown, and
    ``serve_demo`` (the ``--serve-demo`` CLI mode's engine).
  * ``stats`` — per-bucket counters (requests, batches, mean occupancy,
    compiles, cache hits, singular count) and p50/p95/p99 queue +
    execute latency percentiles, surfaced via ``service.stats()``.
"""

from .batcher import (InvertResult, MicroBatcher, ServiceClosedError,
                      ServiceOverloadedError)
from .executors import (MIN_BUCKET_N, BucketExecutor, ExecutorCache,
                        ExecutorKey, bucket_for)
from .service import JordanService, serve_demo
from .stats import ServeStats

__all__ = [
    "InvertResult", "MicroBatcher", "ServiceClosedError",
    "ServiceOverloadedError",
    "MIN_BUCKET_N", "BucketExecutor", "ExecutorCache", "ExecutorKey",
    "bucket_for",
    "JordanService", "serve_demo",
    "ServeStats",
]
