"""Identity padding for ragged shapes.

The reference carries a ragged last block (height ``l = n - m*(Nr-1)``,
main.cpp:133-137) through every kernel via (bl_h, bl_w) arguments
(get/set, main.cpp:685-728).  On TPU, ragged shapes poison static compilation
and MXU tiling, so instead we embed A into the top-left of a padded matrix

    A_pad = [[A, 0], [0, I]]

whose inverse is exactly [[A^-1, 0], [0, I]].  The identity tail is also
inert under the pivoted block elimination: padded diagonal blocks are only
ever selectable as pivots in padded columns (real rows are zero there), and
padded rows are zero in every real column, so they are never picked as real
pivots and the condition-based pivot choice is unchanged.
"""

from __future__ import annotations

import jax.numpy as jnp


def pad_with_identity(a: jnp.ndarray, N: int) -> jnp.ndarray:
    """Embed (n, n) ``a`` into an (N, N) identity-padded matrix."""
    n = a.shape[-1]
    if N == n:
        return a
    if N < n:
        raise ValueError(f"cannot pad {n} down to {N}")
    out = jnp.eye(N, dtype=a.dtype)
    return out.at[:n, :n].set(a)


def unpad(a: jnp.ndarray, n: int) -> jnp.ndarray:
    """Slice the (n, n) top-left corner back out."""
    return a[..., :n, :n]
