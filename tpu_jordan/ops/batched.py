"""Batched inversion: invert a stack of matrices in one jitted vmap.

North-star capability beyond the reference (BASELINE.md: "Batched
512x(2048x2048) Jordan solves (vmap)"): the reference can only invert one
matrix per program run; here the whole blocked Gauss-Jordan algorithm
(ops/jordan.py) vmaps over a leading batch axis, so the MXU sees
batch-stacked matmuls and the pivot probes of every problem in the batch
run together.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .jordan import block_jordan_invert


@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "use_pallas"))
def batched_jordan_invert(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    use_pallas: bool | None = None,
):
    """Invert a (..., n, n) stack; returns (inverses, singular_flags).

    Each batch element gets independent condition-based pivoting and an
    independent singularity flag (shaped like the batch).
    """
    batch_shape = a.shape[:-2]
    n = a.shape[-1]
    flat = a.reshape((-1,) + a.shape[-2:])

    def one(x):
        return block_jordan_invert(
            x, block_size=block_size, eps=eps, precision=precision,
            refine=refine, use_pallas=use_pallas,
        )

    inv, sing = jax.vmap(one)(flat)
    return (
        inv.reshape(batch_shape + (n, n)),
        sing.reshape(batch_shape),
    )
