"""Batched inversion: invert a stack of matrices in one jitted vmap.

North-star capability beyond the reference (BASELINE.md: "Batched
512x(2048x2048) Jordan solves (vmap)"): the reference can only invert one
matrix per program run; here the whole blocked Gauss-Jordan algorithm
vmaps over a leading batch axis, so the MXU sees batch-stacked matmuls
and the pivot probes of every problem in the batch run together.

Engine selection is the in-place 2N³ path always, in one of two forms:
small batches mirror ``driver.single_device_invert`` (the unrolled
trace with static shrinking probe windows — its swap bookkeeping is
traced values, so it vmaps like any other jax code, and the probe's
custom_vmap rule folds the batch axis into the candidate stack); large
batches (Nr > 4 and B·Nr >= 128) route through the fori in-place
engine even though the unrolled trace would be affordable, because its
single probe shape is what compiles reliably at batch scale
(benchmarks/PHASES.md "compile lottery").
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _batched_smalln(flat, m: int, eps, precision, refine,
                    use_pallas: bool):
    """The dedicated small-n batched engine (VERDICT r4 #5): explicit
    batch axes instead of vmap-of-the-single-engine, with each step's
    swap + column-zero + row-write folded around ONE batched eliminate
    matmul.

    The vmapped engine's measured bound at 512x512² was its per-step
    glue — full-W HBM passes materialized by vmapped dynamic
    slices/scatters with per-element pivot indices (~45 ms of the
    91.5 ms solve, benchmarks/PHASES.md "Batched grouped engine").
    Here the per-element data-dependent writes collapse to one
    block-level ``where`` select per step (the swap target row) fused
    into the eliminate subtract's operand read, and everything else is
    a static slice on the batch tensor — the arithmetic is the
    unrolled in-place engine's, element for element (same pivot rule,
    same summation order: batched dot_general contracts each element
    exactly like the single dot), so results bit-match
    ``vmap(block_jordan_invert_inplace)`` and the parity suite pins it.
    """
    from ..config import eps_for
    from .block_inverse import probe_blocks
    from .jordan import _use_pallas_default
    from .jordan_inplace import apply_col_perm, compose_swap_perm
    from .norms import block_inf_norms
    from .padding import pad_with_identity
    from .refine import newton_schulz, resolve_precision

    precision, refine = resolve_precision(precision, refine)
    B, n, _ = flat.shape
    dtype = flat.dtype
    if eps is None:
        eps = eps_for(dtype)
    if use_pallas is None:
        use_pallas = _use_pallas_default(dtype) and m % 8 == 0 and m >= 32
    Nr = -(-n // m)
    N = Nr * m
    # The working state stays in the BLOCK VIEW (B, Nr, m, N) for the
    # whole loop: every per-step mutation is then either a static slice
    # or an elementwise where with block-level masks, so XLA fuses the
    # swap + column-zero + row-write into the eliminate subtract's
    # output pass instead of materializing full-V copies (the vmapped
    # engine's measured tax).
    V = jax.vmap(lambda x: pad_with_identity(x, N))(flat)
    V = V.reshape(B, Nr, m, N)
    bidx = jnp.arange(Nr)

    singular = jnp.zeros((B,), bool)
    swaps = []
    for t in range(Nr):
        nc = Nr - t
        # --- PROBE: the shrinking window of every element, ONE folded
        # launch (main.cpp:1039).
        cands = V[:, t:, :, t * m:(t + 1) * m]              # (B, nc, m, m)
        invs, sing = probe_blocks(cands.reshape(B * nc, m, m), eps,
                                  use_pallas)
        invs = invs.reshape(B, nc, m, m)
        sing = sing.reshape(B, nc)
        key = jnp.where(sing, jnp.asarray(jnp.inf, dtype),
                        block_inf_norms(invs))
        rel = jnp.argmin(key, axis=1)             # (B,) ties -> lowest
        singular = singular | jnp.all(sing, axis=1)
        H = jnp.take_along_axis(
            invs, rel[:, None, None, None], axis=1)[:, 0]   # (B, m, m)
        piv = t + rel                              # (B,) global block row

        # --- Per-element reads: old row t (static) and the pivot row
        # (one gather — the only per-element indexed read).
        rows_t = V[:, t]                                    # (B, m, N)
        rows_p = jnp.take_along_axis(
            V, piv[:, None, None, None], axis=1)[:, 0]      # (B, m, N)
        Et = rows_t[:, :, t * m:(t + 1) * m]                # (B, m, m)

        # --- NORMALIZE (same fold as the single engine).
        prow = jnp.matmul(H, rows_p, precision=precision)   # (B, m, N)
        prow = prow.at[:, :, t * m:(t + 1) * m].set(H)

        # --- Post-swap multipliers WITHOUT a physical swap: block piv
        # becomes old row t's chunk, block t is zeroed (it receives
        # prow below) — selects on the thin (B, Nr, m, m) column tensor.
        is_piv = (bidx[None, :] == piv[:, None])[:, :, None, None]
        Eb = V[:, :, :, t * m:(t + 1) * m]                  # (B, Nr, m, m)
        Eb = jnp.where(is_piv, Et[:, None], Eb)
        Eb = Eb.at[:, t].set(jnp.asarray(0, dtype))
        upd = jnp.matmul(Eb.reshape(B, Nr * m, m), prow,
                         precision=precision).reshape(B, Nr, m, N)

        # --- Update: column t zeroed, the swap target row replaced by
        # old row t (column-zeroed), minus the eliminate update; row t
        # becomes prow.  Static-index .at writes here — measured FASTER
        # than the fully fused where-chain variant (96.9 vs 132 ms at
        # 512x512²/m=128: the broadcast where operands materialize and
        # defeat in-place updates; ablation puts this glue at ~1.4 ms
        # total — benchmarks/PHASES.md round 5).
        V = V.at[:, :, :, t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
        rows_t_z = rows_t.at[:, :, t * m:(t + 1) * m].set(
            jnp.asarray(0, dtype))
        V = jnp.where(is_piv, rows_t_z[:, None], V)
        V = V - upd
        V = V.at[:, t].set(prow)
        swaps.append(piv)

    # --- Unscramble per element: composed swap permutation, one gather.
    swaps_arr = jnp.stack(swaps, axis=1).astype(jnp.int32)  # (B, Nr)
    cols = jax.vmap(lambda s: compose_swap_perm(s, Nr))(swaps_arr)
    V = jax.vmap(apply_col_perm, in_axes=(0, 0, None))(
        V.reshape(B, N, N), cols, m)
    x = V[:, :n, :n]
    x = newton_schulz(flat, x, refine, lax.Precision.HIGHEST)
    return x, singular


@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "use_pallas"))
def batched_jordan_invert(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    use_pallas: bool | None = None,
):
    """Invert a (..., n, n) stack; returns (inverses, singular_flags).

    Each batch element gets independent condition-based pivoting and an
    independent singularity flag (shaped like the batch).
    """
    from ..config import default_block_size
    from ..driver import single_device_invert

    batch_shape = a.shape[:-2]
    n = a.shape[-1]
    flat = a.reshape((-1,) + a.shape[-2:])
    B = flat.shape[0]

    m = min(n, block_size if block_size is not None
            else default_block_size(n))
    Nr = -(-n // m)
    # Engine choice: the unrolled engine's shrinking-window probe emits
    # Nr DISTINCT pallas shapes; at large B x many-shapes the program
    # lands in a measured-failing compile region (B=64 at Nr=8 fails,
    # B=8 at Nr=8 and B=512 at Nr=2 compile — benchmarks/PHASES.md
    # "compile lottery").  The fori engine reuses ONE probe shape for
    # every step, so big batches route through it: it compiles
    # everywhere and measured 3.2 TF/s at 64x2048^2 m=256 where the
    # unrolled engine cannot compile at all.  Small batches keep the
    # unrolled engine's cheaper shrinking-window probes.
    # Small-n big-batch regime: the dedicated batch-first engine (see
    # _batched_smalln).  Nr <= 4 only: that is the validated regime
    # (512x512²), and like the vmapped unrolled engine this emits Nr
    # distinct probe shapes — at Nr 5-8 with big B that is the
    # measured-failing compile region the fori route below exists for.
    # Sub-fp32 storage keeps the established policy: fp32 compute, one
    # final rounding.
    if Nr <= 4 and B >= 32:
        work = flat.astype(jnp.float32) if flat.dtype.itemsize < 4 else flat
        inv, sing = _batched_smalln(work, m, eps, precision, refine,
                                    use_pallas)
        return (
            inv.astype(a.dtype).reshape(batch_shape + (n, n)),
            sing.reshape(batch_shape),
        )

    if Nr > 4 and B * Nr >= 128:
        from .jordan_inplace import block_jordan_invert_inplace_fori

        engine = block_jordan_invert_inplace_fori
    else:
        engine = single_device_invert(n, m)

    def one(x):
        return engine(
            x, block_size=block_size, eps=eps, precision=precision,
            refine=refine, use_pallas=use_pallas,
        )

    inv, sing = jax.vmap(one)(flat)
    return (
        inv.reshape(batch_shape + (n, n)),
        sing.reshape(batch_shape),
    )
