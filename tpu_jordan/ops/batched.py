"""Batched inversion: invert a stack of matrices in one jitted vmap.

North-star capability beyond the reference (BASELINE.md: "Batched
512x(2048x2048) Jordan solves (vmap)"): the reference can only invert one
matrix per program run; here the whole blocked Gauss-Jordan algorithm
vmaps over a leading batch axis, so the MXU sees batch-stacked matmuls
and the pivot probes of every problem in the batch run together.

Engine selection is the in-place 2N³ path always, in one of two forms:
small batches mirror ``driver.single_device_invert`` (the unrolled
trace with static shrinking probe windows — its swap bookkeeping is
traced values, so it vmaps like any other jax code, and the probe's
custom_vmap rule folds the batch axis into the candidate stack); large
batches (Nr > 4 and B·Nr >= 128) route through the fori in-place
engine even though the unrolled trace would be affordable, because its
single probe shape is what compiles reliably at batch scale
(benchmarks/PHASES.md "compile lottery").
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "use_pallas"))
def batched_jordan_invert(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    use_pallas: bool | None = None,
):
    """Invert a (..., n, n) stack; returns (inverses, singular_flags).

    Each batch element gets independent condition-based pivoting and an
    independent singularity flag (shaped like the batch).
    """
    from ..config import default_block_size
    from ..driver import single_device_invert

    batch_shape = a.shape[:-2]
    n = a.shape[-1]
    flat = a.reshape((-1,) + a.shape[-2:])
    B = flat.shape[0]

    m = min(n, block_size if block_size is not None
            else default_block_size(n))
    Nr = -(-n // m)
    # Engine choice: the unrolled engine's shrinking-window probe emits
    # Nr DISTINCT pallas shapes; at large B x many-shapes the program
    # lands in a measured-failing compile region (B=64 at Nr=8 fails,
    # B=8 at Nr=8 and B=512 at Nr=2 compile — benchmarks/PHASES.md
    # "compile lottery").  The fori engine reuses ONE probe shape for
    # every step, so big batches route through it: it compiles
    # everywhere and measured 3.2 TF/s at 64x2048^2 m=256 where the
    # unrolled engine cannot compile at all.  Small batches keep the
    # unrolled engine's cheaper shrinking-window probes.
    if Nr > 4 and B * Nr >= 128:
        from .jordan_inplace import block_jordan_invert_inplace_fori

        engine = block_jordan_invert_inplace_fori
    else:
        engine = single_device_invert(n, m)

    def one(x):
        return engine(
            x, block_size=block_size, eps=eps, precision=precision,
            refine=refine, use_pallas=use_pallas,
        )

    inv, sing = jax.vmap(one)(flat)
    return (
        inv.reshape(batch_shape + (n, n)),
        sing.reshape(batch_shape),
    )
