"""Pallas TPU kernel: batched small-block Gauss–Jordan inverse.

The pivot-candidate probe (inverse + singularity flag for every candidate
block of a column, main.cpp:1039-1066 / inverse_block main.cpp:746-820) is
the hot spot of the TPU inversion: the pure-XLA vmapped version re-reads the
whole candidate stack from HBM on every one of the ``m`` sequential
elimination steps (~5 ms per super-step at m=256 measured on v5e).  This
kernel keeps the augmented stack [blocks | I] resident in VMEM for the whole
elimination, so each step costs ~one VMEM pass instead of ~eight HBM passes.

Algorithm note (TPU-first): partial pivoting is done *implicitly* — no
physical row swaps.  At step k we pick the not-yet-pivoted row with the
largest |column-k| entry (the same pivot sequence the swap-based code
produces), eliminate, and record the choice in a permutation; at the end the
rows are unscrambled with a one-hot matmul on the MXU.  This removes two
full passes (the swap) per step from the inner loop.

Semantics match ops/block_inverse.py::gauss_jordan_inverse with per-block
relative thresholds: a block is singular when an inner pivot falls below
``eps * ‖block‖∞`` or the block norm itself is below eps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import eps_for

# Per-program VMEM budget for the augmented working stack (bytes).  The
# full VMEM is ~16 MB; the stack, input block, and output block must fit.
_W_BUDGET = 4 * 1024 * 1024
# The panel kernel keeps ~3 full-stack temporaries live at the deferred
# update (w read, U@P product, w_ref write), so its per-program stack must
# be smaller to stay under the 16 MB scoped-vmem limit.
_W_BUDGET_PANEL = 1024 * 1024
# The fused (in-place + panel) kernel's stack is width-m, but the
# in/out blocks, the four (cg, b, m) micro-loop carries, and the deferred
# dot temporaries are all live against it: measured scoped-vmem is
# ~0.73 MB per candidate at m=128 (cg=24 needs 17.6 MB), so the stack
# budget must cap cg at ~20 to stay under the 16 MB limit.
_W_BUDGET_FUSED = 5 * 1024 * 1024 // 4


def _chunk_candidates(num_blocks: int, m: int,
                      budget: int | None = None,
                      width_factor: int = 2) -> int:
    """Candidates per grid program: largest divisor of num_blocks whose
    working stack (width_factor * m lanes per candidate) fits the VMEM
    budget."""
    if budget is None:
        budget = _W_BUDGET      # resolved at call time (tests monkeypatch it)
    if m < 128:
        # Small-m kernels admit huge cg under the stack-only budget, but
        # the per-step temporaries (~3-4 stack-sized values live at the
        # rank-1 update) scale with cg too: measured on v5e, m=64 at
        # cg=128 (4 MB stack) exceeds the 16 MB scoped-vmem limit by
        # 4 MB.  Clamp the stack to 1 MB below m=128 (cg=32 at m=64),
        # which keeps the temporaries inside the limit.
        budget = min(budget, 1024 * 1024)
    per_cand = m * width_factor * m * 4
    cap = max(1, budget // per_cand)
    cg = min(num_blocks, cap)
    while num_blocks % cg:
        cg -= 1
    return cg


def _gj_probe_kernel(blocks_ref, inv_ref, w_ref, *, m, eps):
    cg = blocks_ref.shape[0]
    f32 = jnp.float32

    a = blocks_ref[...]                                   # (cg, m, m)
    # ‖block‖∞ per candidate — the relative singularity scale.  Kept
    # lane-wide (cg, m): any (cg, 1) value live across the scf.for loop
    # crashes Mosaic's tiler.
    norms1 = jnp.max(jnp.sum(jnp.abs(a), axis=2), axis=1, keepdims=True)
    norms = norms1 * jnp.ones((cg, m), jnp.float32)       # (cg, m)
    thresh = eps * norms

    w_ref[:, :, :m] = a
    row_ids3 = lax.broadcasted_iota(jnp.int32, (cg, m, m), 1)
    col_ids3 = lax.broadcasted_iota(jnp.int32, (cg, m, m), 2)
    w_ref[:, :, m:] = jnp.where(row_ids3 == col_ids3, 1.0, 0.0).astype(f32)

    row_ids = lax.broadcasted_iota(jnp.int32, (cg, m), 1)  # (cg, m)

    # Mosaic forbids dynamic indexing along the lane (last) dimension, so
    # column k and pivot row r are extracted with masked reductions — pure
    # vector ops, ~one VMEM pass each.  All 3D masks are built from 3D
    # iotas (Mosaic rejects minor-dim insertion on booleans).
    lane_ids = lax.broadcasted_iota(jnp.int32, (1, 1, 2 * m), 2)
    row_ids3a = lax.broadcasted_iota(jnp.int32, (cg, m, 1), 1)

    def step(k, carry):
        # Carries are 2D 32-bit (Mosaic cannot legalize bool/1D loop state):
        # used: (cg, m) f32 0/1; perm: (cg, m) i32; pivs: (cg, m) f32.
        used, perm, pivs = carry
        w = w_ref[...]
        col = jnp.sum(jnp.where(lane_ids == k, w, 0.0), axis=2)  # (cg, m)
        cand = jnp.where(used > 0, -1.0, jnp.abs(col))
        # argmax via max + first-match (Mosaic's argmax lowering rejects
        # the f32->i32 materialization); ties resolve to the lowest row.
        mx = jnp.max(cand, axis=1, keepdims=True)
        r = jnp.min(jnp.where(cand == mx, row_ids, m), axis=1,
                    keepdims=True)                        # (cg, 1) pivot row
        is_r = row_ids == r                               # (cg, m)
        is_r3 = row_ids3a == r[:, :, None]                # (cg, m, 1)
        used = jnp.where(is_r, 1.0, used)
        perm = jnp.where(row_ids == k, r.astype(jnp.int32), perm)
        piv = jnp.sum(jnp.where(is_r, col, 0.0), axis=1, keepdims=True)  # (cg, 1)
        # RAW pivot recorded; the |piv| < thresh singularity test runs
        # once after the loop (same values, 4 fewer ops on the serial
        # op-latency-bound critical path — see _gj_fused_panel_kernel).
        safe_piv = jnp.where(piv == 0.0, 1.0, piv)
        pivs = jnp.where(row_ids == k,
                         piv * jnp.ones((cg, m), f32), pivs)
        # Extract pivot rows (cg, 2m) by masked reduction, normalize.
        prow = jnp.sum(jnp.where(is_r3, w, 0.0), axis=1)
        prow = (prow / safe_piv)[:, None, :]              # (cg, 1, 2m)
        # Rank-1 eliminate; the pivot row itself becomes prow (fused select,
        # single read+write pass).
        factors = jnp.where(is_r, 0.0, col)[:, :, None]
        w_ref[...] = jnp.where(is_r3, prow, w - factors * prow)
        return used, perm, pivs

    used0 = jnp.zeros((cg, m), jnp.float32)
    perm0 = jnp.zeros((cg, m), jnp.int32)
    pivs0 = jnp.ones((cg, m), jnp.float32)
    _, perm, pivs = lax.fori_loop(0, m, step, (used0, perm0, pivs0))
    badlane = ((jnp.abs(pivs) < thresh) | (norms < eps)).astype(f32)
    sing = jnp.max(badlane, axis=1, keepdims=True) * jnp.ones((cg, m), f32)

    # Unscramble: inverse row k = eliminated row perm[k].  One-hot matmul
    # on the MXU instead of per-row gathers.
    # Singularity is signalled by poisoning the block to non-finite values
    # (a separate small flags output cannot satisfy Mosaic's (8, 128)
    # block-tiling rule for every grid split); the host-side wrapper
    # recovers the flag with isfinite.  A legitimately overflowed inverse
    # also reads as singular — the right call for a pivot-quality probe.
    # The poison is applied to b BEFORE the unscramble matmul: sing is f32
    # 0/1 per (cg, m) lane-wide convention, 1 overflows to inf; adding to
    # the MXU *output* instead crashes Mosaic's tiler.
    big = sing * jnp.float32(3.4e38)                      # (cg, m)
    b = w_ref[:, :, m:] + (big * big)[:, :, None]
    onehot = (col_ids3 == perm[:, :, None].astype(jnp.int32)).astype(f32)
    inv_ref[...] = jax.lax.dot_general(
        onehot, b, dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=f32,
        precision=lax.Precision.HIGHEST,  # 0/1 x fp32 must stay exact, not bf16
    )


def _gj_inplace_kernel(blocks_ref, inv_ref, w_ref, *, m, eps):
    """Width-m in-place variant of the probe: the production default.

    Same implicit-pivot GJ elimination and singularity semantics as
    ``_gj_probe_kernel``, but with NO ``[A | I]`` augmentation: the working
    stack is (cg, m, m) — half the VMEM traffic per elimination pass and
    twice the candidates per grid program, the two costs the rank-1 kernel
    is bound by (measured: the probe is VPU-pass-throughput-limited).

    In-place bookkeeping (the scalar analog of ops/jordan_inplace.py):
    maintain the invariant that live column j holds (T·A)[:, j] and each
    eliminated column k holds T[:, r_k], where T is the accumulated
    transform and r_k the pivot row of step k.  Both roles evolve under the
    SAME uniform rank-1 update ``W ← W − col⊗prow_n`` (pivot row ← prow_n),
    because T's columns obey exactly the recurrence the B half of the
    augmented kernel applies; column r_k of T equals e_{r_k} until step k
    (pivot rows are used once), so the freed column k is overwritten with
    e_{r_k}'s update ``(1/piv at r_k, −col/piv elsewhere)``.  Final
    reconstruction: T = W·M and A⁻¹ = Qᵀ·T = M·W·M with
    M[j, :] = onehot(r_j) — two 0/1 MXU dots.
    """
    cg = blocks_ref.shape[0]
    f32 = jnp.float32

    a = blocks_ref[...]                                   # (cg, m, m)
    norms1 = jnp.max(jnp.sum(jnp.abs(a), axis=2), axis=1, keepdims=True)
    norms = norms1 * jnp.ones((cg, m), jnp.float32)       # (cg, m) lane-wide
    thresh = eps * norms

    w_ref[...] = a
    row_ids = lax.broadcasted_iota(jnp.int32, (cg, m), 1)  # (cg, m)
    lane_ids = lax.broadcasted_iota(jnp.int32, (1, 1, m), 2)
    row_ids3a = lax.broadcasted_iota(jnp.int32, (cg, m, 1), 1)

    def step(k, carry):
        # Same Mosaic conventions as _gj_probe_kernel: 2D 32-bit carries,
        # masked-reduction extraction, lane-wide (cg, m) scalars.
        used, perm, sing = carry
        w = w_ref[...]
        col = jnp.sum(jnp.where(lane_ids == k, w, 0.0), axis=2)  # (cg, m)
        cand = jnp.where(used > 0, -1.0, jnp.abs(col))
        mx = jnp.max(cand, axis=1, keepdims=True)
        r = jnp.min(jnp.where(cand == mx, row_ids, m), axis=1,
                    keepdims=True)                        # (cg, 1) pivot row
        is_r = row_ids == r                               # (cg, m)
        is_r3 = row_ids3a == r[:, :, None]                # (cg, m, 1)
        used = jnp.where(is_r, 1.0, used)
        perm = jnp.where(row_ids == k, r.astype(jnp.int32), perm)
        piv = jnp.sum(jnp.where(is_r, col, 0.0), axis=1, keepdims=True)
        bad = jnp.maximum(
            jnp.where(jnp.abs(piv) < thresh, 1.0, 0.0),
            jnp.where(norms < eps, 1.0, 0.0),
        )
        sing = jnp.maximum(sing, bad)                     # (cg, m) broadcast
        safe_piv = jnp.where(piv == 0.0, 1.0, piv)
        prow = jnp.sum(jnp.where(is_r3, w, 0.0), axis=1)
        prow = (prow / safe_piv)[:, None, :]              # (cg, 1, m)
        factors = jnp.where(is_r, 0.0, col)[:, :, None]
        upd = jnp.where(is_r3, prow, w - factors * prow)
        # Freed column k := T_new[:, r_k] = e_r + u (1/piv at the pivot
        # row, −col/piv elsewhere) — fused into the same write pass.
        ucol = jnp.where(is_r, 1.0 / safe_piv, -col / safe_piv)
        w_ref[...] = jnp.where(lane_ids == k, ucol[:, :, None], upd)
        return used, perm, sing

    used0 = jnp.zeros((cg, m), jnp.float32)
    perm0 = jnp.zeros((cg, m), jnp.int32)
    sing0 = jnp.zeros((cg, m), jnp.float32)
    _, perm, sing = lax.fori_loop(0, m, step, (used0, perm0, sing0))

    # Reconstruction + singularity poison (same poison scheme as
    # _gj_probe_kernel): A⁻¹ = M·W·M, M[j, :] = onehot(perm[j]).  The
    # poison is applied in place in the scratch ref and the two dots are
    # staged so at most two (cg, m, m) temporaries are live — a full
    # expression blows the 16 MB scoped-vmem stack at cg=32, m=128.
    big = sing * jnp.float32(3.4e38)                      # (cg, m)
    w_ref[...] = w_ref[...] + (big * big)[:, :, None]
    col_ids3 = lax.broadcasted_iota(jnp.int32, (cg, m, m), 2)
    onehot = (col_ids3 == perm[:, :, None].astype(jnp.int32)).astype(f32)
    bdims = (((2,), (1,)), ((0,), (0,)))
    mw = jax.lax.dot_general(
        onehot, w_ref[...], dimension_numbers=bdims,
        preferred_element_type=f32, precision=lax.Precision.HIGHEST,
    )
    w_ref[...] = mw
    inv_ref[...] = jax.lax.dot_general(
        w_ref[...], onehot, dimension_numbers=bdims,
        preferred_element_type=f32, precision=lax.Precision.HIGHEST,
    )


def _gj_panel_kernel(blocks_ref, inv_ref, w_ref, *, m, b, eps):
    """MXU-blocked panel variant of the probe (VERDICT r2 item #2).

    Identical pivot sequence and singularity semantics to _gj_probe_kernel,
    but the per-column rank-1 elimination touches only an (cg, m, b) panel
    strip; the full-width (cg, m, 2m) update is deferred to ONE batched MXU
    dot per panel.  Algebra: each GJ step is E_j = I + u_j·e_{r_j}^T (both
    the eliminate and the pivot-row normalize add multiples of row r_j), so
    the panel's composition is T = E_{b-1}···E_0 = I + U·R with R the
    stacked raw pivot-row selectors and U built by the rank-1 recurrence
    U ← U + u_j ⊗ U[r_j, :], then U[:, j] = u_j.  The trailing update
    W ← W + U·(R·W) is two MXU dots on raw (pre-panel) W — VPU work drops
    from O(m³) to O(m²·b) per candidate, the rest rides the MXU.
    """
    cg = blocks_ref.shape[0]
    f32 = jnp.float32

    a = blocks_ref[...]                                   # (cg, m, m)
    norms1 = jnp.max(jnp.sum(jnp.abs(a), axis=2), axis=1, keepdims=True)
    norms = norms1 * jnp.ones((cg, m), jnp.float32)       # (cg, m) lane-wide
    thresh = eps * norms

    w_ref[:, :, :m] = a
    row_ids3 = lax.broadcasted_iota(jnp.int32, (cg, m, m), 1)
    col_ids3 = lax.broadcasted_iota(jnp.int32, (cg, m, m), 2)
    w_ref[:, :, m:] = jnp.where(row_ids3 == col_ids3, 1.0, 0.0).astype(f32)

    row_ids = lax.broadcasted_iota(jnp.int32, (cg, m), 1)   # (cg, m)
    row_ids3a = lax.broadcasted_iota(jnp.int32, (cg, m, 1), 1)
    # One-hot (m, b) panel-column selector template (dim0 iota vs k0+j);
    # panel columns always lie in the A half, so selection reads only
    # W[:, :, :m] — half the VMEM traffic and live set.
    sel_rows = lax.broadcasted_iota(jnp.int32, (m, b), 0)
    sel_cols = lax.broadcasted_iota(jnp.int32, (m, b), 1)
    bdims = (((2,), (1,)), ((0,), (0,)))                  # (cg,x,k)·(cg,k,y)

    def panel(K, carry):
        used, perm, sing = carry
        k0 = K * b
        # Extract the panel strip S = W[:, :, k0:k0+b] via a one-hot MXU
        # dot (Mosaic forbids dynamic lane slicing).
        C = jnp.where(sel_rows == k0 + sel_cols, 1.0, 0.0).astype(f32)
        S = jax.lax.dot_general(
            w_ref[:, :, :m], C, dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=f32, precision=lax.Precision.HIGHEST,
        )                                                 # (cg, m, b)
        U = jnp.zeros((cg, m, b), f32)
        # R built incrementally with masked writes (Mosaic cannot stack
        # boolean vregs): row j of R is the one-hot of pivot row r_j.
        R = jnp.zeros((cg, b, m), f32)
        rb_ids = lax.broadcasted_iota(jnp.int32, (cg, b, m), 1)
        rm_ids = lax.broadcasted_iota(jnp.int32, (cg, b, m), 2)
        for j in range(b):                                # unrolled, static
            col = S[:, :, j]                              # (cg, m)
            cand = jnp.where(used > 0, -1.0, jnp.abs(col))
            mx = jnp.max(cand, axis=1, keepdims=True)
            r = jnp.min(jnp.where(cand == mx, row_ids, m), axis=1,
                        keepdims=True)                    # (cg, 1)
            is_r = row_ids == r                           # (cg, m)
            is_r3 = row_ids3a == r[:, :, None]            # (cg, m, 1)
            used = jnp.where(is_r, 1.0, used)
            perm = jnp.where(row_ids == k0 + j, r.astype(jnp.int32), perm)
            piv = jnp.sum(jnp.where(is_r, col, 0.0), axis=1, keepdims=True)
            bad = jnp.maximum(
                jnp.where(jnp.abs(piv) < thresh, 1.0, 0.0),
                jnp.where(norms < eps, 1.0, 0.0),
            )
            sing = jnp.maximum(sing, bad)
            safe_piv = jnp.where(piv == 0.0, 1.0, piv)
            u = jnp.where(is_r, 1.0 / safe_piv - 1.0, -col / safe_piv)
            # Rank-1 panel-strip update (the only full-height VPU work).
            s_r = jnp.sum(jnp.where(is_r3, S, 0.0), axis=1)   # (cg, b)
            S = S + u[:, :, None] * s_r[:, None, :]
            # Transform recurrence: U += u ⊗ U[r, :], then column j = u.
            u_r = jnp.sum(jnp.where(is_r3, U, 0.0), axis=1)   # (cg, b)
            U = U + u[:, :, None] * u_r[:, None, :]
            lane_b = lax.broadcasted_iota(jnp.int32, (cg, m, b), 2)
            U = jnp.where(lane_b == j, U + u[:, :, None], U)
            R = jnp.where((rb_ids == j) & (rm_ids == r[:, :, None]), 1.0, R)
        # Deferred full-width update: W += U @ (R @ W) with R the RAW
        # pivot-row selectors — batched MXU dots.  Applied in A/B halves
        # read directly from the ref so at most one (cg, m, m)-sized
        # temporary is live at a time (a full-width (cg, m, 2m) read +
        # product blows the 16 MB scoped-vmem stack at m=512).
        for half in (0, 1):
            sl = slice(half * m, (half + 1) * m)
            P = jax.lax.dot_general(
                R, w_ref[:, :, sl], dimension_numbers=bdims,
                preferred_element_type=f32, precision=lax.Precision.HIGHEST,
            )                                             # (cg, b, m)
            upd = jax.lax.dot_general(
                U, P, dimension_numbers=bdims,
                preferred_element_type=f32, precision=lax.Precision.HIGHEST,
            )                                             # (cg, m, m)
            w_ref[:, :, sl] = w_ref[:, :, sl] + upd
        return used, perm, sing

    used0 = jnp.zeros((cg, m), jnp.float32)
    perm0 = jnp.zeros((cg, m), jnp.int32)
    sing0 = jnp.zeros((cg, m), jnp.float32)
    _, perm, sing = lax.fori_loop(0, m // b, panel, (used0, perm0, sing0))

    # Unscramble + singularity poison: identical to _gj_probe_kernel.
    big = sing * jnp.float32(3.4e38)                      # (cg, m)
    bmat = w_ref[:, :, m:] + (big * big)[:, :, None]
    onehot = (col_ids3 == perm[:, :, None].astype(jnp.int32)).astype(f32)
    inv_ref[...] = jax.lax.dot_general(
        onehot, bmat, dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=f32,
        precision=lax.Precision.HIGHEST,
    )


def _gj_fused_panel_kernel(blocks_ref, inv_ref, w_ref, *, m, b, eps, hc=1):
    """The production probe: in-place (width-m) storage + b-wide panel
    micro-steps + MXU-deferred trailing updates + DEFERRED DIVISIONS.

    Combines the three measured lessons of the kernel shootout
    (benchmarks/PHASES.md):
      * width-m storage (no [A | I]): half the stack, so cg stays large
        and the whole candidate stack fits one grid program at m=128;
      * per-step VPU work touches only (cg, m, b) panels — the full-width
        rank-1 passes that bound the v1 kernel (4 x (cg, m, 2m) per step)
        shrink by ~2m/b; the full-width update rides the MXU once per
        panel via the composed transform T = E_{b-1}···E_0 = I + U·R;
      * eliminations are UNNORMALIZED: E_j = I + v_j·e_{r_j}ᵀ with
        v_j[r_j] = 0, so pivot rows keep their raw scale through the
        whole elimination and every row is scaled ONCE at the end by the
        exact division 1/piv_k.  This kills the catastrophic
        ``u[r] = 1/piv − 1`` representation error of the v2/v3 kernels
        (relative error ~eps·|piv| in the normalized pivot row) — with
        raw pivot rows the candidate values seen by later steps are
        identical to normalized GJ (S_i − (S_i[k]/piv)·S_r), so the
        pivot sequence is preserved exactly.

    Bookkeeping: live column j holds (T·A)[:, j]; eliminated column k
    holds T[:, r_k] (both evolve under the same uniform update, so the
    deferred W += U·(R·W) covers them together); the panel's own freed
    columns are T[:, r_j] = e_{r_j} + U[:, j] directly — column r_j of R
    is e_j (pivot rows are used once), so T = I + U·R gives the freed
    column from U and R with NO separate forward-composed chain (the
    round-3 kernel carried a redundant Vp recurrence for these — two
    extra (cg, b, m) passes per micro-step; validated rounding-level
    equal, interpret mode) — scattered back with a one-hot MXU dot.
    Final: A⁻¹ = D⁻¹·M·W·M with M[k, :] = onehot(r_k), D = diag(piv_k).
    """
    cg = blocks_ref.shape[0]
    f32 = jnp.float32

    a = blocks_ref[...]                                   # (cg, m, m)
    norms1 = jnp.max(jnp.sum(jnp.abs(a), axis=2), axis=1, keepdims=True)
    norms = norms1 * jnp.ones((cg, m), jnp.float32)       # (cg, m) lane-wide
    thresh = eps * norms

    w_ref[...] = a
    # Panel state is kept TRANSPOSED — St/Ut/R are (cg, b, m) with
    # matrix rows on the LANE dim — so the micro-loop can be a real
    # lax.fori_loop: column j of the panel is a dynamic slice on the
    # sublane dim (legal in Mosaic; dynamic LANE indexing is not), pivot
    # rows are masked lane reductions, and an unrolled Python loop (whose
    # per-iteration temporaries Mosaic keeps live — measured 51 MB of
    # scoped vmem at cg=32, m=128) is avoided entirely.
    row_ids = lax.broadcasted_iota(jnp.int32, (cg, m), 1)
    lane_m = lax.broadcasted_iota(jnp.int32, (1, 1, m), 2)
    sel_rows = lax.broadcasted_iota(jnp.int32, (m, b), 0)
    sel_cols = lax.broadcasted_iota(jnp.int32, (m, b), 1)
    rb_ids = lax.broadcasted_iota(jnp.int32, (cg, b, m), 1)
    lane_bm = lax.broadcasted_iota(jnp.int32, (cg, b, m), 2)
    bdims = (((2,), (1,)), ((0,), (0,)))                  # (cg,x,k)·(cg,k,y)

    def panel(K, carry):
        used, perm, pivs = carry                          # (cg, m) each
        k0 = K * b
        C = jnp.where(sel_rows == k0 + sel_cols, 1.0, 0.0).astype(f32)
        # St[j, i] = W[i, k0+j]: one-hot dot (j, cg, i) then a batch-dim
        # transpose (lane dim untouched — cheap vreg reindexing).
        St = jnp.transpose(jax.lax.dot_general(
            C, w_ref[...], dimension_numbers=(((0,), (2,)), ((), ())),
            preferred_element_type=f32, precision=lax.Precision.HIGHEST,
        ), (1, 0, 2))                                     # (cg, b, m)

        def micro(j, mc):
            St, Ut, R, used, perm, pivs = mc
            # Column j of the panel = sublane j of St, via masked reduce
            # (Mosaic lowers no dynamic_slice on values; the pass is only
            # (cg, b, m) — b/m-th of a full-width pass).
            col = jnp.sum(jnp.where(rb_ids == j, St, 0.0), axis=1)
            cand = jnp.where(used > 0, -1.0, jnp.abs(col))
            mx = jnp.max(cand, axis=1, keepdims=True)
            r = jnp.min(jnp.where(cand == mx, row_ids, m), axis=1,
                        keepdims=True)                    # (cg, 1)
            is_r = row_ids == r                           # (cg, m)
            is_rl = lane_bm == r[:, :, None]              # (cg, b, m)
            used = jnp.where(is_r, 1.0, used)
            kk = k0 + j
            perm = jnp.where(row_ids == kk, r.astype(jnp.int32), perm)
            piv = jnp.sum(jnp.where(is_r, col, 0.0), axis=1, keepdims=True)
            # Singularity is NOT judged here: the RAW pivot is recorded
            # and the |piv| < thresh test runs ONCE after the loop — the
            # stored values are identical to the at-selection-time ones,
            # and dropping the 4 flag ops from the serial micro-step
            # cuts its op-latency-bound critical path (measured: the
            # whole kernel is ~140 ns/vector-op with shape size nearly
            # irrelevant, so op count IS the probe's cost model).
            safe_piv = jnp.where(piv == 0.0, 1.0, piv)
            pivs = jnp.where(row_ids == kk,
                             piv * jnp.ones((cg, m), f32), pivs)
            v = jnp.where(is_r, 0.0, -col / safe_piv)     # (cg, m)
            v3 = v[:, None, :]                            # (cg, 1, m)
            is_j = rb_ids == j                            # (cg, b, m)
            s_r = jnp.sum(jnp.where(is_rl, St, 0.0), axis=2)   # (cg, b)
            St = St + s_r[:, :, None] * v3
            u_r = jnp.sum(jnp.where(is_rl, Ut, 0.0), axis=2)
            Ut = jnp.where(is_j, Ut + v3, Ut + u_r[:, :, None] * v3)
            R = jnp.where(is_j & is_rl, 1.0, R)
            return St, Ut, R, used, perm, pivs

        z = jnp.zeros((cg, b, m), f32)
        _, Ut, R, used, perm, pivs = lax.fori_loop(
            0, b, micro, (St, z, z, used, perm, pivs))

        # Deferred full-width update W += U·(R·W) (R = RAW pivot-row
        # selectors); panel slots are rebuilt from Vp instead.  All dots
        # contract on dim 1 of the transposed state — no lane transposes.
        # Both the update and the panel scatter are staged in ``hc``
        # STATIC column chunks (static lane slices are Mosaic-legal even
        # though dynamic ones are not): correct because chunk c's P reads
        # only chunk c's columns of the pre-update W, which no other
        # chunk's write touches.  hc=1 keeps the tuned m<=256 schedule;
        # hc=2 at m=512 halves the peak (cg, m, m) temporaries — the
        # ~1 MB that used to blow the 16 MB scoped-vmem limit at cg=2.
        for c in range(hc):
            sl = slice(c * (m // hc), (c + 1) * (m // hc))
            P = jax.lax.dot_general(
                R, w_ref[:, :, sl], dimension_numbers=bdims,
                preferred_element_type=f32, precision=lax.Precision.HIGHEST,
            )                                             # (cg, b, m/hc)
            upd = jax.lax.dot_general(
                Ut, P, dimension_numbers=(((1,), (1,)), ((0,), (0,))),
                preferred_element_type=f32, precision=lax.Precision.HIGHEST,
            )                                             # (cg, m, m/hc)
            w_ref[:, :, sl] = w_ref[:, :, sl] + upd       # panel slots: garbage
        Vp = Ut + R                       # T[:, r_j] = e_{r_j} + U[:, j]
        for c in range(hc):
            sl = slice(c * (m // hc), (c + 1) * (m // hc))
            vscat = jax.lax.dot_general(
                Vp, C[sl, :], dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=f32, precision=lax.Precision.HIGHEST,
            )                                             # (cg, m, m/hc)
            lane_c = lane_m[:, :, sl]
            in_panel = (lane_c >= k0) & (lane_c < k0 + b)
            w_ref[:, :, sl] = jnp.where(in_panel, vscat, w_ref[:, :, sl])
        return used, perm, pivs

    used0 = jnp.zeros((cg, m), jnp.float32)
    perm0 = jnp.zeros((cg, m), jnp.int32)
    pivs0 = jnp.ones((cg, m), jnp.float32)
    _, perm, pivs = lax.fori_loop(0, m // b, panel,
                                  (used0, perm0, pivs0))

    # Deferred singularity judgement (see micro): a candidate is singular
    # iff any recorded raw pivot fell below the relative threshold, or
    # the block norm itself is sub-eps; reduced once and broadcast
    # lane-wide ((cg, 1) is only hazardous as LOOP state).
    badlane = ((jnp.abs(pivs) < thresh) | (norms < eps)).astype(f32)
    sing = jnp.max(badlane, axis=1, keepdims=True) * jnp.ones((cg, m), f32)
    pivs = jnp.where(pivs == 0.0, jnp.float32(1.0), pivs)  # safe final divide

    # Reconstruction + poison: A⁻¹ = D⁻¹·M·W·M (staged via the scratch
    # ref so at most two (cg, m, m) temporaries are live at once).
    big = sing * jnp.float32(3.4e38)                      # (cg, m)
    w_ref[...] = w_ref[...] + (big * big)[:, :, None]
    col_ids3 = lax.broadcasted_iota(jnp.int32, (cg, m, m), 2)
    onehot = (col_ids3 == perm[:, :, None].astype(jnp.int32)).astype(f32)
    if hc == 1:
        mw = jax.lax.dot_general(
            onehot, w_ref[...], dimension_numbers=bdims,
            preferred_element_type=f32, precision=lax.Precision.HIGHEST,
        )
        # Row scaling commutes with the right one-hot multiply
        # (D⁻¹·(M·W)·M = (D⁻¹·M·W)·M): folding it here keeps one fewer
        # (cg, m, m) temporary live at the final dot.
        w_ref[...] = mw * (1.0 / pivs)[:, :, None]
        inv_ref[...] = jax.lax.dot_general(
            w_ref[...], onehot, dimension_numbers=bdims,
            preferred_element_type=f32, precision=lax.Precision.HIGHEST,
        )
    else:
        # Column-chunked (hc > 1, the m=512 path): same algebra with the
        # output block as the intermediate, so the largest temporary is
        # (cg, m, m/hc) — the full-width mw no longer fits beside
        # onehot + the refs at m=512 cg=2.
        scale = (1.0 / pivs)[:, :, None]
        for c in range(hc):
            sl = slice(c * (m // hc), (c + 1) * (m // hc))
            inv_ref[:, :, sl] = jax.lax.dot_general(
                onehot, w_ref[:, :, sl], dimension_numbers=bdims,
                preferred_element_type=f32,
                precision=lax.Precision.HIGHEST,
            ) * scale                                     # D⁻¹·M·W chunk
        for c in range(hc):
            sl = slice(c * (m // hc), (c + 1) * (m // hc))
            w_ref[:, :, sl] = jax.lax.dot_general(
                inv_ref[...], onehot[:, :, sl],
                dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                preferred_element_type=f32,
                precision=lax.Precision.HIGHEST,
            )
        inv_ref[...] = w_ref[...]


def _fused_budget(m: int) -> int:
    """Per-program stack budget for the fused kernel (m-dependent hook;
    today a constant — m=512 remains out of the fused kernel's reach:
    cg=1 is a known-failing Mosaic region (unimplemented multi_reduction)
    and cg=2 fails to compile even with the hc-chunked staging that
    removed the diagnosed ~1-3 MB of scoped-VMEM overshoot, so the
    remaining blocker is not the deferred-stage temporaries; the opaque
    remote-compile channel hides the specific pass.  m=512 probes ride
    the rank-1 kernel (measured fine: the m=256 fused configs win the
    block-size shootout anyway, benchmarks/PHASES.md).

    A 2 MB m=256 budget (cg=8) measured 75.3 -> 53.9 us/candidate on
    isolated 512-candidate folded-batch stacks, but cg=8 INSIDE the full
    vmapped engine program fails to compile (reproducibly, while cg=4
    compiles) — so the probe keeps the proven 1.25 MB/cg=4 everywhere;
    the cg=8 gain is recorded in PHASES.md as blocked upside."""
    return _W_BUDGET_FUSED


def _fused_hc(m: int) -> int:
    """Column-chunk count for the fused kernel's deferred stages (kept
    at 1 for the tuned production sizes; the hc>1 staging is
    interpret- and TPU-validated at m=128 and ready if a larger-m
    fused config becomes compilable)."""
    return 2 if m >= 512 else 1


def _panel_width(m: int) -> int | None:
    """Largest supported panel width dividing m (None -> no panel path)."""
    for b in (32, 16, 8):
        if m % b == 0 and m > b:
            return b
    return None


# Max grid programs per pallas launch.  Measured on v5e: the fused
# m=256 kernel compiles at grid 64 and gets an opaque remote-compile
# failure at grid 128 (the m=128 kernel survives 128) — consistent with
# a compiler blowup on long sequential grid loops, not VMEM.  Oversized
# stacks are split into multiple launches of <= cg*_MAX_GRID candidates;
# all production single-solve probes fit one launch, so this only
# engages for big folded batches (custom_vmap rule below).
_MAX_GRID = 64


def _run_probe_kernel(blocks, kernel, m: int, interpret: bool,
                      budget: int | None = None, width_factor: int = 2):
    """Shared pad/chunk/launch/poison-recover harness for the probe
    kernels (width_factor: lanes of scratch per candidate, in units of
    m — 2 for the augmented kernels, 1 for the in-place kernel)."""
    Nr = blocks.shape[0]
    # Mosaic rejects some small-stack shapes ("Not implemented: Sublane
    # broadcast" — measured on v5e: cg=1 with m<=256 fails; cg>=2, and
    # cg=1 with m=512, compile fine).  Padding the stack to a multiple of
    # 8 with identity blocks (well-conditioned, flags False) keeps cg >= 8
    # whenever the VMEM cap allows (m <= 256) and cg >= 2 at m = 512; the
    # outputs are sliced back.  The shrinking-window probe
    # (ops/jordan_inplace.py) hits every count from Nr down to 1.
    Nr_pad = max(8, -(-Nr // 8) * 8)
    if Nr_pad != Nr:
        eyes = jnp.broadcast_to(jnp.eye(m, dtype=jnp.float32),
                                (Nr_pad - Nr, m, m))
        blocks = jnp.concatenate([blocks, eyes], axis=0)
    cg = _chunk_candidates(Nr_pad, m, budget, width_factor)
    if cg < 2 and m <= 256:
        # Known-bad Mosaic region (see comment above); unreachable with the
        # default _W_BUDGET, but guard against shrunken budgets with a real
        # error (an assert is stripped under python -O).
        raise NotImplementedError(
            f"pallas probe: cg={cg} with m={m} hits a known-failing Mosaic "
            "compile path; increase _W_BUDGET or use the XLA fallback"
        )
    def launch(chunk):
        return pl.pallas_call(
            kernel,
            grid=(chunk.shape[0] // cg,),
            in_specs=[
                pl.BlockSpec((cg, m, m), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((cg, m, m), lambda i: (i, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct(chunk.shape, jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((cg, m, width_factor * m), jnp.float32)],
            interpret=interpret,
        )(chunk)

    per = cg * _MAX_GRID
    if Nr_pad <= per:
        inv = launch(blocks)
    else:
        # One launch body compiled ONCE and scanned over equal chunks
        # (multiple distinct fused-kernel custom calls in one program is
        # a measured-failing compile region; a lax.map body is a single
        # call).  Pad the stack to a chunk multiple with identity blocks.
        k = -(-Nr_pad // per)
        if k * per != Nr_pad:
            eyes = jnp.broadcast_to(jnp.eye(m, dtype=jnp.float32),
                                    (k * per - Nr_pad, m, m))
            blocks = jnp.concatenate([blocks, eyes], axis=0)
        inv = lax.map(launch, blocks.reshape(k, per, m, m))
        inv = inv.reshape(k * per, m, m)
    inv = inv[:Nr]
    sing = ~jnp.isfinite(inv).all(axis=(1, 2))
    return inv, sing


def _dispatch_probe(blocks, eps, interpret):
    """The unbatched (single leading stack dim) kernel dispatch."""
    Nr, m, _ = blocks.shape
    blocks = blocks.astype(jnp.float32)
    b = _panel_width(m)
    # m % 128: the transposed panel state puts matrix rows on the lane
    # dim; Mosaic's layout inference rejects the St/vscat dots' shape
    # casts for sub-native lane extents (measured: m=64 fails with
    # "unsupported shape cast", m=128/256 compile).
    if (b is not None and m % 128 == 0
            and 2 * m * m * 4 <= _fused_budget(m)):
        kernel = functools.partial(_gj_fused_panel_kernel, m=m, b=b,
                                   eps=eps, hc=_fused_hc(m))
        return _run_probe_kernel(blocks, kernel, m, interpret,
                                 _fused_budget(m), width_factor=1)
    kernel = functools.partial(_gj_probe_kernel, m=m, eps=eps)
    return _run_probe_kernel(blocks, kernel, m, interpret)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def pallas_batched_block_inverse(
    blocks: jnp.ndarray,
    eps: float | None = None,
    interpret: bool = False,
):
    """Invert a (Nr, m, m) fp32 stack of blocks on-TPU in VMEM.

    Drop-in fast path for ops/block_inverse.py::batched_block_inverse with
    per-block singularity scaling.  Returns (inverses, singular_flags).
    Dispatches to the fused in-place panel kernel when the block size
    supports a panel split AND the VMEM budget admits >= 2 candidates per
    grid program (measured: it wins at m <= 256 — 29.7 -> 18.4 ms on the
    full 4096 m=256 inversion — but fails to compile at m=512 where only
    cg=1 fits); else the augmented rank-1 kernel.  See benchmarks/PHASES.md
    "probe kernel shootout".

    BATCHING (the root cause of the round-3 "B=64 n=1024 m=256 fails to
    compile" edge): pallas_call's default vmap rule prepends a grid
    dimension, and the fused kernel does not survive Mosaic under the
    multi-dim grid (the rank-1 kernel does).  Every candidate is
    independent, so a batch IS just a longer stack — the custom_vmap rule
    below folds any vmapped leading axes into the stack axis and calls
    the same single-grid-dim kernel, which both compiles everywhere the
    unbatched kernel does and amortizes launches better.
    """
    if eps is None:
        eps = eps_for(jnp.float32)

    @jax.custom_batching.custom_vmap
    def core(bl):
        return _dispatch_probe(bl, eps, interpret)

    @core.def_vmap
    def _fold_rule(axis_size, in_batched, bl):  # noqa: ANN001
        # With a single operand the rule is only invoked when that
        # operand is batched (a closed-over constant never reaches the
        # custom_vmap primitive); the assert documents the fold's
        # assumption so a future second operand can't silently fold a
        # non-batch axis.
        assert in_batched == [True], in_batched
        inv, sing = pallas_batched_block_inverse(
            bl.reshape((-1,) + bl.shape[-2:]), eps, interpret)
        return ((inv.reshape(bl.shape), sing.reshape(bl.shape[:-2])),
                (True, True))

    return core(blocks)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def pallas_batched_block_inverse_rank1(
    blocks: jnp.ndarray,
    eps: float | None = None,
    interpret: bool = False,
):
    """The augmented rank-1 (v1) kernel, forced — currently identical to
    the dispatch path; kept addressable so perf comparisons against the
    experimental kernels keep working if the dispatch changes."""
    Nr, m, _ = blocks.shape
    if eps is None:
        eps = eps_for(jnp.float32)
    blocks = blocks.astype(jnp.float32)
    kernel = functools.partial(_gj_probe_kernel, m=m, eps=eps)
    return _run_probe_kernel(blocks, kernel, m, interpret)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def pallas_batched_block_inverse_inplace(
    blocks: jnp.ndarray,
    eps: float | None = None,
    interpret: bool = False,
):
    """The width-m in-place (v3) kernel, forced — despite half the VMEM
    data per pass it measures ~1.6x SLOWER than the rank-1 kernel at
    m=128 (Mosaic schedules the narrower passes worse and the extra
    column-k select adds a pass), so it is not dispatched; kept
    addressable as a recorded experiment."""
    Nr, m, _ = blocks.shape
    if eps is None:
        eps = eps_for(jnp.float32)
    blocks = blocks.astype(jnp.float32)
    kernel = functools.partial(_gj_inplace_kernel, m=m, eps=eps)
    return _run_probe_kernel(blocks, kernel, m, interpret, width_factor=1)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def pallas_batched_block_inverse_fused(
    blocks: jnp.ndarray,
    eps: float | None = None,
    interpret: bool = False,
):
    """The fused in-place panel (v4) kernel, forced — the production
    dispatch for panel-splittable m; kept addressable so perf comparisons
    keep working if the dispatch changes."""
    Nr, m, _ = blocks.shape
    if eps is None:
        eps = eps_for(jnp.float32)
    blocks = blocks.astype(jnp.float32)
    b = _panel_width(m)
    if b is None:
        raise ValueError(f"no panel width divides m={m}")
    kernel = functools.partial(_gj_fused_panel_kernel, m=m, b=b, eps=eps,
                               hc=_fused_hc(m))
    return _run_probe_kernel(blocks, kernel, m, interpret,
                             _fused_budget(m), width_factor=1)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def pallas_batched_block_inverse_panel(
    blocks: jnp.ndarray,
    eps: float | None = None,
    interpret: bool = False,
):
    """The MXU-blocked panel (v2) kernel, forced — measured SLOWER than
    the rank-1 kernel at every production size (its deferred-update
    temporaries force a 4x smaller VMEM budget, and grid programs
    serialize), so it is not dispatched; kept addressable as the recorded
    outcome of the VERDICT r2 #2 experiment."""
    Nr, m, _ = blocks.shape
    if eps is None:
        eps = eps_for(jnp.float32)
    blocks = blocks.astype(jnp.float32)
    b = _panel_width(m)
    if b is None:
        raise ValueError(f"no panel width divides m={m}")
    kernel = functools.partial(_gj_panel_kernel, m=m, b=b, eps=eps)
    return _run_probe_kernel(blocks, kernel, m, interpret, _W_BUDGET_PANEL)
