"""Pallas TPU kernel: batched small-block Gauss–Jordan inverse.

The pivot-candidate probe (inverse + singularity flag for every candidate
block of a column, main.cpp:1039-1066 / inverse_block main.cpp:746-820) is
the hot spot of the TPU inversion: the pure-XLA vmapped version re-reads the
whole candidate stack from HBM on every one of the ``m`` sequential
elimination steps (~5 ms per super-step at m=256 measured on v5e).  This
kernel keeps the augmented stack [blocks | I] resident in VMEM for the whole
elimination, so each step costs ~one VMEM pass instead of ~eight HBM passes.

Algorithm note (TPU-first): partial pivoting is done *implicitly* — no
physical row swaps.  At step k we pick the not-yet-pivoted row with the
largest |column-k| entry (the same pivot sequence the swap-based code
produces), eliminate, and record the choice in a permutation; at the end the
rows are unscrambled with a one-hot matmul on the MXU.  This removes two
full passes (the swap) per step from the inner loop.

Semantics match ops/block_inverse.py::gauss_jordan_inverse with per-block
relative thresholds: a block is singular when an inner pivot falls below
``eps * ‖block‖∞`` or the block norm itself is below eps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import eps_for

# Per-program VMEM budget for the augmented working stack (bytes).  The
# full VMEM is ~16 MB; the stack, input block, and output block must fit.
_W_BUDGET = 4 * 1024 * 1024


def _chunk_candidates(num_blocks: int, m: int) -> int:
    """Candidates per grid program: largest divisor of num_blocks whose
    augmented stack fits the VMEM budget."""
    per_cand = m * 2 * m * 4
    cap = max(1, _W_BUDGET // per_cand)
    cg = min(num_blocks, cap)
    while num_blocks % cg:
        cg -= 1
    return cg


def _gj_probe_kernel(blocks_ref, inv_ref, w_ref, *, m, eps):
    cg = blocks_ref.shape[0]
    f32 = jnp.float32

    a = blocks_ref[...]                                   # (cg, m, m)
    # ‖block‖∞ per candidate — the relative singularity scale.  Kept
    # lane-wide (cg, m): any (cg, 1) value live across the scf.for loop
    # crashes Mosaic's tiler.
    norms1 = jnp.max(jnp.sum(jnp.abs(a), axis=2), axis=1, keepdims=True)
    norms = norms1 * jnp.ones((cg, m), jnp.float32)       # (cg, m)
    thresh = eps * norms

    w_ref[:, :, :m] = a
    row_ids3 = lax.broadcasted_iota(jnp.int32, (cg, m, m), 1)
    col_ids3 = lax.broadcasted_iota(jnp.int32, (cg, m, m), 2)
    w_ref[:, :, m:] = jnp.where(row_ids3 == col_ids3, 1.0, 0.0).astype(f32)

    row_ids = lax.broadcasted_iota(jnp.int32, (cg, m), 1)  # (cg, m)

    # Mosaic forbids dynamic indexing along the lane (last) dimension, so
    # column k and pivot row r are extracted with masked reductions — pure
    # vector ops, ~one VMEM pass each.  All 3D masks are built from 3D
    # iotas (Mosaic rejects minor-dim insertion on booleans).
    lane_ids = lax.broadcasted_iota(jnp.int32, (1, 1, 2 * m), 2)
    row_ids3a = lax.broadcasted_iota(jnp.int32, (cg, m, 1), 1)

    def step(k, carry):
        # Carries are 2D 32-bit (Mosaic cannot legalize bool/1D loop state):
        # used: (cg, m) f32 0/1; perm: (cg, m) i32; sing: (cg, 1) i32.
        used, perm, sing = carry
        w = w_ref[...]
        col = jnp.sum(jnp.where(lane_ids == k, w, 0.0), axis=2)  # (cg, m)
        cand = jnp.where(used > 0, -1.0, jnp.abs(col))
        # argmax via max + first-match (Mosaic's argmax lowering rejects
        # the f32->i32 materialization); ties resolve to the lowest row.
        mx = jnp.max(cand, axis=1, keepdims=True)
        r = jnp.min(jnp.where(cand == mx, row_ids, m), axis=1,
                    keepdims=True)                        # (cg, 1) pivot row
        is_r = row_ids == r                               # (cg, m)
        is_r3 = row_ids3a == r[:, :, None]                # (cg, m, 1)
        used = jnp.where(is_r, 1.0, used)
        perm = jnp.where(row_ids == k, r.astype(jnp.int32), perm)
        piv = jnp.sum(jnp.where(is_r, col, 0.0), axis=1, keepdims=True)  # (cg, 1)
        # f32 0/1 flag arithmetic only, carried lane-wide as (cg, m):
        # Mosaic crashes on (cg, 1) values that stay live across the loop.
        bad = jnp.maximum(
            jnp.where(jnp.abs(piv) < thresh, 1.0, 0.0),
            jnp.where(norms < eps, 1.0, 0.0),
        )
        sing = jnp.maximum(sing, bad)                     # (cg, m) via broadcast
        safe_piv = jnp.where(piv == 0.0, 1.0, piv)
        # Extract pivot rows (cg, 2m) by masked reduction, normalize.
        prow = jnp.sum(jnp.where(is_r3, w, 0.0), axis=1)
        prow = (prow / safe_piv)[:, None, :]              # (cg, 1, 2m)
        # Rank-1 eliminate; the pivot row itself becomes prow (fused select,
        # single read+write pass).
        factors = jnp.where(is_r, 0.0, col)[:, :, None]
        w_ref[...] = jnp.where(is_r3, prow, w - factors * prow)
        return used, perm, sing

    used0 = jnp.zeros((cg, m), jnp.float32)
    perm0 = jnp.zeros((cg, m), jnp.int32)
    sing0 = jnp.zeros((cg, m), jnp.float32)
    _, perm, sing = lax.fori_loop(0, m, step, (used0, perm0, sing0))

    # Unscramble: inverse row k = eliminated row perm[k].  One-hot matmul
    # on the MXU instead of per-row gathers.
    # Singularity is signalled by poisoning the block to non-finite values
    # (a separate small flags output cannot satisfy Mosaic's (8, 128)
    # block-tiling rule for every grid split); the host-side wrapper
    # recovers the flag with isfinite.  A legitimately overflowed inverse
    # also reads as singular — the right call for a pivot-quality probe.
    # The poison is applied to b BEFORE the unscramble matmul: sing is f32
    # 0/1 per (cg, m) lane-wide convention, 1 overflows to inf; adding to
    # the MXU *output* instead crashes Mosaic's tiler.
    big = sing * jnp.float32(3.4e38)                      # (cg, m)
    b = w_ref[:, :, m:] + (big * big)[:, :, None]
    onehot = (col_ids3 == perm[:, :, None].astype(jnp.int32)).astype(f32)
    inv_ref[...] = jax.lax.dot_general(
        onehot, b, dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=f32,
        precision=lax.Precision.HIGHEST,  # 0/1 x fp32 must stay exact, not bf16
    )


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def pallas_batched_block_inverse(
    blocks: jnp.ndarray,
    eps: float | None = None,
    interpret: bool = False,
):
    """Invert a (Nr, m, m) fp32 stack of blocks on-TPU in VMEM.

    Drop-in fast path for ops/block_inverse.py::batched_block_inverse with
    per-block singularity scaling.  Returns (inverses, singular_flags).
    """
    Nr, m, _ = blocks.shape
    if eps is None:
        eps = eps_for(jnp.float32)
    blocks = blocks.astype(jnp.float32)
    # Mosaic rejects some small-stack shapes ("Not implemented: Sublane
    # broadcast" — measured on v5e: cg=1 with m<=256 fails; cg>=2, and
    # cg=1 with m=512, compile fine).  Padding the stack to a multiple of
    # 8 with identity blocks (well-conditioned, flags False) keeps cg >= 8
    # whenever the VMEM cap allows (m <= 256) and cg >= 2 at m = 512; the
    # outputs are sliced back.  The shrinking-window probe
    # (ops/jordan_inplace.py) hits every count from Nr down to 1.
    Nr_pad = max(8, -(-Nr // 8) * 8)
    if Nr_pad != Nr:
        eyes = jnp.broadcast_to(jnp.eye(m, dtype=jnp.float32),
                                (Nr_pad - Nr, m, m))
        blocks = jnp.concatenate([blocks, eyes], axis=0)
    cg = _chunk_candidates(Nr_pad, m)
    if cg < 2 and m <= 256:
        # Known-bad Mosaic region (see comment above); unreachable with the
        # default _W_BUDGET, but guard against shrunken budgets with a real
        # error (an assert is stripped under python -O).
        raise NotImplementedError(
            f"pallas probe: cg={cg} with m={m} hits a known-failing Mosaic "
            "compile path; increase _W_BUDGET or use the XLA fallback"
        )
    grid = (Nr_pad // cg,)

    inv = pl.pallas_call(
        functools.partial(_gj_probe_kernel, m=m, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((cg, m, m), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((cg, m, m), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Nr_pad, m, m), jnp.float32),
        scratch_shapes=[pltpu.VMEM((cg, m, 2 * m), jnp.float32)],
        interpret=interpret,
    )(blocks)
    inv = inv[:Nr]
    sing = ~jnp.isfinite(inv).all(axis=(1, 2))
    return inv, sing
