"""Infinity norms (norm main.cpp:643-667, block_norm main.cpp:669-683).

The reference uses the max-abs-row-sum norm everywhere: as the relative
singularity scale, as the pivot-quality metric (norm of the inverse block),
and for the final residual.  One definition, three call sites — same here.
"""

from __future__ import annotations

import jax.numpy as jnp


def inf_norm(a: jnp.ndarray) -> jnp.ndarray:
    """‖A‖∞ = max_i Σ_j |a_ij| for a 2D matrix (norm, main.cpp:643-667)."""
    return jnp.max(jnp.sum(jnp.abs(a), axis=-1), axis=-1)


def block_inf_norms(blocks: jnp.ndarray) -> jnp.ndarray:
    """‖·‖∞ of each block in a (..., m, m) stack (block_norm, main.cpp:669-683)."""
    return jnp.max(jnp.sum(jnp.abs(blocks), axis=-1), axis=-1)
