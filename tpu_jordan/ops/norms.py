"""Infinity norms (norm main.cpp:643-667, block_norm main.cpp:669-683).

The reference uses the max-abs-row-sum norm everywhere: as the relative
singularity scale, as the pivot-quality metric (norm of the inverse block),
and for the final residual.  One definition, three call sites — same here.
"""

from __future__ import annotations

import jax.numpy as jnp


def inf_norm(a: jnp.ndarray) -> jnp.ndarray:
    """‖A‖∞ = max_i Σ_j |a_ij| for a 2D matrix (norm, main.cpp:643-667)."""
    return jnp.max(jnp.sum(jnp.abs(a), axis=-1), axis=-1)


def block_inf_norms(blocks: jnp.ndarray) -> jnp.ndarray:
    """‖·‖∞ of each block in a (..., m, m) stack (block_norm, main.cpp:669-683)."""
    return jnp.max(jnp.sum(jnp.abs(blocks), axis=-1), axis=-1)


def condition_inf(a: jnp.ndarray, inv: jnp.ndarray) -> jnp.ndarray:
    """κ∞(A) = ‖A‖∞·‖A⁻¹‖∞, evaluated with the computed inverse.

    No reference analog (it never quantifies conditioning; accuracy claims
    there lean on fp64).  Here it anchors the accuracy story: the expected
    relative residual of a backward-stable fp32 elimination is
    ≈ eps·n·κ∞, so benchmarks gate on a *predicted* bound instead of a
    loose static tolerance.  Exact row sums — two O(n²) passes, no power
    iteration; using the computed X for ‖A⁻¹‖∞ is the standard estimate
    (exact up to the O(eps·κ) error already being measured).
    """
    return inf_norm(a) * inf_norm(inv)
