"""Pallas TPU kernel: the fused normalize-and-eliminate superstep update.

The paper's hot loop (main.cpp:1136-1194) normalizes the pivot block-row
by the already-inverted pivot block and then sweeps the rank-one-block
eliminate ``A[i,:] -= A[i,k] @ pivot_row`` over the whole local row
panel.  In the XLA engines those two GEMMs — plus the in-place
bookkeeping writes around them (zero the pivot column, insert the pivot
block, write the normalized row back) — are whatever XLA happens to
fuse; this kernel makes the fusion explicit: each grid program owns one
(R, C) tile of the working matrix, computes the normalized pivot row for
its column strip (``prow = H @ rows_p``, with H inserted at the pivot
block columns by an exact one-hot MXU dot), and applies the trailing
update ``V ← V − U·[P; prow]`` in ONE VMEM-resident read+write pass —
the bookkeeping masks (pivot column zeroing, pivot-row write-back) fold
into the same pass instead of costing separate HBM sweeps.

It is the group-closing superstep of the delayed-group-update engine
(ops/jordan_inplace.py): at the last step j of a group the freshly
normalized pivot row joins the pending panel stack and the group-end
trailing update retires immediately after, so both fuse into one launch.
The arithmetic is element-for-element identical to the XLA engine's
``jnp.matmul`` sequence (one full-contraction dot per output element,
same operand order), which is what makes the fp32 path bit-match the
existing grouped engine — pinned by tests/test_jordan_inplace.py.

Mixed precision (``mode="bf16"``): the recipe of *Large Scale
Distributed Linear Algebra With TPUs* (arXiv:2112.09017) — dot operands
rounded to bf16, accumulation kept fp32 (``preferred_element_type``),
working storage fp32 throughout.  The pivot PROBE stays fp32 regardless
(ops/refine.py's measured verdict: sub-fp32 probes lose Schur
complements), and the driver never returns a bf16-computed inverse
unguarded: the PR 5 residual-gate ladder (refine → fp32 re-solve) is
attached by default (driver.py).

Tile/VMEM budgeting extends the machinery proven in
``ops/pallas_block_inverse.py``: tiles are the largest multiples of the
block size dividing N whose resident set (V in+out, U strip, P strip,
pivot-row strip, one-hot scatter temporaries) fits a fixed VMEM budget.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: Per-program VMEM budget (bytes) for the fused update's resident tile
#: set.  Full VMEM is ~16 MB; the budget leaves headroom for Mosaic's
#: own temporaries, mirroring pallas_block_inverse._W_BUDGET.
_UPD_BUDGET = 6 * 1024 * 1024

#: Hard cap on a tile edge: beyond 512 the MXU sees no larger effective
#: tiles and the VMEM bill grows quadratically.
_MAX_TILE = 512


def _tile_bytes(R: int, C: int, KM: int, m: int) -> int:
    """fp32 bytes resident per grid program: V tile in+out + one dot
    temporary (3·R·C), the U row strip (R·KM), the P column strip
    (KM·C), the raw pivot-row strip + normalized prow + one-hot insert
    (3·m·C), the row-scatter one-hot (R·m), and H (m²)."""
    return 4 * (3 * R * C + R * KM + KM * C + 3 * m * C + R * m + m * m)


def _update_tiles(N: int, KM: int, m: int,
                  budget: int | None = None) -> tuple[int, int]:
    """Square (R, C) tile for the fused update: the largest multiple of
    ``m`` that divides N, is at most ``_MAX_TILE``, and fits the VMEM
    budget; falls back to (m, m) when even that is over budget (the
    caller's problem sizes keep m² far below it)."""
    if budget is None:
        budget = _UPD_BUDGET           # resolved at call time (tests patch)
    best = m
    t = m
    while t <= min(N, _MAX_TILE):
        if N % t == 0 and _tile_bytes(t, t, KM, m) <= budget:
            best = t
        t += m
    return best, best


def _fused_update_kernel(v_ref, u_ref, p_ref, h_ref, rows_ref, out_ref,
                         *, m, t, j, R, C, mode, precision):
    """One (R, C) tile of ``V ← V − U·[P; prow]`` with the pivot-row
    normalize fused (see module docstring).

    Static parameters: ``t`` (global pivot block index — the engines
    unroll the group loop, so every superstep's t is a Python int),
    ``j`` (position of the closing step inside its group), tile sizes.
    All pivot-block masks compare global iotas (tile iota + program
    offset) against the static block bounds; H / prow placements at
    dynamic tile-relative offsets ride exact 0/1 one-hot MXU dots — the
    same Mosaic-proven idiom as the probe kernels' unscramble step
    (dynamic LANE indexing is illegal, one-hot contraction is not).
    """
    f32 = jnp.float32
    dn = (((1,), (0,)), ((), ()))                   # plain 2D matmul
    row0 = pl.program_id(0) * R
    col0 = pl.program_id(1) * C
    tm0, tm1 = t * m, (t + 1) * m

    h = h_ref[...]                                  # (m, m)
    rp = rows_ref[...]                              # (m, C)
    if mode == "bf16":
        # bf16 compute, fp32 accumulate: operands rounded, the dot
        # accumulates in f32 via preferred_element_type.
        hd, rpd = h.astype(jnp.bfloat16), rp.astype(jnp.bfloat16)
    else:
        hd, rpd = h, rp
    # --- NORMALIZE: prow = H @ rows_p for this column strip.
    prow = jax.lax.dot_general(hd, rpd, dimension_numbers=dn,
                               preferred_element_type=f32,
                               precision=precision)          # (m, C)
    # Insert H at the pivot block columns (prow[:, tm0:tm1] = H): an
    # exact 0/1 scatter via the MXU — S[k, c] = 1 iff global column
    # col0+c is tm0+k.
    ccol = lax.broadcasted_iota(jnp.int32, (m, C), 1) + col0
    kio = lax.broadcasted_iota(jnp.int32, (m, C), 0)
    S = (ccol == kio + tm0).astype(f32)
    hins = jax.lax.dot_general(h, S, dimension_numbers=dn,
                               preferred_element_type=f32,
                               precision=lax.Precision.HIGHEST)
    in_tblk_c = (ccol >= tm0) & (ccol < tm1)
    prow = jnp.where(in_tblk_c, hins, prow)

    # --- Assemble the panel stack [P; prow]: the closing step's slot
    # (rows j·m:(j+1)·m, zeros by the caller's contract) takes prow —
    # static-j sublane masks, exact one-hot placement.
    KM = p_ref.shape[0]
    pk = p_ref[...]                                 # (KM, C)
    kio_km = lax.broadcasted_iota(jnp.int32, (KM, m), 0)
    iio_km = lax.broadcasted_iota(jnp.int32, (KM, m), 1)
    Sp = (kio_km == iio_km + j * m).astype(f32)     # (KM, m) 0/1
    prow_slot = jax.lax.dot_general(Sp, prow, dimension_numbers=dn,
                                    preferred_element_type=f32,
                                    precision=lax.Precision.HIGHEST)
    rio_km = lax.broadcasted_iota(jnp.int32, (KM, C), 0)
    in_jblk = (rio_km >= j * m) & (rio_km < (j + 1) * m)
    p_eff = jnp.where(in_jblk, prow_slot, pk)

    u = u_ref[...]                                  # (R, KM)
    if mode == "bf16":
        u, p_eff = u.astype(jnp.bfloat16), p_eff.astype(jnp.bfloat16)
    upd = jax.lax.dot_general(u, p_eff, dimension_numbers=dn,
                              preferred_element_type=f32,
                              precision=precision)  # (R, C)

    # --- ELIMINATE with the bookkeeping masks folded in: the pivot
    # COLUMN block reads as zero (the in-place engines zero it so the
    # update writes the inverse-building column −E·H there), and the
    # pivot ROW block takes prow verbatim (U's pivot rows are zeroed by
    # the engine, so the uniform formula would subtract an exact 0 —
    # the masked write is the same value, one fewer dependency).
    v = v_ref[...]
    grow = lax.broadcasted_iota(jnp.int32, (R, C), 0) + row0
    gcol = lax.broadcasted_iota(jnp.int32, (R, C), 1) + col0
    v = jnp.where((gcol >= tm0) & (gcol < tm1), jnp.float32(0.0), v)
    out = v - upd
    # prow scattered to its global rows: Srow[r, i] = 1 iff global row
    # row0+r is tm0+i (only the owning row tile has any 1s).
    rio = lax.broadcasted_iota(jnp.int32, (R, m), 0) + row0
    iio = lax.broadcasted_iota(jnp.int32, (R, m), 1)
    Srow = (rio == iio + tm0).astype(f32)
    prow_pad = jax.lax.dot_general(Srow, prow, dimension_numbers=dn,
                                   preferred_element_type=f32,
                                   precision=lax.Precision.HIGHEST)
    out_ref[...] = jnp.where((grow >= tm0) & (grow < tm1), prow_pad, out)


@functools.partial(
    jax.jit,
    static_argnames=("t", "j", "m", "mode", "precision", "interpret"))
def fused_normalize_eliminate(V, U, P, H, rows_p, *, t: int, j: int,
                              m: int, mode: str = "fp32",
                              precision=lax.Precision.HIGHEST,
                              interpret: bool = False):
    """The fused superstep update: ``V ← V − U·[P; H@rows_p]`` with the
    pivot-row normalize, H insertion, pivot-column zeroing, and
    pivot-row write-back all in one VMEM-resident pass.

    Caller contract (the grouped engine's group-closing step, after its
    probe/swap/record bookkeeping):

      * ``V`` (N, N) fp32 — post-swap working matrix;
      * ``U`` (N, kg·m) — pending panel columns, pivot-block rows
        zeroed, column-block ``j`` already holding this step's eager
        eliminate column;
      * ``P`` (kg·m, N) — pending normalized pivot rows, row-block
        ``j`` all zeros (the kernel fills it with the freshly
        normalized row), pivot-column block of earlier rows zeroed;
      * ``H`` (m, m) — the inverted pivot block;
      * ``rows_p`` (m, N) — the raw (eagerly updated) pivot block-row;
      * ``t``/``j`` static: global pivot block index / position of the
        closing step in its group.

    ``mode="bf16"`` rounds the dot operands to bf16 and accumulates
    fp32; ``mode="fp32"`` is element-for-element identical to the XLA
    ``jnp.matmul`` sequence (bit-match pinned).
    """
    if mode not in ("fp32", "bf16"):
        raise ValueError(f"unknown kernel precision mode {mode!r}")
    N = V.shape[0]
    KM = U.shape[1]
    V = V.astype(jnp.float32)
    R, C = _update_tiles(N, KM, m)
    kernel = functools.partial(_fused_update_kernel, m=m, t=t, j=j,
                               R=R, C=C, mode=mode, precision=precision)
    return pl.pallas_call(
        kernel,
        grid=(N // R, N // C),
        in_specs=[
            pl.BlockSpec((R, C), lambda i, k: (i, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, KM), lambda i, k: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((KM, C), lambda i, k: (0, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((m, m), lambda i, k: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((m, C), lambda i, k: (0, k),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, C), lambda i, k: (i, k),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((N, N), jnp.float32),
        interpret=interpret,
    )(V, U.astype(jnp.float32), P.astype(jnp.float32),
      H.astype(jnp.float32), rows_p.astype(jnp.float32))


def interpret_default() -> bool:
    """Pallas runs interpreted on CPU (the tier-1 runs); compiled on
    accelerator backends — same convention as the probe's
    ``_use_pallas_default`` (``not in ("cpu",)``): the bench host
    reaches its TPU through the experimental "axon" platform, so a
    ``== "tpu"`` test would silently interpret-mode the kernel on real
    hardware.  Shared by the engine and the phase bracketer."""
    return jax.default_backend() in ("cpu",)


# ---------------------------------------------------------------------------
# Measured phase brackets (the obs-layer tentpole piece): because the
# Pallas path's probe and update are separately launchable kernels, the
# host CAN bracket them — unlike the fused XLA engines, where the
# pivot/permute/eliminate split under `execute` is a flops MODEL
# (obs/spans.attribute_phases, modeled=True).  The fractions below come
# from real timed launches of the actual kernels at the solve's own
# (n, m, group) configuration, cached per configuration so a telemetry'd
# solve pays the bracketing cost once per process.
# ---------------------------------------------------------------------------

_PHASE_FRACTIONS_CACHE: dict = {}

#: Largest matrix edge the bracket operands materialize.  The brackets
#: run on the SOLVE path (between execute and the residual reload, with
#: the inverse still resident) on chips where the driver donates A
#:  precisely because one extra N² buffer decides OOM at 16384²+ — so
#: the bracket problem is capped (64 MB fp32 at the cap) and the
#: per-launch measurements are scaled to the real configuration by the
#: known per-phase work ratios (below).  At n <= the cap the ratios are
#: all 1 and the fractions are pure measurement.
_BRACKET_MAX_N = 4096


def measured_phase_fractions(n: int, block_size: int, group: int,
                             mode: str = "fp32",
                             interpret: bool | None = None) -> dict:
    """Measured pivot/permute/eliminate wall fractions for the
    grouped-pallas engine at one configuration.

    Brackets (one warmup + one timed call each, host-blocked via
    ``obs.spans.timed_blocking`` — the shared wall bracket):

      * ``pivot`` — the pivot-candidate probe kernel on a full-window
        candidate stack;
      * ``permute`` — the block-row swap pair (two dynamic row updates
        on the working matrix);
      * ``eliminate`` — the fused normalize-and-eliminate kernel on a
        representative group-closing superstep (the non-closing steps'
        eager side matmuls ride this bucket too — they are
        eliminate-phase work).

    Beyond ``_BRACKET_MAX_N`` the brackets run on a capped twin of the
    configuration (same m, same group, same tile geometry) and each
    measured per-launch wall is scaled by its phase's work ratio —
    probe programs ∝ stack size, swap bytes ∝ N·m, update tiles ∝ N² —
    times the real per-solve launch counts.  Still measurement-sourced
    (the flops MODEL never enters); the scaling is recorded per phase.

    Returns ``{"pivot": f, "permute": f, "eliminate": f}`` summing to 1.
    """
    import math

    from ..config import eps_for
    from ..obs.spans import timed_blocking
    from .block_inverse import probe_blocks

    m = min(block_size, n)
    Nr = -(-n // m)
    N = Nr * m
    k = max(1, min(group, Nr))
    if interpret is None:
        interpret = interpret_default()
    key = (N, m, k, mode, jax.default_backend())
    if key in _PHASE_FRACTIONS_CACHE:
        return _PHASE_FRACTIONS_CACHE[key]

    use_pallas = not interpret
    eps = eps_for(jnp.float32)
    km = k * m
    # The capped bracket twin: same m/group (tile geometry preserved),
    # matrix edge at most _BRACKET_MAX_N.
    Nr_b = min(Nr, max(k, _BRACKET_MAX_N // m))
    Nb = Nr_b * m

    # Deterministic well-conditioned operands (index-based, no RNG).
    ii = jnp.arange(Nb, dtype=jnp.float32)
    V = (jnp.eye(Nb, dtype=jnp.float32) * jnp.float32(Nb)
         + jnp.sin(ii)[:, None] * jnp.cos(ii)[None, :])
    cands = V[:, :m].reshape(Nr_b, m, m)
    H = jnp.eye(m, dtype=jnp.float32) + 1e-3 * jnp.outer(
        jnp.sin(ii[:m]), jnp.cos(ii[:m])).astype(jnp.float32)
    rows_p = V[:m]
    U = V[:, :km] * jnp.float32(1e-3)
    P = jnp.zeros((km, Nb), jnp.float32)

    def _probe():
        return probe_blocks(cands, eps, use_pallas)

    @jax.jit
    def _swap(v):
        rows_t = lax.dynamic_slice(v, (0, 0), (m, Nb))
        rows_b = lax.dynamic_slice(v, (Nb - m, 0), (m, Nb))
        v = lax.dynamic_update_slice(v, rows_t, (Nb - m, 0))
        return lax.dynamic_update_slice(v, rows_b, (0, 0))

    def _update():
        return fused_normalize_eliminate(
            V, U, P, H, rows_p, t=0, j=k - 1, m=m, mode=mode,
            interpret=interpret)

    # Per-solve multipliers: real launch counts x the capped twin's
    # work ratio for that phase.
    scale = {
        "pivot": Nr * (Nr / Nr_b),           # probe programs ∝ stack
        "permute": Nr * (N / Nb),            # swap bytes ∝ N·m
        "eliminate": max(1, Nr // k) * (N / Nb) ** 2,   # tiles ∝ N²
    }
    brackets = {}
    for name, fn in (("pivot", _probe),
                     ("permute", lambda: _swap(V)),
                     ("eliminate", _update)):
        fn()                                   # warmup: compile excluded
        _, sp = timed_blocking(fn, name=f"bracket_{name}")
        brackets[name] = max(sp.duration, 1e-9) * scale[name]
    total = math.fsum(brackets.values())
    fractions = {p: brackets[p] / total for p in brackets}
    _PHASE_FRACTIONS_CACHE[key] = fractions
    return fractions
