"""Small-block Gauss–Jordan inverse with scalar partial pivoting.

TPU-native rebuild of ``inverse_block`` (main.cpp:746-820): invert an m x m
block in-place by Gauss–Jordan with column partial pivoting, declaring the
block singular when a pivot falls below ``eps * norm`` (relative threshold,
main.cpp:782) or the scale itself vanishes (``|norm| < eps``).

Design notes (TPU-first, not a translation):
  * the k-loop is a ``lax.fori_loop`` with static shapes; row swap and
    elimination are masked whole-matrix ops (rank-1 update on the MXU/VPU),
    never scalar loops;
  * a singular block does not abort — the flag is carried and division is
    guarded, so the op stays batchable: ``vmap`` inverts *all* pivot
    candidates of a block column in one shot (the reference probes them one
    by one, main.cpp:1039-1066 — batching is the MXU win);
  * no data-dependent control flow: singular results are garbage values plus
    a True flag, exactly like the reference's ``return 1``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..config import eps_for
from .norms import inf_norm


def gauss_jordan_inverse(
    a: jnp.ndarray,
    scale_norm: jnp.ndarray | float | None = None,
    eps: float | None = None,
):
    """Invert one m x m block.

    Args:
      a: (m, m) matrix.
      scale_norm: relative scale for the singularity threshold.  The
        reference passes the ∞-norm of the *whole local strip of A*
        (``norm_a``, main.cpp:972/1046), not of the block — pass that for
        parity; defaults to ‖a‖∞.
      eps: relative threshold; defaults to the dtype's (config.eps_for).

    Returns:
      (inv, singular): the inverse (garbage if singular) and a bool flag.
    """
    m = a.shape[-1]
    dtype = a.dtype
    if eps is None:
        eps = eps_for(dtype)
    if scale_norm is None:
        scale_norm = inf_norm(a)
    # Magnitude comparisons run in the REAL dtype (ISSUE 11 complex
    # support: |z| of a complex64 block is float32, and mixing it with a
    # complex threshold would promote the argmax key to complex).  For
    # real dtypes ‖a‖∞ is already non-negative, so abs() is the identity
    # and every comparison below is value-identical to the pre-complex
    # code.
    scale_abs = jnp.abs(jnp.asarray(scale_norm, dtype))
    thresh = jnp.asarray(eps, scale_abs.dtype) * scale_abs

    idx = jnp.arange(m)
    w = jnp.concatenate([a, jnp.eye(m, dtype=dtype)], axis=1)  # (m, 2m)

    def body(k, carry):
        w, singular = carry
        col = lax.dynamic_slice_in_dim(w, k, 1, axis=1)[:, 0]       # (m,)
        # column partial pivot: argmax |w[i,k]| over i >= k (main.cpp:756-763)
        mags = jnp.abs(col)                                    # real dtype
        cand = jnp.where(idx >= k, mags, jnp.asarray(-1.0, mags.dtype))
        r = jnp.argmax(cand)
        # swap rows k and r (masked select; main.cpp:765-781)
        row_k = jnp.take(w, k, axis=0)
        row_r = jnp.take(w, r, axis=0)
        is_k = (idx == k)[:, None]
        is_r = (idx == r)[:, None]
        w = jnp.where(is_k, row_r[None, :], jnp.where(is_r, row_k[None, :], w))
        # singularity gate (main.cpp:782): relative threshold, plus
        # degenerate-scale case |norm| < eps
        piv = jnp.take(row_r, k)
        singular = (
            singular
            | (jnp.abs(piv) < thresh)
            | (scale_abs < jnp.asarray(eps, scale_abs.dtype))
        )
        safe_piv = jnp.where(piv == 0, jnp.asarray(1, dtype), piv)
        prow = jnp.take(w, k, axis=0) / safe_piv                    # (2m,)
        # eliminate above and below (main.cpp:794-817) as one rank-1 update
        colk = lax.dynamic_slice_in_dim(w, k, 1, axis=1)[:, 0]
        factors = jnp.where(idx == k, jnp.asarray(0, dtype), colk)  # (m,)
        w = w - factors[:, None] * prow[None, :]
        w = jnp.where(is_k, prow[None, :], w)
        return w, singular

    # The initial flag is derived from the data (non-finite input ⇒
    # singular) rather than a constant False: correct semantics, and under
    # shard_map the carry then matches the data's device-varying type.
    singular0 = ~jnp.all(jnp.isfinite(a))
    w, singular = lax.fori_loop(0, m, body, (w, singular0))
    return w[:, m:], singular


@partial(jax.jit, static_argnames=("eps",))
def batched_block_inverse(
    blocks: jnp.ndarray,
    scale_norm: jnp.ndarray | float | None = None,
    eps: float | None = None,
):
    """Invert a (..., m, m) stack of blocks in one vmapped sweep.

    This is the pivot-candidate probe (main.cpp:1039-1066) turned into a
    single batched op.  Returns (inverses, singular_flags).
    """
    batch_shape = blocks.shape[:-2]
    m = blocks.shape[-1]
    flat = blocks.reshape((-1, m, m))
    if scale_norm is None:
        inv, sing = jax.vmap(lambda b: gauss_jordan_inverse(b, None, eps))(flat)
    else:
        scale = jnp.broadcast_to(
            jnp.asarray(scale_norm, blocks.dtype), flat.shape[:1]
        )
        inv, sing = jax.vmap(
            lambda b, s: gauss_jordan_inverse(b, s, eps)
        )(flat, scale)
    return inv.reshape(batch_shape + (m, m)), sing.reshape(batch_shape)


def probe_blocks(cands: jnp.ndarray, eps, use_pallas: bool):
    """The pivot-candidate probe dispatch shared by every elimination
    engine: VMEM-resident pallas kernel on TPU, vmapped XLA fallback
    elsewhere.  Returns (inverses, singular_flags)."""
    if use_pallas:
        from .pallas_block_inverse import pallas_batched_block_inverse

        return pallas_batched_block_inverse(cands, eps)
    return batched_block_inverse(cands, None, eps)


def probe_blocks_quarter_masked(cands, t, stride: int, eps,
                                use_pallas: bool):
    """Quarter-window probe ladder for the traced (fori_loop) engines.

    Like ``probe_blocks_half_masked`` but with FOUR window sizes: at
    step ``t`` (global units; ``stride`` converts a window slot to its
    smallest global row, e.g. p for the 1D layout, 1 single-chip) every
    slot below ``t // stride`` is dead, so the ladder probes only the
    trailing w, 3w/4, w/2, or w/4 slots — on TPU the probe's grid
    programs are the cost (per-program cost is flat), so the ladder
    recovers most of the unrolled engines' static-shrinking-window
    advantage (measured: the half cut alone leaves the grouped-fori
    engine ~9-19% behind unrolled at 8192-16384).  Dead slots are padded
    with identity blocks flagged singular, keeping every branch's output
    (w, m, m).

    Four distinct probe shapes in one XLA program is within the
    measured-safe region on this backend (the half cut already ships
    two; A/B'd on chip before adoption — benchmarks/PHASES.md round 5).
    """
    w, m = cands.shape[0], cands.shape[-1]
    if w < 8:
        return probe_blocks(cands, eps, use_pallas)
    q = w // 4

    def mk(start: int):
        def branch(c):
            invs_u, sing_u = probe_blocks(c[start:], eps, use_pallas)
            if not start:
                return invs_u, sing_u
            eye = jnp.broadcast_to(jnp.eye(m, dtype=c.dtype),
                                   (start, m, m))
            return (jnp.concatenate([eye, invs_u]),
                    jnp.concatenate([jnp.ones((start,), bool), sing_u]))

        return branch

    # Quarter index: how many leading quarters are entirely dead.  Slot
    # s covers global rows >= s*stride, so quarter [q*i, q*(i+1)) is
    # dead iff t >= q*(i+1)*stride... conservatively: slots below
    # t // stride are dead; leading dead quarters = (t // stride) // q.
    qi = jnp.clip((t // stride) // q, 0, 3)
    return lax.switch(qi, [mk(0), mk(q), mk(2 * q), mk(3 * q)], cands)


def probe_blocks_half_masked(cands, upper_only, eps, use_pallas: bool):
    """Half-window probe cut shared by the traced (fori_loop) engines.

    When ``upper_only`` (a traced bool — e.g. ``t >= (window//2)*stride``
    with the layout's slot stride), probe only the upper half of the
    candidate window and pad the dead lower half with identity blocks
    flagged singular, so the downstream inf-key masking excludes them
    while every branch keeps the same (w, m, m) shape for ``lax.cond``.
    The unrolled engines shrink the window statically instead; this is
    the traced-shape substitute (reference probes the live window too,
    main.cpp:1039)."""
    w, m = cands.shape[0], cands.shape[-1]
    half = w // 2
    if not half:
        return probe_blocks(cands, eps, use_pallas)

    def _upper(c):
        invs_u, sing_u = probe_blocks(c[half:], eps, use_pallas)
        eye = jnp.broadcast_to(jnp.eye(m, dtype=c.dtype), (half, m, m))
        return (jnp.concatenate([eye, invs_u]),
                jnp.concatenate([jnp.ones((half,), bool), sing_u]))

    return lax.cond(upper_only, _upper,
                    lambda c: probe_blocks(c, eps, use_pallas), cands)
