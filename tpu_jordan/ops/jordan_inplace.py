"""In-place blocked Gauss–Jordan inversion: the single-chip speed path.

Same algorithm semantics as ``ops/jordan.py::block_jordan_invert`` — the
condition-based block pivoting of the reference's ``Jordan``
(main.cpp:953-1204), identical pivot choices — but storing only the N×N
working matrix instead of the augmented [A | B]:

  * the classic in-place Gauss–Jordan update: at step t the eliminated
    column block is *replaced* by the inverse-building column
    (``V[:,t] ← −E·H``, ``V[t,t] ← H``), so no B half exists.  Total flops
    drop from ~4N³ (augmented full-width sweeps) to ~2N³, and per-step HBM
    traffic halves — both measured as the dominant costs of the augmented
    version (benchmarks/PHASES.md).
  * the loop over block columns is UNROLLED (Python loop, one jit trace):
    every slice offset is static, and the pivot probe at step t inverts
    only the ``Nr − t`` remaining candidate rows instead of masking all
    ``Nr`` — half the probe work on average, the other measured hot spot.
    The reference probes exactly this window too (``i >= start_row``,
    main.cpp:1039).
  * row pivoting is physical swaps (as in the reference); in the in-place
    form the final inverse needs the row-swap history replayed as *column*
    swaps in reverse order (standard in-place GJ bookkeeping, no reference
    analog because the reference carries B explicitly).

The augmented ``block_jordan_invert`` remains the reference
implementation (arbitrary Nr without unrolled-compile cost, global_scale
parity mode) and the basis of the sharded paths.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..config import default_block_size, eps_for
from .block_inverse import batched_block_inverse
from .jordan import _use_pallas_default
from .norms import block_inf_norms
from .padding import pad_with_identity, unpad
from .refine import newton_schulz, resolve_precision


@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "use_pallas"))
def block_jordan_invert_inplace(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    use_pallas: bool | None = None,
):
    """Invert ``a`` by in-place blocked Gauss–Jordan with condition-based
    pivoting.  Drop-in for ``block_jordan_invert`` (same pivot rule, same
    (inv, singular) contract); ~2x fewer flops and ~2x less memory
    traffic.  Compile cost scales with Nr (unrolled) — intended for the
    headline configurations (Nr ≲ 64).

    ``precision="mixed"`` runs the sweeps at HIGH + ≥2 HIGHEST
    Newton–Schulz steps (see ops/refine.py::resolve_precision).
    """
    precision, refine = resolve_precision(precision, refine)
    n = a.shape[-1]
    in_dtype = a.dtype
    if jnp.dtype(in_dtype).itemsize < 4:
        # Same sub-fp32 policy as block_jordan_invert: fp32 compute, one
        # final rounding back to the storage dtype.
        x, singular = block_jordan_invert_inplace(
            a.astype(jnp.float32), block_size, eps, precision, refine,
            use_pallas,
        )
        return x.astype(in_dtype), singular
    dtype = a.dtype
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)
    if eps is None:
        eps = eps_for(dtype)
    Nr = -(-n // m)
    N = Nr * m
    V = pad_with_identity(a, N)
    if use_pallas is None:
        use_pallas = _use_pallas_default(dtype) and m % 8 == 0 and m >= 32
    probe_dtype = dtype

    singular = jnp.asarray(False)
    rswaps = []
    for t in range(Nr):
        nc = Nr - t
        # --- PROBE the remaining candidate rows only (main.cpp:1039).
        cands = lax.slice(V, (t * m, t * m), (N, (t + 1) * m))
        cands = cands.reshape(nc, m, m).astype(probe_dtype)
        if use_pallas:
            from .pallas_block_inverse import pallas_batched_block_inverse

            invs, sing = pallas_batched_block_inverse(cands, eps)
        else:
            invs, sing = batched_block_inverse(cands, None, eps)
        key = jnp.where(sing, jnp.asarray(jnp.inf, probe_dtype),
                        block_inf_norms(invs))
        rel = jnp.argmin(key)                     # ties -> lowest row
        singular = singular | jnp.all(sing)
        H = jnp.take(invs, rel, axis=0).astype(dtype)
        piv = t + rel

        # --- SWAP block rows t <-> piv (swap-by-copy, main.cpp:1093-1131).
        rows_t = lax.slice(V, (t * m, 0), ((t + 1) * m, N))
        rows_p = lax.dynamic_slice(V, (piv * m, 0), (m, N))
        V = lax.dynamic_update_slice(V, rows_t, (piv * m, 0))

        # --- NORMALIZE + ELIMINATE, in place: B never exists.  The
        # eliminated column must become the inverse-building column −E·H
        # (H on the pivot row); setting prow's t-block to H and zeroing
        # V's t-column first folds that into the one big matmul
        # (V[:,t] − E·H = −E·H), so no separate column-fix GEMM exists.
        prow = jnp.matmul(H, rows_p, precision=precision)       # (m, N)
        prow = prow.at[:, t * m:(t + 1) * m].set(H)
        E = lax.slice(V, (0, t * m), (N, (t + 1) * m))          # (N, m)
        E = E.at[t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
        V = V.at[:, t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
        V = V - jnp.matmul(E, prow, precision=precision)
        V = V.at[t * m:(t + 1) * m, :].set(prow)
        rswaps.append(piv)

    # --- Unscramble: replay row swaps as column swaps in reverse.
    for t in reversed(range(Nr)):
        piv = rswaps[t]
        col_t = lax.slice(V, (0, t * m), (N, (t + 1) * m))
        col_p = lax.dynamic_slice(V, (0, piv * m), (N, m))
        V = lax.dynamic_update_slice(V, col_t, (0, piv * m))
        V = V.at[:, t * m:(t + 1) * m].set(col_p)

    x = unpad(V, n)
    # Refinement always runs at HIGHEST: its whole job is recovering the
    # accuracy a cheaper sweep precision gave up.
    x = newton_schulz(a, x, refine, lax.Precision.HIGHEST)
    return x, singular
