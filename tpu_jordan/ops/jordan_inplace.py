"""In-place blocked Gauss–Jordan inversion: the single-chip speed path.

Same algorithm semantics as ``ops/jordan.py::block_jordan_invert`` — the
condition-based block pivoting of the reference's ``Jordan``
(main.cpp:953-1204), identical pivot choices — but storing only the N×N
working matrix instead of the augmented [A | B]:

  * the classic in-place Gauss–Jordan update: at step t the eliminated
    column block is *replaced* by the inverse-building column
    (``V[:,t] ← −E·H``, ``V[t,t] ← H``), so no B half exists.  Total flops
    drop from ~4N³ (augmented full-width sweeps) to ~2N³, and per-step HBM
    traffic halves — both measured as the dominant costs of the augmented
    version (benchmarks/PHASES.md).
  * the loop over block columns is UNROLLED (Python loop, one jit trace):
    every slice offset is static, and the pivot probe at step t inverts
    only the ``Nr − t`` remaining candidate rows instead of masking all
    ``Nr`` — half the probe work on average, the other measured hot spot.
    The reference probes exactly this window too (``i >= start_row``,
    main.cpp:1039).
  * row pivoting is physical swaps (as in the reference); in the in-place
    form the final inverse needs the row-swap history replayed as *column*
    swaps in reverse order (standard in-place GJ bookkeeping, no reference
    analog because the reference carries B explicitly).

The augmented ``block_jordan_invert`` remains the reference
implementation (arbitrary Nr without unrolled-compile cost, global_scale
parity mode) and the basis of the sharded paths.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..config import default_block_size, eps_for
from .block_inverse import batched_block_inverse
from .jordan import _use_pallas_default
from .norms import block_inf_norms
from .padding import pad_with_identity, unpad
from .refine import newton_schulz, resolve_precision


class _StepStats:
    """Per-superstep health accumulator for the INSTRUMENTED unrolled
    engines (``collect_stats=True``; ISSUE 10 numerics trace).

    Records, per elimination step, the paper's own selection evidence
    (main.cpp:1026-1074): the chosen pivot block id, the ∞-norm of its
    block inverse (the criterion value — the step's ``key`` minimum),
    the worst FINITE candidate norm (the spread's other end), the
    probe's singular-candidate count, and the running element-growth
    watermark ``max|V|``.  Everything is a reduction over values the
    engine already computed — the stats ride the same executable as
    stacked (Nr,) outputs and the inverse bits are untouched (pinned
    by tests/test_numerics.py)."""

    def __init__(self):
        self.pivot_block, self.pivot_inv_norm = [], []
        self.cand_norm_max, self.singular_candidates = [], []
        self.growth = []
        self._watermark = None

    def probe(self, piv, key, sing):
        finite = jnp.isfinite(key)
        self.pivot_block.append(jnp.asarray(piv, jnp.int32))
        self.pivot_inv_norm.append(jnp.min(key))
        self.cand_norm_max.append(
            jnp.max(jnp.where(finite, key,
                              jnp.asarray(-jnp.inf, key.dtype))))
        self.singular_candidates.append(
            jnp.sum(sing).astype(jnp.int32))

    def sample_growth(self, *arrays):
        """One per-step watermark sample over the live working state
        (the grouped engine passes V and the pending panel U — the
        eliminated columns live there until the group closes)."""
        w = jnp.max(jnp.stack([jnp.max(jnp.abs(x)) for x in arrays]))
        self._watermark = (w if self._watermark is None
                           else jnp.maximum(self._watermark, w))
        self.growth.append(self._watermark)

    def refresh(self, *arrays):
        """Fold a post-group-end state into the LAST recorded step's
        watermark (the trailing V − U·P update lands after its group's
        steps were already sampled)."""
        w = jnp.max(jnp.stack([jnp.max(jnp.abs(x)) for x in arrays]))
        self._watermark = jnp.maximum(self._watermark, w)
        self.growth[-1] = self._watermark

    def stacked(self) -> dict:
        return {
            "pivot_block": jnp.stack(self.pivot_block),
            "pivot_inv_norm": jnp.stack(self.pivot_inv_norm),
            "cand_norm_max": jnp.stack(self.cand_norm_max),
            "singular_candidates": jnp.stack(self.singular_candidates),
            "growth": jnp.stack(self.growth),
        }


def compose_swap_perm(swaps, Nr: int):
    """Fold the row-swap history into ONE block-column permutation.

    The in-place bookkeeping requires replaying the row swaps as column
    swaps in reverse; doing that literally is Nr sequential full-matrix
    column exchanges, and XLA materializes a whole-V copy for each
    (measured 26 ms of pure copies at n=8192 m=256 — 25% of the
    inversion).  Swaps only MOVE columns, so the replay composes into a
    single permutation: simulate the reversed transpositions on an index
    vector (O(Nr) scalar work) and let the caller apply it with one
    blocked gather — one pass over V instead of Nr.

    Returns ``cols`` (Nr,) int32 where output block-column j is input
    block-column ``cols[j]``.
    """
    swaps = jnp.asarray(swaps, jnp.int32)
    # Derive the initial index vector from ``swaps`` (+0·swaps) so that
    # under shard_map it inherits the swap history's device-varying type
    # — a replicated fori_loop carry against a varying output is a type
    # error there.
    cols0 = jnp.arange(Nr, dtype=jnp.int32) + 0 * swaps

    def compose(i, cols):
        t = jnp.asarray(Nr - 1 - i, jnp.int32)
        p = swaps[t]
        ct, cp = cols[t], cols[p]
        return cols.at[t].set(cp).at[p].set(ct)

    return lax.fori_loop(0, Nr, compose, cols0)


def apply_col_perm(V, cols, m: int):
    """Apply a block-column permutation to the LAST axis with one blocked
    gather: out[..., j·m:(j+1)·m] = V[..., cols[j]·m:(cols[j]+1)·m].
    Works on the (N, N) single-chip matrix and the sharded (bpw, m, N)
    block tensors alike."""
    N = V.shape[-1]
    Nr = N // m
    lead = V.shape[:-1]
    out = jnp.take(V.reshape(lead + (Nr, m)), cols, axis=len(lead))
    return out.reshape(lead + (N,))


@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "use_pallas",
    "collect_stats"))
def block_jordan_invert_inplace(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    use_pallas: bool | None = None,
    collect_stats: bool = False,
):
    """Invert ``a`` by in-place blocked Gauss–Jordan with condition-based
    pivoting.  Drop-in for ``block_jordan_invert`` (same pivot rule, same
    (inv, singular) contract); ~2x fewer flops and ~2x less memory
    traffic.  Compile cost scales with Nr (unrolled) — intended for the
    headline configurations (Nr ≲ 64).

    ``precision="mixed"`` runs the sweeps at HIGH + ≥2 HIGHEST
    Newton–Schulz steps (see ops/refine.py::resolve_precision).

    ``collect_stats=True`` (the ISSUE 10 numerics trace) returns
    ``(x, singular, stats)`` with per-superstep health arrays
    (:class:`_StepStats`) stacked into the same executable; the
    inverse is bit-identical to the uninstrumented call.
    """
    precision, refine = resolve_precision(precision, refine)
    n = a.shape[-1]
    in_dtype = a.dtype
    if jnp.dtype(in_dtype).itemsize < 4:
        # Same sub-fp32 policy as block_jordan_invert: fp32 compute, one
        # final rounding back to the storage dtype.
        out = block_jordan_invert_inplace(
            a.astype(jnp.float32), block_size, eps, precision, refine,
            use_pallas, collect_stats,
        )
        if collect_stats:
            x, singular, stats = out
            return x.astype(in_dtype), singular, stats
        x, singular = out
        return x.astype(in_dtype), singular
    dtype = a.dtype
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)
    if eps is None:
        eps = eps_for(dtype)
    Nr = -(-n // m)
    N = Nr * m
    V = pad_with_identity(a, N)
    if use_pallas is None:
        use_pallas = _use_pallas_default(dtype) and m % 8 == 0 and m >= 32
    probe_dtype = dtype

    singular = jnp.asarray(False)
    stats = _StepStats() if collect_stats else None
    rswaps = []
    for t in range(Nr):
        nc = Nr - t
        # --- PROBE the remaining candidate rows only (main.cpp:1039).
        cands = lax.slice(V, (t * m, t * m), (N, (t + 1) * m))
        cands = cands.reshape(nc, m, m).astype(probe_dtype)
        if use_pallas:
            from .pallas_block_inverse import pallas_batched_block_inverse

            invs, sing = pallas_batched_block_inverse(cands, eps)
        else:
            invs, sing = batched_block_inverse(cands, None, eps)
        key = jnp.where(sing, jnp.asarray(jnp.inf, probe_dtype),
                        block_inf_norms(invs))
        rel = jnp.argmin(key)                     # ties -> lowest row
        singular = singular | jnp.all(sing)
        H = jnp.take(invs, rel, axis=0).astype(dtype)
        piv = t + rel
        if stats is not None:
            stats.probe(piv, key, sing)

        # --- SWAP block rows t <-> piv (swap-by-copy, main.cpp:1093-1131).
        rows_t = lax.slice(V, (t * m, 0), ((t + 1) * m, N))
        rows_p = lax.dynamic_slice(V, (piv * m, 0), (m, N))
        V = lax.dynamic_update_slice(V, rows_t, (piv * m, 0))

        # --- NORMALIZE + ELIMINATE, in place: B never exists.  The
        # eliminated column must become the inverse-building column −E·H
        # (H on the pivot row); setting prow's t-block to H and zeroing
        # V's t-column first folds that into the one big matmul
        # (V[:,t] − E·H = −E·H), so no separate column-fix GEMM exists.
        prow = jnp.matmul(H, rows_p, precision=precision)       # (m, N)
        prow = prow.at[:, t * m:(t + 1) * m].set(H)
        E = lax.slice(V, (0, t * m), (N, (t + 1) * m))          # (N, m)
        E = E.at[t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
        V = V.at[:, t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
        V = V - jnp.matmul(E, prow, precision=precision)
        V = V.at[t * m:(t + 1) * m, :].set(prow)
        rswaps.append(piv)
        if stats is not None:
            stats.sample_growth(V)

    # --- Unscramble: the composed swap permutation, one blocked gather.
    V = apply_col_perm(V, compose_swap_perm(jnp.stack(rswaps), Nr), m)

    x = unpad(V, n)
    # Refinement always runs at HIGHEST: its whole job is recovering the
    # accuracy a cheaper sweep precision gave up.
    x = newton_schulz(a, x, refine, lax.Precision.HIGHEST)
    if stats is not None:
        return x, singular, stats.stacked()
    return x, singular


@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "use_pallas",
    "collect_stats"))
def block_jordan_invert_inplace_lookahead(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    use_pallas: bool | None = None,
    collect_stats: bool = False,
):
    """The in-place engine with PROBE-AHEAD scheduling (ISSUE 16): step
    t+1's pivot probe no longer waits for step t's full eliminate sweep.

    Each superstep's eliminate is split into the CRITICAL PANEL — the
    one column block that is step t+1's candidate column — and the
    TRAILING update (every other column).  The panel update is emitted
    first, step t+1's probe (batched block inverses + argmin) launches
    immediately after it, and only then does the trailing eliminate run
    — so a latency-hiding scheduler can overlap the probe with the bulk
    of the rank-m GEMM instead of serializing them.

    Same arithmetic in a reordered schedule: the panel value is the
    column slice of the very matmul the plain engine computes
    (``matmul(E, prow)[:, cols] == matmul(E, prow[:, cols])``
    element-for-element at HIGHEST — each output element is the same
    full contraction over m), so pivot choices, the numerics trace, and
    the inverse bits are pinned IDENTICAL to
    ``block_jordan_invert_inplace``
    (tests/test_jordan_inplace.py::TestLookahead).

    On one chip the probe and the GEMM share the compute units, so the
    single-device win is scheduling slack only; the payoff is on the
    distributed flavors (sharded_inplace/jordan2d_inplace), where the
    probe's cross-worker pmin reduction comes off the superstep critical
    path.  This twin exists so the schedule is validated (and the
    numerics trace comparable) without a mesh.
    """
    precision, refine = resolve_precision(precision, refine)
    n = a.shape[-1]
    in_dtype = a.dtype
    if jnp.dtype(in_dtype).itemsize < 4:
        out = block_jordan_invert_inplace_lookahead(
            a.astype(jnp.float32), block_size, eps, precision, refine,
            use_pallas, collect_stats,
        )
        if collect_stats:
            x, singular, stats = out
            return x.astype(in_dtype), singular, stats
        x, singular = out
        return x.astype(in_dtype), singular
    dtype = a.dtype
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)
    if eps is None:
        eps = eps_for(dtype)
    Nr = -(-n // m)
    N = Nr * m
    V = pad_with_identity(a, N)
    if use_pallas is None:
        use_pallas = _use_pallas_default(dtype) and m % 8 == 0 and m >= 32
    probe_dtype = dtype

    def probe_col(cands, t):
        """The plain engine's probe, verbatim, on a (nc, m, m) candidate
        stack for step ``t`` — returns the step's full pivot decision."""
        if use_pallas:
            from .pallas_block_inverse import pallas_batched_block_inverse

            invs, sing = pallas_batched_block_inverse(cands, eps)
        else:
            invs, sing = batched_block_inverse(cands, None, eps)
        key = jnp.where(sing, jnp.asarray(jnp.inf, probe_dtype),
                        block_inf_norms(invs))
        rel = jnp.argmin(key)                     # ties -> lowest row
        H = jnp.take(invs, rel, axis=0).astype(dtype)
        return H, t + rel, key, sing

    singular = jnp.asarray(False)
    stats = _StepStats() if collect_stats else None
    rswaps = []
    # --- PROLOGUE: step 0's probe runs on the untouched first column
    # (bit-equal to the plain engine's t=0 slice).
    cands0 = lax.slice(V, (0, 0), (N, m)).reshape(Nr, m, m)
    ahead = probe_col(cands0.astype(probe_dtype), 0)
    for t in range(Nr):
        H, piv, key, sing = ahead
        singular = singular | jnp.all(sing)
        if stats is not None:
            stats.probe(piv, key, sing)

        # --- SWAP block rows t <-> piv (swap-by-copy, main.cpp:1093-1131).
        rows_t = lax.slice(V, (t * m, 0), ((t + 1) * m, N))
        rows_p = lax.dynamic_slice(V, (piv * m, 0), (m, N))
        V = lax.dynamic_update_slice(V, rows_t, (piv * m, 0))

        # --- NORMALIZE (same fold as the plain engine).
        prow = jnp.matmul(H, rows_p, precision=precision)       # (m, N)
        prow = prow.at[:, t * m:(t + 1) * m].set(H)
        E = lax.slice(V, (0, t * m), (N, (t + 1) * m))          # (N, m)
        E = E.at[t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
        V = V.at[:, t * m:(t + 1) * m].set(jnp.asarray(0, dtype))

        if t < Nr - 1:
            # --- CRITICAL PANEL first: step t+1's candidate column gets
            # its rank-m update ahead of everything else.  The probe's
            # candidate rows start at (t+1)·m, below the pivot-row write
            # — the slice the plain engine probes next step is exactly
            # this panel.
            c0 = (t + 1) * m
            panel = (lax.slice(V, (0, c0), (N, c0 + m))
                     - jnp.matmul(E, prow[:, c0:c0 + m],
                                  precision=precision))
            # --- PROBE-AHEAD: step t+1's pivot decision, issued before
            # the trailing eliminate so the two can overlap.
            ahead = probe_col(
                panel[c0:].reshape(Nr - t - 1, m, m).astype(probe_dtype),
                t + 1)
            # --- TRAILING ELIMINATE: the remaining columns (same sliced
            # contractions; concat restores the plain engine's V bits).
            left = (lax.slice(V, (0, 0), (N, c0))
                    - jnp.matmul(E, prow[:, :c0], precision=precision))
            right = (lax.slice(V, (0, c0 + m), (N, N))
                     - jnp.matmul(E, prow[:, c0 + m:],
                                  precision=precision))
            V = jnp.concatenate([left, panel, right], axis=1)
        else:
            V = V - jnp.matmul(E, prow, precision=precision)
        V = V.at[t * m:(t + 1) * m, :].set(prow)
        rswaps.append(piv)
        if stats is not None:
            stats.sample_growth(V)

    # --- Unscramble: the composed swap permutation, one blocked gather.
    V = apply_col_perm(V, compose_swap_perm(jnp.stack(rswaps), Nr), m)
    x = unpad(V, n)
    x = newton_schulz(a, x, refine, lax.Precision.HIGHEST)
    if stats is not None:
        return x, singular, stats.stacked()
    return x, singular


@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "use_pallas", "group",
    "collect_stats"))
def block_jordan_invert_inplace_grouped(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    use_pallas: bool | None = None,
    group: int = 4,
    collect_stats: bool = False,
):
    """In-place blocked Gauss–Jordan with DELAYED GROUP UPDATES: the
    single-chip headline engine for large n.

    The plain in-place engine applies a rank-m update to the whole N×N
    working matrix every step: per inversion that is Nr·2N²·4 bytes of
    HBM traffic (∝ N³/m) and Nr thin (N,m)×(m,N) matmuls whose small
    contraction dim underutilizes the MXU.  Here ``group=k`` consecutive
    elimination panels are accumulated into U (N, k·m) / P (k·m, N) and
    applied as ONE (N, k·m)×(k·m, N) matmul per group — k× less traffic,
    MXU-friendly contraction k·m — while the pivot search stays exact:
    the probed column and the pivot row are eagerly updated with the
    pending panels ((N, j·m)×(j·m, m) and (m, j·m)×(j·m, N) side
    matmuls, ~2N²·m·k extra flops per inversion, a few % of 2N³).

    Same condition-based pivot RULE as every other engine (probe the
    live window of column t, argmin ‖block⁻¹‖∞, reference
    main.cpp:1026-1196) and identical results in exact arithmetic; the
    grouped summation order means results match the unrolled engine to
    rounding, not bitwise (standard blocked-elimination trade, the same
    one LAPACK makes vs unblocked reference implementations).

    Group bookkeeping invariants (why the eager formulas stay exact):
      * V's group columns are zeroed at their elimination step, so the
        eager value of any group column is uniformly V − U·P;
      * a finalized pivot row is written into V immediately and its U
        row zeroed, so the group-end subtract leaves it untouched while
        later panels still update it through their own U columns;
      * row swaps move U rows together with V rows (pending
        contributions follow the physical row); the swap history is
        replayed as column swaps in reverse after the loop, exactly as
        in the plain engine.
    """
    precision, refine = resolve_precision(precision, refine)
    n = a.shape[-1]
    in_dtype = a.dtype
    if jnp.dtype(in_dtype).itemsize < 4:
        out = block_jordan_invert_inplace_grouped(
            a.astype(jnp.float32), block_size, eps, precision, refine,
            use_pallas, group, collect_stats,
        )
        if collect_stats:
            x, singular, stats = out
            return x.astype(in_dtype), singular, stats
        x, singular = out
        return x.astype(in_dtype), singular
    dtype = a.dtype
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)
    if eps is None:
        eps = eps_for(dtype)
    Nr = -(-n // m)
    N = Nr * m
    k = max(1, min(group, Nr))
    V = pad_with_identity(a, N)
    if use_pallas is None:
        use_pallas = _use_pallas_default(dtype) and m % 8 == 0 and m >= 32
    from .block_inverse import probe_blocks

    singular = jnp.asarray(False)
    stats = _StepStats() if collect_stats else None
    rswaps = []
    for t0 in range(0, Nr, k):
        kg = min(k, Nr - t0)                   # this group's width
        U = jnp.zeros((N, kg * m), dtype)
        P = jnp.zeros((kg * m, N), dtype)
        for j in range(kg):
            t = t0 + j
            nc = Nr - t
            # --- EAGER CANDIDATE COLUMN: V[:, t] minus pending panels.
            col = lax.slice(V, (0, t * m), (N, (t + 1) * m))
            if j:
                col = col - jnp.matmul(
                    U[:, :j * m], P[:j * m, t * m:(t + 1) * m],
                    precision=precision)
            # --- PROBE the live window (main.cpp:1039).
            cands = col[t * m:].reshape(nc, m, m)
            invs, sing = probe_blocks(cands, eps, use_pallas)
            key = jnp.where(sing, jnp.asarray(jnp.inf, dtype),
                            block_inf_norms(invs))
            rel = jnp.argmin(key)              # ties -> lowest row
            singular = singular | jnp.all(sing)
            H = jnp.take(invs, rel, axis=0).astype(dtype)
            piv = t + rel
            if stats is not None:
                stats.probe(piv, key, sing)

            # --- SWAP rows t <-> piv in V and U (swap-by-copy; pending
            # panel contributions follow the physical row).
            rows_t = lax.slice(V, (t * m, 0), ((t + 1) * m, N))
            rows_p = lax.dynamic_slice(V, (piv * m, 0), (m, N))
            V = lax.dynamic_update_slice(V, rows_t, (piv * m, 0))
            u_t = lax.slice(U, (t * m, 0), ((t + 1) * m, kg * m))
            u_p = lax.dynamic_slice(U, (piv * m, 0), (m, kg * m))
            U = lax.dynamic_update_slice(U, u_t, (piv * m, 0))

            # --- EAGER PIVOT ROW: old piv row minus pending panels.
            if j:
                rows_p = rows_p - jnp.matmul(u_p[:, :j * m], P[:j * m],
                                             precision=precision)
            prow = jnp.matmul(H, rows_p, precision=precision)   # (m, N)
            prow = prow.at[:, t * m:(t + 1) * m].set(H)

            # --- RECORD the panel: E = eager column, rows t/piv
            # exchanged, pivot-row block zeroed.
            col_t_blk = col[t * m:(t + 1) * m]
            col = lax.dynamic_update_slice(col, col_t_blk, (piv * m, 0))
            col = col.at[t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
            # --- BOOKKEEPING WRITES (the invariants above).  Zeroing
            # V's column t also requires cancelling the PENDING panels'
            # contributions to it — sequential zeroing wipes them, so the
            # group-end U·P subtract must not re-apply them.
            V = V.at[:, t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
            if j:
                P = P.at[:j * m, t * m:(t + 1) * m].set(
                    jnp.asarray(0, dtype))
            V = V.at[t * m:(t + 1) * m, :].set(prow)
            U = U.at[t * m:(t + 1) * m, :].set(jnp.asarray(0, dtype))
            U = U.at[:, j * m:(j + 1) * m].set(col)
            P = P.at[j * m:(j + 1) * m, :].set(prow)
            rswaps.append(piv)
            if stats is not None:
                stats.sample_growth(V, U)

        # --- GROUP-END TRAILING UPDATE: one fat MXU matmul.
        V = V - jnp.matmul(U, P, precision=precision)
        if stats is not None:
            stats.refresh(V)

    # --- Unscramble: the composed swap permutation, one blocked gather.
    V = apply_col_perm(V, compose_swap_perm(jnp.stack(rswaps), Nr), m)

    x = unpad(V, n)
    x = newton_schulz(a, x, refine, lax.Precision.HIGHEST)
    if stats is not None:
        return x, singular, stats.stacked()
    return x, singular


@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "use_pallas", "group",
    "collect_stats"))
def block_jordan_invert_inplace_grouped_lookahead(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    use_pallas: bool | None = None,
    group: int = 4,
    collect_stats: bool = False,
):
    """The delayed-group-update engine with PROBE-AHEAD scheduling
    (ISSUE 16): the grouped engine already overlaps WITHIN a group (its
    eager side-updates keep the probe off the trailing matmul), so the
    serial seam left is the group BOUNDARY — the next group's first
    probe waits for the group-end ``V − U·P``.  This twin hoists that
    step's eager candidate column (``V[:, tn] − U·P[:, tn]``, the column
    slice of the very trailing matmul — same full contractions, so the
    values are bit-equal to what the grouped engine slices after the
    update) plus its probe ABOVE the trailing matmul, so the probe can
    run concurrently with the group-end GEMM.

    Pivot choices and the inverse bit-match
    ``block_jordan_invert_inplace_grouped`` exactly
    (tests/test_jordan_inplace.py::TestLookahead)."""
    precision, refine = resolve_precision(precision, refine)
    n = a.shape[-1]
    in_dtype = a.dtype
    if jnp.dtype(in_dtype).itemsize < 4:
        out = block_jordan_invert_inplace_grouped_lookahead(
            a.astype(jnp.float32), block_size, eps, precision, refine,
            use_pallas, group, collect_stats,
        )
        if collect_stats:
            x, singular, stats = out
            return x.astype(in_dtype), singular, stats
        x, singular = out
        return x.astype(in_dtype), singular
    dtype = a.dtype
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)
    if eps is None:
        eps = eps_for(dtype)
    Nr = -(-n // m)
    N = Nr * m
    k = max(1, min(group, Nr))
    V = pad_with_identity(a, N)
    if use_pallas is None:
        use_pallas = _use_pallas_default(dtype) and m % 8 == 0 and m >= 32
    from .block_inverse import probe_blocks

    def probe_col(col, t):
        """The grouped engine's probe, verbatim, on an eager (N, m)
        candidate column for step ``t``."""
        cands = col[t * m:].reshape(Nr - t, m, m)
        invs, sing = probe_blocks(cands, eps, use_pallas)
        key = jnp.where(sing, jnp.asarray(jnp.inf, dtype),
                        block_inf_norms(invs))
        rel = jnp.argmin(key)                  # ties -> lowest row
        H = jnp.take(invs, rel, axis=0).astype(dtype)
        return col, H, t + rel, key, sing

    singular = jnp.asarray(False)
    stats = _StepStats() if collect_stats else None
    rswaps = []
    # --- PROLOGUE: group 0's first probe on the untouched first column.
    ahead = probe_col(lax.slice(V, (0, 0), (N, m)), 0)
    for t0 in range(0, Nr, k):
        kg = min(k, Nr - t0)                   # this group's width
        U = jnp.zeros((N, kg * m), dtype)
        P = jnp.zeros((kg * m, N), dtype)
        for j in range(kg):
            t = t0 + j
            if j:
                # --- EAGER CANDIDATE COLUMN + PROBE, in-group (the
                # grouped engine's own schedule — already overlapped).
                col = lax.slice(V, (0, t * m), (N, (t + 1) * m))
                col = col - jnp.matmul(
                    U[:, :j * m], P[:j * m, t * m:(t + 1) * m],
                    precision=precision)
                col, H, piv, key, sing = probe_col(col, t)
            else:
                # --- PROBE-AHEAD: this group's first decision was made
                # before the previous group-end trailing matmul.
                col, H, piv, key, sing = ahead
            singular = singular | jnp.all(sing)
            if stats is not None:
                stats.probe(piv, key, sing)

            # --- SWAP rows t <-> piv in V and U.
            rows_t = lax.slice(V, (t * m, 0), ((t + 1) * m, N))
            rows_p = lax.dynamic_slice(V, (piv * m, 0), (m, N))
            V = lax.dynamic_update_slice(V, rows_t, (piv * m, 0))
            u_t = lax.slice(U, (t * m, 0), ((t + 1) * m, kg * m))
            u_p = lax.dynamic_slice(U, (piv * m, 0), (m, kg * m))
            U = lax.dynamic_update_slice(U, u_t, (piv * m, 0))

            # --- EAGER PIVOT ROW: old piv row minus pending panels.
            if j:
                rows_p = rows_p - jnp.matmul(u_p[:, :j * m], P[:j * m],
                                             precision=precision)
            prow = jnp.matmul(H, rows_p, precision=precision)   # (m, N)
            prow = prow.at[:, t * m:(t + 1) * m].set(H)

            # --- RECORD the panel (grouped-engine bookkeeping verbatim).
            col_t_blk = col[t * m:(t + 1) * m]
            col = lax.dynamic_update_slice(col, col_t_blk, (piv * m, 0))
            col = col.at[t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
            V = V.at[:, t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
            if j:
                P = P.at[:j * m, t * m:(t + 1) * m].set(
                    jnp.asarray(0, dtype))
            V = V.at[t * m:(t + 1) * m, :].set(prow)
            U = U.at[t * m:(t + 1) * m, :].set(jnp.asarray(0, dtype))
            U = U.at[:, j * m:(j + 1) * m].set(col)
            P = P.at[j * m:(j + 1) * m, :].set(prow)
            rswaps.append(piv)
            if stats is not None:
                stats.sample_growth(V, U)

        tn = t0 + kg
        if tn < Nr:
            # --- CRITICAL PANEL + PROBE-AHEAD: the next group's first
            # eager column is the column slice of the group-end trailing
            # matmul — compute it (and the probe) BEFORE that matmul so
            # the probe overlaps the fat GEMM.
            coln = (lax.slice(V, (0, tn * m), (N, (tn + 1) * m))
                    - jnp.matmul(U, P[:, tn * m:(tn + 1) * m],
                                 precision=precision))
            ahead = probe_col(coln, tn)

        # --- GROUP-END TRAILING UPDATE: one fat MXU matmul.
        V = V - jnp.matmul(U, P, precision=precision)
        if stats is not None:
            stats.refresh(V)

    # --- Unscramble: the composed swap permutation, one blocked gather.
    V = apply_col_perm(V, compose_swap_perm(jnp.stack(rswaps), Nr), m)
    x = unpad(V, n)
    x = newton_schulz(a, x, refine, lax.Precision.HIGHEST)
    if stats is not None:
        return x, singular, stats.stacked()
    return x, singular


@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "use_pallas", "group",
    "mode", "interpret"))
def block_jordan_invert_inplace_grouped_pallas(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    use_pallas: bool | None = None,
    group: int = 4,
    mode: str = "fp32",
    interpret: bool | None = None,
):
    """The delayed-group-update engine with the group-closing superstep
    — pivot-row normalize + trailing eliminate sweep + in-place
    bookkeeping writes — fused into ONE Pallas kernel launch
    (ops/pallas_update.py; ISSUE 6 tentpole).

    Identical pivot choices and BIT-IDENTICAL fp32 results to
    ``block_jordan_invert_inplace_grouped`` (pinned by
    tests/test_jordan_inplace.py): the probe, swaps, eager side-updates
    and non-closing bookkeeping are the same code, and the kernel's
    fused pass computes element-for-element the same full-contraction
    dots as the XLA engine's ``jnp.matmul`` sequence — only the HBM
    pass structure changes (the normalize, the pivot-column zeroing,
    the pivot-row write-back and the group-end ``V − U·P`` collapse
    from separate XLA sweeps into one VMEM-resident read+write of V).

    ``mode="bf16"`` is the mixed-precision path (arXiv:2112.09017):
    kernel dot operands rounded to bf16, fp32 accumulation, fp32
    storage; the pivot PROBE stays fp32, so pivot quality never
    degrades.  A bf16 inverse is bf16-grade accurate — the driver
    attaches the PR 5 residual-gate ladder by default so a failed gate
    walks refine → fp32 re-solve instead of returning silently degraded
    numbers (driver.py, docs/RESILIENCE.md).

    Unrolled-only (every superstep's pivot block index is static — the
    kernel's mask geometry is compile-time): compile cost scales with
    Nr like the other unrolled engines, so the driver gates it to
    Nr <= MAX_UNROLL_NR and larger problems keep the grouped-fori
    engine.
    """
    from .pallas_update import fused_normalize_eliminate, interpret_default

    precision, refine = resolve_precision(precision, refine)
    n = a.shape[-1]
    in_dtype = a.dtype
    if jnp.dtype(in_dtype).itemsize < 4:
        x, singular = block_jordan_invert_inplace_grouped_pallas(
            a.astype(jnp.float32), block_size, eps, precision, refine,
            use_pallas, group, mode, interpret,
        )
        return x.astype(in_dtype), singular
    if jnp.dtype(in_dtype).itemsize > 4:
        raise ValueError(
            "the grouped_pallas engines compute in fp32 (the fused "
            "kernel is fp32-only, like the probe kernel); use "
            "engine='grouped' for float64")
    dtype = a.dtype
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)
    if eps is None:
        eps = eps_for(dtype)
    Nr = -(-n // m)
    N = Nr * m
    k = max(1, min(group, Nr))
    V = pad_with_identity(a, N)
    if use_pallas is None:
        use_pallas = _use_pallas_default(dtype) and m % 8 == 0 and m >= 32
    if interpret is None:
        interpret = interpret_default()
    from .block_inverse import probe_blocks

    singular = jnp.asarray(False)
    rswaps = []
    for t0 in range(0, Nr, k):
        kg = min(k, Nr - t0)                   # this group's width
        U = jnp.zeros((N, kg * m), dtype)
        P = jnp.zeros((kg * m, N), dtype)
        for j in range(kg):
            t = t0 + j
            nc = Nr - t
            # --- EAGER CANDIDATE COLUMN / PROBE / SWAP: the grouped
            # engine's own steps, verbatim (bit-match contract).
            col = lax.slice(V, (0, t * m), (N, (t + 1) * m))
            if j:
                col = col - jnp.matmul(
                    U[:, :j * m], P[:j * m, t * m:(t + 1) * m],
                    precision=precision)
            cands = col[t * m:].reshape(nc, m, m)
            invs, sing = probe_blocks(cands, eps, use_pallas)
            key = jnp.where(sing, jnp.asarray(jnp.inf, dtype),
                            block_inf_norms(invs))
            rel = jnp.argmin(key)              # ties -> lowest row
            singular = singular | jnp.all(sing)
            H = jnp.take(invs, rel, axis=0).astype(dtype)
            piv = t + rel

            rows_t = lax.slice(V, (t * m, 0), ((t + 1) * m, N))
            rows_p = lax.dynamic_slice(V, (piv * m, 0), (m, N))
            V = lax.dynamic_update_slice(V, rows_t, (piv * m, 0))
            u_t = lax.slice(U, (t * m, 0), ((t + 1) * m, kg * m))
            u_p = lax.dynamic_slice(U, (piv * m, 0), (m, kg * m))
            U = lax.dynamic_update_slice(U, u_t, (piv * m, 0))

            # --- EAGER PIVOT ROW: old piv row minus pending panels.
            if j:
                rows_p = rows_p - jnp.matmul(u_p[:, :j * m], P[:j * m],
                                             precision=precision)

            # --- RECORD the panel column (same bookkeeping either way).
            col_t_blk = col[t * m:(t + 1) * m]
            col = lax.dynamic_update_slice(col, col_t_blk, (piv * m, 0))
            col = col.at[t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
            if j:
                P = P.at[:j * m, t * m:(t + 1) * m].set(
                    jnp.asarray(0, dtype))
            U = U.at[t * m:(t + 1) * m, :].set(jnp.asarray(0, dtype))
            U = U.at[:, j * m:(j + 1) * m].set(col)
            rswaps.append(piv)

            if j < kg - 1:
                # Non-closing step: normalize + V bookkeeping in XLA,
                # exactly the grouped engine's writes (P row j feeds the
                # NEXT steps' eager side-updates, so it must exist now).
                prow = jnp.matmul(H, rows_p, precision=precision)
                prow = prow.at[:, t * m:(t + 1) * m].set(H)
                V = V.at[:, t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
                V = V.at[t * m:(t + 1) * m, :].set(prow)
                P = P.at[j * m:(j + 1) * m, :].set(prow)
            else:
                # --- GROUP-CLOSING SUPERSTEP, FUSED: normalize
                # (H @ rows_p + H insertion), pivot-column zeroing,
                # pivot-row write-back, and the group-end trailing
                # eliminate V − U·[P; prow] — one kernel launch, one
                # VMEM-resident pass over V.
                V = fused_normalize_eliminate(
                    V, U, P, H, rows_p, t=t, j=j, m=m, mode=mode,
                    precision=precision, interpret=interpret)

    # --- Unscramble: the composed swap permutation, one blocked gather.
    V = apply_col_perm(V, compose_swap_perm(jnp.stack(rswaps), Nr), m)

    x = unpad(V, n)
    x = newton_schulz(a, x, refine, lax.Precision.HIGHEST)
    return x, singular


def _grouped_step(t, j: int, V, U, P, singular, swaps, *, Nr: int, N: int,
                  m: int, eps, precision, use_pallas: bool):
    """One inner elimination step of a delayed-group-update group.

    ``t`` may be a traced int32 (the fori_loop engine) or a Python int
    (the unrolled tail group); ``j`` (position within the group) is
    always static.  Arithmetic is identical to the unrolled grouped
    engine's inner loop — the probe just runs on the masked window
    (quarter ladder, probe_blocks_quarter_masked) instead of a
    statically shrunk one, which changes launch shapes but not any
    per-candidate value, so results bit-match the unrolled engine.
    """
    from .block_inverse import probe_blocks_quarter_masked

    dtype = V.dtype
    t = jnp.asarray(t, jnp.int32)
    z = jnp.int32(0)     # literal index: x64 would make a bare 0 int64
    gidx = jnp.arange(Nr)
    rowblk = jnp.arange(N) // m

    # --- EAGER CANDIDATE COLUMN: V[:, t] minus pending panels.
    col = lax.dynamic_slice(V, (z, t * m), (N, m))
    if j:
        col = col - jnp.matmul(
            U[:, :j * m], lax.dynamic_slice(P, (z, t * m), (j * m, m)),
            precision=precision)

    # --- PROBE the masked window, quarter ladder (main.cpp:1039).
    invs, sing = probe_blocks_quarter_masked(
        col.reshape(Nr, m, m), t, 1, eps, use_pallas)
    valid = (gidx >= t) & ~sing
    norms = block_inf_norms(invs)
    key = jnp.where(valid, norms, jnp.asarray(jnp.inf, norms.dtype))
    piv = jnp.argmin(key).astype(jnp.int32)      # ties -> lowest row
    step_sing = ~jnp.isfinite(key[piv])
    singular = singular | step_sing
    # All-singular window: the unrolled engine's argmin over its shrunk
    # window lands on rel=0 => piv=t (a benign self-swap); the masked
    # full-window argmin would land on dead row 0 — pin piv=t so the
    # swap history (and the bit-match claim) hold on singular inputs too.
    piv = jnp.where(step_sing, t, piv)
    H = jnp.take(invs, piv, axis=0).astype(dtype)

    # --- SWAP rows t <-> piv in V and U (pending contributions follow
    # the physical row; main.cpp:1093-1131).
    rows_t = lax.dynamic_slice(V, (t * m, z), (m, N))
    rows_p = lax.dynamic_slice(V, (piv * m, z), (m, N))
    V = lax.dynamic_update_slice(V, rows_t, (piv * m, z))
    u_t = lax.dynamic_slice(U, (t * m, z), (m, U.shape[1]))
    u_p = lax.dynamic_slice(U, (piv * m, z), (m, U.shape[1]))
    U = lax.dynamic_update_slice(U, u_t, (piv * m, z))

    # --- EAGER PIVOT ROW: old piv row minus pending panels.
    if j:
        rows_p = rows_p - jnp.matmul(u_p[:, :j * m], P[:j * m],
                                     precision=precision)
    prow = jnp.matmul(H, rows_p, precision=precision)       # (m, N)
    prow = lax.dynamic_update_slice(prow, H, (z, t * m))

    # --- RECORD the panel: eager column with rows t/piv exchanged,
    # pivot-row block zeroed.
    col_t_blk = lax.dynamic_slice(col, (t * m, z), (m, m))
    col = lax.dynamic_update_slice(col, col_t_blk, (piv * m, z))
    col = jnp.where((rowblk == t)[:, None], jnp.asarray(0, dtype), col)

    # --- BOOKKEEPING WRITES (the grouped engine's invariants).
    V = lax.dynamic_update_slice(V, jnp.zeros((N, m), dtype), (z, t * m))
    if j:
        P = lax.dynamic_update_slice(
            P, jnp.zeros((j * m, m), dtype), (z, t * m))
    V = lax.dynamic_update_slice(V, prow, (t * m, z))
    U = lax.dynamic_update_slice(
        U, jnp.zeros((m, U.shape[1]), dtype), (t * m, z))
    U = U.at[:, j * m:(j + 1) * m].set(col)
    P = P.at[j * m:(j + 1) * m, :].set(prow)
    swaps = swaps.at[t].set(piv)
    return V, U, P, singular, swaps


@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "use_pallas", "group"))
def block_jordan_invert_inplace_grouped_fori(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    use_pallas: bool | None = None,
    group: int = 4,
):
    """The delayed-group-update engine with the group loop as a
    ``lax.fori_loop`` — identical pivot choices and bit-identical results
    to ``block_jordan_invert_inplace_grouped`` (pinned by tests), but
    compile cost independent of Nr (the inner group of ``group`` steps is
    the only unrolled region).

    This is what makes the fastest engine affordable to compile at the
    configurations where it wins: the unrolled grouped trace at
    n=16384/m=128 (Nr=128) costs ~88 s — the priciest compile in the
    suite and the direct cause of the round-4 bench losing its headline
    capture to a transient remote-compile failure (VERDICT r4 weak #1) —
    while this trace stays a few seconds at any Nr.  A trailing partial
    group (Nr % group != 0) runs as one unrolled tail after the loop.
    """
    precision, refine = resolve_precision(precision, refine)
    n = a.shape[-1]
    in_dtype = a.dtype
    if jnp.dtype(in_dtype).itemsize < 4:
        x, singular = block_jordan_invert_inplace_grouped_fori(
            a.astype(jnp.float32), block_size, eps, precision, refine,
            use_pallas, group,
        )
        return x.astype(in_dtype), singular
    dtype = a.dtype
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)
    if eps is None:
        eps = eps_for(dtype)
    Nr = -(-n // m)
    N = Nr * m
    k = max(1, min(group, Nr))
    V = pad_with_identity(a, N)
    if use_pallas is None:
        use_pallas = _use_pallas_default(dtype) and m % 8 == 0 and m >= 32
    G, tail = divmod(Nr, k)
    step = partial(_grouped_step, Nr=Nr, N=N, m=m, eps=eps,
                   precision=precision, use_pallas=use_pallas)

    def body(g, carry):
        V, singular, swaps = carry
        t0 = (g * k).astype(jnp.int32)
        U = jnp.zeros((N, k * m), dtype)
        P = jnp.zeros((k * m, N), dtype)
        for j in range(k):
            V, U, P, singular, swaps = step(
                t0 + j, j, V, U, P, singular, swaps)
        # --- GROUP-END TRAILING UPDATE: one fat MXU matmul.
        V = V - jnp.matmul(U, P, precision=precision)
        return V, singular, swaps

    singular0 = jnp.asarray(False)
    swaps0 = jnp.zeros((Nr,), jnp.int32)
    V, singular, swaps = lax.fori_loop(0, G, body, (V, singular0, swaps0))

    if tail:
        U = jnp.zeros((N, tail * m), dtype)
        P = jnp.zeros((tail * m, N), dtype)
        for j in range(tail):
            V, U, P, singular, swaps = step(
                G * k + j, j, V, U, P, singular, swaps)
        V = V - jnp.matmul(U, P, precision=precision)

    # --- Unscramble: the composed swap permutation, one blocked gather.
    V = apply_col_perm(V, compose_swap_perm(swaps, Nr), m)
    x = unpad(V, n)
    x = newton_schulz(a, x, refine, lax.Precision.HIGHEST)
    return x, singular


@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "use_pallas"))
def block_jordan_invert_inplace_fori(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    use_pallas: bool | None = None,
):
    """The in-place 2N³ engine with the block-column loop as a
    ``lax.fori_loop`` — identical pivot choices and results to the
    unrolled ``block_jordan_invert_inplace``, but compile cost independent
    of Nr, so it covers Nr > MAX_UNROLL_NR (n=16384 at the probe-optimal
    m=128 is Nr=128; the unrolled trace there is not affordable).

    Differences from the unrolled engine, all trace-compatibility driven:
      * slice offsets use the traced ``t`` via ``lax.dynamic_slice`` (the
        augmented ``ops/jordan.py`` engine's own pattern);
      * the probe runs on the masked candidate column shrunk by the
        quarter-window ladder (probe_blocks_quarter_masked: a lax.switch
        over window sizes Nr, 3Nr/4, Nr/2, Nr/4 — ~0.63x the full-probe
        launches on average vs the unrolled engine's static ~0.5x; the
        reference probes the live window too, main.cpp:1039);
      * the row-swap history is carried as an (Nr,) int32 array and
        replayed by a second fori_loop.
    """
    precision, refine = resolve_precision(precision, refine)
    n = a.shape[-1]
    in_dtype = a.dtype
    if jnp.dtype(in_dtype).itemsize < 4:
        x, singular = block_jordan_invert_inplace_fori(
            a.astype(jnp.float32), block_size, eps, precision, refine,
            use_pallas,
        )
        return x.astype(in_dtype), singular
    dtype = a.dtype
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)
    if eps is None:
        eps = eps_for(dtype)
    Nr = -(-n // m)
    N = Nr * m
    V = pad_with_identity(a, N)
    if use_pallas is None:
        use_pallas = _use_pallas_default(dtype) and m % 8 == 0 and m >= 32
    def body(t, carry):
        V, singular, swaps = carry
        return _inplace_fori_step(t, V, singular, swaps, Nr=Nr, m=m,
                                  eps=eps, precision=precision,
                                  use_pallas=use_pallas)

    singular0 = jnp.asarray(False)
    swaps0 = jnp.zeros((Nr,), jnp.int32)
    V, singular, swaps = lax.fori_loop(0, Nr, body, (V, singular0, swaps0))

    # --- Unscramble: the composed swap permutation, one blocked gather.
    V = apply_col_perm(V, compose_swap_perm(swaps, Nr), m)
    x = unpad(V, n)
    x = newton_schulz(a, x, refine, lax.Precision.HIGHEST)
    return x, singular


def _inplace_fori_step(t, V, singular, swaps, *, Nr: int, m: int, eps,
                       precision, use_pallas: bool):
    """One traced-``t`` in-place super-step on the full (N, N) working
    set — the fori_loop body of :func:`block_jordan_invert_inplace_fori`,
    factored to module level VERBATIM (same ops, same bits) so the
    checkpointed segment runner (ISSUE 20, resilience/checkpoint.py)
    re-enters the SAME arithmetic at an arbitrary step."""
    from .block_inverse import probe_blocks_quarter_masked

    N = Nr * m
    dtype = V.dtype
    gidx = jnp.arange(Nr)
    rowblk = jnp.arange(N) // m

    # --- PROBE (masked window, quarter ladder; main.cpp:1039).
    col = lax.dynamic_slice(V, (0, t * m), (N, m)).reshape(Nr, m, m)
    invs, sing = probe_blocks_quarter_masked(col, t, 1, eps,
                                             use_pallas)
    valid = (gidx >= t) & ~sing
    key = jnp.where(valid, block_inf_norms(invs),
                    jnp.asarray(jnp.inf, dtype))
    piv = jnp.argmin(key)                     # ties -> lowest row
    singular = singular | ~jnp.isfinite(key[piv])
    H = jnp.take(invs, piv, axis=0).astype(dtype)

    # --- SWAP block rows t <-> piv (swap-by-copy, main.cpp:1093-1131).
    rows_t = lax.dynamic_slice(V, (t * m, 0), (m, N))
    rows_p = lax.dynamic_slice(V, (piv * m, 0), (m, N))
    V = lax.dynamic_update_slice(V, rows_t, (piv * m, 0))

    # --- NORMALIZE + ELIMINATE in place (same fold as the unrolled
    # engine: V[:,t] zeroed so the one matmul writes −E·H there).
    prow = jnp.matmul(H, rows_p, precision=precision)       # (m, N)
    prow = lax.dynamic_update_slice(prow, H, (0, t * m))
    E = lax.dynamic_slice(V, (0, t * m), (N, m))            # (N, m)
    E = jnp.where((rowblk == t)[:, None], jnp.asarray(0, dtype), E)
    V = lax.dynamic_update_slice(
        V, jnp.zeros((N, m), dtype), (0, t * m))
    V = V - jnp.matmul(E, prow, precision=precision)
    V = lax.dynamic_update_slice(V, prow, (t * m, 0))
    return V, singular, swaps.at[t].set(piv.astype(jnp.int32))


# ---------------------------------------------------------------------
# Checkpointed segment executables (ISSUE 20).  A checkpointed invert
# runs supersteps [t0, t1) as ONE jitted executable per segment, the
# (V, swaps, singular) elimination state round-tripping to host between
# segments (byte-exact).  The row-swap history rides as an (Nr,) int32
# array in every flavor (the fori engines' own carry; the unrolled
# engines' Python-list ``rswaps`` holds the same values), and the
# unscramble + unpad move to :func:`invert_finalize` — applied ONCE
# after the last segment, exactly where the monolithic engines apply
# them.  Each segment runs the same per-step arithmetic as its
# monolithic engine, so the concatenation bit-matches the uninterrupted
# run (pinned by tests/test_checkpoint.py).
# ---------------------------------------------------------------------


@partial(jax.jit, static_argnames=("t0", "t1", "Nr", "m", "eps",
                                   "precision", "use_pallas"))
def invert_segment(V, singular, swaps, *, t0: int, t1: int, Nr: int,
                   m: int, eps, precision=lax.Precision.HIGHEST,
                   use_pallas: bool = False):
    """Supersteps [t0, t1) of the UNROLLED in-place invert: the exact
    loop body of :func:`block_jordan_invert_inplace` (static offsets,
    live-window probe), restricted to a static step range, with the
    swap record written into the carried (Nr,) array instead of a
    Python list."""
    N = Nr * m
    dtype = V.dtype
    probe_dtype = dtype
    for t in range(t0, t1):
        nc = Nr - t
        # --- PROBE the remaining candidate rows only (main.cpp:1039).
        cands = lax.slice(V, (t * m, t * m), (N, (t + 1) * m))
        cands = cands.reshape(nc, m, m).astype(probe_dtype)
        if use_pallas:
            from .pallas_block_inverse import pallas_batched_block_inverse

            invs, sing = pallas_batched_block_inverse(cands, eps)
        else:
            invs, sing = batched_block_inverse(cands, None, eps)
        key = jnp.where(sing, jnp.asarray(jnp.inf, probe_dtype),
                        block_inf_norms(invs))
        rel = jnp.argmin(key)                     # ties -> lowest row
        singular = singular | jnp.all(sing)
        H = jnp.take(invs, rel, axis=0).astype(dtype)
        piv = t + rel

        # --- SWAP block rows t <-> piv (swap-by-copy).
        rows_t = lax.slice(V, (t * m, 0), ((t + 1) * m, N))
        rows_p = lax.dynamic_slice(V, (piv * m, 0), (m, N))
        V = lax.dynamic_update_slice(V, rows_t, (piv * m, 0))

        # --- NORMALIZE + ELIMINATE, in place (the one-matmul fold of
        # the monolithic engine).
        prow = jnp.matmul(H, rows_p, precision=precision)       # (m, N)
        prow = prow.at[:, t * m:(t + 1) * m].set(H)
        E = lax.slice(V, (0, t * m), (N, (t + 1) * m))          # (N, m)
        E = E.at[t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
        V = V.at[:, t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
        V = V - jnp.matmul(E, prow, precision=precision)
        V = V.at[t * m:(t + 1) * m, :].set(prow)
        swaps = swaps.at[t].set(jnp.asarray(piv, jnp.int32))
    return V, singular, swaps


@partial(jax.jit, static_argnames=("t0", "t1", "Nr", "m", "eps",
                                   "precision", "use_pallas"))
def invert_segment_fori(V, singular, swaps, *, t0: int, t1: int,
                        Nr: int, m: int, eps,
                        precision=lax.Precision.HIGHEST,
                        use_pallas: bool = False):
    """Supersteps [t0, t1) of the fori in-place invert: a ``fori_loop``
    over the shared :func:`_inplace_fori_step` body — one executable
    shape per segment length, the monolithic fori engine's bits."""
    def body(t, carry):
        V, singular, swaps = carry
        return _inplace_fori_step(t, V, singular, swaps, Nr=Nr, m=m,
                                  eps=eps, precision=precision,
                                  use_pallas=use_pallas)

    return lax.fori_loop(t0, t1, body, (V, singular, swaps))


@partial(jax.jit, static_argnames=("t0", "t1", "Nr", "m", "group",
                                   "eps", "precision", "use_pallas"))
def invert_segment_grouped(V, singular, swaps, *, t0: int, t1: int,
                           Nr: int, m: int, group: int, eps,
                           precision=lax.Precision.HIGHEST,
                           use_pallas: bool = False):
    """Supersteps [t0, t1) of the GROUPED engine, where ``t0`` and
    ``t1`` MUST sit on group boundaries (``t0 % group == 0``; ``t1``
    a group multiple or Nr): the U/P panel accumulators are intra-group
    temporaries — between groups the state is exactly (V, singular,
    swaps), which is what makes group boundaries the only legal
    checkpoint cadence for this flavor (resilience/checkpoint.py rounds
    the cadence up and refuses a resume step off the grid)."""
    from .block_inverse import probe_blocks

    N = Nr * m
    dtype = V.dtype
    k = max(1, min(group, Nr))
    if t0 % k or (t1 % k and t1 != Nr):
        raise ValueError(
            f"grouped segment bounds must sit on group boundaries: "
            f"[{t0}, {t1}) with group={k}")
    for g0 in range(t0, t1, k):
        kg = min(k, Nr - g0)                   # this group's width
        U = jnp.zeros((N, kg * m), dtype)
        P = jnp.zeros((kg * m, N), dtype)
        for j in range(kg):
            t = g0 + j
            nc = Nr - t
            # --- EAGER CANDIDATE COLUMN: V[:, t] minus pending panels.
            col = lax.slice(V, (0, t * m), (N, (t + 1) * m))
            if j:
                col = col - jnp.matmul(
                    U[:, :j * m], P[:j * m, t * m:(t + 1) * m],
                    precision=precision)
            # --- PROBE the live window (main.cpp:1039).
            cands = col[t * m:].reshape(nc, m, m)
            invs, sing = probe_blocks(cands, eps, use_pallas)
            key = jnp.where(sing, jnp.asarray(jnp.inf, dtype),
                            block_inf_norms(invs))
            rel = jnp.argmin(key)              # ties -> lowest row
            singular = singular | jnp.all(sing)
            H = jnp.take(invs, rel, axis=0).astype(dtype)
            piv = t + rel

            # --- SWAP rows t <-> piv in V and U.
            rows_t = lax.slice(V, (t * m, 0), ((t + 1) * m, N))
            rows_p = lax.dynamic_slice(V, (piv * m, 0), (m, N))
            V = lax.dynamic_update_slice(V, rows_t, (piv * m, 0))
            u_t = lax.slice(U, (t * m, 0), ((t + 1) * m, kg * m))
            u_p = lax.dynamic_slice(U, (piv * m, 0), (m, kg * m))
            U = lax.dynamic_update_slice(U, u_t, (piv * m, 0))

            # --- EAGER PIVOT ROW: old piv row minus pending panels.
            if j:
                rows_p = rows_p - jnp.matmul(u_p[:, :j * m], P[:j * m],
                                             precision=precision)
            prow = jnp.matmul(H, rows_p, precision=precision)   # (m, N)
            prow = prow.at[:, t * m:(t + 1) * m].set(H)

            # --- RECORD the panel (the monolithic engine's invariants).
            col_t_blk = col[t * m:(t + 1) * m]
            col = lax.dynamic_update_slice(col, col_t_blk, (piv * m, 0))
            col = col.at[t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
            V = V.at[:, t * m:(t + 1) * m].set(jnp.asarray(0, dtype))
            if j:
                P = P.at[:j * m, t * m:(t + 1) * m].set(
                    jnp.asarray(0, dtype))
            V = V.at[t * m:(t + 1) * m, :].set(prow)
            U = U.at[t * m:(t + 1) * m, :].set(jnp.asarray(0, dtype))
            U = U.at[:, j * m:(j + 1) * m].set(col)
            P = P.at[j * m:(j + 1) * m, :].set(prow)
            swaps = swaps.at[t].set(jnp.asarray(piv, jnp.int32))

        # --- GROUP-END TRAILING UPDATE: one fat MXU matmul.
        V = V - jnp.matmul(U, P, precision=precision)
    return V, singular, swaps


@partial(jax.jit, static_argnames=("n", "Nr", "m"))
def invert_finalize(V, swaps, *, n: int, Nr: int, m: int):
    """The monolithic engines' epilogue as its own executable: compose
    the recorded swap permutation, apply it as one blocked column
    gather, strip the identity padding.  Runs once, after the last
    segment — exactly the ops the uninterrupted engines run after their
    loops, on bit-identical inputs."""
    V = apply_col_perm(V, compose_swap_perm(swaps, Nr), m)
    return unpad(V, n)
