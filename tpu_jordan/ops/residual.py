"""Residual verification ‖A·A⁻¹ − I‖∞.

The reference's de-facto integration test (main.cpp:490-513): after
inversion it recomputes A (destroyed in place), runs the independent
distributed ring GEMM (matrix_mult_matrix, main.cpp:534-641), subtracts I
(minus_i, main.cpp:1206-1224) and takes the max-allreduced ∞-norm.

Single-device version here; the sharded ring-GEMM version lives in
``parallel/ring_gemm.py`` so the check stays *independent* of the inversion
path, as in the reference.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .norms import inf_norm


@partial(jax.jit, static_argnames=("precision",))
def residual_inf_norm(
    a: jnp.ndarray,
    a_inv: jnp.ndarray,
    precision=lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """‖A·A⁻¹ − I‖∞ (main.cpp:501-507: mult, minus_i, norm, MAX-allreduce)."""
    n = a.shape[-1]
    prod = jnp.matmul(a, a_inv, precision=precision)
    return inf_norm(prod - jnp.eye(n, dtype=prod.dtype))
