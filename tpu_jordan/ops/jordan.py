"""Blocked Gauss–Jordan matrix inversion with condition-based block pivoting.

TPU-native rebuild of the reference's core algorithm ``Jordan``
(main.cpp:953-1204): invert an n x n matrix by block Gauss–Jordan elimination
over ``Nr`` block columns, choosing as pivot at each step the block of the
current column whose inverse has the smallest ∞-norm (condition-based
pivoting, main.cpp:1026-1074), with two-level pivoting — scalar partial
pivoting *inside* blocks (inverse_block, main.cpp:746-820) and the
condition-based choice *between* blocks.

Design (TPU-first, per SURVEY.md §7 — not a translation):

  * The whole inversion is ONE jitted ``lax.fori_loop`` over block columns;
    every step is static-shaped.  Slice offsets that depend on the runtime
    pivot choice use ``dynamic_slice`` / ``dynamic_update_slice`` — zero host
    round-trips per step.
  * The pivot probe inverts *all* ``Nr`` candidate blocks of the column in a
    single ``vmap`` (the reference probes them serially one by one,
    main.cpp:1039-1066) — the MXU turns the reference's weakness into a win.
  * State is the augmented matrix ``W = [A | B]`` with ``B`` starting as I
    and ending as A⁻¹, exactly the reference's a/b pair (main.cpp:366-370,
    415).  The elimination sweep is one (N, m) x (m, 2N) matmul per step —
    large, batched, MXU-shaped — instead of the reference's per-block
    ``mult_substr_block`` loop (main.cpp:1165-1193).
  * The row "swap" follows the reference's swap-by-copy trick
    (main.cpp:1093-1131): the pivot row is lifted into a register copy
    before slot ``t`` is overwritten, so no third buffer exists.
  * Ragged tails are handled by identity padding (ops/padding.py), not by
    carrying (bl_h, bl_w) through every kernel like the reference's
    get/set (main.cpp:685-728).
  * Singularity is a carried bool flag (latched when *no* candidate block of
    some column is invertible, main.cpp:1075-1083), returned to the host —
    never a mid-graph abort.

Precision policy (measured on v5e, full ladder in benchmarks/PHASES.md):
Gauss–Jordan elimination needs faithful fp32 products on badly scaled
fixtures — sub-fp32 products (DEFAULT/HIGH) lose the O(1) Schur
complements of the O(n²)-magnitude |i−j| matrix outright and the probe
then (correctly) flags the noise singular.  HIGHEST is therefore the
default; ``precision="mixed"`` (HIGH sweeps + ≥2 HIGHEST Newton–Schulz
steps, ops/refine.py) is the opt-in for well-scaled problems where ~2.7x
cheaper sweeps are worth it.  Sub-fp32 *storage* dtypes (bf16/fp16) are
supported as in/out formats: compute runs in fp32 and the result is
rounded once at the end — carrying bf16 state through the elimination
compounds a rounding injection per step and is measured divergent.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..config import default_block_size, eps_for
from .block_inverse import batched_block_inverse
from .norms import block_inf_norms, inf_norm
from .padding import pad_with_identity, unpad
from .refine import newton_schulz, resolve_precision


def _jordan_step(t, carry, *, Nr: int, m: int, eps: float, precision,
                 global_scale: bool, use_pallas: bool):
    """One super-step of the block elimination (main.cpp:1026-1196)."""
    W, norm_a, singular = carry
    N = Nr * m
    dtype = W.dtype

    # --- PIVOT SEARCH: batch-invert every candidate block of column t
    # (replaces the serial probe loop, main.cpp:1039-1066).
    #
    # Singularity scale: the reference thresholds every inner pivot against
    # eps * ‖A_strip‖ (main.cpp:782, 972) — fine at fp64, but at fp32 the
    # global scale (eps * n²/2 for |i−j|) swallows the genuinely O(1)-sized
    # late Schur-complement pivots and falsely declares large matrices
    # singular.  Default is therefore the numerically standard *per-block*
    # relative threshold; `global_scale=True` restores exact reference
    # semantics (use with fp64).  For block_size == n the two coincide.
    col_t = lax.dynamic_slice(W, (0, t * m), (N, m))            # (N, m)
    # Sub-fp32 inputs were upcast at entry, so the probe dtype is the
    # working dtype (fp32/fp64).
    cands = col_t.reshape(Nr, m, m)
    probe_dtype = dtype
    if use_pallas:
        from .pallas_block_inverse import pallas_batched_block_inverse

        invs, sing = pallas_batched_block_inverse(cands, eps)
    else:
        scale = (norm_a.astype(probe_dtype) if global_scale else None)
        invs, sing = batched_block_inverse(cands, scale, eps)
    inv_norms = block_inf_norms(invs)

    # Condition-based selection: argmin ‖block⁻¹‖ over non-singular
    # candidates in rows >= t — the composite-key argmin that replaces the
    # custom MPI reduction (pivot_op, main.cpp:729-744, 1074).
    valid = (jnp.arange(Nr) >= t) & ~sing
    # inf in the NORMS' dtype (real even for complex W, ISSUE 11): the
    # argmin key must never promote to a complex dtype.
    key = jnp.where(valid, inv_norms, jnp.asarray(jnp.inf, inv_norms.dtype))
    piv = jnp.argmin(key)
    singular = singular | ~jnp.any(valid)                       # main.cpp:1075-1083
    H = jnp.take(invs, piv, axis=0).astype(dtype)               # pivot block inverse

    # --- ROW EXCHANGE: swap block rows t <-> piv.  Like the reference's
    # swap-by-copy (main.cpp:1093-1131): the pivot row is safe in rows_p
    # before slot t is overwritten; slot t is rewritten from the normalized
    # copy below, so only one store per slot happens.
    rows_t = lax.dynamic_slice(W, (t * m, 0), (m, 2 * N))
    rows_p = lax.dynamic_slice(W, (piv * m, 0), (m, 2 * N))
    W = lax.dynamic_update_slice(W, rows_t, (piv * m, 0))

    # --- NORMALIZE the pivot row: prow = H @ row (main.cpp:1133-1159).
    prow = jnp.matmul(H, rows_p, precision=precision)           # (m, 2N)

    # --- ELIMINATE: W[i, :] -= W[i, t-block] @ prow for every block row
    # i != t, as ONE (N, m) x (m, 2N) MXU matmul (main.cpp:1165-1193).
    E = lax.dynamic_slice(W, (0, t * m), (N, m))                # multipliers
    row_blocks = jnp.arange(N) // m
    E = jnp.where((row_blocks == t)[:, None], jnp.asarray(0, dtype), E)
    W = W - jnp.matmul(E, prow, precision=precision)
    W = lax.dynamic_update_slice(W, prow, (t * m, 0))
    return W, norm_a, singular


def _use_pallas_default(dtype) -> bool:
    """Pallas probe: TPU backends with fp32-or-below working dtype (the
    kernel is fp32 and sub-fp32 probes are upcast; fp64 runs on CPU where
    the pure-XLA path is fine)."""
    return (
        jax.default_backend() not in ("cpu",)
        and jnp.dtype(dtype).itemsize <= 4
        and jnp.issubdtype(dtype, jnp.floating)
    )


@partial(jax.jit, static_argnames=(
    "block_size", "eps", "precision", "refine", "global_scale", "use_pallas"))
def block_jordan_invert(
    a: jnp.ndarray,
    block_size: int | None = None,
    eps: float | None = None,
    precision=lax.Precision.HIGHEST,
    refine: int = 0,
    global_scale: bool = False,
    use_pallas: bool | None = None,
):
    """Invert ``a`` by blocked Gauss–Jordan with condition-based pivoting.

    The single-device equivalent of ``Jordan`` (main.cpp:953-1204); the
    sharded version lives in ``parallel/sharded_jordan.py``.

    Args:
      a: (n, n) matrix.
      block_size: pivot block size ``m`` — the reference's runtime tuning
        knob (argv[2], main.cpp:77).  Defaults to an MXU-friendly size.
      eps: relative singularity threshold (EPS, main.cpp:7); defaults to the
        dtype's (config.eps_for).
      precision: matmul precision for the update sweeps.
      refine: number of Newton–Schulz refinement steps ``X ← X(2I − AX)``
        applied to the result.  Each step roughly squares the residual at
        the cost of two GEMMs.  The reference has no analog (its accuracy
        comes from fp64 + a lucky op ordering); on TPU this is the standard
        way to recover fp64-grade residuals from fp32/bf16 arithmetic.
      global_scale: threshold inner pivots against eps * ‖A‖ of the whole
        matrix (exact reference semantics, main.cpp:782/972) instead of the
        per-block norm.  Identical when block_size >= n.
      use_pallas: run the pivot probe in the VMEM-resident pallas kernel
        (ops/pallas_block_inverse.py) — 4-6x faster than the XLA probe on
        TPU.  None = auto (TPU + fp32 + per-block scaling).

    Returns:
      (inv, singular): the inverse (garbage if singular) and a bool flag —
      the analog of Jordan's -2 return (main.cpp:1075-1083).
    """
    precision, refine = resolve_precision(precision, refine)
    n = a.shape[-1]
    in_dtype = a.dtype
    if jnp.dtype(in_dtype).itemsize < 4:
        # Sub-fp32 storage (bf16/fp16): compute in fp32, round the result
        # back.  Carrying the elimination itself in bf16 compounds a
        # rounding injection per super-step and Newton–Schulz cannot
        # converge on bf16 state — measured divergent.  fp32 compute +
        # one final rounding is the standard param/compute-dtype split.
        x, singular = block_jordan_invert(
            a.astype(jnp.float32), block_size, eps, precision, refine,
            global_scale, use_pallas,
        )
        return x.astype(in_dtype), singular
    dtype = a.dtype
    if block_size is None:
        block_size = default_block_size(n)
    m = min(block_size, n)
    if eps is None:
        eps = eps_for(dtype)

    # Relative scale for every singularity test: ‖A‖∞ of the *unpadded*
    # input, computed once — the reference's norm_a (main.cpp:972, 1046).
    norm_a = inf_norm(a)

    Nr = -(-n // m)
    N = Nr * m
    A = pad_with_identity(a, N)
    W = jnp.concatenate([A, jnp.eye(N, dtype=dtype)], axis=1)   # [A | I]

    if use_pallas is None:
        use_pallas = (
            _use_pallas_default(dtype) and not global_scale
            and m % 8 == 0 and m >= 32
        )
    elif use_pallas and global_scale:
        raise ValueError(
            "the pallas probe implements per-block singularity scaling only; "
            "global_scale=True (exact reference semantics) needs the XLA path"
        )
    step = partial(_jordan_step, Nr=Nr, m=m, eps=eps, precision=precision,
                   global_scale=global_scale, use_pallas=use_pallas)
    W, _, singular = lax.fori_loop(
        0, Nr, step, (W, norm_a, jnp.asarray(False))
    )
    x = unpad(W[:, N:], n)
    x = newton_schulz(a, x, refine, lax.Precision.HIGHEST)
    return x, singular
