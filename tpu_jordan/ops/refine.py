"""Newton–Schulz iterative refinement of an approximate inverse.

``X ← X + X(I − AX)`` roughly squares the residual per step at the cost of
two GEMMs.  The reference has no analog (its accuracy comes from fp64); on
TPU this is the standard way to recover fp64-grade residuals from fp32/bf16
arithmetic, and the backbone of the mixed-precision path: a cheap
low-precision elimination followed by a couple of HIGHEST-precision
refinement steps.

Convergence requires the initial residual ‖I − AX₀‖ < 1 in some operator
norm; each step then squares it.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


# Public name -> policy map for the string API (driver.solve --precision).
PRECISIONS = {
    "highest": lax.Precision.HIGHEST,
    "high": lax.Precision.HIGH,
    "default": lax.Precision.DEFAULT,
    "mixed": "mixed",
}


def resolve_precision(precision, refine: int):
    """Resolve a precision policy to (sweep_precision, refine_steps).

    ``"mixed"`` = elimination sweeps at ``Precision.HIGH`` (bf16x3
    products, fp32 accumulation) + at least two Newton–Schulz steps at
    HIGHEST; the pivot probe stays fp32 regardless.  Measured verdict
    (benchmarks/PHASES.md): a NET LOSS for inversion — one NS step is
    4n³ flops, 2x the entire 2n³ elimination, so cheaper sweeps can
    never pay for their own repair; and on badly scaled matrices
    (|i−j| at n ≥ 1024) sub-fp32 products lose the Schur complements
    outright and the probe flags the matrix singular.  Kept as an
    opt-in for experimentation; HIGHEST is the default and is both the
    fastest-to-accuracy and the most robust policy.
    """
    if precision == "mixed":
        return lax.Precision.HIGH, max(refine, 2)
    return precision, refine


def newton_schulz(
    a: jnp.ndarray,
    x: jnp.ndarray,
    steps: int,
    precision=lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """Refine ``x ≈ a⁻¹`` with ``steps`` Newton–Schulz iterations.

    Traceable (pure jnp); callers decide whether it runs under jit.
    """
    if steps <= 0:
        return x
    n = a.shape[-1]
    eye = jnp.eye(n, dtype=x.dtype)
    for _ in range(steps):
        r = eye - jnp.matmul(a, x, precision=precision)
        x = x + jnp.matmul(x, r, precision=precision)
    return x
