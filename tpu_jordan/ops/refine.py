"""Newton–Schulz iterative refinement of an approximate inverse.

``X ← X + X(I − AX)`` roughly squares the residual per step at the cost of
two GEMMs.  The reference has no analog (its accuracy comes from fp64); on
TPU this is the standard way to recover fp64-grade residuals from fp32/bf16
arithmetic, and the backbone of the mixed-precision path: a cheap
low-precision elimination followed by a couple of HIGHEST-precision
refinement steps.

Convergence requires the initial residual ‖I − AX₀‖ < 1 in some operator
norm; each step then squares it.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def newton_schulz(
    a: jnp.ndarray,
    x: jnp.ndarray,
    steps: int,
    precision=lax.Precision.HIGHEST,
) -> jnp.ndarray:
    """Refine ``x ≈ a⁻¹`` with ``steps`` Newton–Schulz iterations.

    Traceable (pure jnp); callers decide whether it runs under jit.
    """
    if steps <= 0:
        return x
    n = a.shape[-1]
    eye = jnp.eye(n, dtype=x.dtype)
    for _ in range(steps):
        r = eye - jnp.matmul(a, x, precision=precision)
        x = x + jnp.matmul(x, r, precision=precision)
    return x
