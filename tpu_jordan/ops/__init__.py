from .batched import batched_jordan_invert
from .block_inverse import batched_block_inverse, gauss_jordan_inverse
from .generators import GENERATORS, abs_diff, generate, hilbert, identity
from .jordan import block_jordan_invert
from .jordan_inplace import (
    block_jordan_invert_inplace,
    block_jordan_invert_inplace_fori,
    block_jordan_invert_inplace_grouped,
    block_jordan_invert_inplace_grouped_fori,
    block_jordan_invert_inplace_grouped_lookahead,
    block_jordan_invert_inplace_grouped_pallas,
    block_jordan_invert_inplace_lookahead,
)
from .norms import block_inf_norms, condition_inf, inf_norm
from .padding import pad_with_identity, unpad
from .refine import newton_schulz
from .residual import residual_inf_norm

__all__ = [
    "GENERATORS",
    "abs_diff",
    "batched_block_inverse",
    "batched_jordan_invert",
    "block_inf_norms",
    "condition_inf",
    "block_jordan_invert",
    "block_jordan_invert_inplace",
    "block_jordan_invert_inplace_fori",
    "block_jordan_invert_inplace_grouped",
    "block_jordan_invert_inplace_grouped_fori",
    "block_jordan_invert_inplace_grouped_lookahead",
    "block_jordan_invert_inplace_grouped_pallas",
    "block_jordan_invert_inplace_lookahead",
    "gauss_jordan_inverse",
    "generate",
    "hilbert",
    "identity",
    "inf_norm",
    "newton_schulz",
    "pad_with_identity",
    "residual_inf_norm",
    "unpad",
]
