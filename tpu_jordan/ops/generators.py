"""Matrix generators (the reference's ``f`` / ``f_i``, main.cpp:47-64).

The reference fills the distributed matrix from a formula ``f(i, j)`` via
``init_matrix`` (main.cpp:128-149).  Here generators are jit-friendly
functions of index grids; ``generate`` materializes any rectangular window,
so per-shard generation under shard_map needs no communication.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax

# f(i, j) signature: takes integer index arrays, returns float array.
GeneratorFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def abs_diff(i, j):
    """Default generator ``f(i,j) = |i - j|`` (main.cpp:47-57).

    Zero diagonal — inverting it *requires* pivoting, which is why the
    reference uses it as the default fixture.
    """
    return jnp.abs(i - j)


def hilbert(i, j):
    """Hilbert matrix ``1 / (i + j + 1)`` (-DHILBERT, main.cpp:49-51).

    Classic ill-conditioned stress test for the singularity threshold.
    """
    return 1.0 / (i + j + 1)


def identity(i, j):
    """Identity generator ``f_i`` (main.cpp:59-64)."""
    return (i == j).astype(jnp.float32)


GENERATORS: dict[str, GeneratorFn] = {
    "absdiff": abs_diff,
    "hilbert": hilbert,
    "identity": identity,
}


def generate(
    fn: GeneratorFn | str,
    shape: tuple[int, int],
    dtype=jnp.float32,
    *,
    row_offset=0,
    col_offset=0,
) -> jnp.ndarray:
    """Materialize ``fn`` over a window of the global index grid.

    ``row_offset``/``col_offset`` may be traced values, so a shard can build
    its own piece of the global matrix inside shard_map — the TPU-native
    replacement for init_matrix's local_to_global walk (main.cpp:128-149).
    """
    if isinstance(fn, str):
        fn = GENERATORS[fn]
    h, w = shape
    ii = row_offset + lax.broadcasted_iota(jnp.int32, (h, w), 0)
    jj = col_offset + lax.broadcasted_iota(jnp.int32, (h, w), 1)
    return fn(ii, jj).astype(dtype)
