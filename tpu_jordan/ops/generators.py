"""Matrix generators (the reference's ``f`` / ``f_i``, main.cpp:47-64).

The reference fills the distributed matrix from a formula ``f(i, j)`` via
``init_matrix`` (main.cpp:128-149).  Here generators are jit-friendly
functions of index grids; ``generate`` materializes any rectangular window,
so per-shard generation under shard_map needs no communication.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax

# f(i, j) signature: takes integer index arrays, returns float array.
GeneratorFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def abs_diff(i, j):
    """Default generator ``f(i,j) = |i - j|`` (main.cpp:47-57).

    Zero diagonal — inverting it *requires* pivoting, which is why the
    reference uses it as the default fixture.
    """
    return jnp.abs(i - j)


def hilbert(i, j):
    """Hilbert matrix ``1 / (i + j + 1)`` (-DHILBERT, main.cpp:49-51).

    Classic ill-conditioned stress test for the singularity threshold.
    """
    return 1.0 / (i + j + 1)


def identity(i, j):
    """Identity generator ``f_i`` (main.cpp:59-64)."""
    return (i == j).astype(jnp.float32)


def rand_uniform(i, j):
    """Deterministic pseudo-random uniform in [-1, 1): a stateless integer
    hash of (i, j) (lowbias32-style avalanche).

    Beyond-reference fixture: the |i−j| matrix's O(n²) dynamic range
    genuinely exceeds fp32 past n=8192 (its Schur cancellations drown in
    noise and the probe correctly flags it singular — measured,
    benchmarks/PHASES.md), so scale demonstrations need a well-conditioned
    matrix.  Being a pure function of global indices, it generates
    shard-locally under shard_map with no communication, like every other
    generator here.
    """
    x = (i.astype(jnp.uint32) * jnp.uint32(73856093)) ^ (
        j.astype(jnp.uint32) * jnp.uint32(19349663))
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x.astype(jnp.float32) * jnp.float32(2.0 / 4294967296.0) - 1.0


def kms(i, j):
    """Kac–Murdock–Szegő matrix ``rho^|i-j|`` with rho = 0.25.

    Symmetric positive definite for |rho| < 1 and strongly diagonally
    dominant at rho = 0.25 (off-diagonal row mass < 2/3 of the unit
    diagonal) — the seeded SPD fixture for the pivot-free solve fast
    path (ISSUE 11): the condition-based probe provably prefers the
    diagonal block, so the pivoting engine and the ``assume="spd"``
    path follow identical arithmetic and bit-match.
    """
    return jnp.power(jnp.float32(0.25),
                     jnp.abs(i - j).astype(jnp.float32))


def crand(i, j):
    """Deterministic complex uniform: ``rand_uniform`` hashes for the
    real part, an index-shifted hash stream for the imaginary part
    (complex-dtype workloads, ISSUE 11).  Use with complex dtypes only —
    casting the result to a real dtype discards the imaginary part.
    """
    re = rand_uniform(i, j)
    im = rand_uniform(i + jnp.int32(0x5BF0), j + jnp.int32(0x2C1B))
    return lax.complex(re, im)


GENERATORS: dict[str, GeneratorFn] = {
    "absdiff": abs_diff,
    "hilbert": hilbert,
    "identity": identity,
    "rand": rand_uniform,
    "kms": kms,
    "crand": crand,
}


def generate(
    fn: GeneratorFn | str,
    shape: tuple[int, int],
    dtype=jnp.float32,
    *,
    row_offset=0,
    col_offset=0,
) -> jnp.ndarray:
    """Materialize ``fn`` over a window of the global index grid.

    ``row_offset``/``col_offset`` may be traced values, so a shard can build
    its own piece of the global matrix inside shard_map — the TPU-native
    replacement for init_matrix's local_to_global walk (main.cpp:128-149).
    """
    if isinstance(fn, str):
        fn = GENERATORS[fn]
    h, w = shape
    ii = row_offset + lax.broadcasted_iota(jnp.int32, (h, w), 0)
    jj = col_offset + lax.broadcasted_iota(jnp.int32, (h, w), 1)
    vals = fn(ii, jj)
    if (jnp.issubdtype(vals.dtype, jnp.complexfloating)
            and not jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)):
        # astype(complex -> real) silently discards the imaginary part
        # (no warning under jit) — a complex generator cast to a real
        # dtype is a caller bug, never a half-real fixture (ISSUE 11).
        raise ValueError(
            f"complex-valued generator cast to real dtype "
            f"{jnp.dtype(dtype).name} would discard the imaginary "
            f"part; request a complex dtype")
    return vals.astype(dtype)
