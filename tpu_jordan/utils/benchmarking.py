"""Tunnel-safe TPU timing.

This environment reaches the TPU through a tunnel with ~100ms RTT and a
readback-pipelining quirk (small ops can hide entirely inside the RTT
window).  Trustworthy method: repeat the op inside ONE jitted ``fori_loop``
with a *dynamic* trip count (one compile serves every rep count) and a
real data dependency between iterations (so XLA cannot hoist the body),
read back a scalar, and measure at two trip counts — the reported
per-iteration time is the slope, so every constant offset (RTT, dispatch,
readback) cancels exactly.
"""

from __future__ import annotations

import time

import numpy as np


def slope_time(fn, args, r1: int = 4, r2: int = 12, trials: int = 3,
               samples: int = 1):
    """Per-iteration seconds of ``fn(*args)``, constant offsets cancelled.

    ``fn`` must return an array; its sum is folded back into ``args[0]``
    (times 1e-30) to chain iterations without changing the computation.

    ``samples > 1`` repeats the whole (r1, r2) slope measurement that
    many times on the SAME compiled executable and returns the list of
    slopes — the median-of-N bench captures (VERDICT r5 weak #1: one
    slope per session can silently lose 15% to the session lottery).
    Reusing the executable matters: a fresh ``slope_time`` call re-jits
    ``many``, and the unrolled engines' compile dwarfs the measurement.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def many(reps, *args):
        def body(i, carry):
            out = fn(*carry)
            a0 = carry[0] + (1e-30 * jnp.sum(out)).astype(carry[0].dtype)
            return (a0,) + carry[1:]

        final = lax.fori_loop(0, reps, body, args)
        return jnp.sum(final[0])

    def measure(reps):
        np.asarray(many(reps, *args))   # warm (absorbs compile on 1st call)
        ts = []
        for _ in range(trials):
            t0 = time.perf_counter()
            np.asarray(many(reps, *args))
            ts.append(time.perf_counter() - t0)
        return float(np.min(ts))

    def sample():
        t1, t2 = measure(r1), measure(r2)
        return (t2 - t1) / (r2 - r1)

    if samples == 1:
        return sample()
    return [sample() for _ in range(samples)]
