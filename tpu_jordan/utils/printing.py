"""Pretty-printing of matrix corners.

Replaces ``print_matrix`` / ``print_row`` (main.cpp:284-341): the reference
gathers the top-left min(n, MAX_P)-corner to rank 0 and prints it with
``"%.2f\\t"`` per element.  On TPU the "gather to rank 0" is just reading a
device array on the host — addressable shards make the corner fetch cheap.
"""

from __future__ import annotations

import numpy as np

from ..config import MAX_PRINT


def format_corner(a, max_p: int = MAX_PRINT) -> str:
    """Format the top-left corner like the reference (main.cpp:284-295)."""
    a = np.asarray(a)
    nm = min(a.shape[0], max_p)
    rows = []
    for i in range(nm):
        rows.append("".join(f"{float(a[i, j]):.2f}\t" for j in range(nm)))
    return "\n".join(rows)


def print_corner(a, max_p: int = MAX_PRINT) -> None:
    print(format_corner(a, max_p))
