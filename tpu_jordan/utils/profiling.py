"""Timing scoreboard and profiler hooks — COMPAT SHIMS over the unified
telemetry layer.

.. deprecated:: ISSUE 4
   The real implementation lives in ``tpu_jordan/obs/`` — span tracing
   in ``obs/spans.py``, the process-wide metrics registry in
   ``obs/metrics.py``, and the exporters (incl. the jax.profiler
   kernel tier this module's ``trace`` used to own) in
   ``obs/export.py``.  This module keeps the original surface —
   ``Scoreboard`` (the glob_time report string, main.cpp:427-458),
   ``timed``, ``trace``, ``invert_flops`` — as thin wrappers so
   existing callers keep working; new code should use
   ``tpu_jordan.obs`` directly (docs/OBSERVABILITY.md).

``timed`` is now span-backed: the bracket IS a span on the given
telemetry (default: the discard-only null sink), its GFLOP/s attached
as a span attribute, and ``Scoreboard.elapsed`` set from the span's
duration — wall-clock and span timing can never disagree.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass

from ..obs.export import profiler_trace as trace  # noqa: F401  (tier 4)
from ..obs.spans import NULL


@dataclass
class Scoreboard:
    """Wall-clock + GFLOP/s record (the glob_time analog)."""

    label: str
    elapsed: float = 0.0
    flops: float | None = None

    @property
    def gflops(self) -> float | None:
        if self.flops is None or self.elapsed <= 0:
            return None
        return self.flops / self.elapsed / 1e9

    def report(self) -> str:
        s = f"glob_time: {self.elapsed:.2f}"
        if self.gflops is not None:
            s += f"  ({self.gflops:.1f} GFLOP/s)"
        return s


@contextlib.contextmanager
def timed(label: str, flops: float | None = None, sync=None,
          telemetry=None):
    """Time a block; ``sync`` (an array or pytree) is block_until_ready'd
    before the clock stops, the single-controller analog of the MAX
    allreduce over per-rank times (main.cpp:455).

    Deprecated shim: the bracket is an ``obs.spans`` span on
    ``telemetry`` (discarded when none is given); GFLOP/s, when
    computable, rides the span as an attribute.
    """
    tel = telemetry if telemetry is not None else NULL
    sb = Scoreboard(label, flops=flops)
    with tel.span(label) as sp:
        yield sb
        if sync is not None:
            import jax

            jax.block_until_ready(sync)
    sb.elapsed = sp.duration
    if sb.gflops is not None:
        sp.attrs["gflops"] = round(sb.gflops, 3)


def invert_flops(n: int) -> float:
    """The 2n^3 Gauss–Jordan inversion convention used by BASELINE.md.

    .. deprecated:: ISSUE 10
       Hand FLOP counting is retired onto ``tpu_jordan/obs/hwcost.py``:
       ``baseline_invert_flops`` (this 2n³ convention, kept for
       BASELINE/BENCH cross-round comparability), ``gauss_jordan_flops``
       ((8/3)n³ — the analytical count of the real blocked algorithm
       including the pivot probe, pinned against
       ``compiled.cost_analysis()`` by tests/test_hwcost.py), and
       ``executable_cost`` (the compiled executable's OWN accounting —
       what bench rows and execute spans now report).  This shim
       delegates; new code should use ``tpu_jordan.obs.hwcost``."""
    from ..obs.hwcost import baseline_invert_flops

    return baseline_invert_flops(n)


def workload_flops(n: int, workload: str = "invert", k: int = 1,
                   rows: int | None = None) -> float:
    """Workload-aware analytic FLOP count (ISSUE 11 satellite).

    ``invert_flops``'s 2n³ convention is an INVERSION convention; a
    solve row divided by it would headline ~2x too fast (Gauss–Jordan
    on [A | B] is ~n³·(1 + k/n) for k right-hand sides, and lstsq adds
    the Gram/projection products).  Deprecated shim like the rest of
    this module: delegates to
    ``tpu_jordan.obs.hwcost.baseline_workload_flops``."""
    from ..obs.hwcost import baseline_workload_flops

    return baseline_workload_flops(n, workload, k=k, rows=rows)
