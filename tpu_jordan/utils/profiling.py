"""Timing scoreboard and profiler hooks.

The reference's only observability is the max-allreduced MPI_Wtime bracket
around Jordan printed as glob_time (main.cpp:427-458) plus a flops
convention of 2n^3.  Here: the same scoreboard (wall seconds + GFLOP/s)
as a context manager, plus `jax.profiler` trace capture for real kernel-
level inspection on TPU.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import jax


@dataclass
class Scoreboard:
    """Wall-clock + GFLOP/s record (the glob_time analog)."""

    label: str
    elapsed: float = 0.0
    flops: float | None = None

    @property
    def gflops(self) -> float | None:
        if self.flops is None or self.elapsed <= 0:
            return None
        return self.flops / self.elapsed / 1e9

    def report(self) -> str:
        s = f"glob_time: {self.elapsed:.2f}"
        if self.gflops is not None:
            s += f"  ({self.gflops:.1f} GFLOP/s)"
        return s


@contextlib.contextmanager
def timed(label: str, flops: float | None = None, sync=None):
    """Time a block; ``sync`` (an array or pytree) is block_until_ready'd
    before the clock stops, the single-controller analog of the MAX
    allreduce over per-rank times (main.cpp:455)."""
    sb = Scoreboard(label, flops=flops)
    t0 = time.perf_counter()
    yield sb
    if sync is not None:
        jax.block_until_ready(sync)
    sb.elapsed = time.perf_counter() - t0


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/tpu_jordan_trace"):
    """Capture a jax.profiler trace (view with TensorBoard/XProf)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def invert_flops(n: int) -> float:
    """The 2n^3 Gauss–Jordan inversion convention used by BASELINE.md."""
    return 2.0 * float(n) ** 3
