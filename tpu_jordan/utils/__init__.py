from .printing import format_corner, print_corner

__all__ = ["format_corner", "print_corner"]
