from .printing import format_corner, print_corner
from .profiling import Scoreboard, invert_flops, timed, trace

__all__ = ["Scoreboard", "format_corner", "invert_flops", "print_corner",
           "timed", "trace"]
