"""Telemetry exporters (ISSUE 4 tentpole part 3) — four tiers:

  1. **One-line JSON** (``to_json_line``) — the ``--serve-demo`` report
     style: one ``json.dumps`` line a log scraper can cut out.
  2. **Prometheus text format** (``to_prometheus`` /
     ``write_metrics``) — ``# HELP``/``# TYPE`` + sample lines,
     scrapeable; histograms export in summary form (quantile-labeled
     lines plus ``_count``/``_sum``).  The CLI's ``--metrics-out``.
  3. **Chrome trace-event JSON** (``to_chrome_trace`` /
     ``write_chrome_trace``) — complete ("X") events from the span
     tree, loadable in Perfetto (https://ui.perfetto.dev) or
     ``chrome://tracing``.  The CLI's ``--trace-json``.
  4. **jax.profiler capture** (``profiler_trace``) — the kernel-level
     ground truth on real hardware (XProf/TensorBoard), folded in from
     ``utils/profiling.trace`` (which now shims to this).

Tiers 1-3 read the span tree / metrics registry the library populated;
tier 4 records what XLA actually launched.
"""

from __future__ import annotations

import contextlib
import json

from . import metrics as _metrics

_PROM_TYPE = {"counter": "counter", "gauge": "gauge",
              "histogram": "summary"}

_QUANTILES = {"p50": "0.5", "p95": "0.95", "p99": "0.99"}


def _escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def to_prometheus(registry: "_metrics.MetricsRegistry | None" = None
                  ) -> str:
    """The registry as Prometheus text exposition format (one trailing
    newline; empty registries export as an empty string).

    Every family gets a ``# HELP`` line next to its ``# TYPE`` —
    scrapers and humans both read them, and ``tools/check_telemetry.py``
    fails a scrape without them (ISSUE 10 satellite).  A family whose
    registration carried no help text exports an explicit
    ``(no help registered)`` marker rather than silently omitting the
    line: the missing documentation is visible, never invisible."""
    reg = registry if registry is not None else _metrics.REGISTRY
    lines: list[str] = []
    for m in reg.collect():
        help_text = " ".join((m.help or "(no help registered)").split())
        lines.append(f"# HELP {m.name} {help_text}")
        lines.append(f"# TYPE {m.name} {_PROM_TYPE[m.kind]}")
        series = m.series() or {(): (0.0 if m.kind != "histogram"
                                     else _metrics.Reservoir())}
        for key, val in sorted(series.items()):
            labels = dict(key)
            if isinstance(val, _metrics.Reservoir):
                pct = val.percentiles()
                for pk, q in _QUANTILES.items():
                    if pct[pk] is not None:
                        qlab = dict(labels, quantile=q)
                        lines.append(f"{m.name}{_fmt_labels(qlab)} "
                                     f"{_fmt_value(pct[pk])}")
                lines.append(f"{m.name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(val.total)}")
                lines.append(f"{m.name}_count{_fmt_labels(labels)} "
                             f"{val.count}")
            else:
                lines.append(f"{m.name}{_fmt_labels(labels)} "
                             f"{_fmt_value(val)}")
    return "\n".join(lines) + ("\n" if lines else "")


def to_chrome_trace(telemetry, journey_events=None) -> dict:
    """The span tree as a Chrome trace-event document: one complete
    ("X") event per finished span, microsecond timestamps on the
    telemetry's own clock base.  Model-attributed phase children carry
    their ``modeled``/``fraction`` attrs in ``args`` so Perfetto shows
    the attribution honestly.

    ``journey_events`` (ISSUE 8): an iterable of flight-recorder
    ``journey`` events — appended as async nestable lanes (one Perfetto
    row per ``request_id`` showing the request's full path; see
    ``obs/journey.async_trace_events``).  ``telemetry`` may be None for
    a journeys-only trace."""
    events = []
    roots = telemetry.roots if telemetry is not None else []
    for root in roots:
        for sp in root.walk():
            events.append({
                "name": sp.name,
                "cat": "tpu_jordan",
                "ph": "X",
                "ts": round(sp.t_start * 1e6, 3),
                "dur": round(sp.duration * 1e6, 3),
                "pid": 0,
                "tid": sp.thread,
                "args": {k: (v if isinstance(v, (str, int, float, bool,
                                                 type(None)))
                             else str(v))
                         for k, v in sp.attrs.items()},
            })
    if journey_events is not None:
        from .journey import async_trace_events

        events.extend(async_trace_events(journey_events))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def to_json_line(registry=None, telemetry=None, **extra) -> str:
    """ONE JSON line — the ``--serve-demo`` report convention: metrics
    snapshot and/or span trees plus any caller extras.

    Caller extras may NOT collide with the payload keys this function
    owns (``metric``/``metrics``/``spans``): a colliding ``**extra``
    used to silently clobber the metrics or span payload — now a typed
    ``UsageError`` (ISSUE 8 satellite)."""
    doc: dict = {"metric": "telemetry"}
    if registry is not None:
        doc["metrics"] = registry.snapshot()
    if telemetry is not None:
        doc["spans"] = [r.to_dict() for r in telemetry.roots]
    clash = sorted(set(extra) & set(doc))
    if clash:
        from ..driver import UsageError

        raise UsageError(
            f"to_json_line extra key(s) {clash} collide with the "
            f"telemetry payload keys {sorted(doc)} — a collision would "
            f"silently clobber the metrics/span payload; rename the "
            f"extras")
    doc.update(extra)
    return json.dumps(doc)


def write_metrics(path: str, registry=None) -> None:
    """Write the Prometheus text format to ``path`` (``--metrics-out``).

    A metrics snapshot re-samples the device live-bytes watermark first
    (ISSUE 13 satellite: the scraped gauges reflect NOW on backends
    that report allocator stats; a backend that never did stays absent
    — never zeroed)."""
    from . import hwcost as _hwcost

    _hwcost.WATERMARK.sample()
    with open(path, "w") as f:
        f.write(to_prometheus(registry))


def write_chrome_trace(path: str, telemetry,
                       journey_events=None) -> None:
    """Write the Chrome trace-event JSON to ``path`` (``--trace-json``);
    open the file in Perfetto to see the phase spans on a timeline —
    plus, when ``journey_events`` is passed (the CLI passes the flight
    recorder's journey slice), one async lane per request."""
    with open(path, "w") as f:
        json.dump(to_chrome_trace(telemetry,
                                  journey_events=journey_events), f)


@contextlib.contextmanager
def profiler_trace(log_dir: str = "/tmp/tpu_jordan_trace"):
    """Tier 4: capture a jax.profiler trace (view with XProf/
    TensorBoard) — real kernel-level timing on TPU, the ground truth the
    model-attributed phase spans approximate.  Folded in from
    ``utils/profiling.trace``, which now delegates here."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
