"""The work observatory (ISSUE 19 tentpole).

The paper's whole reason for the 1D row-block-cyclic decomposition
(``local_to_global``, main.cpp:118-123; the ragged last block,
main.cpp:95-116) is LOAD BALANCE as the elimination's live window
shrinks — yet until this module the observability stack (spans,
journeys, comm, capacity, numerics, hwcost) never measured whether that
balance is actually achieved.  The comm observatory (obs/comm.py,
ISSUE 14) answered "which bytes moved"; this module answers "which
worker did the work, and was the straggler the layout or the replica".
Two layers:

1. **Analytical per-worker work inventories** — for every distributed
   engine configuration, the per-(worker, superstep, phase) useful-FLOP
   inventory is derived EXACTLY from the layout math
   (``parallel/layout.py`` ownership × live-column window × workload),
   in INTEGER arithmetic, so the per-worker shares sum EXACTLY to the
   engine's headline convention total (``obs/hwcost.py``):

     * invert — the in-place engines hold a constant-width window (the
       eliminated A columns become inverse columns in place), so
       ``w(t, r, j) = 2·h_t·h_r·h_j`` over useful block heights ``h``
       (``Σh = n`` per axis) sums to ``2·n³``
       (``baseline_invert_flops``), the factor 2 being the [A|I] pair.
     * solve — the [A|B] elimination's live window SHRINKS: per
       column block the weight is ``2·[j>t] + [j==t]`` (live columns
       are touched by the row scale and the rank-m update; the pivot
       column once), and ``Σ_t h_t·(W_{t-1}+W_t) = n²`` makes
       ``w(t, r) = h_t·h_r·(W_{t-1}+W_t+k)`` sum to ``n³ + n²·k``
       (``baseline_workload_flops(n, "solve", k)``).

   The ragged last block (height ``l = last_block_height(n, m)``) and
   the identity padding to ``Nr`` blocks ride through the heights:
   pad blocks carry ZERO useful work, which is exactly the layout's
   tail imbalance.  Exposed as :class:`WorkReport` on every
   distributed ``SolveResult`` / ``SolveSystemResult`` /
   ``JordanSolver``, with ``tpu_jordan_work_share`` / ``work_skew``
   gauges and execute-span attrs, and pinned against hwcost's
   cost_analysis per-device FLOPs on the real sharded executables
   (:meth:`WorkReport.attach_xla` — SPMD programs report uniform
   per-device cost, so ``devices × per-device`` is judged against the
   PADDED executed-work model, not the useful convention).

2. **Measured fleet skew** — per-replica execute-latency spread
   (``serve/stats.cross_replica_spread`` over the per-replica
   ServeStats rollup) judged by :class:`FleetSkewJudge`: measured p99s
   are NORMALIZED by each replica's analytical expected-latency factor
   (its layout critical path — :func:`expected_latency_factor`) before
   the spread is compared to the threshold, so layout-inherent
   imbalance is never misread as a sick replica.  A suspected
   straggler is a transition-only ``straggler_suspected``
   flight-recorder event with the evidence attached, and the judge's
   live verdict is a pre-shed VETO input for the autoscaler
   (``fleet/autoscaler.py`` — a single sick replica must not shed the
   whole fleet's p99-risk traffic), never a new actuator.

Operator guide: docs/OBSERVABILITY.md (work/skew taxonomy + the
"was it the layout or the replica?" post-mortem).  Gate:
``make work-demo`` → ``tools/check_work.py`` (exit 2 = unaccounted
work or a straggler verdict the evidence can't support).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from . import metrics as _metrics
from . import recorder as _recorder

#: Phase vocabulary (docs/OBSERVABILITY.md): ``pivot`` = the work on
#: the pivot block row itself (r == t: the H application scaling the
#: pivot row); ``eliminate`` = every other owned row's rank-m update.
PHASES = ("pivot", "eliminate")

_M_SHARE = _metrics.gauge(
    "tpu_jordan_work_share",
    "analytical useful-FLOP share of the last distributed solve, per "
    "worker (layout-derived; docs/OBSERVABILITY.md)")
_M_SKEW = _metrics.gauge(
    "tpu_jordan_work_skew",
    "max-over-mean per-worker imbalance factor of the last distributed "
    "solve per engine (1.0 = perfectly balanced)")
_M_STRAGGLER = _metrics.counter(
    "tpu_jordan_straggler_suspected_total",
    "fleet replicas whose normalized execute-latency spread exceeded "
    "the straggler threshold (transition-only, evidence in the flight "
    "recorder)")


def _sig(v: float) -> float:
    return float(f"{float(v):.4g}")


# ---------------------------------------------------------------------
# Layout math: useful block heights and convention totals.
# ---------------------------------------------------------------------


def useful_heights(n: int, m: int) -> list[int]:
    """Heights of the USEFUL block rows (and, by symmetry, block
    columns): ``m`` for every full block, ``last_block_height(n, m)``
    for the ragged tail, nothing for pad blocks.  ``Σ = n`` exactly —
    the invariant every inventory below rests on."""
    from ..parallel.layout import last_block_height, num_block_rows

    Tu = num_block_rows(n, m)
    return [m] * (Tu - 1) + [last_block_height(n, m)]


def convention_flops(n: int, workload: str, k: int = 0) -> int:
    """The engine's headline useful-FLOP convention (obs/hwcost.py):
    invert ``2·n³`` (baseline_invert_flops), solve ``n³ + n²·k``
    (baseline_workload_flops) — as an exact integer."""
    if workload == "invert":
        return 2 * n ** 3
    if workload == "solve":
        return n ** 3 + n ** 2 * int(k)
    raise ValueError(f"no work convention for workload {workload!r}")


def _cyclic_sums(h: list[int], p: int) -> list[int]:
    """``Σ h_r`` over the blocks each of ``p`` cyclic workers owns."""
    out = [0] * p
    for r, hr in enumerate(h):
        out[r % p] += hr
    return out


# ---------------------------------------------------------------------
# The analytical inventories (integer-exact by construction).
# ---------------------------------------------------------------------


def _inventory_1d(lay, workload: str, k: int):
    """Per-(worker, superstep, phase) useful FLOPs on the 1D row-cyclic
    layout: block row r → worker r % p.  Columns are unsharded, so the
    column factor collapses (invert: the constant n-wide window; solve:
    the shrinking ``W_{t-1}+W_t+k`` live width)."""
    n, m, p = lay.n, lay.m, lay.p
    h = useful_heights(n, m)
    R = _cyclic_sums(h, p)
    per_worker = {str(w): {"pivot": 0, "eliminate": 0} for w in range(p)}
    per_superstep = []
    C = 0
    for t, ht in enumerate(h):
        if workload == "invert":
            f = 2 * ht * n
        else:
            w_prev = n - C
            C += ht
            f = ht * (w_prev + (n - C) + k)
        owner = t % p
        tot_t = 0
        for w in range(p):
            piv = f * ht if w == owner else 0
            elim = f * (R[w] - (ht if w == owner else 0))
            per_worker[str(w)]["pivot"] += piv
            per_worker[str(w)]["eliminate"] += elim
            tot_t += piv + elim
        per_superstep.append(tot_t)
    return per_worker, per_superstep


def _inventory_2d(lay, workload: str, k: int):
    """Per-(worker, superstep, phase) useful FLOPs on the 2D
    block-cyclic layout: block (r, j) → worker (r % pr, j % pc).  The
    invert window is constant width (in-place), so a column class's
    share is just its owned heights; the solve window shrinks per the
    ``2·[j>t] + [j==t]`` weight, and the k RHS columns (replicated
    along pc in the engine) are SPLIT cyclically over the column
    workers so the useful total stays exact."""
    n, m, pr, pc = lay.n, lay.m, lay.pr, lay.pc
    h = useful_heights(n, m)
    Rr = _cyclic_sums(h, pr)
    S = _cyclic_sums(h, pc)
    kc = [len(range(c, int(k), pc)) for c in range(pc)]
    per_worker = {f"{wr},{wc}": {"pivot": 0, "eliminate": 0}
                  for wr in range(pr) for wc in range(pc)}
    per_superstep = []
    P = [0] * pc        # Σ h_j over j <= t per column class
    for t, ht in enumerate(h):
        tc = t % pc
        P[tc] += ht
        tot_t = 0
        for wc in range(pc):
            if workload == "invert":
                colw = S[wc]
            else:
                colw = 2 * (S[wc] - P[wc]) + (ht if wc == tc else 0)
                colw += kc[wc]
            f = 2 * ht * colw if workload == "invert" else ht * colw
            owner = t % pr
            for wr in range(pr):
                piv = f * ht if wr == owner else 0
                elim = f * (Rr[wr] - (ht if wr == owner else 0))
                cell = per_worker[f"{wr},{wc}"]
                cell["pivot"] += piv
                cell["eliminate"] += elim
                tot_t += piv + elim
        per_superstep.append(tot_t)
    return per_worker, per_superstep


# ---------------------------------------------------------------------
# The padded executed-work model (the hwcost reconciliation unit).
# ---------------------------------------------------------------------


def executed_model_flops(engine: str, workload: str, *, N: int, m: int,
                         k: int = 0, unroll: bool = False,
                         pc: int = 1) -> float:
    """Modeled FLOPs the sharded executables actually LAUNCH, summed
    over the mesh — padded dimensions, full-width supersteps: the unit
    ``devices × cost_analysis-per-device`` is judged against (SPMD
    programs report uniform per-device cost, hwcost honesty contract).

    * invert: every engine updates the constant padded window each of
      the ``Nr`` supersteps — ``2·N³`` (``2·N²·2N = 4·N³`` for the
      augmented engine's explicit [A|I] strip).
    * solve: the fori flavors keep the full ``N + k`` width
      (``2·N²·(N + k·pc)`` — X is replicated along pc, so the 2D mesh
      really repeats the RHS update pc times); the unrolled flavors
      shrink the live width statically per superstep.
    """
    Nr = N // m
    if workload == "invert":
        width = 2 * N if engine == "augmented" else N
        return 2.0 * N * N * width
    if not unroll:
        return 2.0 * N * N * (N + k * pc)
    total = 0.0
    for t in range(Nr):
        if pc > 1:
            bc1 = Nr // pc
            live = pc * (bc1 - t // pc) * m
        else:
            live = N - t * m
        total += 2.0 * m * N * (live + k * pc)
    return total


#: Engines with a registered work inventory — the same discipline as
#: obs/comm.INVENTORY_ENGINES: :func:`engine_report` refuses unknown
#: names, so a new distributed engine without work accounting fails
#: loudly at its first report.
INVENTORY_ENGINES = frozenset(
    {"inplace", "grouped", "swapfree", "augmented", "solve_sharded",
     "lookahead", "solve_lookahead"})

#: Acceptance band for devices × cost_analysis-per-device against the
#: TRACED model (cost_analysis is a STATIC HLO count: a fori_loop body
#: is counted once, never × its trip count, so fori flavors judge
#: against executed/Nr).  XLA additionally counts the per-superstep
#: pivot inversions, scaling, masking, and candidate passes the
#: leading GEMM-order model deliberately omits — measured 1.5-2.8× the
#: model across the engine zoo on CPU XLA; within the band is a
#: reconciled executable, outside is unaccounted work.
XLA_BAND = (0.5, 4.0)


def engine_report(*, engine: str, lay, dtype=None, k: int = 0,
                  group: int = 0, unroll: bool | None = None
                  ) -> "WorkReport":
    """Build the analytical :class:`WorkReport` for one distributed
    engine configuration.  ``lay`` is the solve's ``CyclicLayout`` /
    ``CyclicLayout2D``; ``k`` the solve workload's RHS column count;
    ``unroll=None`` resolves exactly like the compile front ends (it
    only affects the padded executed model — the useful inventory is
    schedule-independent).  An engine name outside
    :data:`INVENTORY_ENGINES` is a hard ``ValueError``: work
    accounting is part of shipping an engine."""
    from ..parallel.layout import last_block_height, num_block_rows
    from ..parallel.sharded_inplace import MAX_UNROLL_NR

    if engine not in INVENTORY_ENGINES:
        raise ValueError(
            f"no work inventory registered for engine {engine!r} "
            f"(obs/work.INVENTORY_ENGINES); a distributed engine ships "
            f"WITH its analytical work accounting — add its inventory "
            f"before wiring it anywhere")
    if engine in ("swapfree", "augmented"):
        unroll = False
    elif unroll is None:
        unroll = lay.Nr <= MAX_UNROLL_NR
    workload = ("solve" if engine in ("solve_sharded", "solve_lookahead")
                else "invert")
    dt = None
    if dtype is not None:
        import numpy as np

        dt = str(np.dtype(dtype))
    two_d = hasattr(lay, "pc")
    if two_d:
        per_worker, per_superstep = _inventory_2d(lay, workload, int(k))
        mesh = f"{lay.pr}x{lay.pc}"
        workers: object = (lay.pr, lay.pc)
        n_devices = lay.pr * lay.pc
        pc = lay.pc
    else:
        per_worker, per_superstep = _inventory_1d(lay, workload, int(k))
        mesh = f"1D p={lay.p}"
        workers = lay.p
        n_devices = lay.p
        pc = 1
    n, m = lay.n, lay.m
    executed = executed_model_flops(engine, workload, N=lay.N, m=m,
                                    k=int(k), unroll=bool(unroll), pc=pc)
    ideal = executed_model_flops(engine, workload, N=n, m=m, k=int(k),
                                 unroll=bool(unroll), pc=pc)
    return WorkReport(
        engine=engine, mesh=mesh, workers=workers, n=n, block_size=m,
        workload=workload, rhs=int(k), dtype=dt, group=int(group),
        unroll=bool(unroll), n_devices=n_devices,
        supersteps=num_block_rows(n, m), padded_supersteps=lay.Nr,
        padded_n=lay.N, last_height=last_block_height(n, m),
        per_worker=per_worker, per_superstep=per_superstep,
        convention=convention_flops(n, workload, int(k)),
        executed_model=float(executed),
        ragged_penalty=(float(executed) / float(ideal) - 1.0
                        if ideal else 0.0))


# ---------------------------------------------------------------------
# The report: shares, skew, hwcost pin, metrics, span attrs.
# ---------------------------------------------------------------------


@dataclass
class WorkReport:
    """One distributed solve's work record (``SolveResult.work``)."""

    engine: str
    mesh: str
    workers: object
    n: int
    block_size: int
    workload: str           # invert | solve
    rhs: int = 0            # solve-workload RHS columns (0 = invert)
    dtype: str | None = None
    group: int = 0
    unroll: bool = False
    n_devices: int = 1
    supersteps: int = 0     # useful block rows (num_block_rows)
    padded_supersteps: int = 0
    padded_n: int = 0
    last_height: int = 0    # the ragged tail's reduced height
    #: {worker: {"pivot": int, "eliminate": int}} — integer-exact.
    per_worker: dict = field(default_factory=dict)
    #: useful FLOPs per superstep (summed over the mesh) — length
    #: ``supersteps``; pad supersteps carry zero and are omitted.
    per_superstep: list = field(default_factory=list)
    convention: int = 0     # the headline useful total
    executed_model: float = 0.0   # padded launched-work model
    ragged_penalty: float = 0.0   # executed(padded)/executed(exact) − 1
    #: devices × cost_analysis-per-device vs the executed model
    #: (:meth:`attach_xla`); None until a real executable was costed.
    xla: dict | None = None

    # ---- shares ------------------------------------------------------

    def worker_flops(self) -> dict:
        return {w: d["pivot"] + d["eliminate"]
                for w, d in self.per_worker.items()}

    def accounted_flops(self) -> int:
        return sum(self.worker_flops().values())

    @property
    def exact(self) -> bool:
        """The reconciliation invariant: per-worker shares sum EXACTLY
        to the convention total (integer arithmetic, no tolerance)."""
        return self.accounted_flops() == self.convention

    def shares(self) -> dict:
        tot = float(self.convention) or 1.0
        return {w: f / tot for w, f in self.worker_flops().items()}

    def max_worker_flops(self) -> int:
        """The layout's critical path: the most loaded worker's useful
        FLOPs (what a perfectly overlapped superstep schedule waits
        on — the fleet judge's expected-latency unit)."""
        return max(self.worker_flops().values(), default=0)

    def skew(self) -> float:
        """Max-over-mean per-worker imbalance factor (1.0 = balanced;
        the ragged tail and pad blocks push it above 1)."""
        f = list(self.worker_flops().values())
        mean = sum(f) / len(f) if f else 0.0
        return (max(f) / mean) if mean else 1.0

    # ---- the hwcost pin ---------------------------------------------

    def attach_xla(self, cost, span=None) -> dict:
        """Judge ``devices × cost_analysis-per-device`` FLOPs against
        the TRACED work model (:data:`XLA_BAND`).  SPMD executables
        report UNIFORM per-device cost, so ``devices × per-device`` is
        the whole-program static count; cost_analysis counts a
        fori_loop body ONCE (never × trip count), so the fori flavors
        judge against ``executed / Nr``.  The useful convention lives
        in the shares.  An unavailable or flop-less cost_analysis
        stays honest: ``available: False``, never a modeled stand-in
        (obs/hwcost.py's contract)."""
        if cost is None or not getattr(cost, "available", False) \
                or cost.flops is None:
            self.xla = {"available": False}
            return self.xla
        per_dev = float(cost.flops)
        total = per_dev * self.n_devices
        model = float(self.executed_model)
        if not self.unroll and self.padded_supersteps:
            # One traced loop body: a plain fori traces one superstep,
            # the grouped fori traces one full-size group of them.
            traced = (min(self.group, self.padded_supersteps)
                      if self.group > 1 else 1)
            model = model * traced / self.padded_supersteps
        ratio = (total / model) if model > 0 else None
        within = (ratio is not None
                  and XLA_BAND[0] <= ratio <= XLA_BAND[1])
        self.xla = {
            "available": True,
            "per_device_flops": per_dev,
            "devices": self.n_devices,
            "total_flops": total,
            "model_traced_flops": model,
            "model_executed_flops": float(self.executed_model),
            "xla_vs_model": None if ratio is None else _sig(ratio),
            "band": [XLA_BAND[0], XLA_BAND[1]],
            "within": within,
        }
        if span is not None and ratio is not None:
            span.attrs["work_xla_vs_model"] = _sig(ratio)
        return self.xla

    # ---- export ------------------------------------------------------

    def observe_metrics(self) -> None:
        """Set the per-solve work gauges (analytical — exact layout
        math, host-side only: the warm-path zero-compile pins run with
        this on)."""
        for w, s in self.shares().items():
            _M_SHARE.set(s, engine=self.engine, worker=w)
        _M_SKEW.set(self.skew(), engine=self.engine)

    def attach_span(self, span) -> None:
        """Work attrs on a distributed ``execute`` span: the imbalance
        factor, the most loaded worker's share, and the ragged-tail
        penalty the padding costs this shape."""
        span.attrs["work_skew"] = _sig(self.skew())
        span.attrs["work_max_share"] = _sig(
            max(self.shares().values(), default=0.0))
        span.attrs["work_ragged_penalty"] = _sig(self.ragged_penalty)

    def to_json(self) -> dict:
        shares = self.shares()
        return {
            "engine": self.engine, "mesh": self.mesh,
            "workers": (list(self.workers)
                        if isinstance(self.workers, tuple)
                        else self.workers),
            "n": self.n, "block_size": self.block_size,
            "workload": self.workload, "rhs": self.rhs,
            "dtype": self.dtype, "group": self.group,
            "unroll": self.unroll, "n_devices": self.n_devices,
            "supersteps": self.supersteps,
            "padded_supersteps": self.padded_supersteps,
            "padded_n": self.padded_n, "last_height": self.last_height,
            "per_worker": {
                w: {"pivot": d["pivot"], "eliminate": d["eliminate"],
                    "flops": d["pivot"] + d["eliminate"],
                    "share": _sig(shares[w])}
                for w, d in self.per_worker.items()},
            "per_superstep": list(self.per_superstep),
            "totals": {
                "convention_flops": self.convention,
                "accounted_flops": self.accounted_flops(),
                "exact": self.exact,
                "executed_model_flops": self.executed_model,
                "skew": _sig(self.skew()),
                "ragged_penalty": _sig(self.ragged_penalty),
            },
            "xla": self.xla,
        }


#: The last distributed solve's report (the ``--work-report`` CLI
#: snapshot source; process-level, like comm.LAST_REPORT).
_LAST_LOCK = threading.Lock()
LAST_REPORT: WorkReport | None = None


def set_last_report(report: WorkReport) -> None:
    """Record the most recent distributed solve's report (the
    ``--work-report`` snapshot source; called by the driver)."""
    global LAST_REPORT
    with _LAST_LOCK:
        LAST_REPORT = report


def snapshot() -> dict:
    """The process-wide work snapshot (``--work-report``): the last
    distributed solve's full report plus the work metric families."""
    reg = _metrics.REGISTRY.snapshot()
    with _LAST_LOCK:
        last = LAST_REPORT
    return {
        "metric": "work_report",
        "last_solve": None if last is None else last.to_json(),
        "gauges": {name: reg[name] for name in (
            "tpu_jordan_work_share",
            "tpu_jordan_work_skew") if name in reg},
        "counters": {name: reg[name] for name in (
            "tpu_jordan_straggler_suspected_total",) if name in reg},
    }


def write_report(path: str) -> None:
    import json

    with open(path, "w") as f:
        json.dump(snapshot(), f)


# ---------------------------------------------------------------------
# Layer two: measured fleet skew, reconciled against the layout.
# ---------------------------------------------------------------------

#: A replica whose NORMALIZED p99 exceeds the fleet's best by this
#: factor is a suspected straggler.  Normalization divides by the
#: replica's analytical expected-latency factor first, so a replica
#: that is slower because its layout GIVES it more work never trips
#: the threshold (the "layout or replica?" disambiguation).
STRAGGLER_SPREAD = 2.0


def expected_latency_factor(report: WorkReport) -> float:
    """A replica's analytical expected-latency unit: its layout's
    critical path (the most loaded worker's useful FLOPs).  Relative
    across replicas — a homogeneous fleet normalizes to 1, a replica
    on a smaller mesh honestly expects a proportionally larger
    critical path."""
    return float(report.max_worker_flops())


class FleetSkewJudge:
    """The measured-vs-analytical skew reconciler.  ``assess`` takes
    per-replica execute p99s (milliseconds, from the ServeStats
    cross-replica rollup) and optional per-replica analytical
    expected-latency factors; it returns a verdict dict and records a
    TRANSITION-ONLY ``straggler_suspected`` / ``straggler_cleared``
    flight-recorder event pair — a wedged replica must not spam the
    ring every tick.  The live verdict doubles as the autoscaler's
    pre-shed veto input (:meth:`veto`)."""

    def __init__(self, threshold: float = STRAGGLER_SPREAD):
        self.threshold = float(threshold)
        self._lock = threading.Lock()
        self._last: dict | None = None
        self._suspected = False

    def assess(self, p99_ms: dict, expected: dict | None = None) -> dict:
        """Judge one observation of the fleet.  ``p99_ms`` maps replica
        → measured execute p99 (ms); ``expected`` maps replica → its
        analytical expected-latency factor (omitted or equal values =
        homogeneous fleet, raw spread).  Fewer than two replicas with
        data is an honest ``judged: False`` — a one-replica fleet has
        no spread to measure."""
        norm = {}
        for rep, v in p99_ms.items():
            if v is None or v <= 0:
                continue
            e = float(expected.get(rep, 1.0)) if expected else 1.0
            if e <= 0:
                e = 1.0
            norm[str(rep)] = float(v) / e
        verdict: dict = {
            "threshold": self.threshold,
            "p99_ms": {str(r): (None if v is None else float(v))
                       for r, v in p99_ms.items()},
            "expected": ({str(r): float(v) for r, v in expected.items()}
                         if expected else None),
            "normalized": {r: _sig(v) for r, v in norm.items()},
        }
        if len(norm) < 2:
            verdict.update({"judged": False, "suspected": False,
                            "spread": None, "replica": None})
        else:
            worst = max(norm, key=lambda r: norm[r])
            best = min(norm.values())
            spread = norm[worst] / best
            verdict.update({
                "judged": True,
                "spread": _sig(spread),
                "replica": worst,
                "suspected": spread > self.threshold,
            })
        with self._lock:
            was = self._suspected
            now = bool(verdict["suspected"])
            self._suspected = now
            self._last = verdict
        if now and not was:
            _M_STRAGGLER.inc(replica=verdict["replica"])
            _recorder.record(
                "straggler_suspected", replica=verdict["replica"],
                spread=verdict["spread"], threshold=self.threshold,
                p99_ms=verdict["p99_ms"],
                normalized=verdict["normalized"])
        elif was and not now:
            _recorder.record(
                "straggler_cleared", spread=verdict["spread"],
                threshold=self.threshold)
        return verdict

    def veto(self) -> dict | None:
        """The pre-shed veto input: the last verdict IF it currently
        suspects a straggler (one sick replica explains the p99 risk —
        shedding the whole fleet is the wrong actuator; route/drain
        that replica instead), else None."""
        with self._lock:
            if self._suspected and self._last is not None:
                return dict(self._last)
            return None

    @property
    def last_verdict(self) -> dict | None:
        with self._lock:
            return None if self._last is None else dict(self._last)


# ---------------------------------------------------------------------
# The acceptance demo (`make work-demo`, CLI --work-demo).
# ---------------------------------------------------------------------


def _work_leg(name: str, *, n: int, m: int, workers, engine: str,
              gather: bool, group: int = 0, dtype=None,
              generator: str = "absdiff") -> dict:
    import jax.numpy as jnp

    from ..driver import solve

    res = solve(n, m, workers=workers, engine=engine, group=group,
                gather=gather, generator=generator,
                dtype=dtype if dtype is not None else jnp.float32)
    return {"name": name, "n": n, "block_size": m,
            "elapsed_s": res.elapsed,
            "rel_residual": res.rel_residual,
            "work": res.work.to_json()}


def _solve_work_leg(name: str, *, n: int, m: int, workers, gather: bool,
                    k: int, dtype, generator: str,
                    engine: str = "solve_sharded") -> dict:
    import jax.numpy as jnp

    from ..linalg import solve_system
    from ..ops import generate

    dt = jnp.dtype(dtype if dtype is not None else jnp.float32)
    a = generate(generator, (n, n), dt)
    bmat = generate("rand", (n, k), dt, row_offset=n)
    res = solve_system(a, bmat, block_size=m, workers=workers,
                       gather=gather, engine=engine)
    return {"name": name, "n": n, "block_size": m,
            "elapsed_s": res.elapsed,
            "rel_residual": res.rel_residual,
            "work": res.work.to_json()}


def _fleet_skew_legs() -> tuple[list, dict]:
    """The measured-skew legs: synthetic per-replica latencies pushed
    through the REAL rollup + judge path (ServeStats.batch →
    cross_replica_spread → FleetSkewJudge), the work-observatory twin
    of the comm demo's deliberate drift leg.  Three cases: a genuinely
    sick replica (must be a recorded ``straggler_suspected`` event), a
    layout-attributed spread (a replica on a smaller mesh is slower
    exactly in proportion to its analytical critical path — must stay
    CLEAN), and the recovery transition (``straggler_cleared``)."""
    from ..serve.stats import ServeStats, cross_replica_spread

    def replica_stats(slot: int, exec_s: list) -> "ServeStats":
        st = ServeStats(labels={"replica": str(slot)})
        for e in exec_s:
            st.batch("demo", occupancy=1, exec_seconds=e,
                     queue_seconds=())
        return st

    legs = []
    judge = FleetSkewJudge()

    # Leg A: replica 2 is 5x slower than its homogeneous peers — an
    # environmental straggler the judge MUST suspect.
    snaps = [replica_stats(i, [0.010 + 0.001 * j for j in range(8)])
             for i in range(2)]
    snaps.append(replica_stats(2, [0.050 + 0.005 * j for j in range(8)]))
    spread = cross_replica_spread([s.snapshot() for s in snaps])
    p99 = {r: d["exec_ms"]["p99"]
           for r, d in spread["replicas"].items()}
    verdict = judge.assess(p99)
    legs.append({"name": "fleet_straggler_suspected", "synthetic": True,
                 "spread": spread, "verdict": verdict,
                 "expect_suspected": True})

    # Leg B: a heterogeneous fleet — replica 1's layout critical path
    # is ~4x replica 0's, and its measured p99 is slower by the SAME
    # factor: layout-inherent, must NOT be misread as a sick replica.
    from ..parallel.layout import CyclicLayout

    rep_big = engine_report(engine="inplace",
                            lay=CyclicLayout.create(44, 8, 8))
    rep_small = engine_report(engine="inplace",
                              lay=CyclicLayout.create(44, 8, 2))
    expected = {"0": expected_latency_factor(rep_big),
                "1": expected_latency_factor(rep_small)}
    ratio = expected["1"] / expected["0"]
    snaps = [replica_stats(0, [0.010] * 8),
             replica_stats(1, [0.010 * ratio] * 8)]
    spread_b = cross_replica_spread([s.snapshot() for s in snaps])
    p99_b = {r: d["exec_ms"]["p99"]
             for r, d in spread_b["replicas"].items()}
    judge_b = FleetSkewJudge()
    verdict_b = judge_b.assess(p99_b, expected=expected)
    legs.append({"name": "fleet_skew_layout_attributed",
                 "synthetic": True, "spread": spread_b,
                 "expected": expected, "verdict": verdict_b,
                 "expect_suspected": False})

    # Leg C: the first judge sees the straggler recover — the verdict
    # clears and the transition records ``straggler_cleared`` (never a
    # second ``straggler_suspected`` while already suspected).
    p99_rec = {r: 11.0 for r in p99}
    verdict_c = judge.assess(p99_rec)
    legs.append({"name": "fleet_straggler_recovered", "synthetic": True,
                 "verdict": verdict_c, "expect_suspected": False})

    fleet = {"threshold": STRAGGLER_SPREAD,
             "veto_after_recovery": judge.veto()}
    return legs, fleet


def work_demo(n: int = 48, block_size: int = 8, seed: int = 0,
              dtype=None, generator: str = "absdiff") -> dict:
    """The ISSUE 19 acceptance run: distributed solves on 1D and 2D
    meshes — invert and solve workloads, a RAGGED size (the padded
    tail's zero-work blocks skew the shares) and an ALIGNED size (the
    penalty pins to exactly 0) — each leg's per-worker analytical
    shares summing EXACTLY to the convention total and its executable
    judged against cost_analysis (devices × per-device vs the padded
    executed model); then the fleet-skew legs: a synthetic straggler
    that MUST become a recorded ``straggler_suspected`` event, a
    layout-attributed spread that must stay clean, and the recovery
    transition.

    Returns the one-line-JSON report ``tools/check_work.py`` validates
    (exit 2 = unaccounted work or a straggler verdict the evidence
    can't support).  Needs an 8-device mesh: re-execs itself on a
    forced virtual CPU platform when the current process cannot host
    one (the dryrun recipe)."""
    import json
    import subprocess
    import sys

    import jax
    import jax.numpy as jnp

    from .comm import _cpu_env, _repo_root

    del seed  # the demo fixtures are deterministic generators
    dt = jnp.dtype(dtype if dtype is not None else jnp.float32)
    if dt.kind == "c":
        from ..driver import UsageError

        raise UsageError(
            "--work-demo accounts the DISTRIBUTED engines and complex "
            "dtypes run single-device (driver.solve's contract); use "
            "a real dtype")
    try:
        can_inline = len(jax.devices()) >= 8
    except RuntimeError:
        can_inline = False
    if not can_inline:
        x64 = ("jax.config.update('jax_enable_x64', True)\n"
               if dt.itemsize == 8 else "")
        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            + x64 +
            "import json\n"
            "from tpu_jordan.obs.work import work_demo\n"
            f"print(json.dumps(work_demo(n={int(n)}, "
            f"block_size={int(block_size)}, dtype={dt.name!r}, "
            f"generator={generator!r})))\n")
        proc = subprocess.run(
            [sys.executable, "-c", code], env=_cpu_env(8),
            cwd=_repo_root(), capture_output=True, text=True,
            timeout=900)
        if proc.returncode != 0:
            raise RuntimeError(
                f"work_demo subprocess failed (rc={proc.returncode}): "
                f"{proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    m = block_size
    # A ragged point: n chosen so n % m != 0 (the padded identity tail
    # and its zero useful work ride through every share below).
    n_rag = n - m // 2 if n % m == 0 else n
    # An aligned point: n % m == 0 AND p | Nr on the p=4 mesh — the
    # ragged penalty must pin to exactly 0.0.
    n_ali = 8 * m
    mark = _recorder.RECORDER.total
    kw = {"dtype": dt, "generator": generator}
    legs = [
        _work_leg("1d_p4_inplace_gathered", n=n_rag, m=m, workers=4,
                  engine="inplace", gather=True, **kw),
        _work_leg("1d_p4_swapfree_sharded", n=n_rag, m=m, workers=4,
                  engine="swapfree", gather=False, **kw),
        _work_leg("1d_p4_inplace_aligned", n=n_ali, m=m, workers=4,
                  engine="inplace", gather=True, **kw),
        _work_leg("2d_2x2_inplace_gathered", n=n_rag, m=m,
                  workers=(2, 2), engine="inplace", gather=True, **kw),
        _solve_work_leg("1d_p4_solve_gathered", n=n_rag, m=m,
                        workers=4, gather=True, k=3, dtype=dt,
                        generator=generator),
        _solve_work_leg("2d_2x2_solve_sharded", n=n_rag, m=m,
                        workers=(2, 2), gather=False, k=2, dtype=dt,
                        generator=generator),
    ]
    fleet_legs, fleet = _fleet_skew_legs()
    blackbox = _recorder.RECORDER.dump(
        events=_recorder.RECORDER.since(mark))
    straggler_events = [e for e in blackbox["events"]
                        if e["kind"] == "straggler_suspected"]
    cleared_events = [e for e in blackbox["events"]
                      if e["kind"] == "straggler_cleared"]
    unaccounted = [leg["name"] for leg in legs
                   if not leg["work"]["totals"]["exact"]]
    xla_unreconciled = [
        leg["name"] for leg in legs
        if (leg["work"]["xla"] or {}).get("available")
        and not leg["work"]["xla"]["within"]]
    aligned = next(leg for leg in legs
                   if leg["name"] == "1d_p4_inplace_aligned")
    penalty_bad = aligned["work"]["totals"]["ragged_penalty"] != 0.0
    verdict_wrong = [
        leg["name"] for leg in fleet_legs
        if bool(leg["verdict"]["suspected"]) != leg["expect_suspected"]]
    silent_straggler = (
        any(leg["expect_suspected"] for leg in fleet_legs)
        and not straggler_events)
    return {
        "metric": "work_demo",
        "n": n_rag, "aligned_n": n_ali, "block_size": m,
        "dtype": dt.name, "generator": generator,
        "ragged": n_rag % m != 0,
        "legs": legs,
        "fleet_legs": fleet_legs,
        "fleet": fleet,
        "straggler_events": len(straggler_events),
        "cleared_events": len(cleared_events),
        "unaccounted": unaccounted,
        "xla_unreconciled": xla_unreconciled,
        "penalty_nonzero_aligned": penalty_bad,
        "verdict_wrong": verdict_wrong,
        "silent_work": bool(unaccounted or xla_unreconciled
                            or penalty_bad or verdict_wrong
                            or silent_straggler),
        "blackbox": blackbox,
    }
