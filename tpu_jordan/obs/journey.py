"""Request-journey tracing (ISSUE 8 tentpole part 1).

PR 4 answers "where did this solve's milliseconds go" and PR 7 answers
"did any request get silently lost" — this module answers "what
happened to *this* request".  Every request entering the serving
surface (``JordanService.submit`` / ``JordanFleet.submit``) gets a
:class:`RequestContext` carrying a deterministic ``request_id``; every
hop of its life appends a timestamped journey event:

  ==================  =================================================
  event               recorded by
  ==================  =================================================
  submit              the journey log, at context creation
  route               fleet router, on replica acceptance (slot, attempt)
  shed                fleet router, per skipped replica (reason)
  requeue             fleet router, after a replica-death re-dispatch
  reject              router/service, on a typed submit-time rejection
  enqueue             the micro-batcher's bounded-queue admission
  breaker_fast_fail   the batcher's circuit-breaker fast-fail
  dispatch            the dispatcher (batch occupancy + cause:
                      full | deadline | drain)
  executor            the dispatcher (bucket + source:
                      compiled | shared_store | cached)
  retry               the dispatcher's per-batch retry (attempt, error)
  deadline            the typed deadline failure (phase: queue | execute)
  batch_failure       a terminal batch error fanned to this rider
  fault               a request-scoped injected fault (replica_kill)
  served              the replica-level result fan-out (singular, secs)
  result              TERMINAL — outcome ok|error, written by close()
  mesh_admitted       the mesh-lane admission walk (ISSUE 18): this
                      request routed to a distributed lane (mesh +
                      the per-device projection that admitted it)
  ==================  =================================================

Every event is mirrored into the always-on flight recorder
(``obs/recorder.py``, kind ``journey``) with the same timestamp, so a
request's whole path is reconstructible from the black-box dump alone
— the ISSUE 8 acceptance pin — and exportable as one Chrome-trace
async lane per request (:func:`async_trace_events`; Perfetto renders
one row per ``request_id`` with every hop as an instant).

Determinism: ``request_id`` is ``<prefix>-<seq>`` from the log's own
counter — submit order, not wall clock or randomness — so a seeded
demo produces byte-identical ids run after run (the FaultPlan
discipline).  Terminal outcomes feed ``tpu_jordan_request_outcome_total``
(the series ``obs/slo.py`` burn-rates over) and
``tpu_jordan_request_latency_seconds``; both demos derive their outcome
ledgers from journey events through ONE helper (:func:`outcome_ledger`)
so demo ledgers and checker inputs can never drift.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from . import metrics as _metrics
from . import recorder as _recorder

#: Journey events that explain a typed failure (the checker's
#: "no gap" rule: a typed-failure journey must carry at least one).
EXPLANATORY_HOPS = frozenset({
    "shed", "requeue", "reject", "breaker_fast_fail",
    "deadline", "batch_failure", "fault", "retry",
})

#: Completed contexts retained per log (the obs bounded-window policy).
MAX_COMPLETED = 4096

#: Defensive per-request event cap: a pathological requeue loop must
#: not grow one context without bound (the budget bounds it anyway).
MAX_EVENTS_PER_REQUEST = 256

_M_OUTCOME = _metrics.counter(
    "tpu_jordan_request_outcome_total",
    "terminal request outcomes from journey close (ok | error), "
    "labeled by outcome and bucket — the availability series the SLO "
    "burn-rate monitor evaluates")
_M_LATENCY = _metrics.histogram(
    "tpu_jordan_request_latency_seconds",
    "submit-to-terminal-outcome wall seconds per request (journey "
    "close), labeled by bucket — the latency series behind the SLO "
    "p99 objective")


class RequestContext:
    """One request's identity + journey.  Created by
    :meth:`JourneyLog.new`; threaded through the router, replica,
    batcher, and executors; closed exactly once with the terminal
    outcome."""

    __slots__ = ("request_id", "n", "bucket", "workload", "t_created",
                 "_log", "_lock", "_events", "_closed")

    def __init__(self, request_id: str, n: int, bucket: int, log,
                 workload: str = "invert"):
        self.request_id = request_id
        self.n = int(n)
        self.bucket = int(bucket)
        #: the request's workload (ISSUE 11): "invert" or "solve" —
        #: stamped on the submit hop so journey-level traffic splits
        #: per workload without re-deriving it from lane labels.
        self.workload = str(workload)
        self._log = log
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._closed = False
        self.t_created = log.clock()
        self.event("submit", n=self.n, bucket=self.bucket,
                   workload=self.workload)

    def event(self, name: str, **attrs) -> None:
        """One journey hop: appended to this context AND mirrored into
        the flight recorder with the same timestamp (reconstruction
        from the dump alone must never disagree with the live view)."""
        t = self._log.clock()
        ev = {"t": t, "event": str(name)}
        ev.update(attrs)
        with self._lock:
            if self._closed or len(self._events) >= MAX_EVENTS_PER_REQUEST:
                return
            self._events.append(ev)
        self._log.recorder.record(
            "journey", t=t, request_id=self.request_id, event=str(name),
            **attrs)

    def close(self, outcome: str, error: str | None = None,
              **attrs) -> None:
        """Record the terminal ``result`` event (idempotent — the first
        closer wins under the lock; a late requeue/deadline race cannot
        re-open a finished journey) and feed the SLO outcome/latency
        series."""
        t = self._log.clock()
        payload = dict(attrs, outcome=str(outcome))
        if error is not None:
            payload["error"] = str(error)
        ev = dict(payload, t=t, event="result")
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._events.append(ev)
        self._log.recorder.record("journey", t=t,
                                  request_id=self.request_id,
                                  event="result", **payload)
        # Non-invert journeys carry a workload label (ISSUE 12: the
        # update-vs-solve-vs-invert traffic split, visible to one
        # Prometheus scrape); invert keeps its historical label set
        # byte-identical, and the SLO evaluator — which filters by
        # bucket and sums the rest — sees every series either way.
        wl = ({} if self.workload == "invert"
              else {"workload": self.workload})
        _M_OUTCOME.inc(outcome=str(outcome), bucket=self.bucket, **wl)
        _M_LATENCY.observe(t - self.t_created, bucket=self.bucket, **wl)
        self._log._complete(self)

    def close_from_future(self, future) -> None:
        """Terminal-outcome adapter for a ``concurrent.futures`` done
        callback (the standalone-service path; the fleet router closes
        its contexts explicitly)."""
        exc = future.exception() if not future.cancelled() else None
        if future.cancelled():
            self.close("error", error="Cancelled")
        elif exc is not None:
            self.close("error", error=type(exc).__name__)
        else:
            res = future.result()
            self.close("ok", singular=bool(getattr(res, "singular",
                                                   False)))

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def events(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def outcome(self) -> tuple[str, str | None] | None:
        """("ok"|"error", error-type-or-None), or None while open."""
        for e in reversed(self.events()):
            if e["event"] == "result":
                return e["outcome"], e.get("error")
        return None

    def to_dict(self) -> dict:
        return {"request_id": self.request_id, "n": self.n,
                "bucket": self.bucket, "events": self.events()}


#: Instances minted per requested prefix, process-wide: journey ids
#: must be unique across EVERY log in the process — the whole-ring
#: exports (``--trace-json`` lanes, ``--blackbox-out`` dumps) group
#: purely by ``request_id``, and two services both minting
#: ``req-00001`` would merge two different requests into one journey.
_PREFIX_LOCK = threading.Lock()
_PREFIX_COUNTS: dict = {}


class JourneyLog:
    """The per-service/per-fleet context factory and retention window.
    ``new()`` mints deterministic ids in submit order; completed
    contexts are retained in a bounded ring (active ones are tracked
    until closed).

    The SECOND log constructed with a given prefix gets an instance
    suffix (``req``, ``req2``, ``req3``, ...): construction order is
    deterministic in a seeded demo, so ids stay byte-identical run to
    run while never colliding across a run's successive services or
    fleets."""

    def __init__(self, prefix: str = "req", clock=None,
                 max_completed: int = MAX_COMPLETED, recorder=None):
        prefix = str(prefix)
        with _PREFIX_LOCK:
            _PREFIX_COUNTS[prefix] = _PREFIX_COUNTS.get(prefix, 0) + 1
            inst = _PREFIX_COUNTS[prefix]
        self.prefix = prefix if inst == 1 else f"{prefix}{inst}"
        self.clock = clock if clock is not None else time.perf_counter
        self.recorder = (recorder if recorder is not None
                         else _recorder.RECORDER)
        self._lock = threading.Lock()
        self._seq = 0
        self._active: dict[str, RequestContext] = {}
        self._completed: deque = deque(maxlen=int(max_completed))

    def new(self, n: int, bucket: int,
            workload: str = "invert") -> RequestContext:
        with self._lock:
            self._seq += 1
            rid = f"{self.prefix}-{self._seq:05d}"
        ctx = RequestContext(rid, n, bucket, self, workload=workload)
        with self._lock:
            self._active[rid] = ctx
        return ctx

    def _complete(self, ctx: RequestContext) -> None:
        with self._lock:
            self._active.pop(ctx.request_id, None)
            self._completed.append(ctx)

    def contexts(self) -> list[RequestContext]:
        """Completed (oldest first) then still-active contexts."""
        with self._lock:
            return list(self._completed) + list(self._active.values())

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def ledger(self) -> dict:
        """The journey-derived outcome ledger (ISSUE 8 satellite: both
        demos derive their ledgers through this ONE helper)."""
        return outcome_ledger(e for ctx in self.contexts()
                              for e in _ctx_journey_events(ctx))


def _ctx_journey_events(ctx: RequestContext):
    for e in ctx.events():
        ev = dict(e)
        ev["request_id"] = ctx.request_id
        yield ev


def journeys_from_events(events) -> dict[str, list[dict]]:
    """Group flight-recorder ``journey`` events (or any dicts carrying
    ``request_id``/``event``) by request id, preserving order — the
    reconstruction primitive the checkers and the async-lane exporter
    share."""
    out: dict[str, list[dict]] = {}
    for e in events:
        if e.get("kind") not in (None, "journey"):
            continue
        rid = e.get("request_id")
        if rid is None:
            continue
        out.setdefault(str(rid), []).append(e)
    return out


def outcome_ledger(events) -> dict:
    """The outcome ledger derived purely from journey events: how many
    requests were submitted, how many reached a terminal ``result``
    (ok vs typed error, with the per-type breakdown), and how many are
    gaps (submitted, never resolved — the silent-loss signature).

    ONE implementation for the chaos demo, the fleet demo, and the
    checkers: a ledger computed any other way can drift from what the
    black box can actually prove."""
    journeys = journeys_from_events(events)
    ok = errors = 0
    typed: dict[str, int] = {}
    gaps: list[str] = []
    singular = 0
    for rid, evs in journeys.items():
        terminal = next((e for e in reversed(evs)
                         if e.get("event") == "result"), None)
        if terminal is None:
            gaps.append(rid)
        elif terminal.get("outcome") == "ok":
            ok += 1
            singular += int(bool(terminal.get("singular")))
        else:
            errors += 1
            name = str(terminal.get("error", "UnknownError"))
            typed[name] = typed.get(name, 0) + 1
    return {
        "submitted": len(journeys),
        "ok": ok,
        "error": errors,
        "typed_errors": dict(sorted(typed.items())),
        "singular_flagged": singular,
        "gaps": sorted(gaps),
    }


def async_trace_events(events, cat: str = "tpu_jordan_request",
                       pid: int = 0) -> list[dict]:
    """Chrome-trace ASYNC events from journey events: one lane per
    request (nestable ``b``/``e`` bracketing the journey, a nestable
    instant ``n`` per hop), grouped by ``id`` — Perfetto renders one
    row per request showing the full path (docs/OBSERVABILITY.md).

    ``events`` is any iterable of journey-event dicts (a flight-
    recorder slice, a report's ``blackbox.events``, or a
    ``JourneyLog``'s contexts via :func:`journeys_from_events`)."""
    out: list[dict] = []
    for rid, evs in sorted(journeys_from_events(events).items()):
        ts = [float(e["t"]) for e in evs]
        t0, t1 = min(ts), max(ts)
        base = {"cat": cat, "id": rid, "pid": pid, "tid": 0}
        out.append(dict(base, name=rid, ph="b",
                        ts=round(t0 * 1e6, 3)))
        for e in evs:
            args = {k: (v if isinstance(v, (str, int, float, bool,
                                            type(None))) else str(v))
                    for k, v in e.items()
                    if k not in ("t", "kind", "seq", "request_id")}
            out.append(dict(base, name=str(e["event"]), ph="n",
                            ts=round(float(e["t"]) * 1e6, 3),
                            args=args))
        out.append(dict(base, name=rid, ph="e",
                        ts=round(t1 * 1e6, 3)))
    return out
