"""Per-superstep numerical health (ISSUE 10 tentpole part 1).

The paper's two distinctive signals — the condition-based pivot
criterion (the ∞-norm of each candidate block inverse,
main.cpp:1026-1074) and the final residual ‖A·A⁻¹ − I‖∞
(main.cpp:490-513) — are computed on every solve and then discarded
after a single comparison.  Our reproduction did the same: the PR 5
degradation ladder fires on a gate failure with no record of WHY the
numerics went bad.  This module is the record.

Three modes (the ``numerics=`` knob on ``driver.solve``):

  * ``"off"`` (the default, and the serve-path default) — nothing
    collected, nothing observed, zero cost.  The warm-path pins
    (zero compiles, zero measurements) run with this.
  * ``"summary"`` — a :class:`NumericsReport` built ONLY from numbers
    the solve already returns (rel_residual, κ∞, ‖A‖∞): no extra
    device work, honest on every engine including the fused Pallas
    executables the host cannot see inside.
  * ``"trace"`` — the full per-superstep health trace from the
    INSTRUMENTED unrolled engines (``ops/jordan_inplace.py``
    ``collect_stats=True``): per step, the chosen pivot block id, its
    inverse ∞-norm (the paper's selection criterion — the step's
    ``key[rel]``), the worst finite candidate norm (the spread's other
    end), the singular-candidate count, and the running
    element-growth watermark ``max|V|``.  The stats ride the same
    compiled executable as the solve (stacked (Nr,) outputs) and the
    inverse bit-matches the uninstrumented engine — pinned by
    tests/test_numerics.py.  Host-visible engines only: a fused
    executable cannot be bracketed per step, so ``trace`` on the
    augmented / fori-only / distributed / bf16-fused paths is a typed
    ``UsageError``, never a silently different trace (the PR 4
    honesty discipline).

Every non-off report mirrors into the metrics registry
(``tpu_jordan_pivot_condition`` / ``tpu_jordan_growth_factor`` /
``tpu_jordan_residual`` histograms) and threshold exceedances are
recorded as ``numerics_spike`` flight-recorder events BEFORE the PR 5
ladder runs — so a ``recovery_rung`` event is causally preceded (by
``seq``) by the numerics evidence that explains it.
``tools/check_numerics.py`` validates that chain both ways.

Honesty contract: every MEASURED field comes off the executed solve
(the stats outputs, the verified residual).  The per-step
``residual_est`` ladder is the one MODELED field (eps·n·growth/‖A‖∞ —
the classic element-growth error model) and is named in
``NumericsReport.modeled_fields`` so it can never masquerade as a
measurement.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from . import metrics as _metrics
from . import recorder as _recorder

MODES = ("off", "summary", "trace")

#: The modeled per-step residual-estimate ladder's error model:
#: rel_residual ≈ eps · n · growth — the standard backward-error bound
#: with the measured element-growth watermark standing in for the
#: unknowable true growth factor (Higham, Accuracy and Stability,
#: ch. 14; the same eps·n·κ family the PR 5 gate uses).
_EST_NOTE = "eps*n*growth/norm_a (modeled; Higham-style growth bound)"


def resolve_mode(mode) -> str:
    """Validate the ``numerics=`` knob (shared by solve / JordanService
    / CLI so the vocabulary can't drift)."""
    if mode is None:
        return "off"
    if mode not in MODES:
        from ..driver import UsageError

        raise UsageError(f"unknown numerics mode {mode!r}; choose from "
                         f"{'/'.join(MODES)}")
    return mode


# ---------------------------------------------------------------------
# The report
# ---------------------------------------------------------------------

_M_PIVOT = _metrics.histogram(
    "tpu_jordan_pivot_condition",
    "per-superstep ∞-norm of the CHOSEN pivot block inverse — the "
    "paper's selection criterion (main.cpp:1026-1074); trace mode only")
_M_GROWTH = _metrics.histogram(
    "tpu_jordan_growth_factor",
    "element-growth watermark max|V|/‖A‖∞ of the working matrix over "
    "the elimination; trace mode only")
_M_RESIDUAL = _metrics.histogram(
    "tpu_jordan_residual",
    "verified relative residual ‖A·X−I‖∞/‖A‖∞ per solve (summary and "
    "trace modes)")
_M_SPIKES = _metrics.counter(
    "tpu_jordan_numerics_spikes_total",
    "numerics threshold exceedances recorded as flight-recorder "
    "events, labeled by signal")


@dataclass
class NumericsReport:
    """One solve's numerical health record (``SolveResult.numerics``).

    Summary fields are present in both non-off modes; the per-step
    lists (``pivot_block`` .. ``residual_est``) only in ``trace``.
    ``modeled_fields`` names the fields that come from an error MODEL
    rather than a measurement — everything else is read off the
    executed solve."""

    mode: str
    n: int
    block_size: int
    engine: str
    rel_residual: float
    kappa: float
    norm_a: float
    eps: float
    #: which workload produced the record (ISSUE 11): "invert" (the
    #: historical default — eps·n·κ residual semantics) or a solve
    #: workload, whose rel_residual is the κ-FREE ‖A·X − B‖ normwise
    #: backward error and whose ``kappa`` is the ‖A‖‖X‖/‖B‖
    #: lower-bound estimate (linalg/engine.solve_batch_metrics).
    workload: str = "invert"
    # trace-only (None in summary mode) -------------------------------
    trace_engine: str | None = None   # the instrumented twin that ran
    pivot_block: list | None = None         # chosen pivot block per step
    pivot_inv_norm: list | None = None      # ‖H‖∞ = the criterion value
    cand_norm_max: list | None = None       # worst FINITE candidate norm
    singular_candidates: list | None = None  # probe-flagged per step
    growth: list | None = None              # running max|V| watermark
    residual_est: list | None = None        # MODELED eps·n·growth ladder
    residual_est_model: str = _EST_NOTE
    modeled_fields: tuple = ("residual_est",)
    spikes: list = field(default_factory=list)  # record_spikes fills this

    @property
    def growth_factor(self) -> float | None:
        """Final element-growth watermark relative to ‖A‖∞."""
        if not self.growth or not self.norm_a:
            return None
        return float(self.growth[-1]) / self.norm_a

    @property
    def max_pivot_inv_norm(self) -> float | None:
        vals = [v for v in (self.pivot_inv_norm or ())
                if math.isfinite(v)]
        return max(vals) if vals else None

    @property
    def pivot_spread_max(self) -> float | None:
        """Worst per-step candidate-norm spread (max finite candidate
        over the chosen minimum) — how decisive the pivot choice was."""
        if not self.pivot_inv_norm:
            return None
        spreads = [mx / mn for mn, mx in zip(self.pivot_inv_norm,
                                             self.cand_norm_max)
                   if math.isfinite(mn) and math.isfinite(mx) and mn > 0]
        return max(spreads) if spreads else None

    def to_json(self) -> dict:
        doc = {
            "mode": self.mode, "n": self.n,
            "block_size": self.block_size, "engine": self.engine,
            "workload": self.workload,
            "rel_residual": self.rel_residual, "kappa": self.kappa,
            "norm_a": self.norm_a, "eps": self.eps,
            "spikes": list(self.spikes),
        }
        if self.mode == "trace":
            doc.update({
                "trace_engine": self.trace_engine,
                "pivot_block": self.pivot_block,
                "pivot_inv_norm": self.pivot_inv_norm,
                "cand_norm_max": self.cand_norm_max,
                "singular_candidates": self.singular_candidates,
                "growth": self.growth,
                "growth_factor": self.growth_factor,
                "max_pivot_inv_norm": self.max_pivot_inv_norm,
                "pivot_spread_max": self.pivot_spread_max,
                "residual_est": self.residual_est,
                "residual_est_model": self.residual_est_model,
                "modeled_fields": list(self.modeled_fields),
            })
        return doc


def _floats(arr) -> list:
    import numpy as np

    return [float(v) for v in np.asarray(arr, dtype=np.float64)]


def summary_report(*, n: int, block_size: int, engine: str,
                   rel_residual: float, kappa: float, norm_a: float,
                   dtype, workload: str = "invert") -> NumericsReport:
    """``"summary"`` mode: built ONLY from what the solve already
    returned — no extra device work, honest on fused executables.
    ``workload`` tags the record (ISSUE 11) so solve-workload residual
    semantics (κ-free backward error) are never mistaken for invert's
    eps·n·κ model."""
    import jax.numpy as jnp

    return NumericsReport(
        mode="summary", n=n, block_size=block_size, engine=engine,
        rel_residual=float(rel_residual), kappa=float(kappa),
        norm_a=float(norm_a),
        eps=float(jnp.finfo(jnp.dtype(dtype)).eps),
        workload=workload)


def trace_report(stats: dict, *, n: int, block_size: int, engine: str,
                 trace_engine: str, rel_residual: float, kappa: float,
                 norm_a: float, dtype,
                 workload: str = "invert") -> NumericsReport:
    """``"trace"`` mode: the per-superstep stats stacked by the
    instrumented engine (``collect_stats=True``) plus the verified
    end-state numbers.  The modeled ``residual_est`` ladder is derived
    host-side — the device pays nothing for it.  ``workload`` tags the
    record (ISSUE 12 satellite: the solve engine's trace twin) so the
    κ-free backward-error semantics of a solve trace are never
    mistaken for invert's eps·n·κ model."""
    import numpy as np

    rep = summary_report(n=n, block_size=block_size, engine=engine,
                         rel_residual=rel_residual, kappa=kappa,
                         norm_a=norm_a, dtype=dtype, workload=workload)
    rep.mode = "trace"
    rep.trace_engine = trace_engine
    rep.pivot_block = [int(v) for v in np.asarray(stats["pivot_block"])]
    rep.pivot_inv_norm = _floats(stats["pivot_inv_norm"])
    rep.cand_norm_max = _floats(stats["cand_norm_max"])
    rep.singular_candidates = [
        int(v) for v in np.asarray(stats["singular_candidates"])]
    rep.growth = _floats(stats["growth"])
    na = rep.norm_a if rep.norm_a else 1.0
    rep.residual_est = [rep.eps * n * g / na for g in rep.growth]
    return rep


# ---------------------------------------------------------------------
# Registry mirroring + spike events
# ---------------------------------------------------------------------

def observe(report: NumericsReport) -> None:
    """Mirror a report into the process-wide registry (engine-labeled
    series).  Trace-only signals are observed only when measured —
    summary mode never fabricates a pivot/growth sample."""
    if math.isfinite(report.rel_residual):
        labels = {"engine": report.engine}
        if report.workload != "invert":
            labels["workload"] = report.workload
        _M_RESIDUAL.observe(report.rel_residual, **labels)
    if report.mode != "trace":
        return
    for v in report.pivot_inv_norm or ():
        if math.isfinite(v):
            _M_PIVOT.observe(v, engine=report.engine)
    gf = report.growth_factor
    if gf is not None and math.isfinite(gf):
        _M_GROWTH.observe(gf, engine=report.engine)


@dataclass(frozen=True)
class SpikeThresholds:
    """When a health signal becomes a flight-recorder event.

    ``residual`` defaults to the PR 5 expected-error model eps·n·κ∞
    (capped at 0.5, the same non-vacuousness ceiling as the gate) —
    the driver passes the policy's OWN gate threshold when a policy is
    attached, so a gate failure can never outrun its spike.
    ``pivot_condition`` fires on ‖H‖∞·‖A‖∞ (a scale-free condition
    proxy for the chosen pivot block) above ``1/sqrt(eps)``;
    ``growth`` on the element-growth factor."""

    residual: float | None = None       # None = eps·n·max(1,κ) cap 0.5
    pivot_condition: float | None = None  # None = 1/sqrt(eps)
    growth: float = 1e3

    def residual_threshold(self, rep: NumericsReport) -> float:
        if self.residual is not None:
            return self.residual
        kap = rep.kappa if math.isfinite(rep.kappa) else float("inf")
        return min(rep.eps * max(1, rep.n) * max(1.0, kap), 0.5)

    def pivot_threshold(self, rep: NumericsReport) -> float:
        if self.pivot_condition is not None:
            return self.pivot_condition
        return 1.0 / math.sqrt(rep.eps)


def record_spikes(report: NumericsReport,
                  thresholds: SpikeThresholds | None = None,
                  recorder=None) -> list[dict]:
    """Compare the report against the thresholds and record one
    ``numerics_spike`` flight-recorder event per exceedance — the
    causal breadcrumb a later ``recovery_rung`` event points back to.
    Returns the spike dicts (also appended to ``report.spikes``).

    Must be called BEFORE the degradation ladder runs (the driver
    does): the checker validates rung events by preceding-seq spike."""
    thr = thresholds if thresholds is not None else SpikeThresholds()
    rec = recorder if recorder is not None else _recorder.record
    spikes = []

    def spike(signal: str, value: float, threshold: float, **extra):
        ev = {"signal": signal, "value": float(value),
              "threshold": float(threshold), **extra}
        spikes.append(ev)
        _M_SPIKES.inc(signal=signal)
        rec("numerics_spike", n=report.n, engine=report.engine,
            mode=report.mode, **ev)

    rthr = thr.residual_threshold(report)
    rel = report.rel_residual
    if not math.isfinite(rel) or rel > rthr:
        spike("residual", rel, rthr)
    if report.mode == "trace":
        pthr = thr.pivot_threshold(report)
        for t, v in enumerate(report.pivot_inv_norm or ()):
            cond = v * report.norm_a
            if not math.isfinite(cond) or cond > pthr:
                spike("pivot_condition", cond, pthr, step=t,
                      pivot_block=report.pivot_block[t])
        gf = report.growth_factor
        if gf is not None and (not math.isfinite(gf) or gf > thr.growth):
            spike("growth", gf, thr.growth)
    report.spikes.extend(spikes)
    return spikes


def record_drift_spike(*, n: int, engine: str, value: float,
                       threshold: float, recorder=None) -> dict:
    """ISSUE 12: the resident-update ACCUMULATED-DRIFT budget
    exceedance as a ``numerics_spike`` (signal="drift") — the causal
    breadcrumb for a ``re_invert`` rung fired by composition when
    every individual update passed the residual gate (a residual spike
    alone cannot explain that rung)."""
    rec = recorder if recorder is not None else _recorder.record
    ev = {"signal": "drift", "value": float(value),
          "threshold": float(threshold)}
    _M_SPIKES.inc(signal="drift")
    rec("numerics_spike", n=n, engine=engine, mode="summary",
        workload="update", **ev)
    return ev


# ---------------------------------------------------------------------
# The acceptance demo (`make numerics-demo`, CLI --numerics-demo)
# ---------------------------------------------------------------------

def ill_conditioned(n: int, kappa_decades: float = 4.5,
                    seed: int = 7):
    """A deliberately ill-conditioned (κ∞ ~ 10^decades) but well-scaled
    dense matrix: rotated graded diagonal (the PR 5 ladder-acceptance
    fixture, promoted here so the demo and the tests share one
    recipe)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    q1, _ = np.linalg.qr(rng.standard_normal((n, n)))
    q2, _ = np.linalg.qr(rng.standard_normal((n, n)))
    return (q1 * np.logspace(0, -kappa_decades, n)) @ q2


def numerics_demo(n: int = 16, block_size: int = 8, seed: int = 7,
                  kappa_decades: float = 4.5,
                  workload: str = "invert") -> dict:
    """The ISSUE 10 acceptance run: a seeded ill-conditioned solve at
    bf16 storage under the default-shaped ladder policy, traced.

    ``workload="invert"`` (the historical demo): the bf16-grade
    residual fails the fp32-SLO gate, refine diverges (initial
    residual > 1 kills Newton-Schulz), and the fp32 re-solve passes.
    ``workload="solve"`` (ISSUE 11): the same ill-conditioned fixture
    through ``linalg.solve_system`` at bf16 storage — the rounded-X
    backward error fails the fp32-SLO solve gate and ONE refinement
    pass through the same compiled executable recovers.  The solve
    engine has its own per-superstep trace since ISSUE 12
    (``solve_system(numerics="trace")``); the demo keeps summary mode
    so its report shape stays pinned.

    Either way, because numerics observed the solve, the flight
    recorder holds the numerics_spike events BEFORE the
    residual_gate_failure / recovery_rung events they explain.  Prints
    nothing; returns the one-line-JSON report ``tools/
    check_numerics.py`` validates (exit 2 = a rung with no causally
    preceding spike — an unexplained ladder)."""
    import os
    import tempfile

    import numpy as np
    import jax.numpy as jnp

    from ..resilience import ResiliencePolicy
    from .spans import Telemetry

    if workload not in ("invert", "solve"):
        from ..driver import UsageError

        raise UsageError(f"--numerics-demo supports workload "
                         f"invert/solve, not {workload!r}")
    mark = _recorder.RECORDER.total
    tel = Telemetry()
    policy = ResiliencePolicy(gate_dtype="float32")
    if workload == "solve":
        from ..linalg import solve_system

        a = ill_conditioned(n, kappa_decades, seed)
        b = np.random.default_rng(seed + 1).standard_normal((n, 2))
        res = solve_system(a, b, block_size=block_size,
                           dtype=jnp.bfloat16, policy=policy,
                           telemetry=tel, numerics="summary")
    else:
        from ..driver import solve
        from ..io import write_matrix_file

        fd, path = tempfile.mkstemp(prefix="tpu_jordan_numerics_",
                                    suffix=".mat")
        os.close(fd)
        try:
            write_matrix_file(path,
                              ill_conditioned(n, kappa_decades, seed))
            res = solve(n, block_size, file=path, dtype=jnp.bfloat16,
                        policy=policy, telemetry=tel, numerics="trace")
        finally:
            os.unlink(path)

    blackbox = _recorder.RECORDER.dump(
        events=_recorder.RECORDER.since(mark))
    events = blackbox["events"]
    spike_seqs = [e["seq"] for e in events
                  if e["kind"] == "numerics_spike"]
    unexplained = [
        e for e in events
        if e["kind"] in ("recovery_rung", "residual_gate_failure")
        and not any(s < e["seq"] for s in spike_seqs)]
    rep = res.numerics
    return {
        "metric": "numerics_demo",
        "workload": workload,
        "n": n, "block_size": block_size, "seed": seed,
        "kappa_decades": kappa_decades,
        "engine": res.engine,
        "numerics": rep.to_json() if rep is not None else None,
        "recovery": [dict(r) for r in res.recovery],
        "rel_residual": res.rel_residual,
        "spike_count": len(spike_seqs),
        "rung_count": sum(1 for e in events
                          if e["kind"] == "recovery_rung"),
        "unexplained_rungs": [
            {"kind": e["kind"], "seq": e["seq"]} for e in unexplained],
        "silent_rung": bool(unexplained),
        "blackbox": blackbox,
    }
