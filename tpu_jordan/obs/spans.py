"""Phase-span tracing (ISSUE 4 tentpole part 1).

The reference's only timing is one max-allreduced ``MPI_Wtime`` bracket
printed as ``glob_time`` (main.cpp:427-458).  Here: a thread-safe span
TREE with an injectable monotonic clock (deterministic in tests — the
same fake-input discipline as the tuner's injected timings), so "where
did this solve's milliseconds go" has a first-class answer.

Span taxonomy (docs/OBSERVABILITY.md):

  * ``solve`` (root) → ``select`` (autotuner ladder) / ``load`` /
    ``compile`` / ``execute`` / ``gather`` / ``residual``.
  * ``compile`` vs ``execute`` are DISTINCT spans everywhere (driver,
    solver model, serve executors), so an AOT-cache hit is visible as a
    zero-compile trace — the warm-server contract made inspectable.
  * Inside ``execute``, the paper's hot-loop phases — ``pivot``
    (candidate probe + reduction), ``permute`` (row broadcast / swap /
    bucketed-ppermute repairs), ``eliminate`` (normalize + trailing
    sweep) — run inside ONE fused XLA executable, which the host cannot
    bracket.  ``attribute_phases`` subdivides the measured execute span
    with MODEL-attributed children (marked ``modeled=True`` with their
    fraction); the jax.profiler tier (``obs/export.profiler_trace``) is
    the kernel-level ground truth when the model is not enough.
  * ``residual`` (the independent verification) is a REAL span — the
    verify step is host-separable.

Thread model: each thread nests spans on its own stack; a span opened on
a non-request thread (e.g. the serve dispatcher) becomes its own root.
Only root-list mutation takes the lock — parent/child edges are
single-thread by construction.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field

#: The hot-loop phases of the paper's super-step, in execution order
#: (main.cpp:1026-1196 → the engines' probe / broadcast / sweep).
PHASES = ("pivot", "permute", "eliminate")


@dataclass
class Span:
    """One timed interval in the tree.  Times are clock-native (the
    telemetry's injectable clock — ``time.perf_counter`` by default)."""

    name: str
    t_start: float
    t_end: float | None = None
    attrs: dict = field(default_factory=dict)
    children: list = field(default_factory=list)
    thread: int = 0

    @property
    def duration(self) -> float:
        """Seconds (0.0 while the span is still open)."""
        return 0.0 if self.t_end is None else self.t_end - self.t_start

    def child(self, name: str, t_start: float, t_end: float,
              **attrs) -> "Span":
        """Attach an explicitly-timed child (the phase-attribution
        path builds synthetic sub-intervals this way)."""
        sp = Span(name, t_start, t_end, dict(attrs), thread=self.thread)
        self.children.append(sp)
        return sp

    def walk(self):
        """Depth-first iteration over this span and its subtree."""
        yield self
        for c in self.children:
            yield from c.walk()

    def find(self, name: str) -> "Span | None":
        """First span named ``name`` in this subtree (depth-first)."""
        for sp in self.walk():
            if sp.name == name:
                return sp
        return None

    def to_dict(self) -> dict:
        """Plain-JSON view (the one-line JSON exporter's span payload)."""
        return {
            "name": self.name,
            "start": self.t_start,
            "duration": self.duration,
            "attrs": dict(self.attrs),
            "children": [c.to_dict() for c in self.children],
        }


#: Root spans retained per collector; beyond this the OLDEST roots are
#: dropped — a long-lived telemetry'd server (one "execute" root per
#: dispatched batch) must not grow without bound, the same policy as
#: ``obs.metrics.MAX_RESERVOIR_SAMPLES``.
MAX_ROOT_SPANS = 4096


class Telemetry:
    """A span collector: ``span(name)`` opens a child of the current
    thread's innermost open span (or a new root).  ``clock`` is any
    zero-arg monotonic callable — tests inject a fake for deterministic
    trees; production uses ``time.perf_counter``.  At most ``max_roots``
    finished roots are retained (oldest dropped first)."""

    #: Subclass hook: ``NullTelemetry`` flips this so unobserved code
    #: paths still get honest durations without retaining anything.
    retain = True

    def __init__(self, clock=None, max_roots: int = MAX_ROOT_SPANS):
        self.clock = clock if clock is not None else time.perf_counter
        self.max_roots = int(max_roots)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._roots: list[Span] = []

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        sp = Span(name, t_start=self.clock(), attrs=dict(attrs),
                  thread=threading.get_ident())
        stack = self._stack()
        parent = stack[-1] if stack else None
        stack.append(sp)
        try:
            yield sp
        finally:
            sp.t_end = self.clock()
            stack.pop()
            if self.retain:
                if parent is not None:
                    parent.children.append(sp)
                else:
                    with self._lock:
                        self._roots.append(sp)
                        del self._roots[:-self.max_roots]

    @property
    def roots(self) -> list[Span]:
        with self._lock:
            return list(self._roots)

    def spans(self):
        """Every finished span, depth-first across all roots."""
        for r in self.roots:
            yield from r.walk()

    def find(self, name: str) -> Span | None:
        """First finished span with this name, across all roots."""
        for sp in self.spans():
            if sp.name == name:
                return sp
        return None


class NullTelemetry(Telemetry):
    """Measures (real clock, real durations) but retains nothing — the
    default sink when no telemetry is passed, so instrumented code paths
    cost one clock pair and never grow memory."""

    retain = False


#: The shared discard-only sink (safe to share: it retains no state
#: beyond each thread's transient stack).
NULL = NullTelemetry()


def timed_blocking(fn, *args, telemetry=None, name: str = "execute",
                   **attrs):
    """THE wall-clock bracket: run ``fn(*args)``, ``block_until_ready``
    the result (the single-controller analog of the reference's MAX
    allreduce over per-rank times, main.cpp:455), and return
    ``(result, span)``.

    ISSUE 4 satellite: ``driver.py`` carried three hand-rolled
    ``perf_counter``/``block_until_ready`` brackets (solve, solve_batch,
    the distributed core); they all collapse onto this helper, so the
    reported ``elapsed`` and the ``execute`` span duration are the SAME
    number by construction — they can never disagree.
    """
    import jax

    tel = telemetry if telemetry is not None else NULL
    with tel.span(name, **attrs) as sp:
        out = fn(*args)
        jax.block_until_ready(out)
    return out, sp


def attribute_phases_measured(span: Span, fractions: dict,
                              source: str = "kernel_bracket"
                              ) -> list[Span]:
    """Subdivide a measured ``execute`` span into the hot-loop phases
    using MEASURED fractions (``measured=True`` + ``source`` on every
    child — and no ``modeled`` attr, which is how
    tools/check_telemetry.py tells the two apart).

    The Pallas-path engines earn this: their probe, swap, and fused
    update kernels are separately launchable, so the host brackets each
    once per configuration (``ops/pallas_update.measured_phase_
    fractions`` — real ``timed_blocking`` walls of the actual kernels)
    and scales the measured fractions onto the solve's execute span.
    The pure-XLA engines cannot be bracketed inside one fused
    executable and keep the flops model (:func:`attribute_phases`,
    ``modeled=True``).

    ``fractions`` maps each of :data:`PHASES` to its measured share;
    they are renormalized defensively so the children always tile the
    span exactly."""
    total = sum(float(fractions[p]) for p in PHASES)
    out = []
    t = span.t_start
    for i, phase in enumerate(PHASES):
        frac = (float(fractions[phase]) / total) if total > 0 else (
            1.0 / len(PHASES))
        t1 = (span.t_end if i == len(PHASES) - 1
              else t + frac * span.duration)
        out.append(span.child(phase, t, t1, measured=True, source=source,
                              fraction=round(frac, 6)))
        t = t1
    return out


def attribute_phases(span: Span, n: int, block_size: int,
                     distributed: bool = False,
                     lookahead: bool = False) -> list[Span]:
    """Subdivide a measured ``execute`` span into the paper's hot-loop
    phases as MODEL-attributed children (``modeled=True`` + the fraction
    on every child — never mistakable for measured sub-brackets).

    The host cannot bracket phases inside one fused XLA executable, so
    the split uses the same first-order weights the registry's cost
    hooks use: ``eliminate`` carries the 2n³ MXU sweep, ``pivot`` the
    Nr·2m³ (= 2nm²) probe flops, ``permute`` an O(n²) data-movement
    term weighted heavier on distributed meshes (ICI rounds vs local
    copies).  Kernel-level ground truth is the jax.profiler tier
    (``obs/export.profiler_trace``), not this model.

    ``lookahead=True`` (ISSUE 16, the probe-ahead engines) keeps the
    three tiling children UNCHANGED — the schedule reorders work, it
    never changes the arithmetic — and nests a ``probe_ahead`` child
    inside ``eliminate``: the step-(t+1) condition probe re-issued
    inside the trailing-sweep window, where the XLA latency-hiding
    scheduler can overlap its collective with the trailing GEMMs.  Its
    ``fraction`` is the probe share that is hideable (bounded by the
    eliminate share), with ``overlapped=True`` so readers never sum it
    into the tiling.
    """
    m = max(1, min(block_size, n))
    weights = {
        "pivot": 2.0 * n * m * m,
        "permute": (64.0 if distributed else 8.0) * float(n) * n,
        "eliminate": 2.0 * float(n) ** 3,
    }
    total = sum(weights.values())
    out = []
    t = span.t_start
    for i, phase in enumerate(PHASES):
        frac = weights[phase] / total
        t1 = (span.t_end if i == len(PHASES) - 1
              else t + frac * span.duration)
        sp = span.child(phase, t, t1, modeled=True,
                        fraction=round(frac, 6))
        if lookahead and phase == "eliminate":
            hid = min(weights["pivot"], weights["eliminate"])
            sp.child("probe_ahead", t,
                     t + (hid / weights["eliminate"]) * (t1 - t),
                     modeled=True, overlapped=True,
                     fraction=round(hid / total, 6))
        out.append(sp)
        t = t1
    return out
