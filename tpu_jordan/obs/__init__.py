"""tpu_jordan.obs — the unified telemetry layer (ISSUE 4 tentpole).

Three modules replace the three private timing/counter islands the repo
had grown (``utils/profiling.Scoreboard``, the tuner's measurement
counter, ``serve/stats``' per-instance dicts):

  * ``spans`` — thread-safe span tree with an injectable monotonic
    clock: ``solve`` roots with select/load/compile/execute/gather/
    residual children, model-attributed hot-loop phases (pivot /
    permute / eliminate) under ``execute``, and the one shared
    wall-clock bracket ``timed_blocking`` the driver's timings ride.
  * ``metrics`` — the process-wide registry of ``tpu_jordan_*``
    counters/gauges/reservoir histograms (p50/p95/p99) that solve, the
    autotuner, and the serving layer all register into.
  * ``export`` — one-line JSON, Prometheus text, Chrome trace-event
    JSON (Perfetto), plus the jax.profiler kernel tier.

Operator guide: ``docs/OBSERVABILITY.md``.
"""

from . import export, metrics, spans
from .export import (profiler_trace, to_chrome_trace, to_json_line,
                     to_prometheus, write_chrome_trace, write_metrics)
from .metrics import REGISTRY, MetricsRegistry, Reservoir
from .spans import (NULL, NullTelemetry, Span, Telemetry,
                    attribute_phases, attribute_phases_measured,
                    timed_blocking)

__all__ = [
    "export", "metrics", "spans",
    "profiler_trace", "to_chrome_trace", "to_json_line", "to_prometheus",
    "write_chrome_trace", "write_metrics",
    "REGISTRY", "MetricsRegistry", "Reservoir",
    "NULL", "NullTelemetry", "Span", "Telemetry", "attribute_phases",
    "attribute_phases_measured", "timed_blocking",
]
