"""tpu_jordan.obs — the unified telemetry layer (ISSUE 4 tentpole).

Three modules replace the three private timing/counter islands the repo
had grown (``utils/profiling.Scoreboard``, the tuner's measurement
counter, ``serve/stats``' per-instance dicts):

  * ``spans`` — thread-safe span tree with an injectable monotonic
    clock: ``solve`` roots with select/load/compile/execute/gather/
    residual children, model-attributed hot-loop phases (pivot /
    permute / eliminate) under ``execute``, and the one shared
    wall-clock bracket ``timed_blocking`` the driver's timings ride.
  * ``metrics`` — the process-wide registry of ``tpu_jordan_*``
    counters/gauges/reservoir histograms (p50/p95/p99) that solve, the
    autotuner, and the serving layer all register into.
  * ``export`` — one-line JSON, Prometheus text, Chrome trace-event
    JSON (Perfetto), plus the jax.profiler kernel tier.

ISSUE 10 adds the numerics-and-hardware observatory:

  * ``numerics`` — per-superstep numerical health (the paper's pivot
    criterion, candidate spread, element-growth watermark, verified
    residual) behind the ``numerics=`` knob (off/summary/trace), with
    ``numerics_spike`` flight-recorder events causally preceding any
    recovery rung (``tools/check_numerics.py``).
  * ``hwcost`` — XLA ``cost_analysis``/``memory_analysis`` per
    compiled executable, achieved-vs-analytical TFLOP/s and
    arithmetic-intensity attrs on execute spans, per-bucket
    ``tpu_jordan_executable_*`` gauges, device live-bytes watermarks,
    and the ``runtime_env`` fingerprint BENCH rows record.

ISSUE 14 adds the communication observatory:

  * ``comm`` — layout-derived per-superstep collective accounting for
    every distributed engine (bytes/messages by phase and kind, on
    execute spans, ``tpu_jordan_comm_*`` counters and
    ``SolveResult.comm``), the trace-time ``observed == analytical``
    reconciliation behind ``parallel/compat.py``'s collective shims,
    and measured-vs-projected drift against ``benchmarks/comm_model``
    (``comm_drift`` events, ``tools/check_comm.py``).

ISSUE 8 adds the request-scoped triad:

  * ``journey`` — per-request journey tracing: a deterministic
    ``request_id`` minted at submit, every routing/queueing/execution
    hop a timestamped event, exported as one Chrome-trace async lane
    per request and summarized by the shared outcome-ledger helper.
  * ``recorder`` — the always-on bounded flight recorder (black box):
    structured fleet events dumped on failure and validated
    event-by-event by the chaos/fleet checkers.
  * ``slo`` — declarative per-bucket SLOs evaluated by multi-window
    burn rate over registry snapshots (``tools/check_slo.py``).

Operator guide: ``docs/OBSERVABILITY.md``.
"""

from . import (comm, export, hwcost, journey, metrics, numerics,
               recorder, slo, spans)
from .comm import (CommReport, comm_demo, engine_report,
                   record_collectives, recording)
from .export import (profiler_trace, to_chrome_trace, to_json_line,
                     to_prometheus, write_chrome_trace, write_metrics)
from .hwcost import (ExecutableCost, attach_execute_cost,
                     executable_cost, runtime_env)
from .journey import (JourneyLog, RequestContext, async_trace_events,
                      journeys_from_events, outcome_ledger)
from .numerics import (NumericsReport, SpikeThresholds, numerics_demo,
                       record_spikes)
from .metrics import REGISTRY, MetricsRegistry, Reservoir
from .recorder import RECORDER, FlightRecorder
from .slo import SLOMonitor, SLOSpec, bucket_specs
from .spans import (NULL, NullTelemetry, Span, Telemetry,
                    attribute_phases, attribute_phases_measured,
                    timed_blocking)

__all__ = [
    "comm", "export", "hwcost", "journey", "metrics", "numerics",
    "recorder", "slo", "spans",
    "CommReport", "comm_demo", "engine_report", "record_collectives",
    "recording",
    "profiler_trace", "to_chrome_trace", "to_json_line", "to_prometheus",
    "write_chrome_trace", "write_metrics",
    "ExecutableCost", "attach_execute_cost", "executable_cost",
    "runtime_env",
    "NumericsReport", "SpikeThresholds", "numerics_demo",
    "record_spikes",
    "JourneyLog", "RequestContext", "async_trace_events",
    "journeys_from_events", "outcome_ledger",
    "REGISTRY", "MetricsRegistry", "Reservoir",
    "RECORDER", "FlightRecorder",
    "SLOMonitor", "SLOSpec", "bucket_specs",
    "NULL", "NullTelemetry", "Span", "Telemetry", "attribute_phases",
    "attribute_phases_measured", "timed_blocking",
]
