"""The always-on flight recorder (ISSUE 8 tentpole part 2).

A production fleet's worst failures are the ones the process does not
survive to explain: by the time ``--fleet-demo`` exits 2 the replicas
are gone, the span trees were per-``Telemetry`` opt-ins, and the only
evidence left is an end-state ledger.  The flight recorder is the
black box: a bounded ring buffer of STRUCTURED fleet events that is
always recording — route decisions, replica kills, heartbeat-staleness
wedges, breaker transitions, degradation-ladder rungs, injected
faults, and every per-request journey hop (``obs/journey.py``) — so a
post-mortem reconstructs the causal chain (fault → retry/reroute/rung
→ clean response) from the dump alone, without re-running the demo.

Design contract:

  * **always on, near-zero warm cost** — recording is one dict build +
    one lock + one deque append; there is no sampling decision, no I/O,
    no formatting until ``dump()``.  The warm-serve pins (zero
    compiles, zero measurements) run WITH the recorder on.
  * **bounded** — a ring of ``capacity`` events (oldest dropped first);
    ``recorded_total`` vs the retained window makes any drop explicit
    in the dump (``dropped``), never silent.
  * **ordered** — every event carries a process-wide monotone ``seq``,
    so causal chains are checkable even when the wall clock is fake
    (the obs injectable-clock discipline: ``clock`` is any zero-arg
    monotonic callable).
  * **dumped on failure** — the CLI writes the ring on every exit-2
    path automatically, and on demand via ``--blackbox-out PATH``; the
    fleet/chaos demos embed their chaos window's slice in the report,
    which ``tools/check_fleet.py`` / ``tools/check_chaos.py`` validate
    event-by-event (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

#: Ring capacity: a 3-replica/60-request chaos window records a few
#: hundred events; 8192 keeps several windows of history without the
#: recorder ever becoming a memory concern.
DEFAULT_CAPACITY = 8192


class FlightRecorder:
    """The bounded, thread-safe event ring.  ``record(kind, **fields)``
    appends ``{"seq", "t", "kind", **fields}``; ``since(seq)`` slices
    the window a demo wants to embed in its report."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, clock=None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.clock = clock if clock is not None else time.perf_counter
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0

    def record(self, kind: str, t: float | None = None, **fields) -> int:
        """Append one event; returns its ``seq``.  ``t`` lets a caller
        that already read its own clock (a journey hop) stamp both
        stores with the SAME instant."""
        ev = dict(fields)
        ev["kind"] = str(kind)
        ev["t"] = float(t) if t is not None else self.clock()
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
            return self._seq

    @property
    def total(self) -> int:
        """Events recorded over the recorder's lifetime (monotone; the
        next ``record`` gets ``total + 1`` — ``since(total)`` before an
        operation therefore brackets exactly that operation's events)."""
        with self._lock:
            return self._seq

    def events(self, kind: str | None = None) -> list[dict]:
        """The retained window (oldest first), optionally filtered."""
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    def since(self, seq: int) -> list[dict]:
        """Events with ``seq`` strictly greater than ``seq`` (the slice
        a demo embeds: ``mark = recorder.total`` before the window,
        ``recorder.since(mark)`` after)."""
        with self._lock:
            return [e for e in self._ring if e["seq"] > seq]

    def dump(self, events: list[dict] | None = None) -> dict:
        """The black-box document: the retained window (or an explicit
        slice) plus the honesty counters — ``dropped`` > 0 means the
        ring overflowed and reconstruction may have gaps."""
        with self._lock:
            window = list(self._ring) if events is None else list(events)
            total = self._seq
        # seq is dense and monotone, so a window is gap-free iff it is
        # contiguous; events evicted by the ring before the window's
        # first retained seq are the drop count (0 for an explicit
        # slice that was taken before eviction could reach it).
        if events is None:
            dropped = (window[0]["seq"] - 1) if window else total
        else:
            seqs = [e["seq"] for e in window]
            dropped = (seqs[-1] - seqs[0] + 1 - len(seqs)) if seqs else 0
        return {
            "metric": "blackbox",
            "capacity": self.capacity,
            "recorded_total": total,
            "retained": len(window),
            "dropped": dropped,
            "events": window,
        }

    def write(self, path: str, events: list[dict] | None = None) -> None:
        """Write ``dump()`` as one JSON document (the ``--blackbox-out``
        / exit-2 emission)."""
        with open(path, "w") as f:
            json.dump(self.dump(events), f)

    def reset(self) -> None:
        """Drop the ring and the seq counter (TESTS ONLY — a black box
        that can be wiped in production is not a black box)."""
        with self._lock:
            self._ring.clear()
            self._seq = 0


#: THE process-wide recorder: always on, bounded, shared by the fleet,
#: the serve path, and the resilience layer.  Library code records
#: through :func:`record`; demos slice it with ``since``/``dump``.
RECORDER = FlightRecorder()


def record(kind: str, t: float | None = None, **fields) -> int:
    """Record one event into the process-wide ring (the module-level
    convenience every instrumented call site uses)."""
    return RECORDER.record(kind, t=t, **fields)
