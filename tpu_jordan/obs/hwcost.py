"""XLA cost/memory accounting per compiled executable (ISSUE 10
tentpole part 2).

Until this layer, achieved TFLOP/s everywhere came from the
hand-counted 2n³ convention (``utils/profiling.invert_flops``,
BASELINE.md) — fine for cross-round comparability, but blind to what
the COMPILER actually scheduled: the probe's batched block inverses,
the eager side-updates, refinement.  "Large Scale Distributed Linear
Algebra With TPUs" (arXiv:2112.09017) attributes achieved-vs-peak from
the executable's own accounting; this module does the same through
``compiled.cost_analysis()`` (FLOPs, bytes accessed) and
``compiled.memory_analysis()`` (argument/output/temp HBM footprint).

Honesty contract (the PR 4 discipline): every number here is read from
the compiler or the runtime — nothing is modeled.  When a backend does
not expose the analysis, the fields are ``None`` and
``available=False``; a missing number is reported missing, never
silently replaced by a hand count.  The 2n³ BASELINE convention stays
available as :func:`baseline_invert_flops` (what ``gflops`` headline
rows keep for cross-round comparability) and the paper-accounting
analytic is :func:`gauss_jordan_flops` = (8/3)n³ — pinned against the
real ``cost_analysis`` count by tests/test_hwcost.py.

Surfaces:

  * :func:`executable_cost` — one :class:`ExecutableCost` per compiled
    executable (driver solve/solve_batch, JordanSolver, every serve
    ``BucketExecutor``), read once at compile time: zero per-execute
    cost.
  * :func:`attach_execute_cost` — achieved-vs-analytical TFLOP/s and
    arithmetic-intensity attrs on ``execute`` spans.
  * :func:`observe_cost` / ``ServeStats`` — ``tpu_jordan_executable_*``
    gauges keyed by serve bucket, plus the live-bytes device watermark
    gauges (``tpu_jordan_device_bytes_in_use`` /
    ``_peak_bytes_in_use``) where the runtime reports them (TPU yes,
    CPU no — absent, not zeroed).
  * :func:`runtime_env` — jax/jaxlib versions, device kind, host core
    count: the BENCH-row interpretability block (ISSUE 10 satellite).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from . import metrics as _metrics

_M_FLOPS = _metrics.gauge(
    "tpu_jordan_executable_flops",
    "XLA cost_analysis FLOPs of a compiled executable (per serve "
    "bucket / component)")
_M_BYTES = _metrics.gauge(
    "tpu_jordan_executable_bytes_accessed",
    "XLA cost_analysis bytes accessed of a compiled executable")
_M_HBM = _metrics.gauge(
    "tpu_jordan_executable_hbm_bytes",
    "XLA memory_analysis HBM footprint (arguments + outputs + temps) "
    "of a compiled executable")
_M_DEV_USED = _metrics.gauge(
    "tpu_jordan_device_bytes_in_use",
    "live bytes on the device per the runtime allocator (absent on "
    "backends that do not report memory stats)")
_M_DEV_PEAK = _metrics.gauge(
    "tpu_jordan_device_peak_bytes_in_use",
    "peak live-bytes watermark on the device per the runtime "
    "allocator (absent on backends that do not report memory stats)")


def baseline_invert_flops(n: int) -> float:
    """The 2n³ Gauss–Jordan convention used by BASELINE.md and every
    BENCH_r* headline — kept for cross-round comparability (changing
    the unit would orphan the r01+ trajectory)."""
    return 2.0 * float(n) ** 3


def gauss_jordan_flops(n: int) -> float:
    """The (8/3)n³ analytical count of the blocked in-place
    Gauss–Jordan inversion INCLUDING the pivot probe's batched block
    inverses and the normalize side-products — what
    ``cost_analysis()`` reports for the real executable (pinned within
    tolerance by tests/test_hwcost.py at a fixed shape)."""
    return (8.0 / 3.0) * float(n) ** 3


def baseline_workload_flops(n: int, workload: str = "invert",
                            k: int = 1, rows: int | None = None) -> float:
    """Workload-aware analytic FLOP conventions (ISSUE 11 satellite).

    The invert headline keeps the 2n³ BASELINE convention; the solve
    workloads get their own honest denominators so achieved-TFLOP/s
    headlines for the new bench rows are never judged against the wrong
    count (a solve row divided by 2n³ would read ~2x too fast):

      * ``solve`` / ``solve_spd`` — Gauss–Jordan on [A | B] with the
        STATICALLY shrinking live-column window: ~n³·(1 + k/n) for k
        right-hand sides (the ISSUE 11 convention; the SPD path skips
        probe work, not sweep work, so the convention is shared).
      * ``lstsq`` — the normal-equations route: one AᴴA Gram product
        (2·rows·n² for a (rows, n) A), the Aᴴb projection (2·rows·n·k),
        then the n-sized SPD solve.
      * ``update`` — the Sherman–Morrison–Woodbury rank-k
        resident-inverse update (ISSUE 12, linalg/update.py): 4n²k +
        2nk², the CANONICAL two-sided SMW count (the A⁻¹U / VᵀA⁻¹
        products plus the capacitance assembly/solve's nk² term, k³
        dropped as low-order dust).  Deliberately lean, like the 2n³
        invert headline vs its measured (8/3)n³: the executed kernel
        additionally pays the correction-apply and U·Vᵀ-mutation GEMMs
        (~8n²k of update arithmetic total) AND the deliberate O(n³)
        re-verification matmul — all of which show up honestly in the
        ``cost_analysis`` numbers (``*_xla_vs_analytic``) recorded
        next to every headline, never silently inside its denominator.

    A complex FLOP is counted as one flop like everywhere else in the
    BASELINE convention (the ~4x real-op cost of complex arithmetic is
    the hardware's business; ``cost_analysis`` reports the real count
    next to these on every row)."""
    n = float(n)
    k = float(max(1, k))
    if workload == "invert":
        return baseline_invert_flops(int(n))
    if workload in ("solve", "solve_spd"):
        return n ** 3 * (1.0 + k / n)
    if workload == "update":
        return 4.0 * n * n * k + 2.0 * n * k * k
    if workload == "lstsq":
        r = n if rows is None else float(rows)
        return (2.0 * r * n * n + 2.0 * r * n * k
                + n ** 3 * (1.0 + k / n))
    raise ValueError(f"unknown workload {workload!r}")


@dataclass(frozen=True)
class ExecutableCost:
    """Compiler-reported cost/memory of ONE compiled executable.
    ``available=False`` means the backend exposed no analysis — every
    field None, nothing modeled in its place."""

    available: bool
    flops: float | None = None
    bytes_accessed: float | None = None
    argument_bytes: int | None = None
    output_bytes: int | None = None
    temp_bytes: int | None = None
    generated_code_bytes: int | None = None
    source: str = "xla_cost_analysis"

    @property
    def hbm_bytes(self) -> int | None:
        """Peak HBM footprint: arguments + outputs + temps (the
        executable's resident working set; aliased/donated buffers
        count once on the argument side)."""
        parts = [self.argument_bytes, self.output_bytes, self.temp_bytes]
        if all(p is None for p in parts):
            return None
        return sum(int(p) for p in parts if p is not None)

    @property
    def arithmetic_intensity(self) -> float | None:
        """FLOPs per byte accessed — the roofline x-coordinate."""
        if not self.flops or not self.bytes_accessed:
            return None
        return self.flops / self.bytes_accessed

    def to_json(self) -> dict:
        return {
            "available": self.available,
            "source": self.source,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "hbm_bytes": self.hbm_bytes,
            "arithmetic_intensity": (
                None if self.arithmetic_intensity is None
                else round(self.arithmetic_intensity, 2)),
        }


UNAVAILABLE = ExecutableCost(available=False)


def executable_cost(compiled) -> ExecutableCost:
    """Read cost/memory analysis off a compiled executable (a
    ``jax.stages.Compiled`` or anything quacking like one).  Defensive
    on purpose: backends differ in what they expose (list-of-dicts vs
    dict cost analysis, missing memory analysis) and a telemetry read
    must never fail a solve."""
    flops = bytes_accessed = None
    arg_b = out_b = tmp_b = code_b = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            f = ca.get("flops")
            b = ca.get("bytes accessed")
            flops = float(f) if f is not None else None
            bytes_accessed = float(b) if b is not None else None
    except Exception:                            # noqa: BLE001
        pass
    try:
        ma = compiled.memory_analysis()
        arg_b = int(getattr(ma, "argument_size_in_bytes"))
        out_b = int(getattr(ma, "output_size_in_bytes"))
        tmp_b = int(getattr(ma, "temp_size_in_bytes"))
        code_b = int(getattr(ma, "generated_code_size_in_bytes"))
    except Exception:                            # noqa: BLE001
        pass
    if flops is None and bytes_accessed is None and arg_b is None:
        return UNAVAILABLE
    return ExecutableCost(available=True, flops=flops,
                          bytes_accessed=bytes_accessed,
                          argument_bytes=arg_b, output_bytes=out_b,
                          temp_bytes=tmp_b, generated_code_bytes=code_b)


def attach_execute_cost(span, cost: ExecutableCost,
                        analytical_flops: float | None = None) -> None:
    """Achieved-vs-analytical attrs on an ``execute`` span:

      * ``xla_flops`` / ``xla_bytes`` — the compiler's own counts;
      * ``achieved_tflops_xla`` — xla_flops / measured wall;
      * ``achieved_tflops_analytical`` — the hand-convention rate
        (``analytical_flops`` / wall, typically 2n³ — the BASELINE
        headline unit) next to it, so the two accountings are always
        side by side;
      * ``xla_vs_analytical`` — their ratio (how much work the
        compiled program really does per hand-counted flop);
      * ``arithmetic_intensity`` — flops/byte (roofline position).

    No-op when the analysis is unavailable or the span has no
    duration — a missing number stays missing."""
    if not cost.available:
        return

    def sig(v: float) -> float:
        # 4 significant digits, never rounded to zero: a 64² solve's
        # achieved rate is micro-TFLOP/s and must survive rounding.
        return float(f"{v:.4g}")

    el = span.duration
    if cost.flops:
        span.attrs["xla_flops"] = cost.flops
        if el > 0:
            span.attrs["achieved_tflops_xla"] = sig(
                cost.flops / el / 1e12)
    if cost.bytes_accessed:
        span.attrs["xla_bytes"] = cost.bytes_accessed
    ai = cost.arithmetic_intensity
    if ai is not None:
        span.attrs["arithmetic_intensity"] = sig(ai)
    if analytical_flops and el > 0:
        span.attrs["achieved_tflops_analytical"] = sig(
            analytical_flops / el / 1e12)
        if cost.flops:
            span.attrs["xla_vs_analytical"] = sig(
                cost.flops / analytical_flops)


def observe_cost(cost: ExecutableCost, **labels) -> None:
    """Mirror an executable's cost into the registry gauges (labeled
    by serve bucket / component).  Unavailable analysis sets nothing —
    absent is honest, zero would be a lie."""
    if not cost.available:
        return
    if cost.flops is not None:
        _M_FLOPS.set(cost.flops, **labels)
    if cost.bytes_accessed is not None:
        _M_BYTES.set(cost.bytes_accessed, **labels)
    hbm = cost.hbm_bytes
    if hbm is not None:
        _M_HBM.set(hbm, **labels)


def device_memory_stats(device=None) -> dict | None:
    """The runtime allocator's live/peak byte counters for one device,
    or None where the backend reports none (CPU).  Keys normalized to
    ``bytes_in_use`` / ``peak_bytes_in_use`` when present."""
    try:
        import jax

        dev = device if device is not None else jax.local_devices()[0]
        stats = dev.memory_stats()
    except Exception:                            # noqa: BLE001
        return None
    if not stats:
        return None
    return dict(stats)


def observe_device_memory(device=None, **labels) -> dict | None:
    """Sample the device allocator into the watermark gauges; returns
    the raw stats dict (None = backend reports none, gauges
    untouched)."""
    stats = device_memory_stats(device)
    if stats is None:
        return None
    used = stats.get("bytes_in_use")
    peak = stats.get("peak_bytes_in_use")
    if used is not None:
        _M_DEV_USED.set(float(used), **labels)
    if peak is not None:
        _M_DEV_PEAK.set(float(peak), **labels)
    return stats


class DeviceMemoryWatermark:
    """The sticky live-bytes watermark probe (ISSUE 13 satellite,
    fixing the PR 9 one-shot): availability is decided by the FIRST
    probe and never re-litigated —

      * a backend that reported no allocator stats on the first probe
        (CPU) stays ``available=False`` forever: every later ``sample``
        is a lock-check no-op, the gauges are never set, never zeroed,
        never modeled;
      * a backend that DID report stats is re-probed at every
        capacity/metrics snapshot and every served batch
        (``serve/stats.py``) — and a TRANSIENT empty read on such a
        backend returns None without touching the gauges or flipping
        availability (absent is honest; the old per-instance tri-state
        disabled the watermark forever on one hiccup).

    ``sampler`` is injectable (tests pin both behaviors without a TPU).
    """

    def __init__(self, sampler=None):
        self._sampler = (sampler if sampler is not None
                         else device_memory_stats)
        self._lock = threading.Lock()
        #: None = never probed; the first probe's verdict is final.
        self.available: bool | None = None

    def sample(self, **labels) -> dict | None:
        with self._lock:
            if self.available is False:
                return None
        stats = self._sampler()
        with self._lock:
            if self.available is None:
                self.available = stats is not None
        if stats is None:
            return None
        used = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        if used is not None:
            _M_DEV_USED.set(float(used), **labels)
        if peak is not None:
            _M_DEV_PEAK.set(float(peak), **labels)
        return stats


#: THE process-wide watermark (the device allocator is process state):
#: serve stats, the capacity snapshot, and the metrics exporter all
#: sample through this one sticky probe.
WATERMARK = DeviceMemoryWatermark()


def runtime_env() -> dict:
    """The environment fingerprint BENCH rows (and the fleet demo)
    record so cross-round comparisons are interpretable: jax/jaxlib
    versions, backend + device kind + count, host core count.  The
    bench sentinel treats these as context, never as a gate — missing
    fields in old rows are unknown, not regressed (ISSUE 10
    satellite)."""
    import os

    env = {"host_cpu_count": os.cpu_count()}
    try:
        import jax

        env["jax"] = jax.__version__
    except Exception:                            # noqa: BLE001
        env["jax"] = None
    try:
        import jaxlib

        env["jaxlib"] = jaxlib.__version__
    except Exception:                            # noqa: BLE001
        env["jaxlib"] = None
    try:
        import jax

        devs = jax.devices()
        env["backend"] = jax.default_backend()
        env["device_kind"] = devs[0].device_kind if devs else None
        env["device_count"] = len(devs)
    except Exception:                            # noqa: BLE001
        env.setdefault("backend", None)
        env.setdefault("device_kind", None)
        env.setdefault("device_count", None)
    return env
